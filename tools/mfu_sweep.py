"""One MFU trial of the 1B-class bench model per process invocation.

Round-4 tuning harness for the BASELINE.md config-4 headline: sweep
batch size, recompute granularity, optimizer moment dtype, and Pallas
flash-attention block shapes on the real chip, one subprocess per trial
so HBM and the XLA client reset between configs. Prints one JSON line:

    python tools/mfu_sweep.py --batch 8 --moments bfloat16 \
        --recompute selective --bq 256 --bk 512

The winning config goes into bench.py's bench_llama_1b.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="llama",
                    choices=["llama", "bert", "ernie_moe"],
                    help="llama sweeps the 1B headline shape; bert / "
                         "ernie_moe run bench.py's config-3/5 extras "
                         "at the given batch/seq (the llama-only tuning "
                         "flags are ignored there, with a warning)")
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--recompute", default="selective",
                    choices=["none", "full", "selective", "selective_qkv"])
    ap.add_argument("--moments", default="float32",
                    choices=["float32", "bfloat16"])
    ap.add_argument("--bq", type=int, default=0, help="flash BLOCK_Q override")
    ap.add_argument("--bk", type=int, default=0, help="flash BLOCK_K override")
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--steps", type=int, default=6)
    ap.add_argument("--flash", type=int, default=1)
    ap.add_argument("--fused_ce", type=int, default=0,
                    help="1 = chunked fused lm-head+CE (no [T,V] logits)")
    ap.add_argument("--ce_chunks", type=int, default=8,
                    help="row chunks for the fused CE scan")
    args = ap.parse_args()

    from bench import (_enable_compile_cache, _peak, bench_bert,
                       bench_ernie_moe)
    _enable_compile_cache()

    if args.model != "llama":
        ignored = [f for f, cur, dflt in [
            ("--recompute", args.recompute, "selective"),
            ("--moments", args.moments, "float32"),
            ("--bq", args.bq, 0), ("--bk", args.bk, 0),
            ("--layers", args.layers, 4), ("--flash", args.flash, 1),
            ("--fused_ce", args.fused_ce, 0),
        ] if cur != dflt]
        if ignored:
            print(f"note: {' '.join(ignored)} apply to --model llama "
                  f"only; ignored for {args.model}", file=sys.stderr)
        t0 = time.time()
        if args.model == "bert":
            tok, mfu = bench_bert(batch=args.batch, seq=args.seq,
                                  n_steps=args.steps)
            extra = {"mfu_approx": round(mfu, 4)}
        else:
            tok, mfu = bench_ernie_moe(batch=args.batch, seq=args.seq,
                                       n_steps=args.steps)
            extra = {"mfu_routed": round(mfu, 4)}
        print(json.dumps({"model": args.model, "batch": args.batch,
                          "seq": args.seq,
                          "tokens_per_sec": round(tok, 1),
                          "wall_s": round(time.time() - t0, 1), **extra}),
              flush=True)
        return

    import paddle_tpu as paddle
    from paddle_tpu.kernels import flash_attention as fa
    from paddle_tpu.text.models import (LlamaConfig, LlamaForCausalLM,
                                        llama_flops_per_token)

    if args.bq:
        fa.BLOCK_Q = args.bq
    if args.bk:
        fa.BLOCK_K = args.bk

    cfg = LlamaConfig(
        vocab_size=32000, hidden_size=4096, intermediate_size=11008,
        num_hidden_layers=args.layers, num_attention_heads=32,
        num_key_value_heads=32, max_position_embeddings=args.seq,
        recompute=args.recompute != "none",
        recompute_granularity=(args.recompute
                               if args.recompute != "none" else "selective"),
        use_flash_attention=bool(args.flash),
        fused_linear_ce=bool(args.fused_ce),
        fused_ce_chunks=args.ce_chunks)

    paddle.seed(0)
    net = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(rng.integers(
        0, cfg.vocab_size, (args.batch, args.seq)).astype(np.int64))
    labels = paddle.to_tensor(rng.integers(
        0, cfg.vocab_size, (args.batch, args.seq)).astype(np.int64))
    from bench import llama_step_io
    loss_fn, inputs = llama_step_io(cfg, ids, labels)
    moment_dtype = None if args.moments == "float32" else args.moments
    opt = paddle.optimizer.AdamW(3e-4, parameters=net.parameters(),
                                 moment_dtype=moment_dtype)
    step = paddle.jit.TrainStep(net, loss_fn, opt, amp_dtype="bfloat16")

    t0 = time.perf_counter()
    step(inputs, labels)                # compile
    compile_s = time.perf_counter() - t0
    float(step(inputs, labels).numpy())  # warm (fetch = the real sync)
    best_dt = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        for _ in range(args.steps):
            loss = step(inputs, labels)
        float(loss.numpy())
        best_dt = min(best_dt, (time.perf_counter() - t0) / args.steps)
    tokens_per_sec = args.batch * args.seq / best_dt
    peak, _ = _peak()
    mfu = tokens_per_sec * llama_flops_per_token(cfg) / peak
    print(json.dumps({
        "batch": args.batch, "seq": args.seq, "recompute": args.recompute,
        "fused_ce": args.fused_ce,
        "moments": args.moments, "bq": args.bq or fa.BLOCK_Q,
        "bk": args.bk or fa.BLOCK_K, "layers": args.layers,
        "tokens_per_sec": round(tokens_per_sec, 1), "mfu": round(mfu, 4),
        "step_ms": round(best_dt * 1e3, 1), "compile_s": round(compile_s, 1),
    }), flush=True)


if __name__ == "__main__":
    main()
