"""hapi callbacks (reference: python/paddle/hapi/callbacks.py)."""
from __future__ import annotations

import time

import numpy as np

from .. import monitor


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params

    def on_begin(self, mode, logs=None):
        pass

    def on_end(self, mode, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks=None, model=None, **params):
        self.callbacks = list(callbacks or [])
        if params.get("verbose", 2) and not any(
                isinstance(c, ProgBarLogger) for c in self.callbacks):
            self.callbacks.insert(0, ProgBarLogger(
                log_freq=params.get("log_freq", 10),
                verbose=params.get("verbose", 2)))
        # PADDLE_TPU_MONITOR=1 (or monitor.enable()): per-epoch
        # step-time/recompile telemetry lines ride along automatically
        if monitor.enabled() and not any(
                isinstance(c, TelemetryLogger) for c in self.callbacks):
            self.callbacks.append(TelemetryLogger())
        for c in self.callbacks:
            c.set_model(model)
            c.set_params(params)

    def _call(self, name, *args):
        for c in self.callbacks:
            getattr(c, name)(*args)

    def on_begin(self, mode, logs=None):
        self._call("on_begin", mode, logs)

    def on_end(self, mode, logs=None):
        self._call("on_end", mode, logs)

    def on_epoch_begin(self, epoch, logs=None):
        self._call("on_epoch_begin", epoch, logs)

    def on_epoch_end(self, epoch, logs=None):
        self._call("on_epoch_end", epoch, logs)

    def on_batch_begin(self, mode, step, logs=None):
        self._call(f"on_{mode}_batch_begin", step, logs)

    def on_batch_end(self, mode, step, logs=None):
        self._call(f"on_{mode}_batch_end", step, logs)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=10, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_begin(self, mode, logs=None):
        self._t0 = time.time()

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._epoch_t0 = time.time()
        self._samples = 0

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            msg = " - ".join(f"{k}: {v:.4f}" for k, v in (logs or {}).items()
                             if isinstance(v, (int, float)))
            total = f"/{self.steps}" if self.steps else ""
            print(f"Epoch {self.epoch + 1} step {step}{total}: {msg}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._epoch_t0
            msg = " - ".join(f"{k}: {v:.4f}" for k, v in (logs or {}).items()
                             if isinstance(v, (int, float)))
            print(f"Epoch {epoch + 1} done ({dt:.1f}s): {msg}")


class TelemetryLogger(Callback):
    """Per-epoch runtime telemetry through the paddle_tpu.monitor
    registry: step-time stats measured here, XLA recompile count/seconds
    fed by the always-on compile listener (profiler/stats.py). Inserted
    automatically by CallbackList when PADDLE_TPU_MONITOR=1 so every
    Model.fit emits one line per epoch like

        [telemetry] epoch 1: steps 50 avg_step_ms 12.4 (min 11.0 max
        31.2) recompiles 3 compile_s 1.84

    A steady recompiles > 0 after the first epoch is the shape-churn
    signature — run a Profiler and read shape_churn_report() to find
    the op."""

    def __init__(self, verbose=1):
        super().__init__()
        self.verbose = verbose
        self.last_line = None

    def _compiles(self):
        return (monitor.counter("xla.compiles").get(),
                monitor.gauge("xla.compile_secs").get())

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch
        self._steps = 0
        self._dt_total = 0.0
        self._dt_min = float("inf")
        self._dt_max = 0.0
        self._compiles0 = self._compiles()

    def on_train_batch_begin(self, step, logs=None):
        self._t0 = time.perf_counter()

    def on_train_batch_end(self, step, logs=None):
        dt = time.perf_counter() - getattr(self, "_t0", time.perf_counter())
        self._steps += 1
        self._dt_total += dt
        self._dt_min = min(self._dt_min, dt)
        self._dt_max = max(self._dt_max, dt)
        monitor.counter("train.steps").increase()
        monitor.gauge("train.step_ms").set(dt * 1e3)

    def on_epoch_end(self, epoch, logs=None):
        if not getattr(self, "_steps", 0):
            return
        c1, s1 = self._compiles()
        c0, s0 = self._compiles0
        avg = self._dt_total / self._steps * 1e3
        monitor.gauge("train.epoch_recompiles").set(c1 - c0)
        self.last_line = (
            f"[telemetry] epoch {epoch + 1}: steps {self._steps} "
            f"avg_step_ms {avg:.1f} (min {self._dt_min * 1e3:.1f} "
            f"max {self._dt_max * 1e3:.1f}) "
            f"recompiles {c1 - c0} compile_s {s1 - s0:.2f}")
        if self.verbose:
            print(self.last_line)


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            self.model.save(f"{self.save_dir}/{epoch}")


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.verbose = verbose
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.best = None
        self.wait = 0

    def _better(self, cur):
        if self.best is None:
            return True
        if self.mode == "min":
            return cur < self.best - self.min_delta
        return cur > self.best + self.min_delta

    def on_epoch_end(self, epoch, logs=None):
        # prefer the eval metric (same rationale as ReduceLROnPlateau:
        # the reference stops on eval, not the noisy last train batch)
        cur = (logs or {}).get(f"eval_{self.monitor}",
                               (logs or {}).get(self.monitor))
        if cur is None:
            return
        if self._better(cur):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True
                if self.verbose:
                    print(f"Early stopping at epoch {epoch + 1}")


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        return getattr(opt, "_lr_scheduler", None) if opt else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()


class ReduceLROnPlateau(Callback):
    """Reduce LR when a monitored metric stops improving (reference:
    hapi/callbacks.py ReduceLROnPlateau)."""

    def __init__(self, monitor="loss", factor=0.1, patience=10,
                 verbose=1, mode="auto", min_delta=1e-4, cooldown=0,
                 min_lr=0):
        super().__init__()
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = min_delta
        self.cooldown = cooldown
        self.min_lr = min_lr
        if mode == "auto":
            mode = "min" if "acc" not in monitor else "max"
        self.mode = mode
        self.best = None
        self.wait = 0
        self.cooldown_counter = 0

    def _better(self, cur):
        if self.best is None:
            return True
        if self.mode == "min":
            return cur < self.best - self.min_delta
        return cur > self.best + self.min_delta

    def on_epoch_end(self, epoch, logs=None):
        self._check(logs or {})

    def _check(self, logs):
        # eval metrics surface in epoch logs with an eval_ prefix;
        # prefer them (the reference monitors eval, not the last train
        # batch) and fall back to the raw key for train-only fits
        cur = logs.get("eval_" + self.monitor, logs.get(self.monitor))
        if cur is None:
            return
        cur = float(cur[0] if isinstance(cur, (list, tuple)) else cur)
        if self.cooldown_counter > 0:
            # in cooldown: no wait accumulation, no reductions
            self.cooldown_counter -= 1
            self.wait = 0
            if self._better(cur):
                self.best = cur
            return
        if self._better(cur):
            self.best = cur
            self.wait = 0
            return
        self.wait += 1
        if self.wait >= self.patience:
            opt = getattr(self.model, "_optimizer", None)
            changed = False
            if opt is not None and hasattr(opt, "_learning_rate") and \
                    not hasattr(opt._learning_rate, "step"):
                lr = opt.get_lr()
                new_lr = max(lr * self.factor, self.min_lr)
                if new_lr < lr:
                    opt._learning_rate = new_lr
                    changed = True
                    if self.verbose:
                        print(f"ReduceLROnPlateau: lr {lr:g} -> "
                              f"{new_lr:g}")
            if changed:
                self.cooldown_counter = self.cooldown
                self.wait = 0


class VisualDL(Callback):
    """VisualDL logging (reference: hapi/callbacks.py VisualDL). The
    visualdl package is not installed in this environment — constructing
    the callback raises the same ImportError the reference would."""

    def __init__(self, log_dir="vdl_log"):
        raise ImportError(
            "VisualDL is not installed; pip install visualdl to use this "
            "callback (scalar logs are also written by ProgBarLogger)")


class WandbCallback(Callback):
    """Weights & Biases logging (reference: hapi/callbacks.py
    WandbCallback); requires the external wandb package."""

    def __init__(self, *a, **kw):
        raise ImportError(
            "wandb is not installed; pip install wandb to use this "
            "callback")
