"""FLOPs counting for dygraph Layers (reference:
python/paddle/hapi/dynamic_flops.py:40 flops()).

Counts multiply-accumulates as 2 FLOPs = 1 MAC pair the same way the
reference does (it reports MACs-style totals per layer via per-type count
hooks), using forward-post hooks over one traced forward pass.
"""
from __future__ import annotations

import numpy as np

from .. import nn


def _count_linear(layer, inputs, output):
    x = inputs[0] if isinstance(inputs, (tuple, list)) else inputs
    in_f = int(x.shape[-1])
    out_n = int(np.prod(output.shape))
    return out_n * in_f


def _count_conv(layer, inputs, output):
    w = layer.weight
    kernel_ops = int(np.prod(w.shape[1:]))  # in_ch/groups * k
    out_n = int(np.prod(output.shape))
    return out_n * kernel_ops


def _count_norm(layer, inputs, output):
    return 2 * int(np.prod(output.shape))


def _count_act(layer, inputs, output):
    return int(np.prod(output.shape))


def _count_pool(layer, inputs, output):
    return int(np.prod(output.shape))


_COUNT_FNS = []


def _register_defaults():
    pairs = [
        ("Linear", _count_linear), ("Conv1D", _count_conv),
        ("Conv2D", _count_conv), ("Conv3D", _count_conv),
        ("Conv2DTranspose", _count_conv),
        ("BatchNorm", _count_norm), ("BatchNorm1D", _count_norm),
        ("BatchNorm2D", _count_norm), ("BatchNorm3D", _count_norm),
        ("LayerNorm", _count_norm), ("GroupNorm", _count_norm),
        ("ReLU", _count_act), ("ReLU6", _count_act), ("GELU", _count_act),
        ("Sigmoid", _count_act), ("Softmax", _count_act),
        ("AvgPool2D", _count_pool), ("MaxPool2D", _count_pool),
        ("AdaptiveAvgPool2D", _count_pool), ("AdaptiveMaxPool2D", _count_pool),
    ]
    for name, fn in pairs:
        cls = getattr(nn, name, None)
        if cls is not None:
            _COUNT_FNS.append((cls, fn))


_register_defaults()


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Count one forward pass' FLOPs for `net` on zeros of `input_size`.

    custom_ops: {LayerClass: fn(layer, inputs, output) -> flops} overrides.
    Returns the total as an int (reference hapi.dynamic_flops.flops).
    """
    from .. import zeros

    custom = list((custom_ops or {}).items())
    records = []
    handles = []

    def make_hook(layer, fn):
        def hook(lyr, inputs, output):
            out = output[0] if isinstance(output, (tuple, list)) else output
            n = int(fn(lyr, inputs, out))
            records.append((type(lyr).__name__, lyr.full_name()
                            if hasattr(lyr, "full_name") else "", n))
        return hook

    for lyr in net.sublayers(include_self=True):
        fn = None
        for cls, f in custom:
            if isinstance(lyr, cls):
                fn = f
                break
        if fn is None:
            for cls, f in _COUNT_FNS:
                if type(lyr) is cls:
                    fn = f
                    break
        if fn is not None:
            handles.append(lyr.register_forward_post_hook(make_hook(lyr, fn)))

    was_training = net.training
    net.eval()
    try:
        x = zeros(list(input_size), "float32")
        net(x)
    finally:
        for h in handles:
            h.remove()
        if was_training:
            net.train()

    total = sum(n for _, _, n in records)
    if print_detail:
        print(f"{'Layer':<24}{'FLOPs':>16}")
        for name, full, n in records:
            print(f"{name:<24}{n:>16,}")
        print(f"{'Total':<24}{total:>16,}")
    return total
