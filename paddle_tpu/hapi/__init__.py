"""paddle_tpu.hapi (reference: python/paddle/hapi)."""
from . import callbacks  # noqa: F401
from .callbacks import (  # noqa: F401
    Callback, EarlyStopping, LRScheduler, ModelCheckpoint, ProgBarLogger,
    TelemetryLogger,
)
from .model import Model  # noqa: F401
from .summary import summary  # noqa: F401
