"""Model summary (reference: python/paddle/hapi/model_summary.py)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


def summary(net, input_size=None, dtypes=None, input=None):
    """Print a per-layer table; returns {'total_params', 'trainable_params'}."""
    rows = []
    total, trainable = 0, 0
    for name, layer in net.named_sublayers(include_self=True):
        own = [p for p in layer._parameters.values() if p is not None]
        n = int(sum(np.prod(p.shape) if p.shape else 1 for p in own))
        t = int(sum(np.prod(p.shape) if p.shape else 1
                    for p in own if not p.stop_gradient))
        if n:
            rows.append((name or type(layer).__name__,
                         type(layer).__name__, n))
        total += n
        trainable += t
    width = max([len(r[0]) for r in rows], default=10) + 2
    print(f"{'Layer':<{width}}{'Type':<24}{'Params':>12}")
    print("-" * (width + 36))
    for name, tname, n in rows:
        print(f"{name:<{width}}{tname:<24}{n:>12,}")
    print("-" * (width + 36))
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    print(f"Non-trainable params: {total - trainable:,}")
    return {"total_params": total, "trainable_params": trainable}
