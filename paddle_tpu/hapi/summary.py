"""Model summary (reference: python/paddle/hapi/model_summary.py).

With ``input_size`` (or a concrete ``input``) the network runs ONE
forward pass with forward-post hooks on every sublayer, so the table
carries real per-layer OUTPUT SHAPES — including nested container
outputs (tuples/lists/dicts of tensors print every leaf shape),
matching the reference summary's behavior. Without an input the table
degrades to the params-only view.
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


def _leaf_shapes(out):
    """Collect the shapes of every Tensor leaf in a (possibly nested)
    layer output."""
    if isinstance(out, Tensor):
        return [list(out.shape)]
    if isinstance(out, (list, tuple)):
        shapes = []
        for o in out:
            shapes.extend(_leaf_shapes(o))
        return shapes
    if isinstance(out, dict):
        shapes = []
        for o in out.values():
            shapes.extend(_leaf_shapes(o))
        return shapes
    return []


def _fmt_shapes(shapes):
    if not shapes:
        return "-"
    return ", ".join(str(s) for s in shapes)


def _build_inputs(input_size, dtypes):
    """input_size: one shape or a list of shapes; -1/None dims become 1."""
    from .. import to_tensor
    if isinstance(input_size, (list, tuple)) and input_size and \
            isinstance(input_size[0], (list, tuple)):
        sizes = list(input_size)
    else:
        sizes = [input_size]
    if dtypes is None:
        dtypes = ["float32"] * len(sizes)
    elif isinstance(dtypes, str):
        dtypes = [dtypes] * len(sizes)
    ins = []
    for shape, dt in zip(sizes, dtypes):
        shape = [1 if (d is None or int(d) < 0) else int(d)
                 for d in shape]
        if "int" in str(dt):
            ins.append(to_tensor(np.zeros(shape, np.int64)))
        else:
            ins.append(to_tensor(np.zeros(shape, np.float32)))
    return ins


def summary(net, input_size=None, dtypes=None, input=None):
    """Print a per-layer table (output shape + params); returns
    {'total_params', 'trainable_params'}."""
    out_shapes = {}
    if input is not None or input_size is not None:
        ins = ([input] if isinstance(input, Tensor) else list(input)) \
            if input is not None else _build_inputs(input_size, dtypes)
        hooks = []
        for _, layer in net.named_sublayers(include_self=True):
            def mk(lyr):
                def hook(l, inputs, outputs):
                    out_shapes[id(lyr)] = _leaf_shapes(outputs)
                    return None   # observe only — never replace outputs
                return hook
            hooks.append(layer.register_forward_post_hook(mk(layer)))
        # save PER-LAYER training flags: a blanket net.train() on
        # restore would un-freeze deliberately eval()'d sublayers
        modes = [(lyr, lyr.training)
                 for _, lyr in net.named_sublayers(include_self=True)]
        try:
            net.eval()
            from ..core import tape as tape_mod
            with tape_mod.no_grad_guard():
                net(*ins)
        finally:
            for lyr, was in modes:
                lyr.training = was
            for h in hooks:
                h.remove()

    rows = []
    total, trainable = 0, 0
    for name, layer in net.named_sublayers(include_self=True):
        own = [p for p in layer._parameters.values() if p is not None]
        n = int(sum(np.prod(p.shape) if p.shape else 1 for p in own))
        t = int(sum(np.prod(p.shape) if p.shape else 1
                    for p in own if not p.stop_gradient))
        shp = _fmt_shapes(out_shapes.get(id(layer), []))
        if n or id(layer) in out_shapes:
            rows.append((name or type(layer).__name__,
                         type(layer).__name__, shp, n))
        total += n
        trainable += t
    width = max([len(r[0]) for r in rows], default=10) + 2
    swidth = max([len(r[2]) for r in rows], default=12) + 2
    print(f"{'Layer':<{width}}{'Type':<24}"
          f"{'Output Shape':<{swidth}}{'Params':>12}")
    print("-" * (width + swidth + 36))
    for name, tname, shp, n in rows:
        print(f"{name:<{width}}{tname:<24}{shp:<{swidth}}{n:>12,}")
    print("-" * (width + swidth + 36))
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    print(f"Non-trainable params: {total - trainable:,}")
    return {"total_params": total, "trainable_params": trainable}
