"""hapi — paddle.Model high-level training API.

Reference: python/paddle/hapi/model.py:1472 (Model), fit :2200. The
reference picks between a DynamicGraphAdapter and a StaticGraphAdapter; on
TPU there is one adapter: the compiled TrainStep (paddle_tpu.jit), with an
eager fallback when the model/loss isn't jit-traceable.
"""
from __future__ import annotations

import time
from typing import List, Optional

import numpy as np

from ..core.tensor import Tensor
from ..io import DataLoader
from ..jit.api import TrainStep
from ..metric import Metric
from .callbacks import CallbackList, ProgBarLogger


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._loss = None
        self._optimizer = None
        self._metrics: List[Metric] = []
        self._train_step: Optional[TrainStep] = None
        self._compiled_mode = True
        self.stop_training = False

    # -- setup ---------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is not None:
            metrics = metrics if isinstance(metrics, (list, tuple)) \
                else [metrics]
            for m in metrics:
                if not isinstance(m, Metric):
                    raise TypeError(
                        f"metric should be an instance of paddle.metric."
                        f"Metric, got {type(m).__name__} (reference "
                        "hapi/model.py prepare has the same check)")
            self._metrics = list(metrics)
        amp_dtype = None
        if amp_configs:
            level = amp_configs.get("level", "O0") \
                if isinstance(amp_configs, dict) else str(amp_configs)
            if level in ("O1", "O2"):
                import jax.numpy as jnp
                amp_dtype = jnp.bfloat16
        if optimizer is not None and loss is not None:
            try:
                self._train_step = TrainStep(self.network, loss,
                                             optimizer, amp_dtype=amp_dtype)
            except Exception:
                self._train_step = None

    def inspect(self, inputs=None, labels=None, mesh=None):
        """Static lint of the model's compiled program (paddle_tpu.
        analysis): AST trace-safety pass over forward plus jaxpr rule
        passes over an abstract trace — nothing runs on device.

        Shapes come from `inputs`/`labels` (InputSpecs, Tensors, or
        arrays), defaulting to the specs given at construction. After
        prepare(), the *fused train step* (forward + loss + grad +
        update) is linted; before, just the forward. `mesh` (a Mesh,
        AbstractMesh, or {axis: degree} dict — still device-free)
        additionally runs the shard_lint SPMD/collective rules and
        attaches a static cost estimate. Returns an analysis.Report."""
        inputs = inputs if inputs is not None else self._inputs
        labels = labels if labels is not None else self._labels
        if isinstance(labels, (list, tuple)) and len(labels) == 1:
            labels = labels[0]  # fit() feeds the loss one label tensor
        if (self._train_step is not None and inputs is not None
                and labels is not None):
            return self._train_step.inspect(inputs, labels, mesh=mesh)
        from ..jit.api import StaticFunction
        return StaticFunction(self.network,
                              input_spec=inputs).inspect(mesh=mesh)

    # -- single-batch APIs ---------------------------------------------------
    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        if self._train_step is not None:
            loss = self._train_step(tuple(inputs), labels)
            return [float(loss.numpy())]
        out = self.network(*inputs)
        loss = self._loss(out, labels)
        loss.backward()
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
        return [float(loss.numpy())]

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        if self._train_step is not None:
            self._train_step.sync_to_model()
        out = self.network(*inputs)
        metrics_out = []
        if self._loss is not None and labels is not None:
            loss = self._loss(out, labels)
            metrics_out.append(float(loss.numpy()))
        self._update_metrics(out, labels)
        return metrics_out

    def predict_batch(self, inputs):
        self.network.eval()
        if self._train_step is not None:
            self._train_step.sync_to_model()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        out = self.network(*inputs)
        return out

    # -- loops ---------------------------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1,
            verbose=2, drop_last=False, shuffle=True, num_workers=0,
            callbacks=None, accumulate_grad_batches=1, num_iters=None):
        train_loader = self._to_loader(train_data, batch_size, shuffle,
                                       drop_last, num_workers)
        eval_loader = self._to_loader(eval_data, batch_size, False, False,
                                      num_workers) if eval_data is not None \
            else None
        cbks = CallbackList(callbacks, model=self, verbose=verbose,
                            epochs=epochs,
                            steps=self._safe_len(train_loader),
                            metrics=self._metric_names())
        cbks.on_begin("train")
        iters_done = 0
        for epoch in range(epochs):
            if self.stop_training:
                break
            cbks.on_epoch_begin(epoch)
            self.network.train()
            logs = {}
            for step, batch in enumerate(train_loader):
                cbks.on_batch_begin("train", step, logs)
                ins, labs = self._split_batch(batch)
                losses = self.train_batch(ins, labs)
                logs = {"loss": losses[0], "step": step}
                if self._lr_scheduler() is not None:
                    self._lr_scheduler().step()
                cbks.on_batch_end("train", step, logs)
                iters_done += 1
                if num_iters is not None and iters_done >= num_iters:
                    break
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(eval_loader, verbose=0,
                                          _inner=True)
                logs.update({f"eval_{k}": v for k, v in eval_logs.items()})
            cbks.on_epoch_end(epoch, logs)
            if save_dir and (epoch + 1) % save_freq == 0:
                self.save(f"{save_dir}/{epoch}")
        cbks.on_end("train", logs)
        if self._train_step is not None:
            self._train_step.sync_to_model()
        return self

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None,
                 _inner=False):
        loader = self._to_loader(eval_data, batch_size, False, False,
                                 num_workers)
        for m in self._metrics:
            m.reset()
        losses = []
        self.network.eval()
        if self._train_step is not None:
            self._train_step.sync_to_model()
        for batch in loader:
            ins, labs = self._split_batch(batch)
            out = self.network(*(ins if isinstance(ins, (list, tuple))
                                 else [ins]))
            if self._loss is not None and labs is not None:
                losses.append(float(self._loss(out, labs).numpy()))
            self._update_metrics(out, labs)
        logs = {}
        if losses:
            logs["loss"] = float(np.mean(losses))
        for m in self._metrics:
            names = m.name() if isinstance(m.name(), list) else [m.name()]
            vals = m.accumulate()
            vals = vals if isinstance(vals, (list, tuple)) else [vals]
            for n, v in zip(names, vals):
                logs[n] = v
        if verbose and not _inner:
            print("Eval:", " - ".join(f"{k}: {v:.4f}"
                                      for k, v in logs.items()))
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, callbacks=None, verbose=1):
        loader = self._to_loader(test_data, batch_size, False, False,
                                 num_workers)
        outputs = []
        self.network.eval()
        if self._train_step is not None:
            self._train_step.sync_to_model()
        for batch in loader:
            ins, _ = self._split_batch(batch)
            out = self.predict_batch(ins)
            outputs.append(out)
        if stack_outputs and outputs:
            import jax.numpy as jnp
            flat = [o.numpy() if isinstance(o, Tensor) else o
                    for o in outputs]
            return [np.concatenate(flat, axis=0)]
        return outputs

    # -- persistence ---------------------------------------------------------
    def save(self, path, training=True):
        from ..framework.io import save as fsave
        if self._train_step is not None:
            self._train_step.sync_to_model()
        fsave(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            fsave(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io import load as fload
        sd = fload(path + ".pdparams")
        self.network.set_state_dict(sd)
        if not reset_optimizer and self._optimizer is not None:
            try:
                osd = fload(path + ".pdopt")
                self._optimizer.set_state_dict(osd)
            except FileNotFoundError:
                pass
        if self._train_step is not None:
            self._train_step.sync_from_model()
        return self

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        from .summary import summary as _summary
        return _summary(self.network, input_size, dtypes=dtype)

    # -- helpers -------------------------------------------------------------
    def _update_metrics(self, out, labels):
        """Multi-output metric feeding (reference hapi/model.py: each
        network output and each label is a SEPARATE positional arg to
        Metric.compute — a multi-output model's metric sees
        compute(out0, out1, ..., label0, ...))."""
        outs = list(out) if isinstance(out, (list, tuple)) else [out]
        labs = (list(labels) if isinstance(labels, (list, tuple))
                else ([] if labels is None else [labels]))
        for m in self._metrics:
            c = m.compute(*outs, *labs)
            m.update(c)

    def _lr_scheduler(self):
        if self._optimizer is None:
            return None
        return getattr(self._optimizer, "_lr_scheduler", None)

    def _metric_names(self):
        names = ["loss"]
        for m in self._metrics:
            n = m.name()
            names.extend(n if isinstance(n, list) else [n])
        return names

    @staticmethod
    def _safe_len(loader):
        try:
            return len(loader)
        except TypeError:
            return None

    @staticmethod
    def _to_loader(data, batch_size, shuffle, drop_last, num_workers):
        if data is None or isinstance(data, DataLoader):
            return data
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                          drop_last=drop_last, num_workers=num_workers)

    @staticmethod
    def _split_batch(batch):
        if isinstance(batch, (list, tuple)):
            if len(batch) == 1:
                return [batch[0]], None
            ins = batch[:-1]
            return list(ins), batch[-1]
        return [batch], None
