"""paddle.device.xpu module compat — same shims as device.cuda
(reference: python/paddle/device/xpu)."""
from .cuda import *  # noqa: F401,F403
from .cuda import device_count, empty_cache  # noqa: F401


def synchronize(device=None):
    import jax
    (jax.device_put(0) + 0).block_until_ready()
