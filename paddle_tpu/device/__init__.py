"""Device API (reference: python/paddle/device/__init__.py:281 set_device).

TPU is the accelerator; `paddle.device.cuda.*` compat shims map to it so
reference-shaped scripts run unchanged.
"""
from __future__ import annotations

import jax

from ..core.place import (  # noqa: F401
    CPUPlace, CUDAPlace, Place, TPUPlace, XPUPlace, get_device,
    is_compiled_with_cuda, is_compiled_with_tpu, is_compiled_with_xpu,
    set_device,
)


def get_all_device_type():
    return sorted({d.platform for d in jax.devices()})


def get_all_custom_device_type():
    return [t for t in get_all_device_type() if t not in ("cpu",)]


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return [d for d in get_available_device() if not d.startswith("cpu")]


def device_count():
    return len([d for d in jax.devices()
                if d.platform in ("tpu", "axon")]) or 1


class Stream:
    """Compat shim: XLA streams are managed by the runtime; operations on a
    Stream are ordering no-ops (execution is already well-ordered per device)."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        _sync()

    def wait_event(self, event):
        pass

    def wait_stream(self, stream):
        pass

    def record_event(self, event=None):
        return event or Event()

    def query(self):
        return True


class Event:
    def __init__(self, enable_timing=False, blocking=False, interprocess=False):
        pass

    def record(self, stream=None):
        pass

    def query(self):
        return True

    def synchronize(self):
        _sync()

    def elapsed_time(self, end_event):
        return 0.0


def _sync():
    (jax.device_put(0) + 0).block_until_ready()


def synchronize(device=None):
    _sync()


def current_stream(device=None):
    return Stream(device)


def set_stream(stream):
    return stream


class _CudaNamespace:
    Stream = Stream
    Event = Event

    @staticmethod
    def synchronize(device=None):
        _sync()

    @staticmethod
    def device_count():
        return device_count()

    @staticmethod
    def is_available():
        return is_compiled_with_tpu()

    @staticmethod
    def current_stream(device=None):
        return Stream(device)

    @staticmethod
    def max_memory_allocated(device=None):
        from .monitor import max_memory_allocated as f
        return f(device)

    @staticmethod
    def max_memory_reserved(device=None):
        from .monitor import max_memory_reserved as f
        return f(device)

    @staticmethod
    def memory_allocated(device=None):
        from .monitor import memory_allocated as f
        return f(device)

    @staticmethod
    def memory_reserved(device=None):
        from .monitor import memory_reserved as f
        return f(device)

    @staticmethod
    def empty_cache():
        pass

    @staticmethod
    def get_device_properties(device=None):
        d = jax.devices()[0]
        class Props:
            name = getattr(d, "device_kind", "TPU")
            total_memory = _memory_stat("bytes_limit") or (16 << 30)
            major, minor = 0, 0
            multi_processor_count = 1
        return Props()

    @staticmethod
    def get_device_name(device=None):
        return getattr(jax.devices()[0], "device_kind", "TPU")

    @staticmethod
    def get_device_capability(device=None):
        return (0, 0)


def _memory_stat(key):
    from .monitor import _device_stats
    return int(_device_stats(0).get(key, 0))


cuda = _CudaNamespace()
xpu = cuda
from . import monitor  # noqa: F401
from .monitor import (max_memory_allocated, max_memory_reserved,  # noqa: F401
                      memory_allocated, memory_reserved)


def get_cudnn_version():
    """CUDA compat (reference: device.get_cudnn_version): no cuDNN in the
    XLA:TPU stack — None, like a CPU-only reference build."""
    return None


def is_compiled_with_rocm():
    return False


def is_compiled_with_ipu():
    return False


def is_compiled_with_cinn():
    """The graph compiler here is XLA, not CINN."""
    return False


def is_compiled_with_custom_device(device_name=None):
    """PJRT plugins are the custom-device mechanism; the axon TPU platform
    itself loads through one."""
    import jax
    try:
        platforms = {d.platform for d in jax.devices()}
    except Exception:
        return False
    return device_name in platforms if device_name else bool(platforms)


def is_compiled_with_distribute():
    """Distributed is always built in (jax.distributed + mesh)."""
    return True


class IPUPlace:
    def __init__(self, *a):
        raise RuntimeError("IPU is not a PJRT backend in this build")


import contextlib as _contextlib


@_contextlib.contextmanager
def stream_guard(stream=None):
    """Streams are XLA-managed; kept as a no-op scope (reference:
    device.stream_guard)."""
    yield
