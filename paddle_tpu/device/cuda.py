"""paddle.device.cuda as an importable module (reference:
python/paddle/device/cuda): the compat shims map onto the TPU device.

NOTE: importing this module rebinds the paddle.device.cuda attribute
from the namespace object to the module, so everything the namespace
exposed must be re-exported here."""
from . import Event, Stream  # noqa: F401
from . import _CudaNamespace as _NS
from .monitor import (  # noqa: F401
    max_memory_allocated, max_memory_reserved, memory_allocated,
    memory_reserved,
)

from . import _sync as _sync_impl

_ns = _NS()
is_available = _ns.is_available


def synchronize(device=None):
    _sync_impl()


device_count = _ns.device_count
empty_cache = _ns.empty_cache
get_device_properties = _ns.get_device_properties
get_device_name = _ns.get_device_name
get_device_capability = _ns.get_device_capability


def current_stream(device=None):
    """Streams are XLA-managed; a token object for API compat."""
    from . import Stream
    return Stream()


def stream_guard(stream):
    from . import stream_guard as _sg
    return _sg(stream)
