"""Memory stats + named monitors.

Reference: paddle/phi/core/memory/stats.h:140 (peak/current memory
stats exposed as paddle.device.cuda.max_memory_allocated etc.) and
paddle/phi/core/platform/monitor.h (named int64 monitors). Device memory
is XLA-managed on TPU — read through jax's per-device memory_stats;
host RSS/peak and counters come from the native module
(paddle_tpu/csrc/monitor.cpp).
"""
from __future__ import annotations

import ctypes
from typing import Optional

from .. import csrc


def _device_stats(device_id: int = 0) -> dict:
    """THE device memory-stats reader (device/__init__._memory_stat and
    the cuda namespace delegate here — one key mapping, one behavior)."""
    import jax
    try:
        devs = jax.devices()
        if device_id >= len(devs):
            return {}
        return devs[device_id].memory_stats() or {}
    except Exception:
        return {}


def memory_allocated(device=None) -> int:
    """Current device bytes in use (reference
    paddle.device.cuda.memory_allocated)."""
    return int(_device_stats(_id(device)).get("bytes_in_use", 0))


def max_memory_allocated(device=None) -> int:
    return int(_device_stats(_id(device)).get("peak_bytes_in_use", 0))


def memory_reserved(device=None) -> int:
    s = _device_stats(_id(device))
    return int(s.get("bytes_reserved", s.get("bytes_in_use", 0)))


def max_memory_reserved(device=None) -> int:
    s = _device_stats(_id(device))
    return int(s.get("peak_bytes_reserved",
                     s.get("peak_bytes_in_use", 0)))


def _id(device) -> int:
    if device is None:
        return 0
    if isinstance(device, int):
        return device
    s = str(device)
    return int(s.split(":")[-1]) if ":" in s else 0


def host_memory_rss() -> int:
    """Current host RSS bytes (native /proc reader; -1 if unavailable)."""
    lb = csrc.lib()
    return int(lb.host_memory_rss_bytes()) if lb else -1


def host_memory_peak() -> int:
    lb = csrc.lib()
    return int(lb.host_memory_peak_bytes()) if lb else -1


def monitor_add(name: str, value: int) -> None:
    """Record a sample on the named monitor (reference monitor.h)."""
    lb = csrc.lib()
    if lb:
        lb.monitor_add(name.encode(), int(value))


def monitor_get(name: str) -> Optional[dict]:
    lb = csrc.lib()
    if not lb:
        return None
    out = (ctypes.c_int64 * 4)()
    if lb.monitor_get(name.encode(), out) != 0:
        return None
    return {"sum": out[0], "count": out[1], "min": out[2], "max": out[3]}


def monitor_reset(name: str) -> None:
    lb = csrc.lib()
    if lb:
        lb.monitor_reset(name.encode())
