"""AST trace-safety linter — the dy2static analog as a diagnostic pass.

The reference stack *rewrites* un-stageable Python (dy2static AST
transforms turn `if tensor:` into cond ops, PIR passes reject the
rest). On TPU jax.jit traces by execution, so there is nothing to
rewrite — but the same constructs still break the trace, at runtime,
after a compile has already been paid for. This pass finds them ahead
of time by walking `forward` / `to_static` bodies and tracking which
names hold traced values.

Value inference is a three-level lattice, deliberately conservative —
the shipped model zoo must lint clean:

* ``STATIC`` (0) — host-side Python: config knobs, ``.shape``-derived
  ints, ``len()``, identity checks (``x is None``);
* ``TENSOR`` (1) — a traced value: branching on it / concretizing it
  breaks the trace;
* ``CONTAINER`` (2) — a Python tuple/list/dict *of* tensors
  (``*args``, spec lists): truth-testing it is a static length check
  (safe), but indexing yields a TENSOR.

Function parameters are tensor-likely, EXCEPT ``self``/``cls`` and
parameters whose default is a bool/int/float/str literal; ``*args``
and ``**kwargs`` seed as containers. Operations/calls involving a
tensor produce tensors; everything else is host-side Python.

Stdlib-only on purpose: tools/paddle_lint.py loads this module without
paddle_tpu or jax installed.
"""
from __future__ import annotations

import ast
import inspect as _inspect
import os
import textwrap
from typing import Dict, List, Optional, Set

try:
    from .findings import (ERROR, HOST_RNG, TENSOR_BOOL_BRANCH,
                           TENSOR_HOST_SYNC, TENSOR_INPLACE, TENSOR_PY_CAST,
                           WARNING, Finding)
except ImportError:  # loaded file-directly by tools/paddle_lint.py
    from findings import (ERROR, HOST_RNG, TENSOR_BOOL_BRANCH,  # type: ignore
                          TENSOR_HOST_SYNC, TENSOR_INPLACE, TENSOR_PY_CAST,
                          WARNING, Finding)

STATIC, TENSOR, CONTAINER = 0, 1, 2

# attributes/methods of a Tensor that are host-side Python values even
# under a trace (shapes are static in XLA): branching on these is safe
_STATIC_ATTRS = {"shape", "ndim", "dtype", "place", "name", "size",
                 "stop_gradient", "is_leaf", "persistable"}
_STATIC_METHODS = {"dim", "ndimension", "numel", "element_size"}

# host-sync methods: concretize a tracer -> _BREAK_ERRORS at trace time
_HOST_SYNC_METHODS = {"numpy": "TracerArrayConversionError",
                      "item": "TracerArrayConversionError",
                      "tolist": "TracerArrayConversionError"}

_PY_CASTS = {"bool": "TracerBoolConversionError",
             "int": "TracerIntegerConversionError",
             "float": "ConcretizationTypeError"}

# builtins whose result is host-side regardless of tensor arguments
_STATIC_BUILTINS = {"len", "isinstance", "issubclass", "hasattr", "getattr",
                    "setattr", "print", "repr", "str", "id", "type",
                    "callable", "format"}

# module roots whose calls are host-side effects baked into the trace
# as constants (same value on every compiled-step execution)
_HOST_RNG_ROOTS = ("time.", "random.", "np.random.", "numpy.random.")


def _dotted(node: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_static_default(node: Optional[ast.AST]) -> bool:
    return (isinstance(node, ast.Constant)
            and isinstance(node.value, (bool, int, float, str)))


class _FunctionLinter:
    """Lints one function body, tracking value levels per name."""

    def __init__(self, fn: ast.FunctionDef, filename: str,
                 line_offset: int = 0):
        self.fn = fn
        self.filename = filename
        self.line_offset = line_offset
        self.findings: List[Finding] = []
        self.level: Dict[str, int] = {}
        self.params: Set[str] = set()
        self.declared: Set[str] = set()
        self._seed_params()

    def _seed_params(self):
        a = self.fn.args
        positional = list(a.posonlyargs) + list(a.args)
        defaults = list(a.defaults)
        # right-align defaults against positional params
        pad = [None] * (len(positional) - len(defaults))
        for arg, default in zip(positional, pad + defaults):
            self.declared.add(arg.arg)
            if arg.arg in ("self", "cls"):
                continue
            if _is_static_default(default):
                continue  # training=False / axis=1 style config knob
            self.level[arg.arg] = TENSOR
            self.params.add(arg.arg)
        for arg, default in zip(a.kwonlyargs, a.kw_defaults):
            self.declared.add(arg.arg)
            if not _is_static_default(default):
                self.level[arg.arg] = TENSOR
                self.params.add(arg.arg)
        # *args / **kwargs: Python containers whose ELEMENTS are
        # tensor-likely — `if args:` is a static length check, args[0]
        # is a tensor
        if a.vararg is not None:
            self.declared.add(a.vararg.arg)
            self.level[a.vararg.arg] = CONTAINER
        if a.kwarg is not None:
            self.declared.add(a.kwarg.arg)
            self.level[a.kwarg.arg] = CONTAINER

    def _inherit(self, outer: "_FunctionLinter"):
        """Layer the enclosing scope's knowledge under this function's
        own parameters (nested trace helpers see enclosing locals)."""
        for name, lvl in outer.level.items():
            if name not in self.declared:
                self.level.setdefault(name, lvl)
        self.params |= outer.params

    # -- reporting -----------------------------------------------------------
    def _flag(self, rule, severity, node, message, breaks_with="",
              suggestion=""):
        self.findings.append(Finding(
            rule=rule, severity=severity, message=message,
            file=self.filename,
            line=getattr(node, "lineno", 0) + self.line_offset,
            breaks_with=breaks_with, suggestion=suggestion))

    # -- statements ----------------------------------------------------------
    def run(self) -> List[Finding]:
        self.block(self.fn.body)
        return self.findings

    def block(self, stmts):
        for s in stmts:
            self.stmt(s)

    def stmt(self, s):
        if isinstance(s, ast.Assign):
            t = self.expr(s.value)
            for target in s.targets:
                self.bind(target, t)
        elif isinstance(s, ast.AugAssign):
            t = self.expr(s.value)
            if isinstance(s.target, ast.Name):
                t = max(t, self.level.get(s.target.id, STATIC))
            self.bind(s.target, t)
        elif isinstance(s, ast.AnnAssign):
            t = self.expr(s.value) if s.value is not None else STATIC
            self.bind(s.target, t)
        elif isinstance(s, ast.If):
            if self.expr(s.test) == TENSOR:
                self._flag(
                    TENSOR_BOOL_BRANCH, ERROR, s.test,
                    "`if` on a tensor value forces a host sync",
                    breaks_with="TracerBoolConversionError",
                    suggestion="use paddle.static.nn.cond (lax.cond) to "
                               "keep the branch compiled")
            self.block(s.body)
            self.block(s.orelse)
        elif isinstance(s, ast.While):
            if self.expr(s.test) == TENSOR:
                self._flag(
                    TENSOR_BOOL_BRANCH, ERROR, s.test,
                    "`while` on a tensor value forces a host sync per "
                    "iteration",
                    breaks_with="TracerBoolConversionError",
                    suggestion="use paddle.static.nn.while_loop "
                               "(lax.while_loop) to keep the loop compiled")
            self.block(s.body)
            self.block(s.orelse)
        elif isinstance(s, ast.For):
            t = self.expr(s.iter)
            self.bind(s.target, TENSOR if t else STATIC,
                      flag_inplace=False)
            self.block(s.body)
            self.block(s.orelse)
        elif isinstance(s, ast.Assert):
            if self.expr(s.test) == TENSOR:
                self._flag(
                    TENSOR_BOOL_BRANCH, ERROR, s.test,
                    "`assert` on a tensor value forces a host sync",
                    breaks_with="TracerBoolConversionError",
                    suggestion="assert on .shape/.dtype (static), or move "
                               "value checks out of the traced body")
            if s.msg is not None:
                self.expr(s.msg)
        elif isinstance(s, ast.Return):
            if s.value is not None:
                self.expr(s.value)
        elif isinstance(s, ast.Expr):
            self.expr(s.value)
        elif isinstance(s, ast.With):
            for item in s.items:
                self.expr(item.context_expr)
                if item.optional_vars is not None:
                    self.bind(item.optional_vars, STATIC)
            self.block(s.body)
        elif isinstance(s, ast.Try):
            self.block(s.body)
            for h in s.handlers:
                self.block(h.body)
            self.block(s.orelse)
            self.block(s.finalbody)
        elif isinstance(s, ast.FunctionDef):
            # nested helper: its parameters receive values from the
            # traced enclosing body, so seed them tensor-likely (same
            # default-value rule) layered over the enclosing scope
            sub = _FunctionLinter(s, self.filename, self.line_offset)
            sub._inherit(self)
            self.findings.extend(sub.run())
        elif isinstance(s, (ast.Raise, ast.Delete)):
            for child in ast.iter_child_nodes(s):
                if isinstance(child, ast.expr):
                    self.expr(child)
        # Pass/Break/Continue/Import/Global/Nonlocal: nothing to track

    # -- binding -------------------------------------------------------------
    def bind(self, target, level: int, flag_inplace: bool = True):
        if isinstance(target, ast.Name):
            if level:
                self.level[target.id] = level
            else:
                self.level.pop(target.id, None)
                self.params.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            # unpacking a container yields its (tensor) elements
            elt = TENSOR if level else STATIC
            for e in target.elts:
                self.bind(e, elt, flag_inplace)
        elif isinstance(target, ast.Starred):
            self.bind(target.value,
                      CONTAINER if level else STATIC, flag_inplace)
        elif isinstance(target, ast.Subscript):
            base = target.value
            if (flag_inplace and isinstance(base, ast.Name)
                    and base.id in self.params):
                self._flag(
                    TENSOR_INPLACE, WARNING, target,
                    f"in-place subscript store into argument "
                    f"'{base.id}'",
                    suggestion="functional update (paddle.scatter / "
                               "jnp .at[].set) — mutating a traced "
                               "argument leaks tracers or bakes stale "
                               "values")
            self.expr(base)
        # Attribute target (self.x = ...): host-side state, skip

    # -- expressions ---------------------------------------------------------
    def expr(self, e) -> int:
        """Evaluate the value level of `e`, flagging hazards on the
        way. Always walks every subexpression (no short-circuit) so
        nested defects are reported."""
        if e is None:
            return STATIC
        if isinstance(e, ast.Name):
            return self.level.get(e.id, STATIC)
        if isinstance(e, ast.Constant):
            return STATIC
        if isinstance(e, ast.Attribute):
            base = self.expr(e.value)
            if e.attr in _STATIC_ATTRS:
                return STATIC
            return TENSOR if base == TENSOR else STATIC
        if isinstance(e, ast.Call):
            return self._call(e)
        if isinstance(e, ast.BinOp):
            return max(self.expr(e.left), self.expr(e.right))
        if isinstance(e, ast.UnaryOp):
            return self.expr(e.operand)
        if isinstance(e, ast.BoolOp):
            levels = [self.expr(v) for v in e.values]
            # every operand of and/or is truth-tested: a TENSOR operand
            # is the hazard even if another operand is a container
            if TENSOR in levels:
                return TENSOR
            return max(levels, default=STATIC)
        if isinstance(e, ast.Compare):
            parts = [self.expr(e.left)] + [self.expr(c)
                                           for c in e.comparators]
            identity_only = all(isinstance(op, (ast.Is, ast.IsNot, ast.In,
                                                ast.NotIn))
                                for op in e.ops)
            if TENSOR in parts and not identity_only:
                return TENSOR
            return STATIC
        if isinstance(e, ast.Subscript):
            base = self.expr(e.value)
            self.expr(e.slice)
            if base == CONTAINER:
                # slicing a container keeps it a container; indexing
                # yields an element (tensor)
                return CONTAINER if isinstance(e.slice, ast.Slice) \
                    else TENSOR
            return base
        if isinstance(e, ast.IfExp):
            if self.expr(e.test) == TENSOR:
                self._flag(
                    TENSOR_BOOL_BRANCH, ERROR, e.test,
                    "conditional expression on a tensor value forces a "
                    "host sync",
                    breaks_with="TracerBoolConversionError",
                    suggestion="use paddle.where / static.nn.cond")
            return max(self.expr(e.body), self.expr(e.orelse))
        if isinstance(e, (ast.Tuple, ast.List, ast.Set)):
            levels = [self.expr(x) for x in e.elts]
            return CONTAINER if any(levels) else STATIC
        if isinstance(e, ast.Dict):
            for k in e.keys:
                if k is not None:
                    self.expr(k)
            return CONTAINER if any([self.expr(v) for v in e.values]) \
                else STATIC
        if isinstance(e, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            for gen in e.generators:
                t = self.expr(gen.iter)
                self.bind(gen.target, TENSOR if t else STATIC,
                          flag_inplace=False)
                for cond in gen.ifs:
                    self.expr(cond)
            return CONTAINER if self.expr(e.elt) else STATIC
        if isinstance(e, ast.DictComp):
            for gen in e.generators:
                t = self.expr(gen.iter)
                self.bind(gen.target, TENSOR if t else STATIC,
                          flag_inplace=False)
            self.expr(e.key)
            return CONTAINER if self.expr(e.value) else STATIC
        if isinstance(e, ast.JoinedStr):
            for v in e.values:
                if isinstance(v, ast.FormattedValue):
                    self.expr(v.value)
            return STATIC
        if isinstance(e, ast.Starred):
            return self.expr(e.value)
        if isinstance(e, ast.Lambda):
            return STATIC
        if isinstance(e, ast.Slice):
            for part in (e.lower, e.upper, e.step):
                if part is not None:
                    self.expr(part)
            return STATIC
        if isinstance(e, (ast.Await, ast.NamedExpr)):
            inner = self.expr(e.value)
            if isinstance(e, ast.NamedExpr):
                self.bind(e.target, inner)
            return inner
        return STATIC

    def _call(self, e: ast.Call) -> int:
        arg_levels = [self.expr(a) for a in e.args]
        arg_levels += [self.expr(kw.value) for kw in e.keywords]
        any_tensorish = any(arg_levels)
        f = e.func
        if isinstance(f, ast.Name):
            name = f.id
            if name in _PY_CASTS and TENSOR in arg_levels:
                self._flag(
                    TENSOR_PY_CAST, ERROR, e,
                    f"{name}() on a tensor value forces a host sync",
                    breaks_with=_PY_CASTS[name],
                    suggestion="keep the value a tensor (.astype for "
                               "dtype changes); convert outside the "
                               "traced body")
                return STATIC
            if name == "range" and TENSOR in arg_levels:
                self._flag(
                    TENSOR_PY_CAST, ERROR, e,
                    "range() over a tensor value forces a host sync",
                    breaks_with="TracerIntegerConversionError",
                    suggestion="loop bounds must be Python ints under a "
                               "trace; use lax.fori_loop/scan for traced "
                               "bounds")
                return STATIC
            if name in _STATIC_BUILTINS:
                return STATIC
            return TENSOR if any_tensorish else STATIC
        if isinstance(f, ast.Attribute):
            base = self.expr(f.value)
            if base == TENSOR and f.attr in _HOST_SYNC_METHODS:
                self._flag(
                    TENSOR_HOST_SYNC, ERROR, e,
                    f".{f.attr}() on a tensor inside a traced body",
                    breaks_with=_HOST_SYNC_METHODS[f.attr],
                    suggestion="stay in tensor ops, or mark the function "
                               "not_to_static and accept eager execution")
                return STATIC
            if base == TENSOR and f.attr in _STATIC_METHODS:
                return STATIC
            if base == TENSOR and (
                    f.attr == "set_value"
                    or (f.attr.endswith("_")
                        and not f.attr.startswith("_"))):
                # trailing-underscore = the framework's in-place family
                # (fill_/zero_/add_/cast_/..., plus set_value/copy_)
                self._flag(
                    TENSOR_INPLACE, WARNING, e,
                    f"in-place .{f.attr}() on a traced value",
                    suggestion="use the out-of-place variant; in-place "
                               "mutation of values captured from outside "
                               "the trace leaks tracers "
                               "(UnexpectedTracerError)")
                return TENSOR
            dotted = _dotted(f)
            if dotted and any(dotted.startswith(root)
                              for root in _HOST_RNG_ROOTS):
                self._flag(
                    HOST_RNG, WARNING, e,
                    f"host-side call {dotted}() is evaluated ONCE at "
                    f"trace time and baked into the executable",
                    suggestion="use paddle.rand/randn (traced RNG) or "
                               "pass the value as an input")
                return STATIC
            if base == TENSOR or any_tensorish:
                return TENSOR
            return STATIC
        self.expr(f)
        return TENSOR if any_tensorish else STATIC


def _is_to_static_decorator(dec: ast.AST) -> bool:
    if isinstance(dec, ast.Call):
        dec = dec.func
    name = _dotted(dec) if not isinstance(dec, ast.Name) else dec.id
    return bool(name) and name.split(".")[-1] == "to_static"


def lint_source(src: str, filename: str = "<string>",
                line_offset: int = 0,
                all_functions: bool = False) -> List[Finding]:
    """Lint every `forward` method and `to_static`-decorated function in
    `src`. With all_functions=True, lint every function (used when the
    caller knows the code runs under a trace, e.g. inspect())."""
    try:
        tree = ast.parse(textwrap.dedent(src))
    except SyntaxError as exc:
        return [Finding(rule="syntax-error", severity=ERROR,
                        message=str(exc), file=filename,
                        line=exc.lineno or 0)]
    findings: List[Finding] = []
    linted: Set[int] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.FunctionDef) or id(node) in linted:
            continue
        if not (all_functions or node.name == "forward"
                or any(_is_to_static_decorator(d)
                       for d in node.decorator_list)):
            continue
        # nested defs are linted (with scope) by their enclosing linter
        for sub in ast.walk(node):
            if isinstance(sub, ast.FunctionDef):
                linted.add(id(sub))
        findings.extend(
            _FunctionLinter(node, filename, line_offset).run())
    findings.sort(key=lambda f: (f.file, f.line))
    return findings


def lint_file(path: str, all_functions: bool = False) -> List[Finding]:
    with open(path, encoding="utf-8") as f:
        return lint_source(f.read(), filename=path,
                           all_functions=all_functions)


def lint_paths(paths, all_functions: bool = False) -> List[Finding]:
    """Lint files and (recursively) directories of .py files."""
    findings: List[Finding] = []
    for path in paths:
        if os.path.isdir(path):
            for root, _dirs, files in os.walk(path):
                for name in sorted(files):
                    if name.endswith(".py"):
                        findings.extend(lint_file(
                            os.path.join(root, name), all_functions))
        else:
            findings.extend(lint_file(path, all_functions))
    return findings


def lint_callable(fn, name: Optional[str] = None) -> List[Finding]:
    """Lint a live function/method/Layer-forward (inspect() path)."""
    target = fn
    if hasattr(fn, "forward") and not _inspect.isfunction(fn):
        target = fn.forward
    target = _inspect.unwrap(target)
    target = getattr(target, "__func__", target)
    try:
        src = _inspect.getsource(target)
        filename = _inspect.getsourcefile(target) or "<unknown>"
        _lines, first = _inspect.getsourcelines(target)
    except (OSError, TypeError):
        return []
    return lint_source(src, filename=filename, line_offset=first - 1,
                       all_functions=True)
