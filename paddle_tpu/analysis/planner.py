"""Auto-parallel planner — the shard_lint cost model inverted into an
ahead-of-time DP/TP/PP/EP/sharding/SEP plan search.

PR 3's static cost model only *reports*: given a program it derives
per-rank collective bytes, FLOPs and peak-HBM liveness. This module
searches with it — the plan-selection move of arXiv 2112.01075 /
2412.14374, with automatic cross-replica sharding of the weight update
(arXiv 2004.13336) as a first-class plan dimension — entirely
device-free on a 1-CPU box:

1. **Enumerate** the legal mesh factorizations of ``n_devices`` over
   the hybrid axes (dp / mp / pp / ep / sharding / sep), crossed with
   the pipeline schedule space (FThenB / VPP / ZBH1, microbatch
   counts) and the weight-update-sharding bit. Multi-slice topologies
   enumerate a DCN factor on the dp axis (``dcn_slices``).
2. **Prune** with the shard_lint rule set: indivisible collectives
   (heads/intermediate/vocab vs mp, tokens vs ep, seq vs sep, batch vs
   data axes), pipeline imbalance and microbatch arity via
   ``pipeline.schedule_stats``, and a peak-HBM budget
   (``hbm-over-budget`` — the one gate with no lint analog).
3. **Cost** each surviving plan by *tracing* it: a per-rank proxy
   train-step program (the plan's actual collectives — mp psums, sep
   ring ppermutes, ep all_to_alls, dp/sharding gradient psum or the
   ZeRO reduce_scatter + all_gather pair) is abstractly staged under
   the plan's fake mesh with ``lint_sharded`` — ``jax.make_jaxpr``
   under an ``AbstractMesh``, exactly the shard_lint path, so every
   collective is validated AND costed per axis tier.
4. **Rank** by a roofline time combiner (``predict_time``): FLOPs
   against derated chip peak, ring-collective bytes split intra-slice
   (ICI) vs cross-slice (DCN) by axis tier, pipeline bubble fraction
   from ``schedule_stats``, stage-boundary activation traffic.

Calibration contract (docs/ANALYSIS.md "Auto-parallel planner"): the
planner must reproduce the frozen relative ordering of the 13
align-green dryrun configurations (``DRYRUN_EXPECTED_ORDER``; rank
correlation >= 0.9) and pick the known-better member of each plan
family (``family_checks``) before its choices are trusted —
``distributed.dryrun._dryrun_planner`` gates on exactly this, then
runs the chosen plan end-to-end align-checked.

The winner is executable: ``Plan.build_mesh()`` -> a concrete
``jax.sharding.Mesh``, ``Plan.strategy()`` -> a
``fleet.DistributedStrategy`` for ``DistributedTrainStep`` /
``distributed.parallel_step``, ``Plan.to_dict()`` -> the plan dict the
serving layer consumes (``DisaggEngine.from_plan`` /
``ServingFleet.from_plan`` answer "how should decode workers shard?"
via ``plan_serving``).
"""
from __future__ import annotations

import dataclasses
import itertools
import math
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .findings import (BUBBLE_FRACTION, ERROR, HBM_OVER_BUDGET,
                       INDIVISIBLE_COLLECTIVE, MICROBATCH_ARITY,
                       SEGMENT_MISMATCH, STAGE_IMBALANCE, UNEVEN_SPLIT,
                       Finding)

PLAN_AXES = ("dp", "mp", "pp", "ep", "sharding", "sep")
DEFAULT_SCHEDULES = ("FThenB", "VPP", "ZBH1")
DEFAULT_MICRO = (1, 2, 4, 8)
# a schedule idling more than half its wall ticks is rejected outright
# (shard_lint merely warns at 30% — the planner is allowed to keep a
# warned config if nothing better survives, ranking punishes it anyway)
HARD_BUBBLE_FRACTION = 0.5
# >1.5x max/mean per-stage layer weight (shard_lint STAGE_IMBALANCE_RATIO)
STAGE_IMBALANCE_RATIO = 1.5
# bytes per parameter of optimizer state: fp32 grad + two Adam moments
_OPT_STATE_BYTES = 12.0


# ---------------------------------------------------------------------------
# machine + model descriptors
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class MachineSpec:
    """Per-chip roofline numbers the time combiner divides by. Peak
    FLOP/s and HBM bandwidth come from the same table
    ``paddle_tpu.cost_model`` prices single ops with; ICI/DCN
    bandwidths are the ring tiers the collective bytes ride."""
    chip: str = "TPU v5 lite"
    peak_flops: float = 197e12
    hbm_bw: float = 819e9
    hbm_bytes: float = 16e9
    ici_bw: float = 45e9
    dcn_bw: float = 2.5e9
    # achievable fraction of peak for the matmul stream (the bench's
    # measured 1B MFU band) — a constant derating, so it shifts the
    # compute/comm balance, never the compute-vs-compute ordering
    efficiency: float = 0.55

    @classmethod
    def for_chip(cls, name: str, **over) -> "MachineSpec":
        from ..cost_model import _CHIP
        peak, bw = _CHIP.get(name, _CHIP["TPU v5 lite"])
        return cls(chip=name, peak_flops=peak, hbm_bw=bw, **over)


@dataclasses.dataclass(frozen=True)
class ModelSpec:
    """Shape descriptor of one transformer-ish workload — everything
    the proxy program builder needs. ``heads=0`` degrades to a pure
    MLP-block stack (the dryrun pipeline zoo shape); ``vocab=0`` drops
    the LM head; ``n_experts>0`` swaps the dense FFN for a MoE FFN
    dispatched over the ep axis."""
    name: str
    hidden: int
    layers: int
    seq: int
    global_batch: int
    intermediate: int = 0     # 0 -> 4*hidden
    heads: int = 0            # 0 -> no attention (MLP block)
    kv_heads: int = 0         # 0 -> heads (MHA); < heads -> GQA
    vocab: int = 0            # 0 -> no LM head
    n_experts: int = 0        # 0 -> dense FFN
    dtype_bytes: int = 2      # bf16 params/activations

    @property
    def inter(self) -> int:
        return self.intermediate or 4 * self.hidden

    @property
    def kv(self) -> int:
        return self.kv_heads or self.heads

    @property
    def d_head(self) -> int:
        return self.hidden // self.heads if self.heads else 0

    def param_count(self) -> float:
        """Global parameter count (embedding excluded — its FLOPs are a
        gather and its bytes are vocab-major, out of the search's way)."""
        h, i = self.hidden, self.inter
        per_layer = 0.0
        if self.heads:
            per_layer += h * (self.heads + 2 * self.kv) * self.d_head
            per_layer += self.heads * self.d_head * h
        ffn = 2.0 * h * i
        per_layer += ffn * max(1, self.n_experts)
        total = per_layer * self.layers
        if self.vocab:
            total += float(h) * self.vocab
        return float(total)

    @classmethod
    def llama_1b(cls, global_batch: int = 96) -> "ModelSpec":
        """The bench headline shape (1.07B: LLaMA-7B layer geometry x4
        layers, seq 1024, batch 12/chip at 8 chips)."""
        return cls("llama_1b", hidden=4096, layers=4, seq=1024,
                   global_batch=global_batch, intermediate=11008,
                   heads=32, kv_heads=32, vocab=32000)

    @classmethod
    def llama_tiny(cls, layers: int = 4, global_batch: int = 4,
                   seq: int = 16) -> "ModelSpec":
        """The dryrun flagship geometry (_llama_tiny_cfg)."""
        return cls("llama_tiny", hidden=32, layers=layers, seq=seq,
                   global_batch=global_batch, intermediate=64, heads=4,
                   kv_heads=2, vocab=64)


# ---------------------------------------------------------------------------
# plans
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Plan:
    """One point of the configuration space. ``degrees`` are the
    intra-slice (ICI) mesh degrees; ``dcn_degrees`` multiply a named
    axis with a cross-slice (DCN) outer component — the exact
    ``mesh.build_mesh(degrees, dcn_degrees=...)`` contract."""
    degrees: Dict[str, int]
    dcn_degrees: Dict[str, int] = dataclasses.field(default_factory=dict)
    schedule_mode: str = "FThenB"
    n_micro: int = 1
    vpp_degree: int = 1
    # arXiv 2004.13336: shard the weight update (grads reduce-scattered,
    # optimizer state + update 1/n per rank, params all-gathered back)
    # across the 'sharding' axis instead of replicating it — the axis
    # the executable surface (strategy() sharding stage 3) actually
    # shards over. Same collective bytes as that axis's all_reduce —
    # the win is the HBM term.
    shard_weight_update: bool = False

    def degree(self, ax: str) -> int:
        return int(self.degrees.get(ax, 1)) * \
            int(self.dcn_degrees.get(ax, 1))

    @property
    def n_devices(self) -> int:
        axes = set(self.degrees) | set(self.dcn_degrees)
        return int(math.prod(self.degree(ax) for ax in axes))

    def dcn_axes(self) -> Tuple[str, ...]:
        return tuple(ax for ax, d in self.dcn_degrees.items() if d > 1)

    def total_degrees(self) -> Dict[str, int]:
        """{axis: total degree} over axes with degree > 1 — the fake
        mesh the proxy programs trace under (AbstractMesh has no tier
        notion; the combiner re-splits tiers from per-axis bytes)."""
        axes = list(dict.fromkeys(list(self.degrees)
                                  + list(self.dcn_degrees)))
        return {ax: self.degree(ax) for ax in axes if self.degree(ax) > 1}

    def data_axes(self) -> Tuple[str, ...]:
        return tuple(ax for ax in ("dp", "sharding")
                     if self.degree(ax) > 1)

    def describe(self) -> str:
        mesh = "·".join(f"{ax}{self.degree(ax)}"
                        for ax in PLAN_AXES if self.degree(ax) > 1) \
            or "single"
        if self.dcn_axes():
            mesh += f" dcn={{{','.join(f'{a}:{self.dcn_degrees[a]}' for a in self.dcn_axes())}}}"
        bits = [mesh]
        if self.degree("pp") > 1:
            bits.append(f"{self.schedule_mode} M={self.n_micro}")
            if self.vpp_degree > 1:
                bits.append(f"V={self.vpp_degree}")
        if self.shard_weight_update:
            bits.append("zero")
        return " ".join(bits)

    # -- executable surfaces -------------------------------------------------

    def build_mesh(self, devices=None):
        """Concrete ``jax.sharding.Mesh`` for this plan (needs real or
        virtual devices — everything before this point was device-free)."""
        from ..distributed import mesh as mesh_mod
        return mesh_mod.build_mesh(
            dict(self.degrees), devices=devices,
            dcn_degrees=dict(self.dcn_degrees) or None)

    def strategy(self):
        """``fleet.DistributedStrategy`` carrying this plan — feed to
        ``fleet.init`` + ``DistributedTrainStep``."""
        from ..distributed import fleet
        s = fleet.DistributedStrategy()
        s.hybrid_configs = {
            "dp_degree": self.degree("dp"),
            "mp_degree": self.degree("mp"),
            "pp_degree": self.degree("pp"),
            "sharding_degree": self.degree("sharding"),
            "sep_degree": self.degree("sep"),
            "ep_degree": self.degree("ep"),
        }
        if self.shard_weight_update:
            s.sharding_configs = dict(
                s.sharding_configs, stage=3,
                degree=self.degree("sharding"))
        if self.degree("pp") > 1:
            s.pipeline_configs["accumulate_steps"] = self.n_micro
            s.pipeline_configs["schedule_mode"] = self.schedule_mode
        return s

    def to_driver(self, spec: Optional["ModelSpec"] = None,
                  programs=None, placements=None):
        """``mpmd_runtime.MpmdDriver`` over this plan's verified event
        graph — the executable end of the ``plan_graph`` extraction.
        With no ``programs`` the driver walks the schedule symbolically
        (device-free: validates order, routes, channel capacities);
        pass real per-stage programs to execute. Raises
        ``MpmdGraphRejected`` when the plan's schedule fails mpmd_lint.
        """
        from ..distributed import mpmd_graph as mg
        from ..distributed.mpmd_runtime import MpmdDriver
        if self.degree("pp") <= 1:
            raise ValueError(
                "Plan.to_driver needs a pipelined plan (pp > 1); "
                "non-pipelined plans have no cross-stage schedule")
        if spec is not None:
            g = mg.plan_graph(spec, self)
        else:
            g = mg.schedule_graph(self.schedule_mode, self.degree("pp"),
                                  self.n_micro, self.vpp_degree)
        return MpmdDriver(g, programs, placements=placements)

    def to_dict(self) -> Dict[str, object]:
        return {
            "degrees": {ax: d for ax, d in self.degrees.items() if d > 1},
            "dcn_degrees": {ax: d for ax, d in self.dcn_degrees.items()
                            if d > 1},
            "schedule_mode": self.schedule_mode,
            "n_micro": self.n_micro,
            "vpp_degree": self.vpp_degree,
            "shard_weight_update": self.shard_weight_update,
            "hybrid_configs": {
                "dp_degree": self.degree("dp"),
                "mp_degree": self.degree("mp"),
                "pp_degree": self.degree("pp"),
                "sharding_degree": self.degree("sharding"),
                "sep_degree": self.degree("sep"),
                "ep_degree": self.degree("ep"),
            },
        }

    def key(self) -> tuple:
        return (tuple(sorted((a, d) for a, d in self.degrees.items()
                             if d > 1)),
                tuple(sorted((a, d) for a, d in self.dcn_degrees.items()
                             if d > 1)),
                self.schedule_mode if self.degree("pp") > 1 else "",
                self.n_micro, self.vpp_degree, self.shard_weight_update)


@dataclasses.dataclass
class PredictedTime:
    """Roofline combiner output — seconds per optimizer step."""
    compute_s: float = 0.0
    ici_s: float = 0.0
    dcn_s: float = 0.0
    bubble_fraction: float = 0.0
    peak_hbm_bytes: float = 0.0
    step_s: float = float("inf")

    def to_dict(self) -> Dict[str, float]:
        return dataclasses.asdict(self)

    def format(self) -> str:
        from .cost_model import CostEstimate
        h = CostEstimate._human
        return (f"step {self.step_s * 1e3:.3f} ms "
                f"(compute {self.compute_s * 1e3:.3f} + "
                f"ici {self.ici_s * 1e3:.3f} + "
                f"dcn {self.dcn_s * 1e3:.3f} ms, "
                f"bubble {self.bubble_fraction:.0%}, "
                f"peak HBM {h(self.peak_hbm_bytes)})")


@dataclasses.dataclass
class ScoredPlan:
    plan: Plan
    findings: List[Finding] = dataclasses.field(default_factory=list)
    cost: Optional[object] = None        # CostEstimate of the fwd trace
    sync_cost: Optional[object] = None   # CostEstimate of the grad sync
    time: Optional[PredictedTime] = None
    # MPMD schedule verdict (pipelined plans): {"verified": bool,
    # "events": int, "findings": int} from the lint_mpmd model check
    # of the plan's event graph; None for non-pipelined plans
    mpmd: Optional[Dict[str, object]] = None

    @property
    def ok(self) -> bool:
        return self.time is not None and not any(
            f.severity == ERROR for f in self.findings)

    @property
    def step_s(self) -> float:
        return self.time.step_s if self.time is not None else float("inf")

    def why_rejected(self) -> str:
        return "; ".join(f"[{f.rule}] {f.message}" for f in self.findings
                         if f.severity == ERROR) or ""

    def to_dict(self) -> Dict[str, object]:
        return {
            "plan": self.plan.to_dict(),
            "describe": self.plan.describe(),
            "ok": self.ok,
            "findings": [{"rule": f.rule, "severity": f.severity,
                          "message": f.message} for f in self.findings],
            "time": self.time.to_dict() if self.time else None,
            "cost": self.cost.to_dict() if self.cost is not None else None,
            "mpmd": self.mpmd,
        }

    def format(self) -> str:
        head = f"{self.plan.describe():<40} "
        if not self.ok:
            return head + f"REJECTED {self.why_rejected()}"
        return head + self.time.format()


def _reject(rule: str, message: str, suggestion: str = "") -> Finding:
    return Finding(rule=rule, severity=ERROR, message=message,
                   file="<planner>", suggestion=suggestion)


# ---------------------------------------------------------------------------
# legality: per-rank dims + the shard_lint-rule prune
# ---------------------------------------------------------------------------

def plan_dims(spec: ModelSpec, plan: Plan):
    """Per-rank shape table for (spec, plan), or the findings that make
    the pair illegal — every check phrased as the shard_lint rule the
    defect would trip once traced/run."""
    findings: List[Finding] = []
    dp, mp, pp = plan.degree("dp"), plan.degree("mp"), plan.degree("pp")
    ep, sh, sep = plan.degree("ep"), plan.degree("sharding"), \
        plan.degree("sep")
    data = dp * sh
    M = max(1, int(plan.n_micro))

    if spec.global_batch % (data * M):
        findings.append(_reject(
            UNEVEN_SPLIT,
            f"global batch {spec.global_batch} is not divisible by "
            f"dp*sharding*n_micro = {dp}*{sh}*{M}",
            "change the data degrees or microbatch count"))
    if spec.heads:
        if spec.hidden % spec.heads:
            findings.append(_reject(
                INDIVISIBLE_COLLECTIVE,
                f"hidden {spec.hidden} not divisible by heads "
                f"{spec.heads}"))
        if spec.heads % mp:
            findings.append(_reject(
                INDIVISIBLE_COLLECTIVE,
                f"attention heads {spec.heads} not divisible by mp={mp} "
                "— the TP head split has a remainder",
                "pick mp from the divisors of the head count"))
        if spec.kv % mp:
            findings.append(_reject(
                INDIVISIBLE_COLLECTIVE,
                f"kv heads {spec.kv} not divisible by mp={mp} — the KV "
                "projection cannot shard evenly",
                "cap mp at the kv-head count (GQA shards kv first)"))
        if spec.seq % sep:
            findings.append(_reject(
                INDIVISIBLE_COLLECTIVE,
                f"seq {spec.seq} not divisible by sep={sep} — the ring "
                "shards the sequence dim"))
    elif sep > 1:
        findings.append(_reject(
            INDIVISIBLE_COLLECTIVE,
            "sep>1 needs attention (heads=0 model has no sequence ring)"))
    if spec.inter % mp:
        findings.append(_reject(
            INDIVISIBLE_COLLECTIVE,
            f"intermediate {spec.inter} not divisible by mp={mp}"))
    if spec.vocab and spec.vocab % mp:
        findings.append(_reject(
            INDIVISIBLE_COLLECTIVE,
            f"vocab {spec.vocab} not divisible by mp={mp} — the "
            "column-parallel head splits the vocab dim"))
    if ep > 1:
        if not spec.n_experts:
            findings.append(_reject(
                INDIVISIBLE_COLLECTIVE,
                "ep>1 on a dense model (no experts to dispatch)"))
        elif spec.n_experts % ep:
            findings.append(_reject(
                INDIVISIBLE_COLLECTIVE,
                f"{spec.n_experts} experts not divisible by ep={ep}"))

    # pipeline legality — schedule_stats is the shared dispatch point
    stage_layers = spec.layers
    bubble = 0.0
    if pp > 1:
        per = [spec.layers // pp + (1 if s < spec.layers % pp else 0)
               for s in range(pp)]
        if 0 in per:
            findings.append(_reject(
                STAGE_IMBALANCE,
                f"pp={pp} exceeds the {spec.layers}-layer depth — "
                f"stage weights {per} leave empty stages idling the "
                "whole schedule"))
        ratio = max(per) / (sum(per) / len(per)) if min(per) else \
            float("inf")
        if STAGE_IMBALANCE_RATIO < ratio < float("inf"):
            findings.append(_reject(
                STAGE_IMBALANCE,
                f"{spec.layers} layers over pp={pp} stages gives "
                f"per-stage weights {per} (max/mean = {ratio:.2f}x > "
                f"{STAGE_IMBALANCE_RATIO}x) — every other stage idles "
                "while the heaviest computes",
                "pick pp from the divisors of the layer count"))
        stage_layers = max(per)
        if M < pp:
            findings.append(_reject(
                MICROBATCH_ARITY,
                f"pipeline pp={pp} with only M={M} microbatches — the "
                f"schedule needs accumulate_steps >= pp"))
        if plan.vpp_degree > 1 and \
                spec.layers % (pp * plan.vpp_degree):
            findings.append(_reject(
                SEGMENT_MISMATCH,
                f"{spec.layers} layers do not tile pp*vpp = "
                f"{pp}*{plan.vpp_degree} virtual chunks"))
        if not findings:
            from ..distributed.pipeline import schedule_stats
            try:
                stats = schedule_stats(plan.schedule_mode, pp, M,
                                       plan.vpp_degree)
            except ValueError as exc:
                findings.append(_reject(SEGMENT_MISMATCH, str(exc)))
                stats = None
            if stats is not None:
                bubble = float(stats["bubble_fraction"])
                if bubble > HARD_BUBBLE_FRACTION:
                    findings.append(_reject(
                        BUBBLE_FRACTION,
                        f"{plan.schedule_mode} at S={pp} M={M} idles "
                        f"{bubble:.0%} of wall ticks in bubbles "
                        f"(> {HARD_BUBBLE_FRACTION:.0%})",
                        "raise n_micro or switch to VPP/ZBH1"))

    if any(f.severity == ERROR for f in findings):
        return None, findings

    b_micro = spec.global_batch // (data * M)
    s_local = spec.seq // max(1, sep)
    el = spec.n_experts // ep if spec.n_experts else 0
    dims = {
        "b_micro": b_micro,
        "s_local": s_local,
        "heads_local": spec.heads // mp if spec.heads else 0,
        "kv_local": spec.kv // mp if spec.heads else 0,
        "inter_local": spec.inter // mp,
        "vocab_local": spec.vocab // mp if spec.vocab else 0,
        "experts_local": el,
        "stage_layers": stage_layers,
        "bubble": bubble,
    }
    if spec.heads and dims["heads_local"] % max(1, dims["kv_local"]):
        findings.append(_reject(
            INDIVISIBLE_COLLECTIVE,
            f"per-rank q heads {dims['heads_local']} not a multiple of "
            f"per-rank kv heads {dims['kv_local']} (GQA group split)"))
        return None, findings
    if ep > 1:
        tokens = b_micro * s_local
        if tokens % ep or (tokens and el and tokens % el):
            findings.append(_reject(
                INDIVISIBLE_COLLECTIVE,
                f"per-rank tokens {tokens} do not tile the ep={ep} "
                f"all_to_all dispatch buffer ({el} local experts)",
                "change the data degrees / microbatch count so "
                "tokens-per-rank divides ep"))
            return None, findings
    return dims, findings


# ---------------------------------------------------------------------------
# traced proxy programs (the lint_sharded path)
# ---------------------------------------------------------------------------

def _param_shapes(spec: ModelSpec, dims) -> List[Tuple[str, tuple]]:
    """Per-rank parameter tensors of one pipeline stage, stacked over
    its layers (scan consumes the stack, so the cost walk charges the
    full per-rank parameter bytes AND multiplies per-layer FLOPs)."""
    L = dims["stage_layers"]
    h, dh = spec.hidden, spec.d_head
    hl, kl = dims["heads_local"], dims["kv_local"]
    il, el = dims["inter_local"], dims["experts_local"]
    shapes: List[Tuple[str, tuple]] = []
    if spec.heads:
        shapes.append(("wqkv", (L, h, (hl + 2 * kl) * dh)))
        shapes.append(("wo", (L, hl * dh, h)))
    if el:
        shapes.append(("w1", (L, el, h, il)))
        shapes.append(("w2", (L, el, il, h)))
    else:
        shapes.append(("w1", (L, h, il)))
        shapes.append(("w2", (L, il, h)))
    if spec.vocab:
        shapes.append(("whead", (h, dims["vocab_local"])))
    return shapes


def rank_param_bytes(spec: ModelSpec, dims) -> float:
    return float(sum(math.prod(s) for _, s in _param_shapes(spec, dims))
                 * spec.dtype_bytes)


def _fwd_program(spec: ModelSpec, plan: Plan, dims):
    """(fn, arg structs): the per-rank, per-microbatch forward of one
    pipeline stage with the plan's actual collectives. Backward is
    charged analytically in the combiner (x3 FLOPs, x2 activation
    collectives — megatron's conjugate f/g pairs and the ring's
    counter-rotation) so the count is identical on every jax version
    instead of depending on shard_map transpose rules."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    dt = jnp.bfloat16 if spec.dtype_bytes == 2 else jnp.float32
    h, dh = spec.hidden, spec.d_head
    b, s = dims["b_micro"], dims["s_local"]
    hl, kl = dims["heads_local"], dims["kv_local"]
    el = dims["experts_local"]
    mp, sep, ep = plan.degree("mp"), plan.degree("sep"), plan.degree("ep")
    shapes = _param_shapes(spec, dims)
    names = [n for n, _ in shapes]

    def fn(*args):
        ws = dict(zip(names, args[:len(names)]))
        x = args[len(names)]
        whead = ws.pop("whead", None)

        def layer(x, w):
            if spec.heads:
                qkv = x @ w["wqkv"]
                q = qkv[..., :hl * dh].reshape(b, s, hl, dh) \
                    .transpose(0, 2, 1, 3)
                k = qkv[..., hl * dh:(hl + kl) * dh] \
                    .reshape(b, s, kl, dh).transpose(0, 2, 1, 3)
                v = qkv[..., (hl + kl) * dh:].reshape(b, s, kl, dh) \
                    .transpose(0, 2, 1, 3)
                rep = hl // kl

                def widen(t):  # GQA: kv groups -> q heads (zero-cost
                    if rep == 1:  # broadcast, never rotated this wide)
                        return t
                    return jnp.broadcast_to(
                        t[:, :, None], (b, kl, rep, s, dh)) \
                        .reshape(b, hl, s, dh)

                acc = jnp.zeros((b, hl, s, dh), dt)
                ring = [(i, (i + 1) % sep) for i in range(sep)]
                for hop in range(sep):
                    scores = jnp.einsum("bhqd,bhkd->bhqk", q,
                                        widen(k)) / np.sqrt(dh)
                    p = jax.nn.softmax(scores.astype(jnp.float32), -1)
                    acc = acc + jnp.einsum("bhqk,bhkd->bhqd",
                                           p.astype(dt), widen(v))
                    if hop < sep - 1:
                        # the ring rotates the kv-head-sized tensors —
                        # GQA's bandwidth win applies to sep traffic
                        k = lax.ppermute(k, "sep", ring)
                        v = lax.ppermute(v, "sep", ring)
                out = acc.transpose(0, 2, 1, 3).reshape(b, s, hl * dh) \
                    @ w["wo"]
                if mp > 1:
                    out = lax.psum(out, "mp")
                x = x + out
            if el:
                t = b * s
                cap = t // ep
                buf = x.reshape(t, h).reshape(ep, cap, h)
                buf = lax.all_to_all(buf, "ep", split_axis=0,
                                     concat_axis=0)
                xe = buf.reshape(el, (ep * cap) // el, h)
                mid = jax.nn.gelu(jnp.einsum("eth,ehi->eti", xe,
                                             w["w1"]))
                ye = jnp.einsum("eti,eih->eth", mid, w["w2"])
                back = lax.all_to_all(ye.reshape(ep, cap, h), "ep",
                                      split_axis=0, concat_axis=0)
                y = back.reshape(b, s, h)
            else:
                mid = jax.nn.gelu(x @ w["w1"])
                y = mid @ w["w2"]
            if mp > 1:
                y = lax.psum(y, "mp")
            return x + y, jnp.float32(0.0)

        x, _ = lax.scan(layer, x, ws)
        if whead is not None:
            z = x @ whead
            loss = jnp.mean(jnp.square(z.astype(jnp.float32)))
            if mp > 1:  # log-sum-exp style cross-shard reduction
                loss = lax.psum(loss, "mp") / mp
        else:
            loss = jnp.mean(jnp.square(x.astype(jnp.float32)))
        return loss

    args = [jax.ShapeDtypeStruct(shape, dt) for _, shape in shapes]
    args.append(jax.ShapeDtypeStruct((b, s, h), dt))
    return fn, args


def _sync_program(spec: ModelSpec, plan: Plan, dims):
    """(fn, args) for the once-per-step gradient synchronisation over
    the data axes. Mirrors the executable surface exactly: dp replicas
    ring-all_reduce their grads; with ``shard_weight_update`` the
    'sharding' axis instead carries the cross-replica-sharded update of
    arXiv 2004.13336 (reduce_scatter the grads, update 1/n of the
    params, all_gather them back — same ring bytes as its all_reduce,
    1/n optimizer state)."""
    axes = plan.data_axes()
    if not axes:
        return None
    import jax
    import jax.numpy as jnp
    from jax import lax

    dt = jnp.bfloat16 if spec.dtype_bytes == 2 else jnp.float32
    zero_axis = "sharding" if plan.shard_weight_update \
        and plan.degree("sharding") > 1 else None
    psum_axes = tuple(ax for ax in axes if ax != zero_axis)
    n = plan.degree("sharding")
    shapes = _param_shapes(spec, dims)

    def fn(*grads):
        acc = jnp.float32(0.0)
        for g in grads:
            if psum_axes:
                g = lax.psum(g, psum_axes)
            if zero_axis is not None:
                flat = g.reshape(-1)
                pad = (-flat.size) % n
                if pad:
                    flat = jnp.concatenate(
                        [flat, jnp.zeros((pad,), flat.dtype)])
                shard = lax.psum_scatter(flat, (zero_axis,),
                                         scatter_dimension=0, tiled=True)
                full = lax.all_gather(shard, (zero_axis,), tiled=True)
                acc = acc + jnp.sum(full.astype(jnp.float32))
            else:
                acc = acc + jnp.sum(g.astype(jnp.float32))
        return acc

    return fn, [jax.ShapeDtypeStruct(shape, dt) for _, shape in shapes]


# ---------------------------------------------------------------------------
# the roofline combiner
# ---------------------------------------------------------------------------

def predict_time(spec: ModelSpec, plan: Plan, dims, machine: MachineSpec,
                 fwd_cost, sync_cost=None) -> PredictedTime:
    """Combine traced per-rank counts into predicted seconds per step.

    step = (compute + ici + dcn) / (1 - bubble), where
      compute = 3 * fwd FLOPs * M / (peak * efficiency)
      ici/dcn = per-tier collective bytes / tier bandwidth, activation
                collectives x2 (bwd conjugates) x M microbatches, grad
                sync x1, pipeline boundary activations 2*M*V hops
      bubble  = schedule_stats bubble fraction (0 when pp == 1)
    """
    M = max(1, plan.n_micro)
    S = plan.degree("pp")
    dcn_axes = plan.dcn_axes()

    flops = fwd_cost.flops * 3.0 * M
    compute_s = flops / (machine.peak_flops * machine.efficiency)

    f_ici, f_dcn = fwd_cost.tier_bytes(dcn_axes)
    ici_bytes = f_ici * 2.0 * M
    dcn_bytes = f_dcn * 2.0 * M
    if sync_cost is not None:
        s_ici, s_dcn = sync_cost.tier_bytes(dcn_axes)
        ici_bytes += s_ici
        dcn_bytes += s_dcn

    bubble = float(dims.get("bubble", 0.0)) if S > 1 else 0.0
    if S > 1:
        act = dims["b_micro"] * dims["s_local"] * spec.hidden \
            * spec.dtype_bytes
        # each microbatch crosses this rank's stage boundary once fwd,
        # once bwd, per virtual chunk (pp rides ICI by mesh axis order)
        ici_bytes += act * 2.0 * M * max(1, plan.vpp_degree)

    ici_s = ici_bytes / machine.ici_bw
    dcn_s = dcn_bytes / machine.dcn_bw
    work = compute_s + ici_s + dcn_s
    step_s = work / max(1e-9, 1.0 - bubble)
    return PredictedTime(
        compute_s=compute_s, ici_s=ici_s, dcn_s=dcn_s,
        bubble_fraction=bubble,
        peak_hbm_bytes=peak_hbm(spec, plan, dims, fwd_cost),
        step_s=step_s)


def peak_hbm(spec: ModelSpec, plan: Plan, dims, fwd_cost=None) -> float:
    """Per-rank peak-HBM model: traced fwd liveness (params + one
    layer's transients) + optimizer state (fp32 grad + Adam moments,
    / data degree when the weight update is sharded) + activations
    saved for backward + the pipeline microbatch stack."""
    pbytes = rank_param_bytes(spec, dims)
    pcount = pbytes / spec.dtype_bytes
    # the executable surface (Plan.strategy -> sharding_configs stage 3)
    # shards the update over the 'sharding' axis ONLY — dp replicas
    # keep full state — so the HBM model must divide by exactly that
    shard_div = plan.degree("sharding") if plan.shard_weight_update \
        else 1
    states = pcount * _OPT_STATE_BYTES / max(1, shard_div)
    x_bytes = dims["b_micro"] * dims["s_local"] * spec.hidden \
        * spec.dtype_bytes
    acts_saved = x_bytes * dims["stage_layers"] * 2.0
    micro_stack = x_bytes * plan.n_micro if plan.degree("pp") > 1 else 0.0
    base = fwd_cost.peak_hbm_bytes if fwd_cost is not None \
        else pbytes + 4.0 * x_bytes
    return float(base + states + acts_saved + micro_stack)


# ---------------------------------------------------------------------------
# scoring: analytic prescore (cheap) and traced score (exact)
# ---------------------------------------------------------------------------

def prescore_plan(spec: ModelSpec, plan: Plan,
                  machine: Optional[MachineSpec] = None):
    """Closed-form twin of the traced score — no jax import, no trace;
    used to order the enumeration so only the front-runners pay for an
    abstract trace. Returns (step_s, peak_hbm, findings)."""
    machine = machine or MachineSpec()
    dims, findings = plan_dims(spec, plan)
    if dims is None:
        return float("inf"), float("inf"), findings
    b, s = dims["b_micro"], dims["s_local"]
    h, dh = spec.hidden, spec.d_head
    hl, kl, il = dims["heads_local"], dims["kv_local"], \
        dims["inter_local"]
    L = dims["stage_layers"]
    mp, sep, ep = plan.degree("mp"), plan.degree("sep"), plan.degree("ep")
    M = max(1, plan.n_micro)
    dt = spec.dtype_bytes

    flops = 0.0
    act = b * s * h * dt
    ici = dcn = 0.0
    dcn_data = set(plan.dcn_axes())

    def ring(nbytes, axis, factor):
        nonlocal ici, dcn
        moved = factor * nbytes
        if axis in dcn_data:
            dcn += moved
        else:
            ici += moved

    # one layer's FLOPs and collective bytes — both ×L below, exactly
    # like the traced program's scan repeat
    per_layer = 0.0
    layer_ici, layer_dcn = ici, dcn
    if spec.heads:
        per_layer += 2.0 * b * s * h * (hl + 2 * kl) * dh     # qkv
        per_layer += 4.0 * b * hl * s * (s * sep) * dh        # scores+pv
        per_layer += 2.0 * b * s * hl * dh * h                # out proj
        if sep > 1:
            kv_bytes = 2 * b * kl * s * dh * dt
            ring(kv_bytes * (sep - 1), "sep", 1.0)
        if mp > 1:
            ring(act, "mp", 2.0 * (mp - 1) / mp)
    per_layer += 4.0 * b * s * h * il                         # ffn
    if ep > 1:
        buf = b * s * h * dt
        ring(buf * 2, "ep", (ep - 1) / ep)
    if mp > 1:
        ring(act, "mp", 2.0 * (mp - 1) / mp)
    flops += per_layer * L
    ici = layer_ici + (ici - layer_ici) * L
    dcn = layer_dcn + (dcn - layer_dcn) * L
    if spec.vocab:
        flops += 2.0 * b * s * h * dims["vocab_local"]
    flops *= 3.0 * M
    ici *= 2.0 * M
    dcn *= 2.0 * M

    # grad sync, hierarchical like the executable surface: dp ring
    # all_reduce + (under zero) the byte-equivalent rs+ag over sharding
    pbytes = rank_param_bytes(spec, dims)
    zero_axis = "sharding" if plan.shard_weight_update \
        and plan.degree("sharding") > 1 else None
    for ax in plan.data_axes():
        if ax == zero_axis:
            continue
        nax = plan.degree(ax)
        moved = 2.0 * pbytes * (nax - 1) / nax
        if ax in dcn_data:
            dcn += moved
        else:
            ici += moved
    if zero_axis is not None:
        nax = plan.degree(zero_axis)
        moved = 2.0 * pbytes * (nax - 1) / nax
        if zero_axis in dcn_data:
            dcn += moved
        else:
            ici += moved

    S = plan.degree("pp")
    bubble = float(dims.get("bubble", 0.0)) if S > 1 else 0.0
    if S > 1:
        ici += act * 2.0 * M * max(1, plan.vpp_degree)

    compute_s = flops / (machine.peak_flops * machine.efficiency)
    step_s = (compute_s + ici / machine.ici_bw + dcn / machine.dcn_bw) \
        / max(1e-9, 1.0 - bubble)
    return step_s, peak_hbm(spec, plan, dims), findings


def score_plan(spec: ModelSpec, plan: Plan, *,
               machine: Optional[MachineSpec] = None,
               hbm_budget: Optional[float] = None) -> ScoredPlan:
    """Full traced scoring of one plan: legality, abstract-traced fwd +
    grad-sync programs through ``lint_sharded`` (collective validation
    + per-axis cost), roofline combine, HBM gate."""
    from .shard_lint import lint_sharded
    machine = machine or MachineSpec()
    dims, findings = plan_dims(spec, plan)
    out = ScoredPlan(plan=plan, findings=list(findings))
    if dims is None:
        return out

    if plan.degree("pp") > 1:
        # MPMD schedule prune (same pattern as the shard_lint prune):
        # model-check the plan's event graph device-free before paying
        # for the abstract trace; a deadlocking/racing schedule is
        # rejected with the mpmd.* finding attached.
        from paddle_tpu.distributed.mpmd_graph import plan_graph
        from .mpmd_lint import check_graph
        g = plan_graph(spec, plan, dims=dims)
        mrep = check_graph(g)
        out.mpmd = {"verified": not mrep, "events": g.n_events(),
                    "findings": len(mrep)}
        if mrep:
            out.findings.extend(mrep.findings)
            if any(f.severity == ERROR for f in mrep.findings):
                return out

    mesh = plan.total_degrees()
    fn, args = _fwd_program(spec, plan, dims)
    rep = lint_sharded(fn, args, mesh=mesh,
                       subject=f"plan:{plan.describe()}")
    out.findings.extend(rep.findings)
    out.cost = rep.cost
    if any(f.severity == ERROR for f in rep.findings) or rep.cost is None:
        return out

    sync = _sync_program(spec, plan, dims)
    if sync is not None:
        srep = lint_sharded(sync[0], sync[1], mesh=mesh,
                            subject=f"plan-sync:{plan.describe()}")
        out.findings.extend(srep.findings)
        out.sync_cost = srep.cost
        if any(f.severity == ERROR for f in srep.findings):
            return out

    out.time = predict_time(spec, plan, dims, machine, out.cost,
                            out.sync_cost)
    budget = hbm_budget if hbm_budget is not None else machine.hbm_bytes
    if out.time.peak_hbm_bytes > budget:
        from .cost_model import CostEstimate
        h = CostEstimate._human
        out.findings.append(_reject(
            HBM_OVER_BUDGET,
            f"predicted peak HBM {h(out.time.peak_hbm_bytes)} exceeds "
            f"the {h(budget)} budget",
            "raise sharding/mp/pp degrees, shard the weight update, or "
            "cut the microbatch size"))
    return out


# ---------------------------------------------------------------------------
# enumeration + search
# ---------------------------------------------------------------------------

def _divisors(n: int) -> List[int]:
    return [d for d in range(1, n + 1) if n % d == 0]


def _factorizations(n: int, k: int):
    """All ordered k-tuples of divisors of n with product exactly n."""
    divs = _divisors(n)

    def rec(rem, parts):
        if len(parts) == k - 1:
            yield tuple(parts) + (rem,)
            return
        for d in divs:
            if rem % d == 0:
                yield from rec(rem // d, parts + [d])
    yield from rec(n, [])


def enumerate_plans(spec: ModelSpec, n_devices: int, *,
                    axes: Optional[Sequence[str]] = None,
                    schedules: Sequence[str] = DEFAULT_SCHEDULES,
                    micro: Sequence[int] = DEFAULT_MICRO,
                    dcn_slices: int = 1) -> List[Plan]:
    """The legal-ish candidate set (deterministic order). Cheap static
    skips only — real pruning happens in plan_dims/score_plan so every
    rejection carries its finding."""
    if axes is None:
        axes = ["dp", "mp", "pp", "sharding"]
        if spec.heads:
            axes.append("sep")
        if spec.n_experts:
            axes.append("ep")
    axes = tuple(axes)
    plans: List[Plan] = []
    seen = set()

    def add(p: Plan):
        if p.key() not in seen:
            seen.add(p.key())
            plans.append(p)

    for degs in _factorizations(n_devices, len(axes)):
        cfg = dict(zip(axes, degs))
        pp = cfg.get("pp", 1)
        dcn_opts = [{}]
        if dcn_slices > 1:
            if cfg.get("dp", 1) % dcn_slices:
                continue  # multi-slice: dp carries the DCN factor
            ici_cfg = dict(cfg)
            ici_cfg["dp"] = cfg["dp"] // dcn_slices
            cfg = ici_cfg
            dcn_opts = [{"dp": dcn_slices}]
        # zero (the 2004.13336 update sharding) rides the dedicated
        # 'sharding' axis of the executable surface — it is vacuous
        # (a duplicate plan) unless that axis has degree > 1
        swu_opts = (False, True) if cfg.get("sharding", 1) > 1 \
            else (False,)
        for dcn in dcn_opts:
            if pp == 1:
                for swu in swu_opts:
                    add(Plan(degrees=dict(cfg), dcn_degrees=dict(dcn),
                             shard_weight_update=swu))
                continue
            for mode in schedules:
                vpps = (2,) if mode in ("VPP", "ZBVPP") else (1,)
                for V, m, swu in itertools.product(
                        vpps, micro, swu_opts):
                    if m < pp:
                        continue
                    add(Plan(degrees=dict(cfg), dcn_degrees=dict(dcn),
                             schedule_mode=mode, n_micro=m,
                             vpp_degree=V, shard_weight_update=swu))
    return plans


def search_plans(spec: ModelSpec, n_devices: int, *,
                 machine: Optional[MachineSpec] = None,
                 hbm_budget: Optional[float] = None,
                 top_n: int = 8, trace_top: int = 16,
                 axes: Optional[Sequence[str]] = None,
                 schedules: Sequence[str] = DEFAULT_SCHEDULES,
                 micro: Sequence[int] = DEFAULT_MICRO,
                 dcn_slices: int = 1,
                 keep_rejected: bool = False) -> List[ScoredPlan]:
    """THE entry point: enumerate -> prescore-order -> trace + lint +
    rank the front-runners. Returns ScoredPlans sorted best-first
    (rejected ones appended when ``keep_rejected``). Deterministic:
    same inputs, same list."""
    machine = machine or MachineSpec()
    budget = hbm_budget if hbm_budget is not None else machine.hbm_bytes
    pres: List[Tuple[float, int, Plan]] = []
    rejected: List[ScoredPlan] = []
    for i, plan in enumerate(enumerate_plans(
            spec, n_devices, axes=axes, schedules=schedules,
            micro=micro, dcn_slices=dcn_slices)):
        step_s, hbm, findings = prescore_plan(spec, plan,
                                              machine=machine)
        if any(f.severity == ERROR for f in findings):
            if keep_rejected:
                rejected.append(ScoredPlan(plan=plan, findings=findings))
            continue
        # analytic-over-budget plans rank AFTER every in-budget plan
        # (the prescore HBM is approximate — the traced verdict decides
        # — but they must never starve legal plans of a trace slot)
        pres.append((hbm > budget, step_s, i, plan))
    pres.sort(key=lambda t: (t[0], t[1], t[2]))

    scored: List[ScoredPlan] = []
    for _, _, _, plan in pres[:max(1, trace_top)]:
        sp = score_plan(spec, plan, machine=machine, hbm_budget=budget)
        (scored if sp.ok else rejected).append(sp)
    scored.sort(key=lambda sp: sp.step_s)
    out = scored[:top_n]
    if keep_rejected:
        out = out + rejected
    return out


def best_plan(spec: ModelSpec, n_devices: int, **kw) -> ScoredPlan:
    ranked = [sp for sp in search_plans(spec, n_devices, **kw) if sp.ok]
    if not ranked:
        raise RuntimeError(
            f"planner: no legal plan for {spec.name} on {n_devices} "
            "device(s) under the given budget")
    return ranked[0]


# ---------------------------------------------------------------------------
# serving plans (DisaggEngine / ServingFleet hooks)
# ---------------------------------------------------------------------------

def plan_serving(spec: ModelSpec, n_devices: int, *,
                 machine: Optional[MachineSpec] = None,
                 prefill_fraction: float = 0.5) -> Dict[str, object]:
    """Answer "how should the decode workers shard?" — decode is
    HBM-bandwidth-bound (every generated token re-reads the weights),
    so per-token time ~ params*dtype / (mp * hbm_bw) + 2 per-layer mp
    all_reduces of the hidden vector over ICI. Picks the mp degree
    minimizing that, subject to the weights fitting one worker's HBM,
    then splits the remaining chips prefill/decode MPMD-style.
    Consumed by ``DisaggEngine.from_plan`` / ``ServingFleet.from_plan``
    (docs/SERVING.md cross-links)."""
    machine = machine or MachineSpec()
    pbytes = spec.param_count() * spec.dtype_bytes
    best_mp, best_t, best_cost = 1, float("inf"), float("inf")
    for mp in _divisors(n_devices):
        if spec.heads and (spec.kv % mp or spec.heads % mp
                           or spec.inter % mp):
            continue
        if pbytes / mp > machine.hbm_bytes:
            continue
        read_s = pbytes / mp / machine.hbm_bw
        comm_s = 0.0
        if mp > 1:
            act = spec.hidden * spec.dtype_bytes
            comm_s = spec.layers * 2 * 2.0 * act * (mp - 1) / mp \
                / machine.ici_bw
        t = read_s + comm_s
        # fleet objective: per-CHIP token cost (t * mp) — replication
        # wins unless the weights force a split (TP's extra chips buy
        # latency, never aggregate throughput: the mp all_reduce is a
        # pure tax). Strict < keeps the smallest qualifying mp.
        if t * mp < best_cost:
            best_mp, best_t, best_cost = mp, t, t * mp
    if best_t == float("inf"):
        raise RuntimeError(
            f"planner: {spec.name} weights "
            f"({pbytes / 2**30:.1f} GiB) fit no mp degree on "
            f"{n_devices} chip(s) of {machine.hbm_bytes / 2**30:.0f} GiB")
    groups = max(1, n_devices // best_mp)
    if groups <= 1:
        # one chip group: the prefill and decode surfaces share it
        # (in-process MPMD split, no extra chips claimed)
        prefill = decode = 1
    else:
        prefill = min(groups - 1,
                      max(1, int(round(groups * prefill_fraction))))
        decode = groups - prefill
    return {
        "decode_mp": best_mp,
        "prefill_workers": prefill,
        "decode_workers": decode,
        "replicas": groups,
        "predicted_decode_s_per_token": best_t,
    }


# ---------------------------------------------------------------------------
# calibration: the 13 align-green dryrun configurations
# ---------------------------------------------------------------------------

# Frozen predicted-time ordering, fastest first (the calibration
# ledger; MULTICHIP_r06 pins the phase list these mirror). Audit trail,
# tiny shapes throughout so collective/boundary terms matter as much as
# FLOPs: het (8-hidden MLP, 16-batch) leads; zb < pp on the identical
# workload (ZBH1's near-zero bubble vs GPipe's (S-1)/(S-1+M)); ep's
# single MoE layer undercuts zbvpp's 16-layer stack; 3d pays two mp
# psums per layer but only M=2 boundary hops; sep rotates the KV ring
# at seq 32; vpp carries 16 layers + a vocab-32 head at seq 8; hybrid
# adds the vocab-64 head on hidden 32 with full ZeRO sync; the llama
# pair adds GQA attention (llama-sep < llama4d: 2 vs 4 layers, and
# llama-sep edges out hybrid once the hierarchical dp-psum +
# sharding-rs/ag sync charges hybrid's two data axes separately); dcn
# is the tiny model whose dp grad ring rides the 2.5 GB/s DCN tier
# (18x slower than ICI — the tier split IS the story); sep8k is the
# catastrophic outlier (8192^2-token attention: ~1000x everything
# else). Regenerate with calibration_report()["order"] and re-audit
# whenever the combiner changes on purpose.
DRYRUN_EXPECTED_ORDER = (
    "het", "zb", "pp", "ep", "zbvpp", "3d", "sep", "vpp", "llama-sep",
    "hybrid", "llama4d", "dcn", "sep8k")

# within-family ordering at the 1B workload: (family, candidates,
# expected winner index) — the physics each plan dimension must get
# right before the planner may pick new configs
_MLP16 = dict(hidden=16, layers=8, seq=1, global_batch=64,
              intermediate=16)


def dryrun_calibration_configs() -> List[Tuple[str, ModelSpec, Plan]]:
    """(name, spec, plan) mirroring distributed/dryrun.py's 13
    align-green phases at n_devices=8 geometry — the fixed points the
    planner is validated against (the known-good configs it must rank
    correctly before it earns the right to pick new ones)."""
    mk = ModelSpec
    return [
        ("hybrid",
         mk("hybrid", hidden=32, layers=1, seq=8, global_batch=8,
            intermediate=128, vocab=64),
         Plan({"dp": 2, "sharding": 2, "mp": 2},
              shard_weight_update=True)),
        ("pp",
         mk("pp", hidden=16, layers=8, seq=1, global_batch=16,
            intermediate=16),
         Plan({"pp": 4, "dp": 2}, schedule_mode="FThenB", n_micro=4)),
        ("vpp",
         mk("vpp", hidden=16, layers=16, seq=8, global_batch=8,
            intermediate=16, vocab=32),
         Plan({"pp": 4, "dp": 2}, schedule_mode="VPP", n_micro=4,
              vpp_degree=2)),
        ("zb",
         mk("zb", hidden=16, layers=8, seq=1, global_batch=16,
            intermediate=16),
         Plan({"pp": 4, "dp": 2}, schedule_mode="ZBH1", n_micro=8)),
        ("zbvpp",
         mk("zbvpp", hidden=16, layers=16, seq=1, global_batch=16,
            intermediate=16),
         Plan({"pp": 4, "dp": 2}, schedule_mode="ZBVPP", n_micro=4,
              vpp_degree=2)),
        ("het",
         mk("het", hidden=8, layers=6, seq=1, global_batch=16,
            intermediate=8),
         Plan({"pp": 4}, schedule_mode="FThenB", n_micro=4)),
        ("ep",
         mk("ep", hidden=16, layers=1, seq=8, global_batch=8,
            intermediate=32, n_experts=4, vocab=8),
         Plan({"ep": 4, "dp": 2})),
        ("sep",
         mk("sep", hidden=16, layers=1, seq=32, global_batch=4,
            intermediate=16, heads=2, vocab=8),
         Plan({"sep": 4, "dp": 2})),
        ("3d",
         mk("3d", hidden=16, layers=4, seq=1, global_batch=8,
            intermediate=64),
         Plan({"pp": 2, "dp": 2, "mp": 2}, schedule_mode="FThenB",
              n_micro=2)),
        ("dcn",
         mk("dcn", hidden=16, layers=1, seq=1, global_batch=8,
            intermediate=64, vocab=8),
         Plan({"dp": 1, "sharding": 2, "mp": 2},
              dcn_degrees={"dp": 2})),
        ("llama4d",
         ModelSpec.llama_tiny(layers=4, global_batch=4, seq=16),
         Plan({"pp": 2, "sharding": 2, "mp": 2},
              schedule_mode="FThenB", n_micro=2,
              shard_weight_update=True)),
        ("llama-sep",
         ModelSpec.llama_tiny(layers=2, global_batch=2, seq=16),
         Plan({"sharding": 2, "sep": 2, "mp": 2},
              shard_weight_update=True)),
        ("sep8k",
         mk("sep8k", hidden=32, layers=1, seq=8192, global_batch=1,
            intermediate=32, heads=1),
         Plan({"sep": 2})),
    ]


def family_checks() -> List[Tuple[str, ModelSpec, List[Plan], int]]:
    """(family, spec, candidates, index-of-expected-winner): identical
    workload, one plan dimension varied — the ordering the combiner
    must reproduce at a realistic (1B) shape."""
    lb = ModelSpec.llama_1b(global_batch=64)
    return [
        # pipeline schedule: zero-bubble beats GPipe at the same mesh
        ("pp-schedule", lb,
         [Plan({"pp": 4, "dp": 2}, schedule_mode="FThenB", n_micro=8),
          Plan({"pp": 4, "dp": 2}, schedule_mode="ZBH1", n_micro=8)],
         1),
        # interleaving divides the bubble (V=2 at the same M; pp=2 so
        # the 4-layer 1B stack tiles pp*vpp chunks)
        ("interleave", lb,
         [Plan({"pp": 2, "dp": 4}, schedule_mode="FThenB", n_micro=2),
          Plan({"pp": 2, "dp": 4}, schedule_mode="VPP", n_micro=2,
               vpp_degree=2)],
         1),
        # axis tier: the same mesh with dp over DCN loses to pure ICI
        ("tier", lb,
         [Plan({"dp": 2, "sharding": 2, "mp": 2},
               shard_weight_update=True),
          Plan({"dp": 1, "sharding": 2, "mp": 2},
               dcn_degrees={"dp": 2}, shard_weight_update=True)],
         0),
        # tp width: mp=8 on a 4-layer 1B model is comm-bound vs mp=2
        ("tp-width", lb,
         [Plan({"mp": 8}),
          Plan({"mp": 2, "dp": 4}, shard_weight_update=True)],
         1),
    ]


def _spearman(a: Sequence[float], b: Sequence[float]) -> float:
    """Spearman rank correlation (no scipy in the container)."""
    def ranks(xs):
        order = sorted(range(len(xs)), key=lambda i: xs[i])
        r = [0.0] * len(xs)
        for rank, i in enumerate(order):
            r[i] = float(rank)
        return r
    ra, rb = np.asarray(ranks(list(a))), np.asarray(ranks(list(b)))
    ra -= ra.mean()
    rb -= rb.mean()
    denom = float(np.sqrt((ra * ra).sum() * (rb * rb).sum()))
    return float((ra * rb).sum() / denom) if denom else 1.0


def calibration_report(machine: Optional[MachineSpec] = None,
                       hbm_budget: float = float("inf")) -> Dict[str, object]:
    """Score the 13 dryrun configs + the family checks; the gate the
    dryrun planner phase (and tests/bench) consume. A passing report
    has every config lint-clean, ``spearman >= 0.9`` against the
    frozen ledger, and every family winner correct."""
    machine = machine or MachineSpec()
    rows = []
    for name, spec, plan in dryrun_calibration_configs():
        sp = score_plan(spec, plan, machine=machine,
                        hbm_budget=hbm_budget)
        rows.append({"name": name, "ok": sp.ok,
                     "step_s": sp.step_s,
                     "findings": [f.rule for f in sp.findings],
                     "time": sp.time.to_dict() if sp.time else None})
    by_name = {r["name"]: r["step_s"] for r in rows}
    predicted = [by_name[n] for n in DRYRUN_EXPECTED_ORDER]
    spearman = _spearman(predicted, list(range(len(predicted))))
    order = [r["name"] for r in sorted(rows, key=lambda r: r["step_s"])]

    families = {}
    for fam, spec, cands, want in family_checks():
        times = [score_plan(spec, p, machine=machine,
                            hbm_budget=hbm_budget).step_s
                 for p in cands]
        got = int(np.argmin(times))
        families[fam] = {"expected": want, "got": got,
                         "ok": got == want,
                         "times": times}
    return {
        "configs": rows,
        "order": order,
        "expected_order": list(DRYRUN_EXPECTED_ORDER),
        "spearman": spearman,
        "all_lint_clean": all(r["ok"] for r in rows),
        "families": families,
        "families_ok": all(f["ok"] for f in families.values()),
        "passed": (spearman >= 0.9
                   and all(r["ok"] for r in rows)
                   and all(f["ok"] for f in families.values())),
    }
