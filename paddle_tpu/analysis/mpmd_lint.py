"""mpmd_lint — device-free model checker over MPMD pipeline event
graphs (docs/ANALYSIS.md "MPMD schedule rules").

``distributed.mpmd_graph`` extracts every compiled schedule
(FThenB/VPP/ZBH1/ZBVPP, planner ``Plan`` schedules, the sep rings and
the disagg migration path) into per-stage event programs with explicit
send/recv declarations, bounded buffers and declared dataflow deps.
This pass model-checks a graph without devices:

* ``mpmd.deadlock``          — a cycle in the happens-before relation
  (per-stage program order + matched comm edges + bounded-channel
  back-edges: the i-th send on a capacity-C route cannot run before
  the (i-C)-th recv has drained its slot).
* ``mpmd.unmatched-p2p``     — FIFO matching per route: the i-th send
  must pair with the i-th recv, tag/shape/dtype exact; orphans and
  order flips are the findings.
* ``mpmd.buffer-race``       — write-before-read-complete on a reused
  activation/grad slot (or a read of a never-written slot), walked in
  stage program order.
* ``mpmd.hbm-over-budget``   — per-stage in-flight buffer high-water
  (occupied slots x slot bytes) against the cost model's HBM budget —
  the planner rule, re-checked against the schedule's actual slot
  lifetimes.
* ``mpmd.dataflow-mismatch`` — the tick order must topologically
  linearize the declared microbatch dataflow DAG (every dep lands
  strictly earlier; every matched hop arrives a tick before its
  consumer), and the graph's tick/bubble accounting must agree with
  ``pipeline.schedule_stats``.
* ``mpmd.stale-weight``      — a W-phase weight write scheduled before
  a same-(stage, chunk) fwd still consuming the pre-update version.

Rule ids are ``mpmd.``-prefixed so the shared emit path lands them as
``lint.mpmd.*`` monitor counters. Like the other linters everything is
pure static analysis — the 8 MULTICHIP phases the pinned runtime cannot
execute are exactly the ones this makes checkable today.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from .findings import (ERROR, MPMD_BUFFER_RACE, MPMD_DATAFLOW_MISMATCH,
                       MPMD_DEADLOCK, MPMD_HBM_OVER_BUDGET, MPMD_RULES,
                       MPMD_STALE_WEIGHT, MPMD_UNMATCHED_P2P, Finding,
                       Report)


def _find(g, rule: str, message: str, suggestion: str = "") -> Finding:
    return Finding(rule=rule, severity=ERROR, message=message,
                   file=g.file, line=g.line, suggestion=suggestion)


# -- p2p matching ------------------------------------------------------------

def _match_p2p(g, report: Report):
    """FIFO matching per route; returns the matched (send_idx, recv_idx)
    comm pairs as event-index edges, plus per-route send/recv event
    lists for the capacity back-edges."""
    order: Dict[Tuple[int, int, str, int], int] = {}
    events = []
    for ev in g.events():
        order[ev.key] = len(events)
        events.append(ev)
    sends: Dict[Tuple[int, int], List[Tuple[int, object]]] = {}
    recvs: Dict[Tuple[int, int], List[Tuple[int, object]]] = {}
    for i, ev in enumerate(events):
        for msg in ev.sends:
            sends.setdefault((ev.stage, msg.peer), []).append((i, msg))
        for msg in ev.recvs:
            recvs.setdefault((msg.peer, ev.stage), []).append((i, msg))
    comm_edges: List[Tuple[int, int]] = []
    route_pairs: Dict[Tuple[int, int], List[Tuple[int, int]]] = {}
    for route in sorted(set(sends) | set(recvs)):
        ss, rr = sends.get(route, []), recvs.get(route, [])
        bad = None
        for i in range(min(len(ss), len(rr))):
            (si, sm), (ri, rm) = ss[i], rr[i]
            if sm.tag != rm.tag or sm.shape != rm.shape \
                    or sm.dtype != rm.dtype:
                bad = (f"message {i} on route {route[0]}->{route[1]} "
                       f"pairs send {sm.tag}/{sm.shape}/{sm.dtype} "
                       f"({events[si].describe()}) with recv "
                       f"{rm.tag}/{rm.shape}/{rm.dtype} "
                       f"({events[ri].describe()}) — the FIFO channel "
                       f"delivers the wrong payload")
                break
            comm_edges.append((si, ri))
            route_pairs.setdefault(route, []).append((si, ri))
        if bad is None and len(ss) != len(rr):
            kind = "send" if len(ss) > len(rr) else "recv"
            extra = abs(len(ss) - len(rr))
            ev = events[(ss if len(ss) > len(rr) else rr)[-1][0]]
            bad = (f"route {route[0]}->{route[1]} has {extra} orphan "
                   f"{kind}(s) (last: {ev.describe()}) — every send "
                   f"needs exactly one ordered matching recv")
        if bad is not None:
            report.add(_find(
                g, MPMD_UNMATCHED_P2P, bad,
                suggestion="align the send/recv schedules per route "
                           "(same count, same order, exact "
                           "shape/dtype)"))
    return events, order, comm_edges, route_pairs, sends, recvs


# -- happens-before + deadlock -----------------------------------------------

def _happens_before(g, events, comm_edges, route_pairs):
    """Edge list (a, b, strong). Strong edges are strictly-before
    (per-stage program order; a matched message must be sent before it
    is received). Channel-capacity back-edges — send i cannot deposit
    until recv i-cap drained its slot — are WEAK (before-or-
    simultaneous): the lockstep ppermute drains and refills a route's
    register in the same tick, one atomic rotate, so a pure back-edge
    cycle is exactly that simultaneous exchange, not a hazard."""
    edges: List[Tuple[int, int, bool]] = []
    idx = 0
    # event order in events is per-stage program order (g.events()),
    # so stage programs occupy contiguous index ranges
    for s in range(g.n_stages):
        prog = g.programs.get(s, ())
        for k in range(len(prog) - 1):
            edges.append((idx + k, idx + k + 1, True))
        idx += len(prog)
    for a, b in comm_edges:
        edges.append((a, b, True))
    cap_default = g.DEFAULT_CHANNEL_CAPACITY
    for route, pairs in route_pairs.items():
        cap = g.channel_capacity.get(route, cap_default)
        for i in range(cap, len(pairs)):
            edges.append((pairs[i - cap][1], pairs[i][0], False))
    return edges


def _sccs(n, edges) -> List[int]:
    """Iterative Tarjan; returns the SCC id per node."""
    adj: List[List[int]] = [[] for _ in range(n)]
    for a, b, _ in edges:
        adj[a].append(b)
    index = [0] * n
    low = [0] * n
    on = [False] * n
    comp = [-1] * n
    stack: List[int] = []
    counter = [1]
    ncomp = [0]
    for root in range(n):
        if index[root]:
            continue
        work = [(root, 0)]
        while work:
            node, pi = work[-1]
            if pi == 0:
                index[node] = low[node] = counter[0]
                counter[0] += 1
                stack.append(node)
                on[node] = True
            recurse = False
            for j in range(pi, len(adj[node])):
                nxt = adj[node][j]
                if index[nxt] == 0:
                    work[-1] = (node, j + 1)
                    work.append((nxt, 0))
                    recurse = True
                    break
                if on[nxt]:
                    low[node] = min(low[node], index[nxt])
            if recurse:
                continue
            if low[node] == index[node]:
                while True:
                    w = stack.pop()
                    on[w] = False
                    comp[w] = ncomp[0]
                    if w == node:
                        break
                ncomp[0] += 1
            work.pop()
            if work:
                p = work[-1][0]
                low[p] = min(low[p], low[node])
    return comp


def _find_deadlock(n, edges) -> Optional[List[int]]:
    """A strong (strictly-before) edge inside an SCC lies on a cycle
    that no execution order can satisfy — deadlock. Returns a witness
    cycle, or None. Pure-weak SCCs (simultaneous lockstep exchanges)
    are realizable and ignored."""
    comp = _sccs(n, edges)
    strong = None
    for a, b, is_strong in edges:
        if is_strong and comp[a] == comp[b]:
            strong = (a, b)
            break
    if strong is None:
        return None
    a, b = strong
    # witness: shortest path b -> a inside the SCC, closed by a -> b
    adj: List[List[int]] = [[] for _ in range(n)]
    for u, v, _ in edges:
        if comp[u] == comp[a] and comp[v] == comp[a]:
            adj[u].append(v)
    prev = {b: None}
    frontier = [b]
    while frontier and a not in prev:
        nxt = []
        for u in frontier:
            for v in adj[u]:
                if v not in prev:
                    prev[v] = u
                    nxt.append(v)
        frontier = nxt
    path = [a]
    while path[-1] != b and path[-1] in prev and prev[path[-1]] is not None:
        path.append(prev[path[-1]])
    path.reverse()              # b ... a
    return [a] + path[:-1] if len(path) > 1 else [a, b]


# -- buffers: races + high-water ---------------------------------------------

def _check_buffers(g, report: Report, hbm_budget: Optional[float]):
    worst = (0, None)   # (high_water_bytes, stage)
    for s in range(g.n_stages):
        pending: Dict[Tuple[str, int], int] = {}
        flagged = set()
        occupancy = 0
        high = 0
        for ev in g.programs.get(s, ()):
            for buf, slot in ev.reads:
                spec = g.buffers.get((s, buf))
                if pending.get((buf, slot), 0) > 0:
                    pending[(buf, slot)] -= 1
                    occupancy -= spec.slot_bytes if spec else 0
                elif buf not in flagged:
                    flagged.add(buf)
                    report.add(_find(
                        g, MPMD_BUFFER_RACE,
                        f"stage {s}: {ev.describe()} reads "
                        f"{buf}[{slot}] before any unconsumed write — "
                        f"the slot's value was never produced (or was "
                        f"already drained)",
                        suggestion="re-order the schedule so every "
                                   "read follows its producing write"))
            for buf, slot in ev.writes:
                spec = g.buffers.get((s, buf))
                if pending.get((buf, slot), 0) > 0 \
                        and buf not in flagged:
                    flagged.add(buf)
                    report.add(_find(
                        g, MPMD_BUFFER_RACE,
                        f"stage {s}: {ev.describe()} overwrites "
                        f"{buf}[{slot}] while a previous value is "
                        f"still unread — write-before-read-complete "
                        f"on a reused slot",
                        suggestion="give the buffer more slots or "
                                   "delay the write until the reader "
                                   "drains the slot"))
                pending[(buf, slot)] = pending.get((buf, slot), 0) + 1
                occupancy += spec.slot_bytes if spec else 0
                high = max(high, occupancy)
        if high > worst[0]:
            worst = (high, s)
    if hbm_budget is not None and worst[1] is not None \
            and worst[0] > hbm_budget:
        report.add(_find(
            g, MPMD_HBM_OVER_BUDGET,
            f"stage {worst[1]}: in-flight buffer high-water "
            f"{worst[0]} bytes exceeds the {int(hbm_budget)}-byte HBM "
            f"budget — the schedule holds too many live slots at once",
            suggestion="raise n_micro granularity, drop buffer slots, "
                       "or pick a schedule with a shorter slot "
                       "lifetime (ZBH1 drains W early)"))
    return worst[0]


# -- stale weights -----------------------------------------------------------

def _check_stale_weights(g, report: Report):
    for s in range(g.n_stages):
        w_seen = set()
        flagged = set()
        for ev in g.programs.get(s, ()):
            if ev.phase == "w":
                w_seen.add(ev.chunk)
            elif ev.phase == "fwd" and ev.chunk in w_seen \
                    and ev.chunk not in flagged:
                flagged.add(ev.chunk)
                report.add(_find(
                    g, MPMD_STALE_WEIGHT,
                    f"stage {s}: {ev.describe()} consumes chunk "
                    f"{ev.chunk} weights AFTER a W-phase write of the "
                    f"same version — the reordered update poisons the "
                    f"remaining forwards of this step",
                    suggestion="keep every W event after the last fwd "
                               "of its (stage, chunk) within the step"))


# -- dataflow linearization + bubble accounting ------------------------------

def _exec_index(g):
    """(tick, stage-local position) per event key — the lockstep
    execution order the compiled scan realizes."""
    out = {}
    for s in range(g.n_stages):
        for k, ev in enumerate(g.programs.get(s, ())):
            out[ev.key] = (ev.tick, s, k)
    return out

def _check_dataflow(g, report: Report, events, route_pairs):
    ix = _exec_index(g)
    key_ev = {ev.key: ev for ev in events}
    bad_deps = 0
    first = None
    for a, b in g.deps:
        if a not in ix or b not in ix:
            report.add(_find(
                g, MPMD_DATAFLOW_MISMATCH,
                f"dataflow dep references a missing event: "
                f"{a if a not in ix else b} — the schedule never "
                f"executes it",
                suggestion="emit every (stage, micro, phase) the "
                           "dataflow DAG requires"))
            return
        ta, tb = ix[a][0], ix[b][0]
        same_stage = a[0] == b[0]
        ok = ta < tb or (same_stage and ta == tb
                         and ix[a][2] < ix[b][2])
        if not ok:
            bad_deps += 1
            if first is None:
                first = (key_ev[a], key_ev[b])
    if bad_deps:
        a, b = first
        report.add(_find(
            g, MPMD_DATAFLOW_MISMATCH,
            f"execution order is not a topological linearization of "
            f"the dataflow DAG: {b.describe()} runs at/before its "
            f"dependency {a.describe()} ({bad_deps} violated dep(s)) "
            f"— token/grad exactness is lost",
            suggestion="re-derive the tick equations; every consumer "
                       "must tick strictly after its producer"))
        return
    # one-hop-per-tick feasibility of every matched message
    for route, pairs in route_pairs.items():
        for si, ri in pairs:
            if events[ri].tick < events[si].tick + 1:
                report.add(_find(
                    g, MPMD_DATAFLOW_MISMATCH,
                    f"message {events[si].describe()} -> "
                    f"{events[ri].describe()} on route "
                    f"{route[0]}->{route[1]} arrives the tick it is "
                    f"sent — the lockstep ring delivers one hop per "
                    f"tick",
                    suggestion="delay the consumer a tick (the "
                               "schedule is one tick too tight)"))
                return
    # bubble accounting vs pipeline.schedule_stats
    stats = g.meta.get("stats")
    if not stats or g.n_stages <= 1:
        return
    fwd_ticks = [ev.tick for ev in events if ev.phase == "fwd"]
    span = max(fwd_ticks) - min(fwd_ticks) + 1 if fwd_ticks else 0
    want_units = g.n_micro * g.vpp_degree
    per_stage = [sum(1 for ev in g.programs.get(s, ())
                     if ev.phase == "fwd")
                 for s in range(g.n_stages)]
    if span != stats["ticks"] or any(c != want_units
                                     for c in per_stage):
        report.add(_find(
            g, MPMD_DATAFLOW_MISMATCH,
            f"bubble accounting disagrees with schedule_stats"
            f"({g.schedule_mode}, S={g.n_stages}, M={g.n_micro}, "
            f"V={g.vpp_degree}): graph fwd span {span} ticks / "
            f"per-stage units {per_stage}, stats expect "
            f"{stats['ticks']} ticks / {want_units} units per stage",
            suggestion="the event graph and the compiled schedule "
                       "have drifted — re-derive the builder from "
                       "the scan body"))


# -- entry points ------------------------------------------------------------

def check_graph(graph, *, hbm_budget: Optional[float] = None,
                subject: Optional[str] = None) -> Report:
    """Run every mpmd.* rule over one event graph."""
    report = Report(subject=subject or graph.subject)
    events, order, comm_edges, route_pairs, sends, recvs = \
        _match_p2p(graph, report)
    edges = _happens_before(graph, events, comm_edges, route_pairs)
    cycle = _find_deadlock(len(events), edges)
    if cycle is not None:
        path = " -> ".join(events[i].describe() for i in cycle[:8])
        report.add(_find(
            graph, MPMD_DEADLOCK,
            f"happens-before cycle (schedule cannot make progress): "
            f"{path} -> {events[cycle[0]].describe()} — a blocked "
            f"send/recv waits on work that waits on it",
            suggestion="raise the route's channel capacity or re-order "
                       "the consumer so the bounded slot drains first"))
    _check_buffers(graph, report, hbm_budget)
    _check_stale_weights(graph, report)
    if cycle is None:
        _check_dataflow(graph, report, events, route_pairs)
    return report


def lint_mpmd(obj=None, *, spec=None, n_stages: Optional[int] = None,
              n_micro: Optional[int] = None,
              schedule_mode: Optional[str] = None,
              vpp_degree: Optional[int] = None,
              act_shape: Optional[Tuple[int, ...]] = None,
              hbm_budget: Optional[float] = None,
              subject: Optional[str] = None) -> Report:
    """Model-check a schedule or plan device-free.

    ``obj`` may be an ``MpmdGraph``, a planner ``Plan`` (with ``spec``
    for the proxy-trace dims), a ``PipelineLayer``/``PipelineParallel``
    (same resolution as ``lint_pipeline``), or ``None`` with explicit
    ``schedule_mode``/``n_stages``/``n_micro``/``vpp_degree`` kwargs."""
    from paddle_tpu.distributed import mpmd_graph as mg
    if obj is None:
        if n_stages is None or n_micro is None:
            raise ValueError("lint_mpmd() needs a graph/plan/pipeline "
                             "or explicit n_stages + n_micro")
        g = mg.schedule_graph(schedule_mode or "FThenB", n_stages,
                              n_micro, vpp_degree or 1,
                              act_shape=act_shape or (4, 16))
    elif isinstance(obj, mg.MpmdGraph):
        g = obj
    elif hasattr(obj, "degrees") and hasattr(obj, "schedule_mode"):
        from .planner import ModelSpec
        g = mg.plan_graph(spec or ModelSpec(
            "proxy", hidden=16, layers=8, seq=1,
            global_batch=4 * max(1, obj.n_micro), intermediate=16), obj)
    else:
        g = mg.pipeline_graph(obj, n_micro=n_micro,
                              schedule_mode=schedule_mode,
                              vpp_degree=vpp_degree,
                              act_shape=act_shape)
    return check_graph(g, hbm_budget=hbm_budget, subject=subject)


def emit_mpmd(report: Report) -> Report:
    """Route a lint_mpmd() report through the monitor: counts the
    check, and a non-empty report flows through the shared emit path —
    the ``mpmd.``-prefixed rule ids land as ``lint.mpmd.*`` counters."""
    from .. import monitor
    monitor.counter("lint.mpmd.checks").increase()
    if report:
        from . import emit_findings
        emit_findings(report)
    return report


__all__ = ["MPMD_RULES", "check_graph", "emit_mpmd", "lint_mpmd"]
