"""Finding/Report data model shared by both linters and the CLI.

Stdlib-only on purpose: tools/paddle_lint.py loads this file (and
ast_lint.py) directly, without importing paddle_tpu or jax, so the CLI
works on a machine that has neither installed.
"""
from __future__ import annotations

import dataclasses
import json
from typing import Dict, Iterator, List, Optional

# -- rule catalog (docs/ANALYSIS.md documents each one) ----------------------

# AST (trace-safety) rules — what dy2static's transforms reject/rewrite
TENSOR_BOOL_BRANCH = "tensor-bool-branch"
TENSOR_HOST_SYNC = "tensor-host-sync"
TENSOR_PY_CAST = "tensor-py-cast"
TENSOR_INPLACE = "tensor-inplace"
HOST_RNG = "host-rng"

# jaxpr (staged-program) rules — what the abstract trace reveals
GRAPH_BREAK = "graph-break"
TRACE_FAILED = "trace-failed"
DTYPE_PROMOTION = "dtype-promotion"
LARGE_CONSTANT = "large-constant"
DEAD_COMPUTATION = "dead-computation"
UNUSED_INPUT = "unused-input"
CONSTANT_OUTPUT = "constant-output"
UNROLLED_LOOP = "unrolled-loop"
STATIC_ARG_RECOMPILE = "static-arg-recompile"
MOE_SLOW_DISPATCH = "moe-slow-dispatch"

# shard (SPMD/collective) rules — what shard_lint's device-free trace
# under a fake mesh reveals (docs/ANALYSIS.md "shard_lint")
BAD_AXIS_NAME = "bad-axis-name"
UNALIGNED_GROUP = "unaligned-group"
INDIVISIBLE_COLLECTIVE = "indivisible-collective"
UNEVEN_SPLIT = "uneven-split"
TENSOR_LIST_ARITY = "tensor-list-arity"
P2P_IN_TRACE = "p2p-in-trace"
NON_RING_PERMUTE = "non-ring-permute"

# pipeline-schedule rules — static checks over PipelineLayer metadata
STAGE_IMBALANCE = "stage-imbalance"
BUBBLE_FRACTION = "bubble-fraction"
SEGMENT_MISMATCH = "segment-mismatch"
MICROBATCH_ARITY = "microbatch-arity"

# planner rules — the auto-parallel plan search (analysis.planner)
# reuses the shard/pipeline rules above for everything it can express
# with them; HBM is the one gate with no lint analog
HBM_OVER_BUDGET = "hbm-over-budget"

# hot-path (serving tick) rules — what hotpath_lint's executable
# inventory + scheduler-source walk reveals (docs/ANALYSIS.md
# "Hot-path rules"). Prefixed "hotpath." so the per-rule monitor
# counters land under lint.hotpath.* through the shared emit path.
MISSED_DONATION = "hotpath.missed-donation"
FETCH_SET_BLOAT = "hotpath.fetch-set-bloat"
HOST_SYNC_IN_TICK = "hotpath.host-sync-in-tick"
STEADY_TICK_UPLOAD = "hotpath.steady-tick-upload"
RECOMPILE_RISK_KEY = "hotpath.recompile-risk-key"

# MPMD schedule rules — what mpmd_lint's device-free model check over
# a distributed.mpmd_graph event graph reveals (docs/ANALYSIS.md "MPMD
# schedule rules"). Prefixed "mpmd." so the per-rule monitor counters
# land under lint.mpmd.* through the shared emit path.
MPMD_DEADLOCK = "mpmd.deadlock"
MPMD_UNMATCHED_P2P = "mpmd.unmatched-p2p"
MPMD_BUFFER_RACE = "mpmd.buffer-race"
MPMD_HBM_OVER_BUDGET = "mpmd.hbm-over-budget"
MPMD_DATAFLOW_MISMATCH = "mpmd.dataflow-mismatch"
MPMD_STALE_WEIGHT = "mpmd.stale-weight"

AST_RULES = (TENSOR_BOOL_BRANCH, TENSOR_HOST_SYNC, TENSOR_PY_CAST,
             TENSOR_INPLACE, HOST_RNG)
JAXPR_RULES = (GRAPH_BREAK, TRACE_FAILED, DTYPE_PROMOTION,
               LARGE_CONSTANT, DEAD_COMPUTATION, UNUSED_INPUT,
               CONSTANT_OUTPUT, UNROLLED_LOOP, STATIC_ARG_RECOMPILE,
               MOE_SLOW_DISPATCH)
SHARD_RULES = (BAD_AXIS_NAME, UNALIGNED_GROUP, INDIVISIBLE_COLLECTIVE,
               UNEVEN_SPLIT, TENSOR_LIST_ARITY, P2P_IN_TRACE,
               NON_RING_PERMUTE)
PIPELINE_RULES = (STAGE_IMBALANCE, BUBBLE_FRACTION, SEGMENT_MISMATCH,
                  MICROBATCH_ARITY)
PLANNER_RULES = (HBM_OVER_BUDGET,)
HOTPATH_RULES = (MISSED_DONATION, FETCH_SET_BLOAT, HOST_SYNC_IN_TICK,
                 STEADY_TICK_UPLOAD, RECOMPILE_RISK_KEY)
MPMD_RULES = (MPMD_DEADLOCK, MPMD_UNMATCHED_P2P, MPMD_BUFFER_RACE,
              MPMD_HBM_OVER_BUDGET, MPMD_DATAFLOW_MISMATCH,
              MPMD_STALE_WEIGHT)

ERROR = "error"      # will raise at trace time (a _BREAK_ERRORS member)
WARNING = "warning"  # traces, but recompiles / wastes memory / is wrong
INFO = "info"


@dataclasses.dataclass
class Finding:
    rule: str
    severity: str
    message: str
    file: str = "<unknown>"
    line: int = 0
    # the exact jit.api.StaticFunction._BREAK_ERRORS member this defect
    # raises at trace time ("" for defects that trace but misbehave)
    breaks_with: str = ""
    suggestion: str = ""

    def format(self) -> str:
        loc = f"{self.file}:{self.line}" if self.line else self.file
        out = f"{loc}: {self.severity}: [{self.rule}] {self.message}"
        if self.breaks_with:
            out += f" (raises {self.breaks_with} at trace time)"
        if self.suggestion:
            out += f" — {self.suggestion}"
        return out

    def to_dict(self) -> Dict[str, object]:
        return dataclasses.asdict(self)


class Report:
    """An ordered collection of findings with formatting helpers.

    Truthiness is "has findings", so `if report:` reads naturally in
    both the CLI (exit nonzero) and the first-compile hook."""

    def __init__(self, findings: Optional[List[Finding]] = None,
                 subject: str = ""):
        self.findings: List[Finding] = list(findings or [])
        self.subject = subject
        # optional static cost estimate (analysis.cost_model.CostEstimate
        # duck-typed: .format_table() / .to_dict()) — attached by
        # shard_lint-aware inspect paths, never required. Kept as a bare
        # attribute so this file stays stdlib-only.
        self.cost = None

    def add(self, finding: Finding):
        self.findings.append(finding)

    def extend(self, findings) -> "Report":
        for f in findings:
            self.findings.append(f)
        return self

    def __bool__(self) -> bool:
        return bool(self.findings)

    def __len__(self) -> int:
        return len(self.findings)

    def __iter__(self) -> Iterator[Finding]:
        return iter(self.findings)

    def rules(self) -> List[str]:
        seen: List[str] = []
        for f in self.findings:
            if f.rule not in seen:
                seen.append(f.rule)
        return seen

    def by_rule(self) -> Dict[str, List[Finding]]:
        out: Dict[str, List[Finding]] = {}
        for f in self.findings:
            out.setdefault(f.rule, []).append(f)
        return out

    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == ERROR]

    def format(self, cost: bool = True) -> str:
        if not self.findings:
            head = self.subject or "program"
            out = f"{head}: no findings"
        else:
            lines = []
            if self.subject:
                lines.append(f"== {self.subject}: {len(self.findings)} "
                             f"finding(s) ==")
            lines.extend(f.format() for f in self.findings)
            out = "\n".join(lines)
        if cost and self.cost is not None:
            out += "\n" + self.cost.format_table()
        return out

    def to_json(self) -> str:
        # machine contract (CI / editors): one finding per object, every
        # Finding field present, stable rule ids, plus per-rule counts
        payload = {"subject": self.subject,
                   "findings": [f.to_dict() for f in self.findings],
                   "counts": {r: len(fs)
                              for r, fs in self.by_rule().items()}}
        if self.cost is not None:
            payload["cost"] = self.cost.to_dict()
        return json.dumps(payload, indent=2)

    def __repr__(self):
        return (f"Report(subject={self.subject!r}, "
                f"findings={len(self.findings)})")
