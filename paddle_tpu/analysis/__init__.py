"""paddle_tpu.analysis — trace-time program linting (static analysis).

The static half of the correctness tooling: where `paddle_tpu.monitor`
and the profiler report graph breaks, recompiles, and waste *after*
they happen (docs/OBSERVABILITY.md), this package finds them before
anything executes:

* `ast_lint`   — the dy2static analog: walks `forward` / `to_static`
  bodies and flags code that will break (or silently poison) a trace,
  with the exact `_BREAK_ERRORS` member it will raise.
* `jaxpr_lint` — abstractly traces a function / StaticFunction /
  TrainStep via `jax.make_jaxpr` over `InputSpec`-derived shape structs
  (no device execution) and lints the staged program: dtype promotion,
  baked-in constants, dead computation, unused (donated) inputs,
  unrolled Python loops, recompile-risk static args.
* `hotpath_lint` — audits a serving surface's tick loop: the compiled
  executable inventory (donation, fetch set, cache keys) plus the
  scheduler source (host syncs, steady-tick uploads), device-free.
* `mpmd_lint`  — model-checks a pipeline schedule's MPMD event graph
  (`distributed.mpmd_graph`): deadlock, unmatched p2p, buffer races,
  HBM high-water, dataflow linearization, stale weights — the static
  verifier for schedules the pinned runtime cannot execute.

Surfaces: `StaticFunction.inspect()` / `TrainStep.inspect()` /
`Model.inspect()`, `inspect_hotpath()` on the serving engines, the
opt-in `PADDLE_TPU_LINT=1` first-compile hook, and the
dependency-free `tools/paddle_lint.py` CLI. Rule catalog:
docs/ANALYSIS.md.
"""
from __future__ import annotations

import os

from .ast_lint import (lint_callable, lint_file, lint_paths,  # noqa: F401
                       lint_source)
from .cost_model import CostEstimate, estimate_jaxpr  # noqa: F401
from .findings import (AST_RULES, ERROR, HOTPATH_RULES, INFO,  # noqa: F401
                       JAXPR_RULES, MPMD_RULES, PIPELINE_RULES,
                       SHARD_RULES, WARNING, Finding, Report)
from .hotpath_lint import (ExecutableSpec, HotpathInventory,  # noqa: F401
                           emit_hotpath, lint_inventory, lint_surface,
                           sweep_serving_stack)
from .mpmd_lint import check_graph, emit_mpmd, lint_mpmd  # noqa: F401
from .jaxpr_lint import (lint_closed_jaxpr, lint_static_args,  # noqa: F401
                         lint_static_function, lint_train_step,
                         lint_traceable, to_shape_struct)
from .planner import (MachineSpec, ModelSpec, Plan,  # noqa: F401
                      ScoredPlan, best_plan, calibration_report,
                      plan_serving, score_plan, search_plans)
from .shard_lint import (lint_pipeline, lint_records,  # noqa: F401
                         lint_sharded)


def lint_enabled() -> bool:
    """True when the opt-in first-compile lint hook is on
    (``PADDLE_TPU_LINT=1``)."""
    return os.environ.get("PADDLE_TPU_LINT", "0").lower() in (
        "1", "true", "yes", "on")


def lint_on_first_compile(inspect_fn, *args, **kwargs):
    """Shared first-compile hook body for StaticFunction and TrainStep:
    opt-in via PADDLE_TPU_LINT=1, and never allowed to break the
    compiling call."""
    if not lint_enabled():
        return
    try:
        emit_findings(inspect_fn(*args, **kwargs))
    except Exception:
        pass


def emit_findings(report: Report) -> Report:
    """Route a lint report through paddle_tpu.monitor (counters per
    rule, lint.cost.* gauges for an attached cost estimate) and warn
    once with the formatted findings. Used by the first-compile hook;
    cheap no-op for an empty cost-less report."""
    if report.cost is not None:
        from .cost_model import emit_cost
        emit_cost(report.cost)
    if not report:
        return report
    from .. import monitor
    monitor.counter("lint.findings").increase(len(report))
    for rule, fs in report.by_rule().items():
        monitor.counter(f"lint.{rule}").increase(len(fs))
    import warnings
    warnings.warn(f"[paddle_tpu.analysis]\n{report.format()}",
                  stacklevel=3)
    return report
