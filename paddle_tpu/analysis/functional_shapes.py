"""Shape-only views of layer/optimizer state for abstract tracing.

The jaxpr linter traces the same pure functions jit.api compiles, but
with every array replaced by a `jax.ShapeDtypeStruct` — shapes and
dtypes in, no buffers touched, nothing executed on device.
"""
from __future__ import annotations

import jax


def tree_structs(tree):
    """Replace every array leaf of a pytree with its ShapeDtypeStruct."""
    return jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype)
        if hasattr(a, "shape") else a, tree)


def rng_key_struct():
    """ShapeDtypeStruct of a framework PRNG key WITHOUT consuming one:
    inspect() must not advance the random stream (a lint must never
    change the program's numbers)."""
    return jax.eval_shape(lambda: jax.random.key(0))


def layer_state_structs(layer):
    """(params, buffers, frozen) as ShapeDtypeStruct pytrees, matching
    jit.functional.get_params/get_buffers/get_frozen."""
    from ..jit.functional import get_buffers, get_frozen, get_params
    return (tree_structs(get_params(layer)),
            tree_structs(get_buffers(layer)),
            tree_structs(get_frozen(layer)))
