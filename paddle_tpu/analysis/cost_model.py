"""Static cost model over a closed jaxpr — bytes moved, FLOPs, peak HBM.

The runtime profiler measures these after a step has executed; this
pass derives the same three numbers from the abstract trace alone, so a
partition plan can be rejected before any device is attached (the
plan-selection move of arXiv 2112.01075 / 2412.14374, surfaced as lint
output instead of a planner).

All estimates are per *rank* when the jaxpr came from a shard_map
manual region (shapes in the jaxpr are already per-device there) and
global otherwise — shard_lint's entry points trace through shard_map,
so its reports are per-rank. Plain-jit traces under a mesh
(`inspect(mesh=...)`) carry a `note` saying so: GSPMD-auto programs
get their collectives from the XLA partitioner, which a static jaxpr
walk cannot see.

Deliberately distinct from `paddle_tpu.cost_model` (the roofline
CostModel): that package turns op shapes into *time* on a specific
chip (peak FLOP/s, HBM/ICI bandwidth, in-place calibration); this one
derives *counts* (bytes, FLOPs, live bytes) from a program. Feed these
counts into `CostModel.collective_time`/`matmul_time` to get seconds —
the ring factors here and there must agree.

Formulas (docs/ANALYSIS.md "cost model"):

* collective bytes, per rank, for an n-device axis group over an
  operand of b bytes:
    - psum / pmax / pmin (all_reduce):   2 * b * (n-1)/n   (ring)
    - all_gather:                        b * (n-1)          (b = shard)
    - psum_scatter (reduce_scatter):     b * (n-1)/n
    - all_to_all:                        b * (n-1)/n
    - ppermute (send+recv one hop):      b
* FLOPs: 2*M*N*K per dot_general contraction (x batch),
  2 * out_numel * (Cin/groups * prod(kernel)) per conv, 1 FLOP per
  output element for everything else that computes.
* peak HBM: liveness walk over the equations in program order —
  allocate outvars, free invars at their last use; the running
  maximum plus closed-over constants is the estimate. Control-flow
  bodies (scan/cond/pjit/shard_map) contribute max(inner peak) on top
  of the live set at their call site.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional

import jax
import numpy as np

# primitives that move data across mesh axes, with their per-rank byte
# multiplier as a function of the axis-group size n
_COLLECTIVE_FACTORS = {
    "psum": lambda n: 2.0 * (n - 1) / n,
    "pmax": lambda n: 2.0 * (n - 1) / n,
    "pmin": lambda n: 2.0 * (n - 1) / n,
    "all_gather": lambda n: float(n - 1),
    "psum_scatter": lambda n: (n - 1) / n,
    "reduce_scatter": lambda n: (n - 1) / n,
    "all_to_all": lambda n: (n - 1) / n,
    "ppermute": lambda n: 1.0,
    "pshuffle": lambda n: 1.0,
}

# pure layout/metadata plumbing: zero FLOPs
_ZERO_FLOP = {"broadcast_in_dim", "reshape", "convert_element_type",
              "squeeze", "expand_dims", "transpose", "slice", "iota",
              "copy", "stop_gradient", "pvary", "pcast", "constant",
              "dynamic_slice", "dynamic_update_slice", "concatenate",
              "gather", "scatter", "pad", "rev", "device_put",
              "sharding_constraint"}


def _nbytes(aval) -> int:
    shape = tuple(getattr(aval, "shape", ()) or ())
    try:
        itemsize = np.dtype(aval.dtype).itemsize
    except TypeError:
        itemsize = 2 if str(getattr(aval, "dtype", "")) == "bfloat16" else 4
    return int(math.prod(shape)) * itemsize


def axis_sizes(mesh) -> Dict[str, int]:
    """{axis name: degree} for a jax Mesh OR AbstractMesh (device-free).
    One implementation only — distributed.mesh owns it."""
    if mesh is None:
        return {}
    from ..distributed.mesh import mesh_axis_sizes
    return mesh_axis_sizes(mesh)


def _eqn_axes(eqn) -> tuple:
    """Mesh axis names a collective eqn moves data over."""
    axes = eqn.params.get("axes") or eqn.params.get("axis_name") or ()
    if not isinstance(axes, (tuple, list)):
        axes = (axes,)
    return tuple(str(ax) for ax in axes)


def _group_size(eqn, sizes: Dict[str, int]) -> int:
    n = 1
    for ax in _eqn_axes(eqn):
        n *= int(sizes.get(ax, 1))
    groups = eqn.params.get("axis_index_groups")
    if groups:
        n = len(groups[0])
    return max(n, 1)


def _dot_flops(eqn) -> float:
    ((lc, rc), (lb, rb)) = eqn.params["dimension_numbers"]
    lhs = eqn.invars[0].aval.shape
    rhs = eqn.invars[1].aval.shape
    batch = math.prod(lhs[i] for i in lb) if lb else 1
    k = math.prod(lhs[i] for i in lc) if lc else 1
    m = math.prod(d for i, d in enumerate(lhs) if i not in lc and i not in lb)
    n = math.prod(d for i, d in enumerate(rhs) if i not in rc and i not in rb)
    return 2.0 * batch * m * n * k


def _conv_flops(eqn) -> float:
    out = eqn.outvars[0].aval.shape
    rhs = eqn.invars[1].aval.shape  # kernel, layout-dependent
    # MACs = out_numel * (kernel numel / out_channels) regardless of the
    # dimension_numbers layout: kernel numel already folds Cin/groups,
    # so NO extra division by feature_group_count
    out_numel = math.prod(out)
    kernel = math.prod(rhs)
    dn = eqn.params.get("dimension_numbers")
    if hasattr(dn, "rhs_spec"):  # rhs_spec[0] = kernel out-channel dim
        out_ch = rhs[dn.rhs_spec[0]]
    else:
        out_ch = max(1, min(rhs))  # conservative when layout is unknown
    return 2.0 * out_numel * (kernel / max(out_ch, 1))


@dataclasses.dataclass
class CostEstimate:
    """Static per-rank cost of one staged program."""
    flops: float = 0.0
    collective_bytes: Dict[str, float] = dataclasses.field(
        default_factory=dict)
    collective_calls: Dict[str, int] = dataclasses.field(
        default_factory=dict)
    # same bytes keyed by the mesh axes they ride ("dp", "dp,sharding",
    # ...) — the planner's tier split (ICI vs DCN) reads this
    collective_bytes_by_axis: Dict[str, float] = dataclasses.field(
        default_factory=dict)
    peak_hbm_bytes: float = 0.0
    n_devices: int = 1
    # qualifier printed with the table, e.g. the GSPMD-auto caveat (the
    # partitioner inserts collectives this static walk cannot see)
    note: str = ""

    @property
    def total_collective_bytes(self) -> float:
        return float(sum(self.collective_bytes.values()))

    def tier_bytes(self, dcn_axes=()) -> "tuple[float, float]":
        """Split the per-rank collective bytes into (ici, dcn) tiers: a
        collective whose group touches ANY axis in `dcn_axes` is charged
        to the DCN tier wholesale (its ring spans slices, so the slow
        hop gates the whole rotation)."""
        dcn_axes = set(dcn_axes)
        ici = dcn = 0.0
        for key, b in self.collective_bytes_by_axis.items():
            if dcn_axes and set(key.split(",")) & dcn_axes:
                dcn += b
            else:
                ici += b
        return ici, dcn

    def merge(self, other: "CostEstimate") -> "CostEstimate":
        self.flops += other.flops
        for k, v in other.collective_bytes.items():
            self.collective_bytes[k] = self.collective_bytes.get(k, 0) + v
        for k, v in other.collective_calls.items():
            self.collective_calls[k] = self.collective_calls.get(k, 0) + v
        for k, v in other.collective_bytes_by_axis.items():
            self.collective_bytes_by_axis[k] = \
                self.collective_bytes_by_axis.get(k, 0) + v
        self.peak_hbm_bytes = max(self.peak_hbm_bytes, other.peak_hbm_bytes)
        self.n_devices = max(self.n_devices, other.n_devices)
        self.note = self.note or other.note
        return self

    def to_dict(self) -> Dict[str, object]:
        return {
            "flops": self.flops,
            "collective_bytes": dict(self.collective_bytes),
            "collective_calls": dict(self.collective_calls),
            "collective_bytes_by_axis": dict(self.collective_bytes_by_axis),
            "total_collective_bytes": self.total_collective_bytes,
            "peak_hbm_bytes": self.peak_hbm_bytes,
            "n_devices": self.n_devices,
            "note": self.note,
        }

    @staticmethod
    def _human(n: float) -> str:
        for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
            if abs(n) < 1024 or unit == "TiB":
                return f"{n:.1f} {unit}" if unit != "B" \
                    else f"{n:.0f} {unit}"
            n /= 1024.0
        return f"{n:.1f} TiB"

    def format_table(self) -> str:
        lines = ["-- static cost (per rank) --",
                 f"  flops            {self.flops:.3e}",
                 f"  peak HBM         {self._human(self.peak_hbm_bytes)}"]
        if self.collective_bytes:
            lines.append(f"  collective bytes "
                         f"{self._human(self.total_collective_bytes)}")
            for kind in sorted(self.collective_bytes):
                lines.append(
                    f"    {kind:<14} {self.collective_calls[kind]:>4} "
                    f"call(s)  {self._human(self.collective_bytes[kind])}")
        else:
            lines.append("  collective bytes 0 B (no explicit "
                         "collectives traced)")
        if self.note:
            lines.append(f"  note: {self.note}")
        return "\n".join(lines)


def _walk(jaxpr, sizes: Dict[str, int], est: CostEstimate,
          repeat: float = 1.0) -> float:
    """Accumulate flops/bytes of `jaxpr` into `est` and return its peak
    live-bytes estimate (invars/consts excluded — charged by caller)."""
    # last-use index per var id for the liveness walk
    last_use: Dict[int, int] = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if not isinstance(v, jax.core.Literal):
                last_use[id(v)] = i
    for v in jaxpr.outvars:
        last_use[id(v)] = len(jaxpr.eqns)

    live: Dict[int, int] = {}
    peak = 0.0
    for i, eqn in enumerate(jaxpr.eqns):
        name = eqn.primitive.name
        inner_peak = 0.0
        inner_repeat = repeat
        sub = []
        if name == "scan":
            inner_repeat *= int(eqn.params.get("length", 1) or 1)
        for p in eqn.params.values():
            if isinstance(p, jax.core.ClosedJaxpr):
                sub.append(p.jaxpr)
            elif isinstance(p, jax.core.Jaxpr):
                sub.append(p)
            elif isinstance(p, (list, tuple)):
                sub.extend(q.jaxpr if isinstance(q, jax.core.ClosedJaxpr)
                           else q for q in p
                           if isinstance(q, (jax.core.Jaxpr,
                                             jax.core.ClosedJaxpr)))
        if name == "cond":
            # branches are alternatives: flops of the widest branch,
            # peak of the most memory-hungry one (they may differ)
            branch_est = []
            for s in sub:
                e = CostEstimate()
                pk = _walk(s, sizes, e, repeat)
                branch_est.append((e, pk))
            if branch_est:
                widest, _ = max(branch_est, key=lambda t: t[0].flops)
                est.merge(widest)
                inner_peak = max(pk for _, pk in branch_est)
        else:
            for s in sub:
                inner_peak = max(inner_peak,
                                 _walk(s, sizes, est, inner_repeat))

        if name in _COLLECTIVE_FACTORS:
            n = _group_size(eqn, sizes)
            if n > 1:
                b = sum(_nbytes(v.aval) for v in eqn.invars
                        if hasattr(v, "aval"))
                kind = "all_reduce" if name in ("psum", "pmax", "pmin") \
                    else name
                moved = _COLLECTIVE_FACTORS[name](n) * b * repeat
                est.collective_bytes[kind] = \
                    est.collective_bytes.get(kind, 0.0) + moved
                est.collective_calls[kind] = \
                    est.collective_calls.get(kind, 0) + int(repeat)
                axes_key = ",".join(_eqn_axes(eqn)) or "<group>"
                est.collective_bytes_by_axis[axes_key] = \
                    est.collective_bytes_by_axis.get(axes_key, 0.0) + moved
                est.n_devices = max(est.n_devices, n)
        elif name == "dot_general":
            est.flops += _dot_flops(eqn) * repeat
        elif name == "conv_general_dilated":
            est.flops += _conv_flops(eqn) * repeat
        elif not sub and name not in _ZERO_FLOP:
            est.flops += sum(
                math.prod(getattr(v.aval, "shape", ()) or ())
                for v in eqn.outvars if hasattr(v, "aval")) * repeat

        # liveness accounting
        for v in eqn.outvars:
            if hasattr(v, "aval"):
                live[id(v)] = _nbytes(v.aval)
        peak = max(peak, sum(live.values()) + inner_peak)
        for v in list(eqn.invars) + list(eqn.outvars):
            if not isinstance(v, jax.core.Literal) \
                    and last_use.get(id(v), -1) <= i:
                live.pop(id(v), None)
    return peak


def estimate_jaxpr(closed, mesh=None) -> CostEstimate:
    """Static cost of a ClosedJaxpr: FLOPs, per-collective bytes moved,
    and a peak-HBM estimate. Never executes anything."""
    est = CostEstimate()
    sizes = axis_sizes(mesh)
    est.n_devices = max(1, math.prod(sizes.values()) if sizes else 1)
    base = sum(_nbytes(v.aval) for v in closed.jaxpr.invars)
    base += sum(int(getattr(c, "nbytes", 0)) for c in closed.consts)
    inner = _walk(closed.jaxpr, sizes, est)
    est.peak_hbm_bytes = base + inner
    return est


def emit_cost(est: Optional[CostEstimate]):
    """Publish a cost estimate as lint.cost.* monitor gauges (same
    registry the runtime telemetry uses, docs/OBSERVABILITY.md)."""
    if est is None:
        return
    from .. import monitor
    monitor.gauge("lint.cost.flops").set(est.flops)
    monitor.gauge("lint.cost.collective_bytes").set(
        est.total_collective_bytes)
    monitor.gauge("lint.cost.peak_hbm_bytes").set(est.peak_hbm_bytes)
