"""Jaxpr linter — abstract-trace a program and lint the staged IR.

The runtime telemetry layer (docs/OBSERVABILITY.md) reports graph
breaks, recompiles, and waste *after* they have cost a trace or a
compile. This pass gets the same signals ahead of time: the function is
traced with `jax.make_jaxpr` over `ShapeDtypeStruct`s (derived from
`InputSpec`s or sample inputs) — no device execution, no compile — and
rule passes walk the resulting jaxpr:

* dtype-promotion     — silent upcasts (f32->f64 under x64, f16/bf16
                        compute promoted to f32 by a stray numpy scalar)
* large-constant      — big arrays closed over and baked into every
                        executable copy of the program
* dead-computation    — equations unreachable from any output (traced,
                        compiled, executed for nothing)
* unused-input        — inputs (incl. donated ones) no output depends on
* constant-output     — outputs that do not depend on any input
* unrolled-loop       — long runs of identical equation blocks, the
                        signature of a Python loop traced inline
* static-arg-recompile— Python scalars in the call signature: every
                        distinct value is a new XLA executable

Entry points `lint_traceable` (plain fn), `lint_static_function`, and
`lint_train_step` mirror the three compile surfaces in paddle_tpu.jit.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from .findings import (CONSTANT_OUTPUT, DEAD_COMPUTATION, DTYPE_PROMOTION,
                       ERROR, GRAPH_BREAK, INFO, LARGE_CONSTANT,
                       MOE_SLOW_DISPATCH, STATIC_ARG_RECOMPILE,
                       TRACE_FAILED, UNROLLED_LOOP, UNUSED_INPUT, WARNING,
                       Finding, Report)

def _break_errors():
    """jit.api's graph-break error set, not a copy — hitting one during
    the ABSTRACT trace is the linter predicting the runtime break, and
    the two sets must never diverge."""
    from ..jit.api import StaticFunction
    return StaticFunction._BREAK_ERRORS


def _abstract_trace(report: Report, fn, *args, **kwargs):
    """make_jaxpr that converts trace failures into findings instead of
    raising: inspect() must stay total on exactly the programs it
    exists to diagnose. Returns (closed_jaxpr, out_shape) or None."""
    break_errors = _break_errors()
    try:
        return jax.make_jaxpr(fn, return_shape=True)(*args, **kwargs)
    except break_errors as exc:
        first = str(exc).strip().splitlines()[0]
        report.add(Finding(
            rule=GRAPH_BREAK, severity=ERROR,
            message=f"the trace itself breaks: {first}",
            breaks_with=type(exc).__name__,
            suggestion="at runtime this call falls back to eager "
                       "(sublayer-segmented for Layers); restructure with "
                       "static.nn.cond/while_loop to keep it compiled"))
        return None
    except Exception as exc:  # infra/shape artifact — report, don't raise
        first = str(exc).strip().splitlines()[0]
        report.add(Finding(
            rule=TRACE_FAILED, severity=WARNING,
            message=f"abstract trace failed "
                    f"({type(exc).__name__}): {first}",
            suggestion="jaxpr rules were skipped; check the example "
                       "shapes/specs match what the function expects"))
        return None

# a closed-over constant this big belongs in the arguments (XLA embeds
# consts into the executable; donation can't reuse their memory)
CONST_BYTES_THRESHOLD = 256 * 1024
# identical equation blocks repeated this many times = Python loop
# unrolled into the trace (stacked same-shape layers below this count
# are normal model structure, not a finding)
UNROLL_MIN_REPEATS = 8
UNROLL_MAX_PERIOD = 64


def _float_width(dtype) -> int:
    try:
        d = np.dtype(dtype)
    except TypeError:
        return 0
    if d.kind == 'f':
        return d.itemsize * 8
    if str(dtype) == "bfloat16":
        return 16
    return 0


_FRAMEWORK_DIRS = (f"paddle_tpu{os.sep}ops", f"paddle_tpu{os.sep}core",
                   f"paddle_tpu{os.sep}nn", f"paddle_tpu{os.sep}jit",
                   f"paddle_tpu{os.sep}analysis")


def _eqn_loc(eqn) -> Tuple[str, int]:
    """Best-effort *user* file:line for an equation via jax source
    info — skipping paddle_tpu's own dispatch/op wrappers so findings
    point at model code, not the framework frame that issued the
    primitive."""
    try:
        from jax._src import source_info_util
        frames = list(source_info_util.user_frames(eqn.source_info))
        for frame in frames:
            if not any(d in frame.file_name for d in _FRAMEWORK_DIRS):
                return frame.file_name, frame.start_line
        if frames:
            return frames[0].file_name, frames[0].start_line
    except Exception:
        pass
    return "<jaxpr>", 0


def _eqn_sig(eqn) -> tuple:
    """Structural signature for repeated-block detection."""
    def aval_sig(v):
        aval = getattr(v, "aval", None)
        if aval is None:  # Literal
            return ("lit", repr(getattr(v, "val", v)))
        return (tuple(getattr(aval, "shape", ())),
                str(getattr(aval, "dtype", "?")))
    name = eqn.primitive.name
    if name == "pjit":  # jnp ops like cumsum hide behind pjit
        name = f"pjit:{eqn.params.get('name', '?')}"
    return (name,
            tuple(aval_sig(v) for v in eqn.invars),
            tuple(aval_sig(v) for v in eqn.outvars))


def _walk_eqns(jaxpr):
    """Yield equations of `jaxpr` and every sub-jaxpr (scan/cond/pjit
    bodies), so dtype rules see through structured control flow."""
    for eqn in jaxpr.eqns:
        yield eqn
        for p in eqn.params.values():
            for sub in _subjaxprs(p):
                yield from _walk_eqns(sub)


def _subjaxprs(p):
    core = jax.core
    if isinstance(p, core.ClosedJaxpr):
        yield p.jaxpr
    elif isinstance(p, core.Jaxpr):
        yield p
    elif isinstance(p, (list, tuple)):
        for item in p:
            yield from _subjaxprs(item)


# -- rule passes -------------------------------------------------------------

def _check_promotion(closed, findings: List[Finding]):
    jaxpr = closed.jaxpr
    widths = [_float_width(v.aval.dtype) for v in jaxpr.invars]
    # read const dtypes WITHOUT np.asarray: that would device-to-host
    # copy exactly the large baked arrays the next rule flags
    widths += [_float_width(getattr(c, "dtype", np.float32))
               for c in closed.consts
               if hasattr(c, "dtype") or isinstance(c, float)]
    base = max([w for w in widths if w], default=32)
    seen = set()
    for eqn in _walk_eqns(jaxpr):
        local = [_float_width(v.aval.dtype) for v in eqn.invars
                 if getattr(v, "aval", None) is not None]
        local_max = max([w for w in local if w], default=0)
        for out in eqn.outvars:
            aval = getattr(out, "aval", None)
            if aval is None:
                continue
            w = _float_width(getattr(aval, "dtype", None))
            if w <= base or w <= local_max:
                continue  # only the eqn doing the widening, once
            in_dtypes = sorted({str(v.aval.dtype) for v in eqn.invars
                                if getattr(v, "aval", None) is not None
                                and _float_width(v.aval.dtype)})
            key = (str(aval.dtype), tuple(in_dtypes))
            if key in seen:
                continue
            seen.add(key)
            fname, line = _eqn_loc(eqn)
            src = in_dtypes[0] if in_dtypes else f"float{base}"
            findings.append(Finding(
                rule=DTYPE_PROMOTION, severity=WARNING,
                message=f"silent dtype promotion {src} -> {aval.dtype} in "
                        f"'{eqn.primitive.name}' (widest input float is "
                        f"float{base})",
                file=fname, line=line,
                suggestion="a Python/numpy scalar or x64 mode is widening "
                           "the compute dtype; cast the constant to the "
                           "input dtype"))


def _check_large_consts(closed, findings: List[Finding],
                        threshold: int):
    for c in closed.consts:
        nbytes = getattr(c, "nbytes", 0)
        if nbytes >= threshold:
            findings.append(Finding(
                rule=LARGE_CONSTANT, severity=WARNING,
                message=f"{nbytes / 1024:.0f} KiB constant "
                        f"{tuple(getattr(c, 'shape', ()))} closed over and "
                        f"baked into the executable",
                suggestion="pass it as an argument (and donate it) instead "
                           "of capturing it — every signature's executable "
                           "embeds its own copy"))


def _live_eqn_mask(jaxpr) -> List[bool]:
    live_vars = {id(v) for v in jaxpr.outvars if hasattr(v, "aval")}
    mask = [False] * len(jaxpr.eqns)
    for i in range(len(jaxpr.eqns) - 1, -1, -1):
        eqn = jaxpr.eqns[i]
        if eqn.effects or any(id(v) in live_vars for v in eqn.outvars):
            mask[i] = True
            for v in eqn.invars:
                if hasattr(v, "aval") and not isinstance(v, jax.core.Literal):
                    live_vars.add(id(v))
    return mask


# dead eqns of these primitives are free: layout/shape plumbing that
# XLA's own DCE strips before codegen. Autodiff partial-eval routinely
# leaves dead broadcasts behind in grad programs — only dead COMPUTE
# equations are worth a finding.
_TRIVIAL_DEAD = {"broadcast_in_dim", "reshape", "convert_element_type",
                 "squeeze", "expand_dims", "transpose", "slice", "iota",
                 "copy", "stop_gradient"}


def _check_dead_code(closed, findings: List[Finding]):
    jaxpr = closed.jaxpr
    mask = _live_eqn_mask(jaxpr)
    dead = [jaxpr.eqns[i] for i, alive in enumerate(mask)
            if not alive
            and jaxpr.eqns[i].primitive.name not in _TRIVIAL_DEAD]
    if not dead:
        return
    by_loc: Dict[Tuple[str, int], List[str]] = {}
    for eqn in dead:
        by_loc.setdefault(_eqn_loc(eqn), []).append(eqn.primitive.name)
    for (fname, line), prims in sorted(by_loc.items()):
        names = ", ".join(sorted(set(prims))[:4])
        findings.append(Finding(
            rule=DEAD_COMPUTATION, severity=WARNING,
            message=f"{len(prims)} equation(s) ({names}) feed no output — "
                    "traced and compiled for nothing",
            file=fname, line=line,
            suggestion="drop the computation or return its result"))


def _used_var_ids(jaxpr) -> set:
    used = set()
    for eqn in jaxpr.eqns:
        for v in eqn.invars:
            if not isinstance(v, jax.core.Literal):
                used.add(id(v))
    for v in jaxpr.outvars:
        if hasattr(v, "aval") and not isinstance(v, jax.core.Literal):
            used.add(id(v))
    return used


def _check_unused_inputs(closed, findings: List[Finding],
                         check_idx: Sequence[int],
                         labels: Dict[int, str],
                         donated: Sequence[int] = ()):
    jaxpr = closed.jaxpr
    used = _used_var_ids(jaxpr)
    donated = set(donated)
    for i in check_idx:
        v = jaxpr.invars[i]
        if id(v) in used:
            continue
        name = labels.get(i, f"input #{i}")
        aval = v.aval
        if i in donated:
            findings.append(Finding(
                rule=UNUSED_INPUT, severity=WARNING,
                message=f"donated input {name} "
                        f"({tuple(aval.shape)}:{aval.dtype}) is never used "
                        "— its buffer is freed for nothing",
                suggestion="remove it from the step signature or stop "
                           "donating it"))
        else:
            findings.append(Finding(
                rule=UNUSED_INPUT, severity=WARNING,
                message=f"{name} ({tuple(aval.shape)}:{aval.dtype}) "
                        "does not contribute to any output",
                suggestion="remove the argument, or check for a "
                           "shadowed/overwritten name in the function body"))


def _check_constant_outputs(closed, findings: List[Finding],
                            n_user_out: Optional[int]):
    jaxpr = closed.jaxpr
    reachable = {id(v) for v in jaxpr.invars}
    for eqn in jaxpr.eqns:
        if any(not isinstance(v, jax.core.Literal) and id(v) in reachable
               for v in eqn.invars):
            for v in eqn.outvars:
                reachable.add(id(v))
    outs = jaxpr.outvars if n_user_out is None \
        else jaxpr.outvars[:n_user_out]
    for k, v in enumerate(outs):
        is_const = isinstance(v, jax.core.Literal) or id(v) not in reachable
        if is_const:
            aval = getattr(v, "aval", None)
            desc = (f"({tuple(aval.shape)}:{aval.dtype})"
                    if aval is not None else f"= {getattr(v, 'val', '?')!r}")
            findings.append(Finding(
                rule=CONSTANT_OUTPUT, severity=WARNING,
                message=f"output #{k} {desc} does not depend on any input "
                        "— it is a trace-time constant",
                suggestion="compute it once outside the compiled function"))


def _check_unrolled(closed, findings: List[Finding],
                    min_repeats: int):
    sigs = [_eqn_sig(e) for e in closed.jaxpr.eqns]
    n = len(sigs)
    best = None  # (repeats, period, end)
    for period in range(1, min(UNROLL_MAX_PERIOD, n // 2) + 1):
        run = 0
        for i in range(n - period):
            run = run + 1 if sigs[i] == sigs[i + period] else 0
            repeats = run // period + 1
            if repeats >= min_repeats and (
                    best is None or repeats > best[0]):
                best = (repeats, period, i + period)
    if best is None:
        return
    repeats, period, end = best
    start = end - period + 1  # one representative block
    eqn = closed.jaxpr.eqns[start]
    fname, line = _eqn_loc(eqn)
    prims = [s[0] for s in sigs[start:start + period]]
    findings.append(Finding(
        rule=UNROLLED_LOOP, severity=WARNING,
        message=f"a block of {period} equation(s) "
                f"({', '.join(prims[:4])}{'...' if period > 4 else ''}) "
                f"repeats {repeats}x with identical shapes — a Python "
                "loop unrolled into the trace",
        file=fname, line=line,
        suggestion="roll it with lax.scan / paddle.static.nn.while_loop: "
                   "same math, ~1/N the trace+compile time"))


# the named-jit dispatch/combine implementations MoELayer stages per
# mode (incubate/distributed/models/moe/moe_layer.py): their pjit
# equations carry the function name, which is how a traced program
# reveals which MoE dispatch it baked in
_MOE_SLOW_DISPATCH_FNS = {"moe_dispatch_einsum": "einsum",
                          "moe_dispatch_scatter": "scatter"}


def _check_moe_dispatch(closed, findings: List[Finding]):
    """Perf rule (mirrors the recompile-risk rule's advisory role): an
    einsum/scatter MoE dispatch inside a traced program is the
    O(N*E*C*H) / no-dead-slot-skipping path — dispatch_mode="pallas"
    runs the fused grouped-matmul kernel instead (docs/KERNELS.md).
    One finding per dispatch mode found, at the first occurrence."""
    seen = set()
    for eqn in _walk_eqns(closed.jaxpr):
        if eqn.primitive.name != "pjit":
            continue
        mode = _MOE_SLOW_DISPATCH_FNS.get(eqn.params.get("name"))
        if mode is None or mode in seen:
            continue
        seen.add(mode)
        fname, line = _eqn_loc(eqn)
        findings.append(Finding(
            rule=MOE_SLOW_DISPATCH, severity=INFO,
            message=f"MoE '{mode}' dispatch traced into this program "
                    "— token movement and the expert FFN run unfused "
                    "(dead capacity slots still pay full FLOPs)",
            file=fname, line=line,
            suggestion="construct the MoELayer with "
                       "dispatch_mode='pallas' (the default) so the "
                       "fused grouped-matmul kernel serves eligible "
                       "geometries — note a pallas-mode layer that "
                       "LEGITIMATELY degraded (ep-sharded mesh, "
                       "non-TPU trace) also stages this path; "
                       "kernels.moe.dispatch_path.fallback.* names "
                       "the reason"))


def lint_closed_jaxpr(closed, *,
                      user_invar_idx: Optional[Sequence[int]] = None,
                      invar_labels: Optional[Dict[int, str]] = None,
                      donated_idx: Sequence[int] = (),
                      n_user_out: Optional[int] = None,
                      const_bytes_threshold: int = CONST_BYTES_THRESHOLD,
                      unroll_min_repeats: int = UNROLL_MIN_REPEATS
                      ) -> List[Finding]:
    """Run every jaxpr rule pass over a ClosedJaxpr."""
    findings: List[Finding] = []
    if user_invar_idx is None:
        user_invar_idx = range(len(closed.jaxpr.invars))
    _check_promotion(closed, findings)
    _check_large_consts(closed, findings, const_bytes_threshold)
    _check_dead_code(closed, findings)
    _check_unused_inputs(closed, findings, user_invar_idx,
                         invar_labels or {}, donated_idx)
    _check_constant_outputs(closed, findings, n_user_out)
    _check_unrolled(closed, findings, unroll_min_repeats)
    _check_moe_dispatch(closed, findings)
    return findings


# -- spec handling -----------------------------------------------------------

def to_shape_struct(x, fill_dim: int = 2):
    """InputSpec / Tensor / array / ShapeDtypeStruct -> ShapeDtypeStruct.
    Returns None for host-side Python values (static args). Unknown
    InputSpec dims (None / -1) are filled with `fill_dim` — rule passes
    only need a representative concrete shape."""
    from ..core.tensor import Tensor
    from ..jit.api import InputSpec
    if isinstance(x, jax.ShapeDtypeStruct):
        return x
    if isinstance(x, InputSpec):
        from ..core import dtype as dtype_mod
        shape = tuple(fill_dim if d in (None, -1) else int(d)
                      for d in x.shape)
        return jax.ShapeDtypeStruct(shape, dtype_mod.dtype(x.dtype).np_dtype)
    if isinstance(x, Tensor):
        return jax.ShapeDtypeStruct(x._data.shape, x._data.dtype)
    if isinstance(x, (jax.Array, np.ndarray)):
        return jax.ShapeDtypeStruct(x.shape, x.dtype)
    return None


def _scalar_struct(v):
    if isinstance(v, bool):
        return jax.ShapeDtypeStruct((), np.bool_)
    if isinstance(v, int):
        return jax.ShapeDtypeStruct((), np.int32)
    if isinstance(v, float):
        return jax.ShapeDtypeStruct((), np.float32)
    return None


def lint_static_args(args, kwargs=None) -> List[Finding]:
    """The recompile-risk rule: every Python scalar in the example call
    lands in `_sig_of` by value — each distinct value is a separate
    trace + XLA compile."""
    findings: List[Finding] = []
    items = [(f"positional arg #{i}", a) for i, a in enumerate(args)]
    items += [(f"kwarg '{k}'", v) for k, v in sorted((kwargs or {}).items())]
    for where, v in items:
        if to_shape_struct(v) is not None or v is None:
            continue
        if isinstance(v, float) and not isinstance(v, bool):
            findings.append(Finding(
                rule=STATIC_ARG_RECOMPILE, severity=WARNING,
                message=f"{where} is a Python float ({v!r}): every "
                        "distinct value compiles a NEW executable "
                        "(float-valued keys explode the signature cache)",
                suggestion="pass it as a 0-d tensor "
                           "(paddle.to_tensor(v)) so one executable "
                           "serves all values"))
        elif isinstance(v, (bool, int, str)):
            findings.append(Finding(
                rule=STATIC_ARG_RECOMPILE, severity=INFO,
                message=f"{where} is a static {type(v).__name__} "
                        f"({v!r}): each distinct value is a separate "
                        "compile cache entry",
                suggestion="fine for a handful of values (flags, modes); "
                           "pass tensors for anything data-dependent"))
    return findings


# -- entry points ------------------------------------------------------------

def lint_traceable(fn, args=(), kwargs=None, *,
                   subject: Optional[str] = None,
                   **rule_opts) -> Report:
    """Abstract-trace a plain function at the given specs and lint it.

    `args`/`kwargs` may mix InputSpec / Tensor / arrays (traced) with
    Python scalars (static, checked by the recompile rule)."""
    kwargs = kwargs or {}
    report = Report(subject=subject
                    or getattr(fn, "__qualname__", repr(fn)))
    report.extend(lint_static_args(args, kwargs))

    structs, static_idx = [], []
    for i, a in enumerate(args):
        s = to_shape_struct(a)
        if s is None:
            s = _scalar_struct(a)
            if s is None:
                static_idx.append(i)
        structs.append(s)
    static_kwargs = {}
    traced_kwargs = {}
    for k, v in kwargs.items():
        s = to_shape_struct(v)
        if s is None:
            static_kwargs[k] = v
        else:
            traced_kwargs[k] = s

    def call(*traced, **tkw):
        full = list(traced)
        for i in static_idx:
            full.insert(i, args[i])
        return fn(*full, **tkw, **static_kwargs)

    traced_args = [s for i, s in enumerate(structs) if i not in static_idx]
    traced = _abstract_trace(report, call, *traced_args, **traced_kwargs)
    if traced is not None:
        report.extend(lint_closed_jaxpr(traced[0], **rule_opts))
    return report


def _with_mesh(lint_impl, mesh, *args, **kwargs) -> Report:
    """Run a lint entry point with shard_lint's collective recorder and
    a (fake) mesh installed: the same abstract trace then also yields
    SPMD/collective findings and a static cost estimate."""
    from . import cost_model
    from .shard_lint import (as_mesh, lint_jaxpr_collectives,
                             lint_records, recording)
    mesh = as_mesh(mesh)
    with recording(mesh) as rec:
        report, closed = lint_impl(*args, **kwargs)
    report.extend(lint_records(rec.records, mesh))
    if closed is not None:
        report.extend(lint_jaxpr_collectives(closed, mesh))
        report.cost = cost_model.estimate_jaxpr(closed, mesh)
        # this trace is a plain jit program, not a shard_map manual
        # region: GSPMD-auto partitioning will insert collectives (and
        # shrink per-rank shapes) at compile time — counts here cover
        # the explicit collectives only, and FLOPs/HBM are global-shape
        report.cost.note = (
            "GSPMD-auto trace: explicit collectives only; the XLA "
            "partitioner adds resharding traffic at compile time, and "
            "FLOPs/HBM are global (undivided) shapes")
    return report


def lint_static_function(sf, args=None, kwargs=None, mesh=None) -> Report:
    """Lint a jit.StaticFunction exactly as __call__ would stage it.

    With no sample `args`, the stored InputSpec list supplies the
    shapes — fully ahead-of-time inspection. With `mesh` (a Mesh,
    AbstractMesh, or {axis: degree} dict — no devices needed) the same
    trace additionally runs the shard_lint collective rules and
    attaches a static cost estimate."""
    if mesh is not None:
        return _with_mesh(_lint_static_function, mesh, sf, args, kwargs)
    return _lint_static_function(sf, args, kwargs)[0]


def _lint_static_function(sf, args=None, kwargs=None):
    from .ast_lint import lint_callable

    name = getattr(sf._fn, "__qualname__", repr(sf._fn))
    report = Report(subject=f"to_static({name})")
    report.extend(lint_callable(sf._layer if sf._layer is not None
                                else sf._fn))

    kwargs = dict(kwargs or {})
    if args is None:
        spec = sf._input_spec
        if spec is None:
            # nothing to trace against: AST findings only
            return report, None
        args = list(spec) if isinstance(spec, (list, tuple)) else [spec]

    tensor_args, kw_structs, static_kwargs = list(args), {}, {}
    for k, v in kwargs.items():
        s = to_shape_struct(v)
        if s is not None:
            kw_structs[k] = s  # traced by name, like __call__
        else:
            static_kwargs[k] = v
    report.extend(lint_static_args(args, static_kwargs))

    # mirror __call__'s argument handling exactly: arrays/specs trace
    # abstractly, Python scalars trace as 0-d weak-typed arrays (that
    # is what jax.jit does to them at runtime), anything else (None,
    # strings) passes through verbatim so arity and failure modes match
    # the real call
    arr_structs = []
    for a in tensor_args:
        s = to_shape_struct(a)
        if s is None:
            s = _scalar_struct(a)
        arr_structs.append(a if s is None else s)
    pure = sf._pure(static_kwargs)

    # pure's traced args flatten as (kw dict leaves in sorted-key
    # order, then positional arrays) — labels must respect that or an
    # unused-input finding names the wrong argument
    def user_labels(base):
        labels, i = {}, base
        for k in sorted(kw_structs):
            for _leaf in jax.tree_util.tree_leaves(kw_structs[k]):
                labels[i] = f"kwarg '{k}'"
                i += 1
        for j, s in enumerate(arr_structs):
            # None passthroughs contribute no invar leaves
            for _leaf in jax.tree_util.tree_leaves(s):
                labels[i] = f"input #{j}"
                i += 1
        return labels

    if sf._layer is None:
        traced = _abstract_trace(report, pure, kw_structs, *arr_structs)
        if traced is None:
            return report, None
        closed, _out_shape = traced
        labels = user_labels(0)
        report.extend(lint_closed_jaxpr(closed, invar_labels=labels))
        return report, closed

    from .functional_shapes import layer_state_structs, rng_key_struct
    params_s, buffers_s, frozen_s = layer_state_structs(sf._layer)
    key_s = rng_key_struct()
    traced = _abstract_trace(report, pure, params_s, buffers_s, frozen_s,
                             key_s, kw_structs, *arr_structs)
    if traced is None:
        return report, None
    closed, out_shape = traced
    n_state = sum(len(jax.tree_util.tree_leaves(t))
                  for t in (params_s, buffers_s, frozen_s)) + 1
    n_in = len(closed.jaxpr.invars)
    user_idx = list(range(n_state, n_in))
    labels = user_labels(n_state)
    n_user_out = len(jax.tree_util.tree_leaves(out_shape[0]))
    report.extend(lint_closed_jaxpr(
        closed, user_invar_idx=user_idx, invar_labels=labels,
        n_user_out=n_user_out))
    return report, closed


def lint_train_step(ts, inputs, labels, mesh=None) -> Report:
    """Lint a jit.TrainStep's fused step program at the given specs.

    Checks the same jaxpr rules plus unused *donated* inputs: a donated
    buffer no output depends on is memory freed for nothing. With
    `mesh`, shard_lint collective rules + the cost model run over the
    same trace (device-free)."""
    if mesh is not None:
        return _with_mesh(_lint_train_step, mesh, ts, inputs, labels)
    return _lint_train_step(ts, inputs, labels)[0]


def _lint_train_step(ts, inputs, labels):
    import jax.numpy as jnp

    from .ast_lint import lint_callable
    from .functional_shapes import rng_key_struct, tree_structs

    report = Report(subject=f"TrainStep({type(ts._model).__name__})")
    report.extend(lint_callable(ts._model))

    if not isinstance(inputs, (list, tuple)):
        inputs = (inputs,)
    in_structs = tuple(to_shape_struct(x) for x in inputs)
    lab_structs = jax.tree_util.tree_map(
        lambda t: to_shape_struct(t), labels,
        is_leaf=lambda t: to_shape_struct(t) is not None)
    params_s = tree_structs(ts._params)
    buffers_s = tree_structs(ts._buffers)
    frozen_s = tree_structs(ts._frozen)
    opt_s = tree_structs(ts._opt_state)
    key_s = rng_key_struct()
    lr_s = jax.ShapeDtypeStruct((), jnp.float32)

    step = ts._build_step()  # the un-jitted python step
    traced = _abstract_trace(report, step, params_s, buffers_s, frozen_s,
                             opt_s, key_s, lr_s, in_structs, lab_structs)
    if traced is None:
        return report, None
    closed, out_shape = traced

    counts = [len(jax.tree_util.tree_leaves(t))
              for t in (params_s, buffers_s, frozen_s, opt_s)]
    n_p, n_b, n_f, n_o = counts
    base = n_p + n_b + n_f + n_o + 2  # + key + lr
    n_in = len(closed.jaxpr.invars)
    labels_map: Dict[int, str] = {}
    # donated leaves: params (0), buffers (1), opt_state (3)
    donated = list(range(0, n_p)) + list(range(n_p, n_p + n_b)) + \
        list(range(n_p + n_b + n_f, n_p + n_b + n_f + n_o))
    for i, k in enumerate(sorted(params_s)):
        labels_map[i] = f"param '{k}'"
    for i, k in enumerate(sorted(buffers_s)):
        labels_map[n_p + i] = f"buffer '{k}'"
    for i in range(base, n_in):
        labels_map[i] = f"data input #{i - base}"
    check_idx = donated + list(range(base, n_in))
    report.extend(lint_closed_jaxpr(
        closed, user_invar_idx=check_idx, invar_labels=labels_map,
        donated_idx=donated))
    return report, closed
