"""hotpath_lint — device-free host/device boundary audit of a serving
tick (docs/ANALYSIS.md "Hot-path rules").

Where ast_lint/jaxpr_lint audit one traced function and shard_lint one
sharded program, this linter audits a serving SURFACE (Engine,
DisaggEngine, ServingFleet, BatchEncoder): the full inventory of its
compiled per-tick executables plus the scheduler source that drives
them. PR 15's gauges (``serving.host_ms_per_tick``) measure how much
host Python a tick pays; this pass names the causes, statically,
without a device:

* ``hotpath.missed-donation``   — a pool-sized argument (KV/scale/
  draft pools, resident decode state) flows to a same-shaped output
  without being donated: XLA must copy it in HBM every tick.
* ``hotpath.fetch-set-bloat``   — a per-tick output beyond the small
  token/ok vectors is materialized to host: every extra fetch is a
  forced sync.
* ``hotpath.host-sync-in-tick`` — the scheduler source syncs outside
  the attributed path: ``.item()``/``np.asarray``/implicit bool/len on
  a freshly dispatched device value that never went through
  ``_sync_timed``, a bare ``jax.block_until_ready``, host wall-clock
  (``time.time``/``time.sleep``) or host RNG inside the tick.
* ``hotpath.steady-tick-upload`` — the dirty-row-merge discipline: a
  steady tick uploads NOTHING, so any host->device transfer
  (``jnp.asarray``/``device_put``/``self._up``) in a steady-path
  function must sit under a dirty-flag ``if`` guard.
* ``hotpath.recompile-risk-key`` — an executable-cache dict keyed by a
  Python float/object that can vary per tick retraces instead of
  reusing a warm executable.

Everything here is abstract: executables are traced with
``jax.make_jaxpr`` over ShapeDtypeStructs (no device execution, CPU
container is enough) and the scheduler is walked as SOURCE — the same
discipline as jaxpr_lint. The runtime complement is the
``PADDLE_TPU_LINT=1`` transfer-guard the engines arm around steady
decode ticks, which turns any implicit transfer this pass missed into
a raise instead of a silent sync.

Scope note: device-value tracking in the scheduler walk is name-based
(results unpacked from a dispatched executable). Deliberate rare-path
attribute fetches (e.g. pulling an RNG row off the resident state at
preemption) are out of scope — they are commented host syncs on
non-steady paths, not per-tick costs.
"""
from __future__ import annotations

import ast
import dataclasses
import inspect
import textwrap
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from .findings import (FETCH_SET_BLOAT, HOST_SYNC_IN_TICK,
                       HOTPATH_RULES, MISSED_DONATION,
                       RECOMPILE_RISK_KEY, STEADY_TICK_UPLOAD, WARNING,
                       Finding, Report)

# "pool-sized": below this an undonated round trip is noise (rng keys,
# per-slot vectors), above it the per-tick HBM copy is real
POOL_BYTES_FLOOR = 64 * 1024
# the token/ok fetch vectors are O(max_slots) ints; anything past this
# on the per-tick fetch set is a bulk device->host pull
FETCH_BYTES_FLOOR = 16 * 1024


@dataclasses.dataclass
class ExecutableSpec:
    """One compiled per-tick surface: the UN-jitted body, abstract-
    traceable args (arrays or ShapeDtypeStructs), its donation set,
    and which top-level outputs the scheduler fetches to host.
    ``deliverable`` marks fetched outputs that ARE the service's
    payload (an embedding batch) and therefore exempt from the
    fetch-size floor."""
    name: str
    body: Callable
    args: Tuple
    donate: Tuple[int, ...] = ()
    fetched: Tuple[int, ...] = ()
    deliverable: Tuple[int, ...] = ()
    per_tick: bool = True


@dataclasses.dataclass
class HotpathInventory:
    """Everything hotpath_lint needs from a serving surface: its
    executables, the scheduler functions that run each tick, which of
    those are on the STEADY decode path (upload discipline applies),
    and its executable-cache key sets."""
    subject: str
    executables: List[ExecutableSpec]
    tick_functions: List[Callable]
    steady_functions: Tuple[str, ...] = ()
    cache_keys: Optional[Dict[str, Iterable]] = None
    file: str = "<unknown>"
    line: int = 0


def struct_of(tree):
    """Pytree of arrays/structs -> pytree of ShapeDtypeStructs (the
    abstract-trace currency; never touches device data)."""
    import jax
    import numpy as np

    def one(x):
        if isinstance(x, jax.ShapeDtypeStruct):
            return x
        return jax.ShapeDtypeStruct(tuple(np.shape(x)),
                                    getattr(x, "dtype", np.int32))

    return jax.tree_util.tree_map(one, tree)


def _nbytes(leaf) -> int:
    n = 1
    for d in leaf.shape:
        n *= int(d)
    return n * leaf.dtype.itemsize


def _body_loc(body) -> Tuple[str, int]:
    code = getattr(body, "__code__", None)
    if code is None:
        return "<unknown>", 0
    return code.co_filename, code.co_firstlineno


def _lint_executable(report: Report, spec: ExecutableSpec) -> None:
    import jax

    from . import jaxpr_lint
    args = tuple(struct_of(a) for a in spec.args)
    traced = jaxpr_lint._abstract_trace(report, spec.body, *args)
    if traced is None:
        return                      # trace failure already reported
    _closed, out_shape = traced
    fname, fline = _body_loc(spec.body)
    out_leaves = jax.tree_util.tree_leaves(out_shape)
    out_keys = {(tuple(l.shape), str(l.dtype)) for l in out_leaves}
    donated = set(spec.donate)
    for i, arg in enumerate(args):
        if i in donated:
            continue
        hits = [l for l in jax.tree_util.tree_leaves(arg)
                if _nbytes(l) >= POOL_BYTES_FLOOR
                and (tuple(l.shape), str(l.dtype)) in out_keys]
        if hits:
            total = sum(_nbytes(l) for l in hits)
            report.add(Finding(
                MISSED_DONATION, WARNING,
                f"executable {spec.name}: argument {i} "
                f"({len(hits)} pool-sized leaf/leaves, {total} bytes) "
                f"flows to same-shaped outputs undonated — XLA copies "
                f"it in HBM every dispatch",
                file=fname, line=fline,
                suggestion=f"add {i} to donate_argnums so the update "
                           f"aliases in place"))
    outs = out_shape if isinstance(out_shape, (tuple, list)) \
        else (out_shape,)
    for idx in spec.fetched:
        if idx in spec.deliverable or idx >= len(outs):
            continue
        total = sum(_nbytes(l)
                    for l in jax.tree_util.tree_leaves(outs[idx]))
        if total > FETCH_BYTES_FLOOR:
            report.add(Finding(
                FETCH_SET_BLOAT, WARNING,
                f"executable {spec.name}: per-tick fetch of output "
                f"{idx} pulls {total} bytes to host — beyond the "
                f"token/ok vectors, every extra fetch is a forced "
                f"sync",
                file=fname, line=fline,
                suggestion="keep bulk results device-resident (feed "
                           "them to the next executable) or batch the "
                           "fetch outside the tick"))


def _lint_cache_keys(report: Report, inv: HotpathInventory) -> None:
    for name, keys in (inv.cache_keys or {}).items():
        bad = []
        for key in keys:
            parts = key if isinstance(key, tuple) else (key,)
            for p in parts:
                if p is None or isinstance(p, (bool, int, str, bytes)):
                    continue
                bad.append(f"{type(p).__name__} {p!r}")
                break
        if bad:
            report.add(Finding(
                RECOMPILE_RISK_KEY, WARNING,
                f"executable cache {name} keyed by {', '.join(bad)} — "
                f"a float/object key that varies per tick compiles a "
                f"fresh executable instead of reusing a warm one",
                file=inv.file, line=inv.line,
                suggestion="key on ints/strings (bucket sizes, "
                           "variant names); pass varying values as "
                           "traced arrays"))


# -- scheduler-source walk ----------------------------------------------------

_NP_FETCH = ("np.asarray", "np.array", "numpy.asarray", "numpy.array")
_UPLOAD_CALLS = ("jnp.asarray", "jnp.array", "jax.numpy.asarray",
                 "jax.numpy.array", "jax.device_put", "self._up")
_HOST_CLOCK = ("time.time", "time.sleep")
_HOST_RNG_PREFIXES = ("random.", "np.random.", "numpy.random.")
_FETCH_METHODS = ("item", "tolist", "numpy")
_SYNC_ATTR = "_sync_timed"


def _dotted(node) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else ""
    return ""


class _TickLinter(ast.NodeVisitor):
    """Walks ONE scheduler function. Names unpacked from a dispatched
    executable (``fn = self._get_*(...)``; ``a, b = fn(...)``) are
    DEVICE values; ``self._sync_timed(...)`` attributes their wait.
    Fetching, branching, or casting an unsynced device name is a
    finding; on steady-path functions, so is an unguarded upload."""

    def __init__(self, report: Report, filename: str, off: int,
                 fn_name: str, steady: bool):
        self.report = report
        self.filename = filename
        self.off = off
        self.fn_name = fn_name
        self.steady = steady
        self.fn_like: set = set()
        self.device: set = set()
        self.synced: set = set()
        self.if_depth = 0

    def _flag(self, rule: str, node, msg: str, suggestion: str = ""):
        self.report.add(Finding(
            rule, WARNING, f"{self.fn_name}: {msg}",
            file=self.filename, line=node.lineno + self.off,
            suggestion=suggestion))

    # -- assignments: track dispatchers and their device results -------------

    def visit_Assign(self, node: ast.Assign):
        val = node.value
        names = []
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                names.append(tgt.id)
            elif isinstance(tgt, (ast.Tuple, ast.List)):
                names.extend(e.id for e in tgt.elts
                             if isinstance(e, ast.Name))
        if isinstance(val, ast.Call):
            callee = _dotted(val.func)
            if callee.startswith("self._get_"):
                self.fn_like.update(names)
            elif (isinstance(val.func, ast.Name)
                  and val.func.id in self.fn_like) \
                    or callee == "self._dispatch_steady":
                self.device.update(names)
            elif callee in _NP_FETCH:
                # `x = np.asarray(x)` rebinds to a host array
                self.visit(val)
                for n in names:
                    self.device.discard(n)
                return
        self.visit(val)

    # -- calls: syncs, fetches, clocks, uploads ------------------------------

    def visit_Call(self, node: ast.Call):
        callee = _dotted(node.func)
        if callee == f"self.{_SYNC_ATTR}":
            for arg in node.args:
                self.synced.update(n.id for n in ast.walk(arg)
                                   if isinstance(n, ast.Name))
            return
        if callee in _NP_FETCH and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Name) and arg.id in self.device \
                    and arg.id not in self.synced:
                self._flag(
                    HOST_SYNC_IN_TICK, node,
                    f"np.asarray({arg.id}) fetches a dispatched "
                    f"device value that never went through "
                    f"{_SYNC_ATTR}",
                    suggestion=f"add {arg.id} to the "
                               f"{_SYNC_ATTR}(...) tuple so the wait "
                               f"is attributed to the device share")
        elif callee == "jax.block_until_ready" \
                and self.fn_name != _SYNC_ATTR:
            self._flag(
                HOST_SYNC_IN_TICK, node,
                "un-attributed jax.block_until_ready",
                suggestion=f"route the wait through {_SYNC_ATTR} so "
                           f"host/device tick attribution stays "
                           f"honest")
        elif callee in _HOST_CLOCK \
                or callee.startswith(_HOST_RNG_PREFIXES):
            self._flag(
                HOST_SYNC_IN_TICK, node,
                f"host {callee}() inside the tick path",
                suggestion="use the injectable clock / a monotonic "
                           "timer, and keep RNG in traced keys")
        elif callee in ("bool", "int", "float", "len") and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Name) and arg.id in self.device \
                    and arg.id not in self.synced:
                self._flag(
                    HOST_SYNC_IN_TICK, node,
                    f"{callee}({arg.id}) forces an unsynced device "
                    f"value to host",
                    suggestion=f"sync {arg.id} via {_SYNC_ATTR} "
                               f"first, then read the host copy")
        elif isinstance(node.func, ast.Attribute) \
                and node.func.attr in _FETCH_METHODS \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id in self.device:
            self._flag(
                HOST_SYNC_IN_TICK, node,
                f".{node.func.attr}() on dispatched device value "
                f"{node.func.value.id}",
                suggestion=f"sync via {_SYNC_ATTR} and read the "
                           f"np.asarray copy instead")
        if self.steady and callee in _UPLOAD_CALLS \
                and self.if_depth == 0:
            self._flag(
                STEADY_TICK_UPLOAD, node,
                f"unconditional host->device upload ({callee}) on the "
                f"steady decode path — a steady tick must upload "
                f"nothing",
                suggestion="guard the upload behind the dirty-row "
                           "flags (the merge-on-dirty discipline) or "
                           "keep the value device-resident")
        self.generic_visit(node)

    # -- implicit bool on a device value -------------------------------------

    def _check_test(self, test):
        name = None
        if isinstance(test, ast.Name):
            name = test.id
        elif isinstance(test, ast.UnaryOp) \
                and isinstance(test.op, ast.Not) \
                and isinstance(test.operand, ast.Name):
            name = test.operand.id
        if name is not None and name in self.device \
                and name not in self.synced:
            self._flag(
                HOST_SYNC_IN_TICK, test,
                f"implicit bool on unsynced device value {name} "
                f"(branch forces a host sync)",
                suggestion=f"sync {name} via {_SYNC_ATTR} and branch "
                           f"on the host copy")

    def visit_If(self, node: ast.If):
        self._check_test(node.test)
        self.visit(node.test)
        self.if_depth += 1
        for stmt in node.body + node.orelse:
            self.visit(stmt)
        self.if_depth -= 1

    def visit_IfExp(self, node: ast.IfExp):
        self._check_test(node.test)
        self.visit(node.test)
        self.if_depth += 1
        self.visit(node.body)
        self.visit(node.orelse)
        self.if_depth -= 1

    def visit_While(self, node: ast.While):
        self._check_test(node.test)
        self.generic_visit(node)


def _lint_tick_fn(report: Report, fn, steady_names) -> None:
    raw = inspect.unwrap(fn)
    code = getattr(raw, "__func__", raw)
    try:
        lines, first = inspect.getsourcelines(code)
        filename = inspect.getsourcefile(code) or "<unknown>"
    except (OSError, TypeError):
        return
    try:
        tree = ast.parse(textwrap.dedent("".join(lines)))
    except SyntaxError:
        return
    if not tree.body or not isinstance(
            tree.body[0], (ast.FunctionDef, ast.AsyncFunctionDef)):
        return
    fdef = tree.body[0]
    linter = _TickLinter(report, filename, first - 1, fdef.name,
                         steady=fdef.name in steady_names)
    for stmt in fdef.body:
        linter.visit(stmt)


# -- entry points -------------------------------------------------------------

def lint_inventory(inv: HotpathInventory) -> Report:
    """Run every hot-path rule over one surface's inventory."""
    report = Report(subject=inv.subject)
    for spec in inv.executables:
        _lint_executable(report, spec)
    _lint_cache_keys(report, inv)
    steady = tuple(inv.steady_functions or ())
    for fn in inv.tick_functions:
        _lint_tick_fn(report, fn, steady)
    return report


def lint_surface(obj) -> Report:
    """Lint any object exposing ``_hotpath_inventory()`` (Engine,
    DisaggEngine, ServingFleet, BatchEncoder, or a test double)."""
    return lint_inventory(obj._hotpath_inventory())


def emit_hotpath(report: Report) -> Report:
    """Route an inspect_hotpath() report through the monitor: always
    counts the inspection, and a non-empty report flows through the
    shared emit path — the ``hotpath.``-prefixed rule ids land as
    ``lint.hotpath.*`` counters."""
    from .. import monitor
    monitor.counter("lint.hotpath.inspections").increase()
    if report:
        from . import emit_findings
        emit_findings(report)
    return report


def sweep_serving_stack(surfaces=("engine", "disagg", "fleet",
                                  "encoder", "mpmd"),
                        drive=True) -> Dict[str, Report]:
    """Build + briefly drive a tiny instance of each serving surface
    on the local (CPU is fine) backend and lint it warm — the CLI's
    ``--hotpath`` sweep and the tier-1 zero-false-positive gate.

    ``drive=False`` skips the warm-up requests and lints each surface
    cold: the inventories fall back to their default variant/bucket
    sets, so every rule still runs over every executable body — only
    the runtime-populated cache-key sets shrink. Used by
    ``paddle_lint --self-check`` where the sweep rides along a much
    larger package walk."""
    import numpy as np

    import paddle_tpu as paddle
    reports: Dict[str, Report] = {}
    prompts = [np.arange(1, 6, dtype=np.int64),
               np.arange(2, 9, dtype=np.int64)]

    def llama():
        from paddle_tpu.text.models import LlamaConfig, LlamaForCausalLM
        paddle.seed(0)
        cfg = LlamaConfig.tiny(vocab=64, hidden=32, layers=2, heads=2)
        cfg.use_flash_attention = False
        net = LlamaForCausalLM(cfg)
        net.eval()
        return net

    if "engine" in surfaces:
        from paddle_tpu.inference import Engine, SamplingParams
        eng = Engine(llama(), max_slots=2, page_size=8, pool_pages=32,
                     max_context=64, multi_tick=4)
        if drive:
            eng.run([(p, SamplingParams(max_new_tokens=3))
                     for p in prompts])
        reports["engine"] = lint_surface(eng)
    if "disagg" in surfaces:
        from paddle_tpu.inference import DisaggEngine, SamplingParams
        eng = DisaggEngine(llama(), prefill_workers=1, decode_workers=1,
                           max_slots=2, page_size=8, pool_pages=32,
                           max_context=64)
        if drive:
            eng.run([(p, SamplingParams(max_new_tokens=3))
                     for p in prompts])
        reports["disagg"] = lint_surface(eng)
    if "fleet" in surfaces:
        from paddle_tpu.inference import SamplingParams, ServingFleet
        eng = ServingFleet(llama(), replicas=2, max_slots=2,
                           page_size=8, pool_pages=32, max_context=64)
        if drive:
            eng.run([(p, SamplingParams(max_new_tokens=3))
                     for p in prompts])
        reports["fleet"] = lint_surface(eng)
    if "encoder" in surfaces:
        from paddle_tpu.inference import BatchEncoder
        from paddle_tpu.text.models import BertConfig, BertModel
        paddle.seed(0)
        cfg = BertConfig.tiny(vocab=64, hidden=32, layers=2, heads=2)
        bert = BertModel(cfg)
        bert.eval()
        svc = BatchEncoder(bert, max_batch=2, bucket=16, max_seq=32)
        if drive:
            svc.run([p.tolist() for p in prompts])
        reports["encoder"] = lint_surface(svc)
    if "mpmd" in surfaces:
        import jax.numpy as jnp

        from paddle_tpu.distributed.mpmd_runtime import MpmdRingExecutor
        ex = MpmdRingExecutor(2, causal=True)
        if drive:
            rng = np.random.default_rng(0)
            q = jnp.asarray(rng.standard_normal((1, 2, 8, 4)),
                            jnp.float32)
            numel = float(q.size)
            ex.run(q, q, q,
                   dout_fn=lambda r, ob: ob * (2.0 / numel))
        reports["mpmd"] = lint_surface(ex)
    return reports


__all__ = ["ExecutableSpec", "HotpathInventory", "HOTPATH_RULES",
           "POOL_BYTES_FLOOR", "FETCH_BYTES_FLOOR", "emit_hotpath",
           "lint_inventory", "lint_surface", "struct_of",
           "sweep_serving_stack"]
