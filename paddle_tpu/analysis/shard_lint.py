"""shard_lint — ahead-of-time SPMD/collective analyzer.

`jaxpr_lint` checks single-device programs; this pass checks the layers
the system actually scales on — mesh/SPMD, collectives, pipeline and
zero-bubble schedules — with ZERO devices attached. A bad partition
spec, an indivisible all_to_all, or a stage-imbalanced pipeline today
only fails (or silently wastes HBM/ICI bandwidth) once hardware is
present; here the same defects fall out of an abstract
`jax.make_jaxpr` trace under a *fake mesh* (`jax.sharding.AbstractMesh`
— no device array, so an 8-rank plan lints on a 1-CPU laptop).

Two sources of evidence, one Report:

* **Collective call records.** While the abstract trace runs, a
  recorder installed into `distributed.communication.collectives`
  captures every collective entry point's (op, group, operand shape,
  list arity, split sizes) with the USER file:line. Validation against
  the fake mesh catches axis names that match no mesh axis (the
  runtime path would silently degrade to the eager identity),
  rank-misaligned groups, indivisible dim-0 splits, uneven
  `alltoall_single` splits, wrong tensor-list arity, and `send`/`recv`
  inside traced code.
* **The staged jaxpr.** Rule passes walk the traced program for
  `ppermute` permutations that do not cover the axis ring (uncovered
  ranks silently receive zeros), and the static cost model
  (`analysis.cost_model`) folds every collective/contraction into
  per-rank bytes-moved / FLOPs / peak-HBM numbers — the quantities
  arXiv 2112.01075 and 2412.14374 plan with, emitted here as
  `lint.cost.*` gauges and a `--cost` CLI table.

`lint_pipeline` checks schedule plans (PipelineLayer /
PipelineParallel) without tracing shard_map at all: stage
parameter/FLOP imbalance, bubble fraction per schedule mode (the exact
`schedule_stats` formulas the compiled schedules use), microbatch
arity, and heterogeneous-segment mismatches.
"""
from __future__ import annotations

import contextlib
import inspect as _inspect
import math
import os
import traceback
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from . import cost_model
from .findings import (BAD_AXIS_NAME, BUBBLE_FRACTION, ERROR, GRAPH_BREAK,
                      INDIVISIBLE_COLLECTIVE, MICROBATCH_ARITY,
                      NON_RING_PERMUTE, P2P_IN_TRACE, SEGMENT_MISMATCH,
                      STAGE_IMBALANCE, TENSOR_LIST_ARITY, TRACE_FAILED,
                      UNALIGNED_GROUP, UNEVEN_SPLIT, WARNING, Finding,
                      Report)
from .jaxpr_lint import _eqn_loc, _walk_eqns, to_shape_struct

# a schedule spending more than this fraction of wall ticks in bubbles
# is flagged (GPipe at the common accumulate_steps == pp setting sits
# at (S-1)/(2S-1) ~ 0.43 — exactly the config worth a warning)
BUBBLE_WARN_FRACTION = 0.30
# max/mean per-stage weight above this flags a lopsided segmentation
STAGE_IMBALANCE_RATIO = 1.5

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _user_loc() -> Tuple[str, int]:
    """First stack frame outside paddle_tpu (and jax) — the user call
    site a finding should point at."""
    for frame in reversed(traceback.extract_stack()):
        fn = frame.filename
        if fn.startswith(_PKG_DIR) or f"{os.sep}jax{os.sep}" in fn \
                or fn.startswith("<"):
            continue
        return fn, int(frame.lineno or 0)
    return "<unknown>", 0


def _layer_loc(obj) -> Tuple[str, int]:
    """Best-effort file:line of a layer/callable's definition."""
    try:
        target = obj if _inspect.isfunction(obj) else type(obj)
        fn = _inspect.getsourcefile(target) or "<unknown>"
        line = _inspect.getsourcelines(target)[1]
        return fn, int(line)
    except (OSError, TypeError):
        return "<unknown>", 0


def as_mesh(mesh):
    """Accept a Mesh, AbstractMesh, or {axis: degree} dict (turned into
    a device-free fake mesh)."""
    if isinstance(mesh, dict):
        from ..distributed import mesh as mesh_mod
        return mesh_mod.fake_mesh(mesh)
    return mesh


class CollectiveRecorder:
    """Collects one record per collective call made during an abstract
    trace (installed via `recording()`); group metadata is extracted
    defensively so a broken group still yields a record, not a crash."""

    def __init__(self):
        self.records: List[Dict[str, Any]] = []

    def add(self, op: str, group, shape=(), dtype="", n_list=None,
            splits=None):
        axes: Optional[Tuple[str, ...]]
        unaligned = False
        try:
            axes = tuple(group.axis_names)
        except ValueError:
            axes, unaligned = None, True
        except Exception:
            axes = None
        try:
            nranks = int(group.nranks)
        except Exception:
            nranks = 1
        fname, line = _user_loc()
        self.records.append({
            "op": op, "axes": axes, "unaligned": unaligned,
            "nranks": nranks, "ranks": getattr(group, "_ranks", None),
            "group": getattr(group, "name", ""), "shape": tuple(shape),
            "dtype": dtype, "n_list": n_list, "splits": splits,
            "file": fname, "line": line,
        })


@contextlib.contextmanager
def recording(mesh=None):
    """Install the collective recorder (and, when given, the fake mesh
    as the global paddle mesh so Group/axis introspection resolves
    device-free). Restores both on exit — lint must never leak state
    into the program under analysis.

    LINT-INTERNAL, and process-global: while the recorder is installed,
    collective arg validation is reported as findings instead of raised,
    and invalid calls degrade to identity so one abstract trace can
    surface every defect. Never wrap code that actually EXECUTES — it
    would run with validation off (deliberately not exported from
    paddle_tpu.analysis for this reason)."""
    from ..distributed import mesh as mesh_mod
    from ..distributed.communication import collectives as coll
    rec = CollectiveRecorder()
    prev_rec = coll._collective_recorder
    prev_mesh = mesh_mod.get_mesh()
    coll._collective_recorder = rec
    if mesh is not None:
        mesh_mod._global_mesh = as_mesh(mesh)
    try:
        yield rec
    finally:
        coll._collective_recorder = prev_rec
        mesh_mod._global_mesh = prev_mesh


# -- record validation -------------------------------------------------------

_SPLITTING = ("all_to_all", "alltoall_single", "reduce_scatter")


def lint_records(records: Sequence[Dict[str, Any]],
                 mesh) -> List[Finding]:
    """Validate recorded collective calls against the (fake) mesh."""
    sizes = cost_model.axis_sizes(as_mesh(mesh))
    findings: List[Finding] = []
    seen = set()

    def add(f: Finding):
        key = (f.rule, f.file, f.line, f.message)
        if key not in seen:
            seen.add(key)
            findings.append(f)

    for r in records:
        op, fname, line = r["op"], r["file"], r["line"]
        if op in ("send", "recv"):
            add(Finding(
                rule=P2P_IN_TRACE, severity=ERROR,
                message=f"{op}() inside traced code — raw p2p has no XLA "
                        "lowering on TPU (RuntimeError when the axis is "
                        "bound, silent no-op otherwise)",
                file=fname, line=line,
                suggestion="use p2p_shift (lax.ppermute) or a compiled "
                           "pipeline schedule for stage-to-stage "
                           "transfer"))
            continue
        if r["unaligned"]:
            add(Finding(
                rule=UNALIGNED_GROUP, severity=ERROR,
                message=f"{op} over group built from ranks={r['ranks']} "
                        "which match no axis-group of the mesh — compiled "
                        "collectives need axis-aligned groups",
                file=fname, line=line,
                suggestion="build the mesh so the group is one axis, or "
                           "pass axis_name= to new_group"))
            continue
        axes = r["axes"] or ()
        missing = [ax for ax in axes if ax not in sizes]
        if missing:
            add(Finding(
                rule=BAD_AXIS_NAME, severity=ERROR,
                message=f"{op} over axis name(s) {missing} not in the "
                        f"mesh ({tuple(sizes) or 'no axes'}) — at runtime "
                        "the axis never binds, so the collective SILENTLY "
                        "degrades to the eager identity path",
                file=fname, line=line,
                suggestion="fix the axis name (mesh axes are "
                           f"{tuple(sizes)}) or add the axis to "
                           "build_mesh(degrees=...)"))
            continue
        n = 1
        for ax in axes:
            n *= sizes.get(ax, 1)
        if not axes:
            n = max(1, r["nranks"])
        if n <= 1:
            continue
        if op in _SPLITTING:
            if r["n_list"] is not None and r["n_list"] > 0 \
                    and r["n_list"] != n:
                add(Finding(
                    rule=TENSOR_LIST_ARITY, severity=ERROR,
                    message=f"{op}: tensor list has {r['n_list']} "
                            f"entries but the group spans {n} rank(s) — "
                            "one entry per rank required",
                    file=fname, line=line,
                    suggestion=f"pass exactly {n} tensors (group axes "
                               f"{axes})"))
            elif r["n_list"] is None and r["shape"]:
                dim0 = r["shape"][0]
                # single-tensor all_to_all lowers UNTILED: dim 0 must
                # EQUAL the group size; the tiled forms need dim 0
                # divisible by it
                bad = dim0 != n if op == "all_to_all" else dim0 % n != 0
                if bad:
                    req = ("must equal" if op == "all_to_all"
                           else "is not divisible by")
                    add(Finding(
                        rule=INDIVISIBLE_COLLECTIVE, severity=ERROR,
                        message=f"{op}: input dim 0 ({dim0}) {req} the "
                                f"group size ({n}) — lax rejects the "
                                "split at trace time, after a device is "
                                "attached",
                        file=fname, line=line,
                        suggestion=("pass one dim-0 slice per rank (or "
                                    "use alltoall_single for the tiled "
                                    "even-split form)"
                                    if op == "all_to_all" else
                                    "pad dim 0 to a multiple of the "
                                    "axis degree, or change the mesh "
                                    "degree")))
        if op == "alltoall_single" and r["splits"]:
            for sizes_ in r["splits"]:
                if sizes_ and len(set(sizes_)) > 1:
                    add(Finding(
                        rule=UNEVEN_SPLIT, severity=ERROR,
                        message=f"alltoall_single with uneven split "
                                f"sizes {list(sizes_)} — lax.all_to_all "
                                "is tiled; this raises "
                                "NotImplementedError at runtime",
                        file=fname, line=line,
                        suggestion="pad the shards to equal size (even "
                                   "splits) or drop the split_sizes "
                                   "arguments"))
                    break
        if op == "scatter" and r["n_list"] not in (None, 0) \
                and r["n_list"] != n:
            add(Finding(
                rule=TENSOR_LIST_ARITY, severity=ERROR,
                message=f"scatter: tensor_list has {r['n_list']} entries "
                        f"but the group spans {n} rank(s)",
                file=fname, line=line,
                suggestion=f"pass exactly {n} tensors"))
    return findings


# -- jaxpr passes ------------------------------------------------------------

def lint_jaxpr_collectives(closed, mesh) -> List[Finding]:
    """Walk the staged program for collective defects the record pass
    cannot see: raw lax.ppermute rings that do not cover the axis."""
    sizes = cost_model.axis_sizes(as_mesh(mesh))
    findings: List[Finding] = []
    seen = set()
    for eqn in _walk_eqns(closed.jaxpr):
        if eqn.primitive.name != "ppermute":
            continue
        axes = eqn.params.get("axis_name")
        if not isinstance(axes, (tuple, list)):
            axes = (axes,)
        n = 1
        for ax in axes:
            n *= sizes.get(ax, 1)
        perm = list(eqn.params.get("perm") or ())
        srcs = [p[0] for p in perm]
        dsts = [p[1] for p in perm]
        full = set(range(n))
        ok = (len(set(srcs)) == len(srcs) and len(set(dsts)) == len(dsts)
              and set(srcs) == full and set(dsts) == full)
        if ok or n <= 1:
            continue
        fname, line = _eqn_loc(eqn)
        key = (fname, line, tuple(perm))
        if key in seen:
            continue
        seen.add(key)
        uncovered = sorted(full - set(dsts))
        findings.append(Finding(
            rule=NON_RING_PERMUTE, severity=WARNING,
            message=f"ppermute over axis {axes} (size {n}) with perm "
                    f"{perm} is not a full permutation — rank(s) "
                    f"{uncovered[:4]}{'...' if len(uncovered) > 4 else ''} "
                    "silently receive zeros",
            file=fname, line=line,
            suggestion="cover every rank, e.g. ring_perm(n): "
                       "[(i, (i+shift) % n) for i in range(n)]"))
    return findings


# -- sharded-program entry point --------------------------------------------

def lint_sharded(fn, args=(), kwargs=None, *, mesh,
                 in_specs=None, out_specs=None,
                 subject: Optional[str] = None,
                 with_cost: bool = True) -> Report:
    """Abstract-trace `fn` inside a shard_map manual region over ALL
    axes of the (fake) mesh and run every shard rule + the cost model.

    `args` may be InputSpec / Tensor / array / ShapeDtypeStruct — only
    shapes and dtypes are read; nothing executes on any device.
    `in_specs` defaults to fully-replicated (each rank sees the whole
    example), so per-rank shapes equal the given shapes."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    mesh = as_mesh(mesh)
    kwargs = dict(kwargs or {})
    report = Report(subject=subject
                    or getattr(fn, "__qualname__", repr(fn)))

    structs = []
    for a in args:
        s = to_shape_struct(a)
        structs.append(s if s is not None else a)
    if in_specs is None:
        in_specs = tuple(P() for _ in structs)
    if out_specs is None:
        out_specs = P()

    def call(*xs):
        return fn(*xs, **kwargs)

    wrapped = shard_map(call, mesh=mesh, in_specs=tuple(in_specs),
                        out_specs=out_specs, check_rep=False)
    closed = None
    with recording(mesh) as rec:
        try:
            closed = jax.make_jaxpr(wrapped)(*structs)
        except Exception as exc:  # classified below — inspect stays total
            report.add(_classify_trace_error(exc))
    report.extend(lint_records(rec.records, mesh))
    if closed is not None:
        report.extend(lint_jaxpr_collectives(closed, mesh))
        if with_cost:
            report.cost = cost_model.estimate_jaxpr(closed, mesh)
    return report


def _classify_trace_error(exc: Exception) -> Finding:
    """Turn an abstract-trace failure into the most specific finding:
    raw-lax collective errors get their own rules, graph breaks keep
    jaxpr_lint's classification, the rest is trace-failed."""
    msg = str(exc).strip().splitlines()[0] if str(exc).strip() else ""
    if "divisible by the size of the named axis" in msg \
            or "to be divisible by" in msg and "axis" in msg:
        return Finding(
            rule=INDIVISIBLE_COLLECTIVE, severity=ERROR,
            message=f"collective split rejected at trace time: {msg}",
            suggestion="pad the split dim to a multiple of the axis "
                       "degree, or change the mesh degree")
    if isinstance(exc, NameError) and "unbound axis name" in msg:
        return Finding(
            rule=BAD_AXIS_NAME, severity=ERROR,
            message=f"collective over an axis the mesh does not bind: "
                    f"{msg}",
            suggestion="fix the axis name or add it to the mesh degrees")
    from .jaxpr_lint import _break_errors
    if isinstance(exc, _break_errors()):
        return Finding(
            rule=GRAPH_BREAK, severity=ERROR,
            message=f"the sharded trace itself breaks: {msg}",
            breaks_with=type(exc).__name__,
            suggestion="restructure with lax.cond/while_loop so the "
                       "sharded program stays compiled")
    return Finding(
        rule=TRACE_FAILED, severity=WARNING,
        message=f"abstract sharded trace failed "
                f"({type(exc).__name__}): {msg}",
        suggestion="shard rules were skipped; check the example "
                   "shapes/specs and in_specs match the function")


# -- pipeline / schedule entry point -----------------------------------------

def _stage_param_numel(items) -> int:
    total = 0
    seen = set()
    for item in items:
        lyr = item[0] if isinstance(item, tuple) else item
        params = getattr(lyr, "parameters", None)
        if params is None:
            continue
        for p in params():
            if id(p) in seen:
                continue
            seen.add(id(p))
            total += int(math.prod(p.shape) if p.shape else 1)
    return total


def _imbalance(values: List[float]) -> float:
    live = [v for v in values if v > 0]
    if len(live) < 2:
        return 1.0
    return max(live) / (sum(live) / len(live))


def lint_pipeline(pipe, *, n_micro: Optional[int] = None,
                  schedule_mode: Optional[str] = None,
                  vpp_degree: Optional[int] = None,
                  input_spec=None,
                  subject: Optional[str] = None,
                  with_cost: bool = True) -> Report:
    """Statically check a pipeline plan — no mesh, no devices, no
    shard_map: stage imbalance, bubble fraction, microbatch arity,
    heterogeneous-segment mismatches, plus (with an input_spec) a
    per-stage FLOP profile and schedule cost estimate.

    `pipe` is a PipelineLayer or a PipelineParallel (whose strategy
    supplies n_micro/schedule_mode/vpp_degree defaults)."""
    model = None
    if hasattr(pipe, "_layers") and hasattr(pipe, "accumulate_steps"):
        model, pipe = pipe, pipe._layers
    S = int(pipe.get_num_stages())
    V = int(vpp_degree if vpp_degree is not None else
            (model.vpp_degree if model is not None
             else getattr(pipe, "_num_virtual_stages", 1)) or 1)
    M = int(n_micro if n_micro is not None else
            (model.accumulate_steps if model is not None else S) or S)
    mode = (schedule_mode if schedule_mode is not None else
            (model.schedule_mode if model is not None else "")) or \
        ("VPP" if V > 1 else "FThenB")

    report = Report(subject=subject or f"pipeline({type(pipe).__name__}, "
                    f"S={S}, M={M}, mode={mode})")
    if S <= 1:
        return report

    first_item = pipe.stage_items(0)[0] if pipe.stage_items(0) else pipe
    pfile, pline = _layer_loc(first_item[0] if isinstance(first_item, tuple)
                              else first_item)

    # -- microbatch arity ---------------------------------------------------
    if V > 1 and M < S:
        report.add(Finding(
            rule=MICROBATCH_ARITY, severity=ERROR,
            message=f"interleaved (VPP/ZBVPP) schedule needs "
                    f"accumulate_steps >= pp degree, got M={M} < S={S} — "
                    "the schedule constructor raises ValueError",
            file=pfile, line=pline,
            suggestion=f"set pipeline_configs['accumulate_steps'] >= {S}"))

    # -- het / segment checks -----------------------------------------------
    bounds = pipe.segment_parts
    stage_sizes = [bounds[i + 1] - bounds[i] for i in range(S)]
    explicit = isinstance(getattr(pipe, "_seg_method", None), (list, tuple))
    uniform = len(set(stage_sizes)) == 1
    if explicit and not uniform and mode.upper() in ("ZBH1", "ZBVPP"):
        report.add(Finding(
            rule=SEGMENT_MISMATCH, severity=ERROR,
            message=f"non-uniform explicit segments {stage_sizes} compose "
                    f"with FThenB only — schedule_mode={mode!r} raises "
                    "ValueError at construction",
            file=pfile, line=pline,
            suggestion="use FThenB with the het schedule, or re-balance "
                       "the segments uniformly"))

    # -- stage parameter imbalance ------------------------------------------
    param_numels = [float(_stage_param_numel(pipe.stage_items(s)))
                    for s in range(S)]
    ratio = _imbalance(param_numels)
    if ratio > STAGE_IMBALANCE_RATIO:
        worst = int(np.argmax(param_numels))
        report.add(Finding(
            rule=STAGE_IMBALANCE, severity=WARNING,
            message=f"per-stage parameter counts "
                    f"{[int(v) for v in param_numels]} are imbalanced "
                    f"(max/mean = {ratio:.2f}x, stage {worst} heaviest) — "
                    "every other stage idles while it computes",
            file=pfile, line=pline,
            suggestion="re-segment (seg_method) so stage parameter/FLOP "
                       "weights are within ~1.5x of the mean"))

    # -- per-stage FLOPs + activation-shape chain (needs shapes) ------------
    stage_flops: List[float] = []
    act_bytes = 0
    if input_spec is not None:
        x = to_shape_struct(input_spec)
        act_bytes = int(math.prod(x.shape)) * np.dtype(x.dtype).itemsize \
            if x is not None else 0
        from ..core import tape as tape_mod
        from ..core.tensor import Tensor
        for s in range(S):
            items = pipe.stage_items(s)

            def stage_fn(arr, _items=items):
                with tape_mod.no_grad_guard():
                    t = Tensor._from_array(arr)
                    for item in _items:
                        t = pipe._apply(item, t)
                return t._data if isinstance(t, Tensor) else t

            try:
                closed, out_shape = jax.make_jaxpr(
                    stage_fn, return_shape=True)(x)
            except Exception as exc:
                first = str(exc).strip().splitlines()[0]
                report.add(Finding(
                    rule=TRACE_FAILED, severity=WARNING,
                    message=f"stage {s} abstract trace failed "
                            f"({type(exc).__name__}): {first}",
                    file=pfile, line=pline,
                    suggestion="per-stage FLOP/segment checks were "
                               "skipped from this stage on"))
                break
            stage_flops.append(
                cost_model.estimate_jaxpr(closed).flops)
            out = jax.tree_util.tree_leaves(out_shape)[0]
            if tuple(out.shape) != tuple(x.shape) and \
                    not (explicit and not uniform
                         and mode.upper() in ("", "FTHENB", "1F1B")):
                it0 = items[0]
                sfile, sline = _layer_loc(
                    it0[0] if isinstance(it0, tuple) else it0)
                report.add(Finding(
                    rule=SEGMENT_MISMATCH, severity=ERROR,
                    message=f"stage {s} maps activation "
                            f"{tuple(x.shape)} -> {tuple(out.shape)} but "
                            f"the {mode} schedule's ppermute ring needs "
                            "identical shapes on every stage boundary",
                    file=sfile, line=sline,
                    suggestion="make stages shape-homogeneous, or use an "
                               "explicit non-uniform seg_method with "
                               "FThenB (the het path)"))
            x = jax.ShapeDtypeStruct(out.shape, out.dtype)
        if len(stage_flops) == S:
            fratio = _imbalance(stage_flops)
            if fratio > STAGE_IMBALANCE_RATIO:
                worst = int(np.argmax(stage_flops))
                report.add(Finding(
                    rule=STAGE_IMBALANCE, severity=WARNING,
                    message=f"per-stage FLOPs "
                            f"{[f'{v:.2e}' for v in stage_flops]} are "
                            f"imbalanced (max/mean = {fratio:.2f}x, stage "
                            f"{worst} heaviest)",
                    file=pfile, line=pline,
                    suggestion="re-segment so per-stage FLOPs are within "
                               "~1.5x of the mean"))

    # -- bubble fraction ----------------------------------------------------
    from ..distributed.pipeline import schedule_stats
    try:
        stats = schedule_stats(mode, S, max(M, 1), V)
    except ValueError:
        stats = None
    if stats is not None and M >= 1 and not (V > 1 and M < S):
        bf = float(stats["bubble_fraction"])
        if bf > BUBBLE_WARN_FRACTION:
            # smallest M with an acceptable GPipe bubble, as a hint
            m_ok = math.ceil((S - 1) * (1 - BUBBLE_WARN_FRACTION)
                             / BUBBLE_WARN_FRACTION)
            report.add(Finding(
                rule=BUBBLE_FRACTION, severity=WARNING,
                message=f"{mode} with S={S} stages and M={M} microbatches "
                        f"idles {bf:.0%} of wall ticks in pipeline "
                        "bubbles",
                file=pfile, line=pline,
                suggestion=f"raise accumulate_steps (>= {m_ok} keeps "
                           f"GPipe under {BUBBLE_WARN_FRACTION:.0%}) or "
                           "switch to VPP/ZBH1 (vpp_degree>1 divides the "
                           "bubble by V)"))

    # -- schedule cost estimate ---------------------------------------------
    if with_cost and stats is not None:
        est = cost_model.CostEstimate(n_devices=S)
        per_stage = max(stage_flops) if stage_flops else 0.0
        est.flops = per_stage * M
        # every schedule's "ticks" is its forward-phase hop count (ZB's
        # weighted wall_units are cost units, not hops) — comparable
        # across modes, forward-pass traffic like the FLOP figure above
        ticks = int(stats.get("ticks", 0))
        if act_bytes and ticks:
            est.collective_bytes["ppermute"] = float(act_bytes * ticks)
            est.collective_calls["ppermute"] = ticks
        if act_bytes:
            # xs microbatch stack + double-buffered boundary activations
            est.peak_hbm_bytes = float(act_bytes * (M + 2))
        report.cost = est
    return report
