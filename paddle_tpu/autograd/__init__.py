"""paddle_tpu.autograd (reference: python/paddle/autograd)."""
from .backward_mode import backward  # noqa: F401
from .py_layer import (  # noqa: F401
    PyLayer, PyLayerContext, saved_tensors_hooks,
)
from .functional import grad, jacobian, hessian, vjp, jvp  # noqa: F401
from ..core.tape import no_grad_guard as no_grad  # noqa: F401
from ..core.tape import enable_grad_guard as enable_grad  # noqa: F401
from ..core.tape import is_grad_enabled  # noqa: F401


class set_grad_enabled:
    def __init__(self, mode: bool):
        from ..core import tape
        self._mode = mode
        self._prev = tape._state.grad_enabled
        tape._state.grad_enabled = mode

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        from ..core import tape
        tape._state.grad_enabled = self._prev
        return False
