"""PyLayer — user-defined VJP in Python.

Reference: python/paddle/autograd/py_layer.py. Rebuilt on the tape: forward
runs under no_grad, then a TapeNode is installed whose vjp calls the user's
static backward().
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import tape as tape_mod
from ..core.dispatch import unwrap, wrap
from ..core.tensor import Tensor


_hooks_stack: list = []  # active saved_tensors_hooks (pack, unpack) pairs


class saved_tensors_hooks:
    """Intercept PyLayer saved tensors with pack/unpack hooks (reference:
    python/paddle/autograd/saved_tensors_hooks.py).

    pack_hook(tensor) runs at save_for_backward time (e.g. offload to host
    numpy); unpack_hook(packed) runs when backward reads saved_tensor().
    Only PyLayer saves route through here — built-in ops' residuals live
    inside jax.vjp closures, where XLA already owns their lifetime.
    """

    def __init__(self, pack_hook, unpack_hook):
        self.pack_hook = pack_hook
        self.unpack_hook = unpack_hook

    def __enter__(self):
        _hooks_stack.append((self.pack_hook, self.unpack_hook))
        return self

    def __exit__(self, *exc):
        _hooks_stack.pop()
        return False


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self._unpack = None
        self.materialize_grads = True

    def save_for_backward(self, *tensors):
        if _hooks_stack:
            pack, unpack = _hooks_stack[-1]
            self._saved = tuple(pack(t) for t in tensors)
            self._unpack = unpack
        else:
            self._saved = tensors

    def saved_tensor(self):
        """paddle API: a method, not a property
        (python/paddle/autograd/py_layer.py PyLayerContext.saved_tensor)."""
        if self._unpack is not None:
            return tuple(self._unpack(p) for p in self._saved)
        return self._saved

    saved_tensors = saved_tensor

    def set_materialize_grads(self, value: bool):
        self.materialize_grads = bool(value)


class PyLayerMeta(type):
    pass


class PyLayer(metaclass=PyLayerMeta):
    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        with tape_mod.no_grad_guard():
            outs = cls.forward(ctx, *args, **kwargs)

        single = not isinstance(outs, (tuple, list))
        out_list = [outs] if single else list(outs)

        tensor_inputs = [a for a in args if isinstance(a, Tensor)]
        diff_inputs = [t for t in tensor_inputs
                       if not t.stop_gradient
                       and jnp.issubdtype(t._data.dtype, jnp.inexact)]
        if tape_mod.is_grad_enabled() and diff_inputs:
            out_tensors = [o for o in out_list if isinstance(o, Tensor)]

            def vjp_fn(cotangents):
                if not isinstance(cotangents, (tuple, list)):
                    cotangents = (cotangents,)
                grads_in = [wrap(c) if c is not None else None
                            for c in cotangents]
                grads_out = cls.backward(
                    ctx, *(grads_in if len(grads_in) > 1 else grads_in))
                if not isinstance(grads_out, (tuple, list)):
                    grads_out = (grads_out,)
                return tuple(unwrap(g) if g is not None else None
                             for g in grads_out)

            # adapt: tape passes flat tuple of cotangents
            def adapted(flat_cts):
                res = vjp_fn(flat_cts)
                return res

            def adapted_single(ct):
                return vjp_fn((ct,))

            n_out = len(out_tensors)
            node = tape_mod.TapeNode(
                cls.__name__,
                adapted_single if n_out == 1 else adapted,
                [t._ensure_meta() for t in diff_inputs],
                list(diff_inputs),
                [(o._data.shape, o._data.dtype) for o in out_tensors])
            for k, o in enumerate(out_tensors):
                o.stop_gradient = False
                m = o._ensure_meta()
                m.node = node
                m.output_index = k
                o.is_leaf_ = False
        return outs


LegacyPyLayer = PyLayer


def once_differentiable(fn):
    return fn
