"""paddle.autograd.backward (reference: python/paddle/autograd/backward_mode.py)."""
from ..core import tape


def backward(tensors, grad_tensors=None, retain_graph=False):
    tape.backward(tensors, grad_tensors, retain_graph=retain_graph)
