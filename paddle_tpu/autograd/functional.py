"""Functional autograd: grad, vjp, jvp, Jacobian, Hessian.

Reference: python/paddle/incubate/autograd/functional.py:50,124,214,308 and
paddle.grad. On TPU these map directly onto jax.vjp/jvp/jacobian — the
framework's functional transforms are jax's, exposed with paddle signatures.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import tape as tape_mod
from ..core.dispatch import unwrap, wrap
from ..core.tensor import Tensor


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    """paddle.grad — tape-based partial derivative query."""
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    # grads collect into a sink dict: paddle.grad must leave every
    # tensor's .grad untouched (including NON-input leaves)
    sink = {}
    tape_mod.backward(list(outputs), grad_outputs,
                      retain_graph=True if retain_graph is None
                      else retain_graph,
                      create_graph=create_graph, grad_sink=sink,
                      capture_ids=frozenset(id(t) for t in inputs))
    results = []
    for t in inputs:
        g = sink.get(id(t))
        if g is not None and not isinstance(g, Tensor):
            g = Tensor._from_array(g, stop_gradient=True)
        if g is None and not allow_unused:
            g = Tensor._from_array(jnp.zeros_like(t._data))
        results.append(g)
    return results


def _as_fn_and_arrays(func, xs):
    xs = xs if isinstance(xs, (list, tuple)) else [xs]
    arrays = [unwrap(x) for x in xs]

    def fn(*arrs):
        with tape_mod.no_grad_guard():
            ins = [Tensor._from_array(a) for a in arrs]
            out = func(*ins)
        if isinstance(out, (list, tuple)):
            return tuple(unwrap(o) for o in out)
        return unwrap(out)
    return fn, arrays


def vjp(func, xs, v=None):
    fn, arrays = _as_fn_and_arrays(func, xs)
    out, vjp_fn = jax.vjp(fn, *arrays)
    if v is None:
        v = jax.tree_util.tree_map(jnp.ones_like, out)
    else:
        v = jax.tree_util.tree_map(unwrap, v,
                                   is_leaf=lambda x: isinstance(x, Tensor))
    grads = vjp_fn(v)
    wrap_t = lambda tr: jax.tree_util.tree_map(wrap, tr)
    grads = grads[0] if len(grads) == 1 else list(grads)
    return wrap_t(out), wrap_t(grads)


def jvp(func, xs, v=None):
    fn, arrays = _as_fn_and_arrays(func, xs)
    if v is None:
        tangents = [jnp.ones_like(a) for a in arrays]
    else:
        v = v if isinstance(v, (list, tuple)) else [v]
        tangents = [unwrap(t) for t in v]
    out, jv = jax.jvp(fn, tuple(arrays), tuple(tangents))
    wrap_t = lambda tr: jax.tree_util.tree_map(wrap, tr)
    return wrap_t(out), wrap_t(jv)


class Jacobian:
    """Lazy Jacobian (reference: incubate/autograd/functional.py:214)."""

    def __init__(self, func, xs, is_batched=False):
        fn, arrays = _as_fn_and_arrays(func, xs)
        jac = jax.jacrev(fn, argnums=tuple(range(len(arrays))))(*arrays)
        if len(arrays) == 1 and not isinstance(jac, tuple):
            jac = (jac,)
        if isinstance(jac, tuple) and len(jac) == 1:
            self._value = wrap(jnp.asarray(jac[0]))
        else:
            self._value = [wrap(jnp.asarray(j)) for j in jac]
        self.is_batched = is_batched

    def __getitem__(self, idx):
        v = self._value if isinstance(self._value, Tensor) else \
            self._value[0]
        return v[idx]

    @property
    def shape(self):
        v = self._value if isinstance(self._value, Tensor) else \
            self._value[0]
        return v.shape


class Hessian:
    def __init__(self, func, xs, is_batched=False):
        fn, arrays = _as_fn_and_arrays(func, xs)
        hess = jax.hessian(fn)(*arrays)
        self._value = wrap(jnp.asarray(hess))
        self.is_batched = is_batched

    def __getitem__(self, idx):
        return self._value[idx]

    @property
    def shape(self):
        return self._value.shape


def jacobian(func, xs, is_batched=False):
    return Jacobian(func, xs, is_batched)


def hessian(func, xs, is_batched=False):
    return Hessian(func, xs, is_batched)


def forward_grad(func, xs, v=None):
    """Forward-mode AD (reference: incubate/autograd/primapi.py:36)."""
    return jvp(func, xs, v)[1]
