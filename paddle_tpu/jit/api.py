"""paddle_tpu.jit — to_static and the compiled TrainStep.

Reference: python/paddle/jit/api.py:197 (to_static). The reference needs a
bytecode JIT (SOT) + AST rewriting + a static IR + its own executor; on TPU
jax.jit IS that entire stack: to_static wraps a function/Layer so calls
trace once per input signature and run the cached XLA executable.

TrainStep is the performance path (SURVEY.md §7.2 stage 3): one jax.jit
containing forward + loss + backward (jax.grad) + optimizer update +
buffer updates, with donated argnums so parameter/optimizer-state memory is
reused in place on TPU.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import random as random_mod
from ..core import tape as tape_mod
from ..core.dispatch import run_op, unwrap, wrap
from ..core.tensor import Tensor
from .functional import (functional_call, get_buffers, get_frozen,
                         get_params, write_back)


class InputSpec:
    """Shape/dtype spec (reference: paddle.static.InputSpec)."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = list(shape)
        self.dtype = dtype
        self.name = name

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype})"


def _sig_of(args, kwargs):
    parts = []
    for a in args:
        if isinstance(a, Tensor):
            parts.append(("T", tuple(a._data.shape), str(a._data.dtype)))
        elif isinstance(a, (jnp.ndarray, jax.Array, np.ndarray)):
            parts.append(("A", tuple(a.shape), str(a.dtype)))
        else:
            parts.append(("S", repr(a)))
    for k in sorted(kwargs):
        v = kwargs[k]
        if isinstance(v, Tensor):
            parts.append((k, tuple(v._data.shape), str(v._data.dtype)))
        else:
            parts.append((k, repr(v)))
    return tuple(parts)


class StaticFunction:
    """A function compiled per input signature; Tensor-in/Tensor-out and
    differentiable through the dygraph tape (the compiled forward is one
    tape op whose vjp is the compiled backward)."""

    def __init__(self, fn, input_spec=None, build_strategy=None,
                 backend=None, full_graph=True):
        self._fn = fn
        self._layer = None
        if hasattr(fn, "forward") and hasattr(fn, "named_parameters"):
            self._layer = fn
            self._fn = fn.forward
        self._input_spec = input_spec
        self._cache = {}
        # signatures that graph-broke -> eager calls since the pin; other
        # signatures keep their compiled entries. A pin is dropped (and
        # compilation retried) every _RETRY_AFTER fallback calls, so a
        # signature that traced badly once — e.g. before a warmup flag
        # flipped — is not condemned to eager forever. After
        # _MAX_RETRIES failed retries the pin becomes permanent — a
        # genuinely value-dependent branch must not pay a guaranteed-to-
        # fail re-trace every 16th call for the life of the process
        self._eager_sigs = {}
        self._retry_counts = {}
        self._child_sf = None  # lazily-built per-sublayer compilers
        self._warned_break = False
        functools.update_wrapper(self, self._fn)

    _RETRY_AFTER = 16
    _MAX_RETRIES = 3

    @property
    def layer(self):
        return self._layer

    def concrete_program(self):
        return None  # no program world on TPU

    def _pure(self, static_kwargs):
        layer = self._layer
        fn = self._fn

        if layer is None:
            def pure(*arrays):
                with tape_mod.no_grad_guard():
                    targs = [Tensor._from_array(a) for a in arrays]
                    out = fn(*targs, **static_kwargs)
                return jax.tree_util.tree_map(
                    lambda t: t._data if isinstance(t, Tensor) else t, out,
                    is_leaf=lambda t: isinstance(t, Tensor))
            return pure

        def pure(params, buffers, frozen, key, *arrays):
            out, new_buf = functional_call(
                layer, params, buffers, arrays, static_kwargs,
                frozen=frozen, rng_key=key)
            return out, new_buf
        return pure

    # tracer-concretization errors = the reference's "graph break":
    # value-dependent Python control flow the tracer cannot stage
    # (reference jit/sot/translate.py:91 falls back to eager for the
    # un-traceable region; here the region is the whole call)
    _BREAK_ERRORS = (
        jax.errors.TracerBoolConversionError,
        jax.errors.TracerIntegerConversionError,
        jax.errors.TracerArrayConversionError,
        jax.errors.ConcretizationTypeError,
    )

    def _graph_break(self, exc, args, kwargs):
        if not self._warned_break:
            import warnings
            name = getattr(self._fn, "__qualname__", repr(self._fn))
            how = ("keeping each traceable sublayer compiled and running "
                   "only the parent control flow eagerly"
                   if self._layer is not None else
                   "falling back to eager for this function")
            warnings.warn(
                f"to_static({name}): value-dependent Python control flow "
                f"cannot be traced ({type(exc).__name__}); {how}. Use "
                "paddle.static.nn.cond / while_loop to keep the whole "
                "graph compiled.", stacklevel=3)
            self._warned_break = True
        return self._fallback_call(args, kwargs)

    def _fallback_call(self, args, kwargs):
        """The reference's SOT breaks the graph at the un-traceable
        opcode and keeps the regions on both sides compiled
        (jit/sot/translate.py:91). The per-sublayer analog: run the
        parent's forward as Python, but route every sublayer call that
        originates from the eager region through its own StaticFunction
        — a 10-layer model with one value-dependent branch keeps the
        other layers compiled. Sublayer calls that happen *inside* an
        enclosing trace inline their original forward, so the largest
        traceable subtree compiles as one unit. Plain functions (no
        layer tree to segment) run fully eager."""
        if self._layer is None:
            return self._fn(*args, **kwargs)
        layer = self._layer
        # the compiled sublayer path returns fresh (tape-less) Tensors,
        # same as the whole-layer compiled path; when the caller is
        # recording gradients — through the params OR through a
        # grad-requiring input (frozen-model adversarial/inversion
        # loops) — the only correct fallback is full eager
        def _wants_grad(obj):
            leaves = jax.tree_util.tree_leaves(
                obj, is_leaf=lambda t: isinstance(t, Tensor))
            return any(isinstance(t, Tensor) and not t.stop_gradient
                       for t in leaves)

        if tape_mod.is_grad_enabled() and (
                any(not p.stop_gradient for p in layer.parameters())
                or _wants_grad((args, kwargs))):
            return layer(*args, **kwargs)
        if self._child_sf is None:
            self._child_sf = {}
        patched = []
        try:
            for name, child in layer.named_sublayers():
                if "forward" in child.__dict__:
                    continue  # already patched (shared module)
                sf = self._child_sf.get(name)
                if sf is None:
                    sf = StaticFunction(child)
                    self._child_sf[name] = sf
                child.forward = _child_compiled_forward(child, sf)
                patched.append(child)
            return layer(*args, **kwargs)
        finally:
            for child in patched:
                try:
                    del child.forward
                except AttributeError:
                    pass

    def __call__(self, *args, **kwargs):
        tensor_args = []
        static_kwargs = {}
        for a in args:
            tensor_args.append(a)
        for k, v in kwargs.items():
            if isinstance(v, Tensor):
                tensor_args.append(v)  # rare; treat as positional tail
            else:
                static_kwargs[k] = v
        sig = _sig_of(tensor_args, static_kwargs)
        pinned = self._eager_sigs.get(sig)
        if pinned is not None:
            if (pinned + 1 < self._RETRY_AFTER
                    or self._retry_counts.get(sig, 0)
                    >= self._MAX_RETRIES):
                if pinned + 1 < self._RETRY_AFTER:
                    self._eager_sigs[sig] = pinned + 1
                return self._fallback_call(args, kwargs)
            # the branch value (or a warmup flag) may have changed since
            # the pin: drop it and give the full graph another chance
            del self._eager_sigs[sig]
            self._retry_counts[sig] = self._retry_counts.get(sig, 0) + 1
        entry = self._cache.get(sig)
        if self._layer is None:
            if entry is None:
                entry = jax.jit(self._pure(static_kwargs))
                self._cache[sig] = entry
            try:
                # ONE tape op: compiled forward, vjp = compiled backward
                return run_op("jit_fn", entry, tensor_args)
            except self._BREAK_ERRORS as exc:
                self._eager_sigs[sig] = 0
                return self._graph_break(exc, args, kwargs)

        layer = self._layer
        params = get_params(layer)
        buffers = get_buffers(layer)
        frozen = get_frozen(layer)
        if entry is None:
            entry = jax.jit(self._pure(static_kwargs))
            self._cache[sig] = entry
        key = random_mod.next_key()
        arrays = [unwrap(a) for a in tensor_args]
        try:
            out_arrays, new_buf = entry(params, buffers, frozen, key,
                                        *arrays)
        except self._BREAK_ERRORS as exc:
            self._eager_sigs[sig] = 0
            return self._graph_break(exc, args, kwargs)
        write_back(layer, {}, new_buf)
        return jax.tree_util.tree_map(
            lambda a: wrap(a), out_arrays,
            is_leaf=lambda a: isinstance(a, (jax.Array, np.ndarray)))


def _under_trace(args, kwargs):
    leaves = jax.tree_util.tree_leaves(
        (args, kwargs),
        is_leaf=lambda t: isinstance(t, Tensor))
    for leaf in leaves:
        arr = leaf._data if isinstance(leaf, Tensor) else leaf
        if isinstance(arr, jax.core.Tracer):
            return True
    return False


def _child_compiled_forward(child, sf):
    """Instance-level forward override used during a parent's partial
    (graph-broken) call: the sublayer call goes through its own
    StaticFunction. The override is lifted around the delegated call so
    tracing (and any eager fallback inside ``sf``) reaches the real
    forward instead of recursing into this wrapper. Calls arriving with
    tracer inputs are already inside an enclosing sublayer's trace —
    inline the original forward there (a nested StaticFunction would
    write traced buffers back into live layers)."""
    def wrapper(*a, **kw):
        del child.forward
        try:
            if _under_trace(a, kw):
                return child.forward(*a, **kw)
            return sf(*a, **kw)
        finally:
            child.forward = wrapper
    return wrapper


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, **kwargs):
    """Decorator/wrapper compiling a function or Layer's forward."""
    def wrap_fn(fn):
        return StaticFunction(fn, input_spec, build_strategy, backend)
    if function is None:
        return wrap_fn
    return wrap_fn(function)


def not_to_static(fn=None):
    if fn is None:
        return lambda f: f
    return fn


def enable_to_static(flag=True):
    pass


class TrainStep:
    """Whole-train-step compilation:

        loss = step(inputs, labels)

    runs forward + loss + jax.grad + optimizer update + buffer update as a
    single donated jax.jit executable and syncs results back into the
    Layer/Optimizer objects so eager code (hooks, prints, checkpoints)
    sees fresh state.
    """

    def __init__(self, model, loss_fn, optimizer, amp_dtype=None,
                 donate=True):
        self._model = model
        self._loss_fn = loss_fn
        self._opt = optimizer
        self._amp_dtype = amp_dtype
        self._params = get_params(model)
        self._frozen = get_frozen(model)
        self._buffers = get_buffers(model)
        self._opt_state = optimizer.init_state_pytree(self._params)
        self._compiled = {}
        self._donate = donate
        from .functional import _tensor_registry
        self._registry = _tensor_registry(model)

    def _make_step(self):
        model, loss_fn, opt = self._model, self._loss_fn, self._opt
        amp_dtype = self._amp_dtype

        def loss_of(params, buffers, frozen, key, inputs, labels):
            if amp_dtype is not None:
                cast_params = jax.tree_util.tree_map(
                    lambda a: a.astype(amp_dtype)
                    if jnp.issubdtype(a.dtype, jnp.floating) else a, params)
                # O2 semantics: float inputs run in the compute dtype too
                # (lax.conv rejects mixed fp32-input/bf16-weight; labels
                # stay untouched for the loss)
                inputs = jax.tree_util.tree_map(
                    lambda a: a.astype(amp_dtype)
                    if jnp.issubdtype(a.dtype, jnp.floating) else a, inputs)
            else:
                cast_params = params
            out, new_buf = functional_call(
                model, cast_params, buffers, inputs, {},
                frozen=frozen, rng_key=key, training=True)
            with tape_mod.no_grad_guard():
                out_t = jax.tree_util.tree_map(
                    lambda a: Tensor._from_array(a), out,
                    is_leaf=lambda a: isinstance(a, jax.Array))
                lab_t = jax.tree_util.tree_map(
                    lambda a: Tensor._from_array(a), labels,
                    is_leaf=lambda a: isinstance(a, jax.Array))
                loss = loss_fn(out_t, lab_t)
            return unwrap(loss).astype(jnp.float32), new_buf

        def step(params, buffers, frozen, opt_state, key, lr, inputs,
                 labels):
            (loss, new_buf), grads = jax.value_and_grad(
                loss_of, has_aux=True)(params, buffers, frozen, key, inputs,
                                       labels)
            if opt._grad_clip is not None:
                grads = _clip_pytree(grads, opt._grad_clip)
            new_params, new_opt_state = opt.apply_gradients_pytree(
                params, grads, opt_state, lr)
            return new_params, new_buf, new_opt_state, loss

        donate = (0, 1, 3) if self._donate else ()
        return jax.jit(step, donate_argnums=donate)

    def __call__(self, inputs, labels):
        if not isinstance(inputs, (list, tuple)):
            inputs = (inputs,)
        in_arrays = tuple(unwrap(x) for x in inputs)
        lab_arrays = jax.tree_util.tree_map(
            lambda t: unwrap(t), labels,
            is_leaf=lambda t: isinstance(t, Tensor))
        sig = tuple((a.shape, str(a.dtype)) for a in in_arrays)
        fn = self._compiled.get(sig)
        if fn is None:
            fn = self._make_step()
            self._compiled[sig] = fn
        key = random_mod.next_key()
        lr = jnp.asarray(self._opt.get_lr(), jnp.float32)
        self._params, self._buffers, self._opt_state, loss = fn(
            self._params, self._buffers, self._frozen, self._opt_state, key,
            lr, in_arrays, lab_arrays)
        # re-point the Layer's tensors at the fresh outputs (reference
        # swap, no copies) — the donated inputs they held are now deleted,
        # and any eager read (state_dict/checkpoint/print) must see live
        # arrays without an explicit sync_to_model call
        write_back(self._model, self._params, self._buffers,
                   registry=self._registry)
        from ..distributed import watchdog
        watchdog.maybe_start_and_tick()
        return wrap(loss)

    def sync_to_model(self):
        """Write compiled-side state back into Layer/Optimizer tensors."""
        write_back(self._model, self._params, self._buffers)
        name_of = {name: p for name, p in self._model.named_parameters()}
        for name, state in self._opt_state.items():
            p = name_of.get(name)
            if p is not None:
                self._opt._accumulators[id(p)] = dict(state)

    def sync_from_model(self):
        self._params = get_params(self._model)
        self._frozen = get_frozen(self._model)
        self._buffers = get_buffers(self._model)

    @property
    def loss_scale(self):
        return 1.0


def _clip_pytree(grads, clip):
    """Apply a nn.Clip* object to a {name: array} pytree inside jit."""
    from ..nn.clip import (ClipGradByGlobalNorm, ClipGradByNorm,
                           ClipGradByValue)
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    if isinstance(clip, ClipGradByValue):
        leaves = [jnp.clip(g, clip.min, clip.max) for g in leaves]
    elif isinstance(clip, ClipGradByNorm):
        out = []
        for g in leaves:
            n = jnp.sqrt(jnp.sum(jnp.square(g)))
            s = jnp.where(n > clip.clip_norm,
                          clip.clip_norm / jnp.maximum(n, 1e-12), 1.0)
            out.append(g * s)
        leaves = out
    elif isinstance(clip, ClipGradByGlobalNorm):
        total = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                    for g in leaves)
        gn = jnp.sqrt(total)
        scale = clip.clip_norm / jnp.maximum(gn, clip.clip_norm)
        leaves = [(g.astype(jnp.float32) * scale).astype(g.dtype)
                  for g in leaves]
    return jax.tree_util.tree_unflatten(treedef, leaves)


def compile_train_step(model, loss_fn, optimizer, **kw):
    return TrainStep(model, loss_fn, optimizer, **kw)
