"""paddle_tpu.jit — to_static and the compiled TrainStep.

Reference: python/paddle/jit/api.py:197 (to_static). The reference needs a
bytecode JIT (SOT) + AST rewriting + a static IR + its own executor; on TPU
jax.jit IS that entire stack: to_static wraps a function/Layer so calls
trace once per input signature and run the cached XLA executable.

TrainStep is the performance path (SURVEY.md §7.2 stage 3): one jax.jit
containing forward + loss + backward (jax.grad) + optimizer update +
buffer updates, with donated argnums so parameter/optimizer-state memory is
reused in place on TPU.
"""
from __future__ import annotations

import functools
import inspect as _inspect
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import random as random_mod
from ..core import tape as tape_mod
from ..core.dispatch import run_op, unwrap, wrap
from ..core.tensor import Tensor
from .functional import (functional_call, get_buffers, get_frozen,
                         get_params, write_back)


class InputSpec:
    """Shape/dtype spec (reference: paddle.static.InputSpec)."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = list(shape)
        self.dtype = dtype
        self.name = name

    def matches(self, shape, dtype) -> Optional[str]:
        """None if (shape, dtype) satisfies the spec, else the reason.
        None/-1 spec dims are wildcards (dynamic batch)."""
        shape = tuple(shape)
        if len(shape) != len(self.shape):
            return (f"rank mismatch: got {list(shape)}, spec expects "
                    f"{self.shape}")
        for got, want in zip(shape, self.shape):
            if want not in (None, -1) and got != want:
                return (f"shape mismatch: got {list(shape)}, spec expects "
                        f"{self.shape}")
        from ..core import dtype as dtype_mod
        try:
            want_np = dtype_mod.dtype(self.dtype).np_dtype
        except Exception:
            # a typo'd spec dtype must not silently disable the check
            return (f"spec dtype {self.dtype!r} is not a known dtype "
                    "(typo in the InputSpec?)")
        if np.dtype(dtype) != np.dtype(want_np):
            return f"dtype mismatch: got {dtype}, spec expects {self.dtype}"
        return None

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype})"


def _sig_of(args, kwargs):
    parts = []
    for a in args:
        if isinstance(a, Tensor):
            parts.append(("T", tuple(a._data.shape), str(a._data.dtype)))
        elif isinstance(a, (jnp.ndarray, jax.Array, np.ndarray)):
            parts.append(("A", tuple(a.shape), str(a.dtype)))
        else:
            parts.append(("S", repr(a)))
    for k in sorted(kwargs):
        v = kwargs[k]
        if isinstance(v, Tensor):
            parts.append((k, tuple(v._data.shape), str(v._data.dtype)))
        elif isinstance(v, (jnp.ndarray, jax.Array, np.ndarray)):
            # shape/dtype only — repr(v) would bake element VALUES into
            # the cache key (a new entry per batch of data, and keys the
            # size of the array's print form)
            parts.append((k, tuple(v.shape), str(v.dtype)))
        else:
            parts.append((k, repr(v)))
    return tuple(parts)


class StaticFunction:
    """A function compiled per input signature; Tensor-in/Tensor-out and
    differentiable through the dygraph tape (the compiled forward is one
    tape op whose vjp is the compiled backward)."""

    def __init__(self, fn, input_spec=None, build_strategy=None,
                 backend=None, full_graph=True):
        self._fn = fn
        self._layer = None
        if hasattr(fn, "forward") and hasattr(fn, "named_parameters"):
            self._layer = fn
            self._fn = fn.forward
        self._input_spec = input_spec
        self._cache = {}
        # signatures that graph-broke -> eager calls since the pin; other
        # signatures keep their compiled entries. A pin is dropped (and
        # compilation retried) every _RETRY_AFTER fallback calls, so a
        # signature that traced badly once — e.g. before a warmup flag
        # flipped — is not condemned to eager forever. After
        # _MAX_RETRIES failed retries the pin becomes permanent — a
        # genuinely value-dependent branch must not pay a guaranteed-to-
        # fail re-trace every 16th call for the life of the process
        self._eager_sigs = {}
        self._retry_counts = {}
        self._child_sf = None  # lazily-built per-sublayer compilers
        self._warned_break = False
        functools.update_wrapper(self, self._fn)

    _RETRY_AFTER = 16
    _MAX_RETRIES = 3

    @property
    def layer(self):
        return self._layer

    @property
    def input_spec(self):
        return self._input_spec

    def concrete_program(self):
        return None  # no program world on TPU

    def _spec_list(self):
        if self._input_spec is None:
            return None
        return list(self._input_spec) \
            if isinstance(self._input_spec, (list, tuple)) \
            else [self._input_spec]

    def _validate_input_spec(self, tensor_args):
        """Honor the stored InputSpec: reject calls whose array shapes/
        dtypes contradict the declared signature (the reference's
        dy2static does this at Program build; here the check is the
        only thing standing between a typo and a silent recompile)."""
        specs = self._spec_list()
        if not specs:
            return
        for i, (spec, a) in enumerate(zip(specs, tensor_args)):
            if not isinstance(spec, InputSpec):
                continue
            arr = a._data if isinstance(a, Tensor) else a
            if not isinstance(arr, (jnp.ndarray, jax.Array, np.ndarray)):
                continue
            why = spec.matches(arr.shape, arr.dtype)
            if why is not None:
                name = getattr(self._fn, "__qualname__", "to_static fn")
                raise ValueError(
                    f"{name}: input #{i} violates input_spec: {why}")

    def inspect(self, *args, mesh=None, **kwargs):
        """Statically lint this function at the given example inputs —
        AST trace-safety pass plus jaxpr rule passes over an abstract
        trace (jax.make_jaxpr on ShapeDtypeStructs; nothing runs on
        device). With no arguments, shapes come from the stored
        InputSpec list. `mesh` (a Mesh, AbstractMesh, or {axis: degree}
        dict — still device-free) additionally runs the shard_lint
        SPMD/collective rules and attaches a static cost estimate.
        Returns an analysis.Report."""
        from ..analysis import lint_static_function
        return lint_static_function(self, args if args else None, kwargs,
                                    mesh=mesh)

    def _maybe_lint_first_compile(self, args, kwargs):
        """Opt-in (PADDLE_TPU_LINT=1) hook run when a signature first
        compiles: findings go through paddle_tpu.monitor counters and
        one warning. Never allowed to break the call."""
        from ..analysis import lint_on_first_compile
        lint_on_first_compile(self.inspect, *args, **kwargs)

    def _pure(self, static_kwargs):
        layer = self._layer
        fn = self._fn

        # array-valued kwargs ride along as one traced dict pytree,
        # re-wrapped and bound BY NAME — positional-tail binding would
        # attach them to the wrong parameter, and leaving them in
        # static_kwargs would bake their values into the closure while
        # the cache key only carries shape/dtype
        def wrap_kw(arr_kwargs):
            kw = dict(static_kwargs)
            for k, a in arr_kwargs.items():
                kw[k] = Tensor._from_array(a)
            return kw

        if layer is None:
            def pure(arr_kwargs, *arrays):
                with tape_mod.no_grad_guard():
                    targs = [Tensor._from_array(a) for a in arrays]
                    out = fn(*targs, **wrap_kw(arr_kwargs))
                return jax.tree_util.tree_map(
                    lambda t: t._data if isinstance(t, Tensor) else t, out,
                    is_leaf=lambda t: isinstance(t, Tensor))
            return pure

        def pure(params, buffers, frozen, key, arr_kwargs, *arrays):
            out, new_buf = functional_call(
                layer, params, buffers, arrays, wrap_kw(arr_kwargs),
                frozen=frozen, rng_key=key)
            return out, new_buf
        return pure

    # tracer-concretization errors = the reference's "graph break":
    # value-dependent Python control flow the tracer cannot stage
    # (reference jit/sot/translate.py:91 falls back to eager for the
    # un-traceable region; here the region is the whole call)
    _BREAK_ERRORS = (
        jax.errors.TracerBoolConversionError,
        jax.errors.TracerIntegerConversionError,
        jax.errors.TracerArrayConversionError,
        jax.errors.ConcretizationTypeError,
    )

    def _graph_break(self, exc, args, kwargs):
        if not self._warned_break:
            import warnings
            name = getattr(self._fn, "__qualname__", repr(self._fn))
            how = ("keeping each traceable sublayer compiled and running "
                   "only the parent control flow eagerly"
                   if self._layer is not None else
                   "falling back to eager for this function")
            warnings.warn(
                f"to_static({name}): value-dependent Python control flow "
                f"cannot be traced ({type(exc).__name__}); {how}. Use "
                "paddle.static.nn.cond / while_loop to keep the whole "
                "graph compiled.", stacklevel=3)
            self._warned_break = True
        return self._fallback_call(args, kwargs)

    def _fallback_call(self, args, kwargs):
        """The reference's SOT breaks the graph at the un-traceable
        opcode and keeps the regions on both sides compiled
        (jit/sot/translate.py:91). The per-sublayer analog: run the
        parent's forward as Python, but route every sublayer call that
        originates from the eager region through its own StaticFunction
        — a 10-layer model with one value-dependent branch keeps the
        other layers compiled. Sublayer calls that happen *inside* an
        enclosing trace inline their original forward, so the largest
        traceable subtree compiles as one unit. Plain functions (no
        layer tree to segment) run fully eager."""
        if self._layer is None:
            return self._fn(*args, **kwargs)
        layer = self._layer
        # the compiled sublayer path returns fresh (tape-less) Tensors,
        # same as the whole-layer compiled path; when the caller is
        # recording gradients — through the params OR through a
        # grad-requiring input (frozen-model adversarial/inversion
        # loops) — the only correct fallback is full eager
        def _wants_grad(obj):
            leaves = jax.tree_util.tree_leaves(
                obj, is_leaf=lambda t: isinstance(t, Tensor))
            return any(isinstance(t, Tensor) and not t.stop_gradient
                       for t in leaves)

        if tape_mod.is_grad_enabled() and (
                any(not p.stop_gradient for p in layer.parameters())
                or _wants_grad((args, kwargs))):
            return layer(*args, **kwargs)
        if self._child_sf is None:
            self._child_sf = {}
        patched = []
        try:
            for name, child in layer.named_sublayers():
                if "forward" in child.__dict__:
                    continue  # already patched (shared module)
                sf = self._child_sf.get(name)
                if sf is None:
                    sf = StaticFunction(child)
                    self._child_sf[name] = sf
                child.forward = _child_compiled_forward(child, sf)
                patched.append(child)
            return layer(*args, **kwargs)
        finally:
            for child in patched:
                try:
                    del child.forward
                except AttributeError:
                    pass

    def _positionalize(self, tensor_args, kwargs):
        """Move keyword-passed arrays into their positional slots (by
        the function's signature) while the slots stay contiguous.
        Positional arrays get the full treatment — gradient flow, spec
        validation, _sig_of keying; only non-contiguous array kwargs
        are left to the (non-differentiable) traced-dict path."""
        if not kwargs:
            return kwargs
        try:
            params = list(_inspect.signature(
                self._fn).parameters.values())
        except (TypeError, ValueError):
            return kwargs
        kwargs = dict(kwargs)
        for p in params[len(tensor_args):]:
            if (p.kind != p.POSITIONAL_OR_KEYWORD
                    or p.name not in kwargs
                    or not isinstance(kwargs[p.name],
                                      (Tensor, jnp.ndarray, jax.Array,
                                       np.ndarray))):
                break
            tensor_args.append(kwargs.pop(p.name))
        return kwargs

    def __call__(self, *args, **kwargs):
        tensor_args = list(args)
        kwargs = self._positionalize(tensor_args, kwargs)
        # the positionalized form IS the call from here on — the
        # graph-break fallback and the lint hook must see the same
        # program the trace saw, not the original kwargs (a moved
        # kwarg would silently fall back to its default)
        args = tuple(tensor_args)
        tensor_kwargs = {}
        static_kwargs = {}
        for k, v in kwargs.items():
            if isinstance(v, Tensor) and not v.stop_gradient \
                    and tape_mod.is_grad_enabled():
                import warnings
                warnings.warn(
                    f"to_static: tensor kwarg '{k}' requires grad but "
                    "cannot take a positional slot (keyword-only, or "
                    "behind a non-tensor kwarg); gradients do NOT flow "
                    "through keyword tensors in the compiled path — "
                    "pass it positionally.", stacklevel=2)
            if isinstance(v, (Tensor, jnp.ndarray, jax.Array, np.ndarray)):
                # traced by name through _pure's arr_kwargs dict: in
                # static_kwargs the VALUES would be baked into the
                # jitted closure while the cache key only carries
                # shape/dtype (stale replay); on the positional tail
                # they would bind to the wrong parameter. Gradients do
                # NOT flow through this dict — only through positional
                # (incl. positionalized) tensors
                tensor_kwargs[k] = v
            else:
                static_kwargs[k] = v
        self._validate_input_spec(tensor_args)
        sig = _sig_of(tensor_args, {**static_kwargs, **tensor_kwargs})
        kw_arrays = {k: unwrap(v) for k, v in tensor_kwargs.items()}
        pinned = self._eager_sigs.get(sig)
        if pinned is not None:
            if (pinned + 1 < self._RETRY_AFTER
                    or self._retry_counts.get(sig, 0)
                    >= self._MAX_RETRIES):
                if pinned + 1 < self._RETRY_AFTER:
                    self._eager_sigs[sig] = pinned + 1
                return self._fallback_call(args, kwargs)
            # the branch value (or a warmup flag) may have changed since
            # the pin: drop it and give the full graph another chance
            del self._eager_sigs[sig]
            self._retry_counts[sig] = self._retry_counts.get(sig, 0) + 1
        entry = self._cache.get(sig)
        if self._layer is None:
            if entry is None:
                entry = jax.jit(self._pure(static_kwargs))
                self._cache[sig] = entry
                self._maybe_lint_first_compile(args, kwargs)
            try:
                # ONE tape op: compiled forward, vjp = compiled backward
                # (kwarg arrays ride in the leading dict — non-diff)
                return run_op("jit_fn", entry, [kw_arrays] + tensor_args)
            except self._BREAK_ERRORS as exc:
                self._eager_sigs[sig] = 0
                return self._graph_break(exc, args, kwargs)

        layer = self._layer
        params = get_params(layer)
        buffers = get_buffers(layer)
        frozen = get_frozen(layer)
        if entry is None:
            entry = jax.jit(self._pure(static_kwargs))
            self._cache[sig] = entry
            self._maybe_lint_first_compile(args, kwargs)
        key = random_mod.next_key()
        arrays = [unwrap(a) for a in tensor_args]
        try:
            out_arrays, new_buf = entry(params, buffers, frozen, key,
                                        kw_arrays, *arrays)
        except self._BREAK_ERRORS as exc:
            self._eager_sigs[sig] = 0
            return self._graph_break(exc, args, kwargs)
        write_back(layer, {}, new_buf)
        return jax.tree_util.tree_map(
            lambda a: wrap(a), out_arrays,
            is_leaf=lambda a: isinstance(a, (jax.Array, np.ndarray)))


def _under_trace(args, kwargs):
    leaves = jax.tree_util.tree_leaves(
        (args, kwargs),
        is_leaf=lambda t: isinstance(t, Tensor))
    for leaf in leaves:
        arr = leaf._data if isinstance(leaf, Tensor) else leaf
        if isinstance(arr, jax.core.Tracer):
            return True
    return False


def _child_compiled_forward(child, sf):
    """Instance-level forward override used during a parent's partial
    (graph-broken) call: the sublayer call goes through its own
    StaticFunction. The override is lifted around the delegated call so
    tracing (and any eager fallback inside ``sf``) reaches the real
    forward instead of recursing into this wrapper. Calls arriving with
    tracer inputs are already inside an enclosing sublayer's trace —
    inline the original forward there (a nested StaticFunction would
    write traced buffers back into live layers)."""
    def wrapper(*a, **kw):
        del child.forward
        try:
            if _under_trace(a, kw):
                return child.forward(*a, **kw)
            return sf(*a, **kw)
        finally:
            child.forward = wrapper
    return wrapper


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, **kwargs):
    """Decorator/wrapper compiling a function or Layer's forward."""
    def wrap_fn(fn):
        return StaticFunction(fn, input_spec, build_strategy, backend)
    if function is None:
        return wrap_fn
    return wrap_fn(function)


def not_to_static(fn=None):
    if fn is None:
        return lambda f: f
    return fn


def enable_to_static(flag=True):
    pass


class TrainStep:
    """Whole-train-step compilation:

        loss = step(inputs, labels)

    runs forward + loss + jax.grad + optimizer update + buffer update as a
    single donated jax.jit executable and syncs results back into the
    Layer/Optimizer objects so eager code (hooks, prints, checkpoints)
    sees fresh state.
    """

    def __init__(self, model, loss_fn, optimizer, amp_dtype=None,
                 donate=True):
        self._model = model
        self._loss_fn = loss_fn
        self._opt = optimizer
        self._amp_dtype = amp_dtype
        self._params = get_params(model)
        self._frozen = get_frozen(model)
        self._buffers = get_buffers(model)
        self._opt_state = optimizer.init_state_pytree(self._params)
        self._compiled = {}
        self._donate = donate
        from .functional import _tensor_registry
        self._registry = _tensor_registry(model)

    def _build_step(self):
        """The raw python step function (un-jitted) — also traced
        abstractly by analysis.lint_train_step."""
        model, loss_fn, opt = self._model, self._loss_fn, self._opt
        amp_dtype = self._amp_dtype

        def loss_of(params, buffers, frozen, key, inputs, labels):
            if amp_dtype is not None:
                cast_params = jax.tree_util.tree_map(
                    lambda a: a.astype(amp_dtype)
                    if jnp.issubdtype(a.dtype, jnp.floating) else a, params)
                # O2 semantics: float inputs run in the compute dtype too
                # (lax.conv rejects mixed fp32-input/bf16-weight; labels
                # stay untouched for the loss)
                inputs = jax.tree_util.tree_map(
                    lambda a: a.astype(amp_dtype)
                    if jnp.issubdtype(a.dtype, jnp.floating) else a, inputs)
            else:
                cast_params = params
            out, new_buf = functional_call(
                model, cast_params, buffers, inputs, {},
                frozen=frozen, rng_key=key, training=True)
            with tape_mod.no_grad_guard():
                out_t = jax.tree_util.tree_map(
                    lambda a: Tensor._from_array(a), out,
                    is_leaf=lambda a: isinstance(a, jax.Array))
                lab_t = jax.tree_util.tree_map(
                    lambda a: Tensor._from_array(a), labels,
                    is_leaf=lambda a: isinstance(a, jax.Array))
                loss = loss_fn(out_t, lab_t)
            return unwrap(loss).astype(jnp.float32), new_buf

        def step(params, buffers, frozen, opt_state, key, lr, inputs,
                 labels):
            (loss, new_buf), grads = jax.value_and_grad(
                loss_of, has_aux=True)(params, buffers, frozen, key, inputs,
                                       labels)
            if opt._grad_clip is not None:
                grads = _clip_pytree(grads, opt._grad_clip)
            new_params, new_opt_state = opt.apply_gradients_pytree(
                params, grads, opt_state, lr)
            return new_params, new_buf, new_opt_state, loss

        return step

    def _make_step(self):
        donate = (0, 1, 3) if self._donate else ()
        return jax.jit(self._build_step(), donate_argnums=donate)

    @staticmethod
    def _leaf_sig(tree):
        return tuple(
            (tuple(a.shape), str(a.dtype))
            if isinstance(a, (jnp.ndarray, jax.Array, np.ndarray))
            else ("S", repr(a))
            for a in jax.tree_util.tree_leaves(tree))

    def inspect(self, inputs, labels, mesh=None):
        """Statically lint the fused train step at the given example
        inputs/labels (Tensors, arrays, or InputSpecs — only shapes and
        dtypes are read; nothing executes on device). `mesh` adds the
        shard_lint collective rules + cost model over the same trace.
        Returns an analysis.Report."""
        from ..analysis import lint_train_step
        return lint_train_step(self, inputs, labels, mesh=mesh)

    def __call__(self, inputs, labels):
        if not isinstance(inputs, (list, tuple)):
            inputs = (inputs,)
        in_arrays = tuple(unwrap(x) for x in inputs)
        lab_arrays = jax.tree_util.tree_map(
            lambda t: unwrap(t), labels,
            is_leaf=lambda t: isinstance(t, Tensor))
        # label leaves are part of the executable's signature too: a
        # label shape/dtype change must not silently reuse (and retrace
        # under) the executable cached for the old labels
        sig = (self._leaf_sig(in_arrays), self._leaf_sig(lab_arrays))
        fn = self._compiled.get(sig)
        if fn is None:
            fn = self._make_step()
            self._compiled[sig] = fn
            from ..analysis import lint_on_first_compile
            lint_on_first_compile(self.inspect, inputs, labels)
        key = random_mod.next_key()
        lr = jnp.asarray(self._opt.get_lr(), jnp.float32)
        self._params, self._buffers, self._opt_state, loss = fn(
            self._params, self._buffers, self._frozen, self._opt_state, key,
            lr, in_arrays, lab_arrays)
        # re-point the Layer's tensors at the fresh outputs (reference
        # swap, no copies) — the donated inputs they held are now deleted,
        # and any eager read (state_dict/checkpoint/print) must see live
        # arrays without an explicit sync_to_model call
        write_back(self._model, self._params, self._buffers,
                   registry=self._registry)
        from ..distributed import watchdog
        watchdog.maybe_start_and_tick()
        return wrap(loss)

    def sync_to_model(self):
        """Write compiled-side state back into Layer/Optimizer tensors."""
        write_back(self._model, self._params, self._buffers)
        name_of = {name: p for name, p in self._model.named_parameters()}
        for name, state in self._opt_state.items():
            p = name_of.get(name)
            if p is not None:
                self._opt._accumulators[id(p)] = dict(state)

    def sync_from_model(self):
        self._params = get_params(self._model)
        self._frozen = get_frozen(self._model)
        self._buffers = get_buffers(self._model)

    @property
    def loss_scale(self):
        return 1.0


def _clip_pytree(grads, clip):
    """Apply a nn.Clip* object to a {name: array} pytree inside jit."""
    from ..nn.clip import (ClipGradByGlobalNorm, ClipGradByNorm,
                           ClipGradByValue)
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    if isinstance(clip, ClipGradByValue):
        leaves = [jnp.clip(g, clip.min, clip.max) for g in leaves]
    elif isinstance(clip, ClipGradByNorm):
        out = []
        for g in leaves:
            n = jnp.sqrt(jnp.sum(jnp.square(g)))
            s = jnp.where(n > clip.clip_norm,
                          clip.clip_norm / jnp.maximum(n, 1e-12), 1.0)
            out.append(g * s)
        leaves = out
    elif isinstance(clip, ClipGradByGlobalNorm):
        total = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                    for g in leaves)
        gn = jnp.sqrt(total)
        scale = clip.clip_norm / jnp.maximum(gn, clip.clip_norm)
        leaves = [(g.astype(jnp.float32) * scale).astype(g.dtype)
                  for g in leaves]
    return jax.tree_util.tree_unflatten(treedef, leaves)


def compile_train_step(model, loss_fn, optimizer, **kw):
    return TrainStep(model, loss_fn, optimizer, **kw)
