"""paddle_tpu.jit (reference: python/paddle/jit — SOT + dy2static + save).

jax.jit replaces the reference's entire compilation stack; see api.py.
"""
from .api import (  # noqa: F401
    InputSpec, StaticFunction, TrainStep, compile_train_step,
    enable_to_static, not_to_static, to_static,
)
from .functional import functional_call, get_buffers, get_params  # noqa: F401
from .serialization import load, save  # noqa: F401
from .serialization import TranslatedLayer  # noqa: F401

_ignored_modules: list = []


def ignore_module(modules):
    """Compat shim (reference: paddle.jit.ignore_module). The reference's
    SOT tracer skips these modules during bytecode capture; jax.jit traces
    by execution so there is nothing to skip — the list is recorded for
    introspection only."""
    global _ignored_modules
    if not isinstance(modules, (list, tuple)):
        modules = [modules]
    _ignored_modules.extend(modules)


def set_code_level(level=100, also_to_stdout=False):
    """Log transformed-code verbosity (reference: paddle.jit.set_code_level).
    There is no AST transform here; kept for API parity as a logging knob."""
    import logging
    logging.getLogger("paddle_tpu.jit").setLevel(logging.DEBUG)


def set_verbosity(level=0, also_to_stdout=False):
    """Set jit logging verbosity (reference: paddle.jit.set_verbosity)."""
    import logging
    lvl = logging.DEBUG if level > 0 else logging.WARNING
    logging.getLogger("paddle_tpu.jit").setLevel(lvl)
