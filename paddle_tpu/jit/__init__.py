"""paddle_tpu.jit (reference: python/paddle/jit — SOT + dy2static + save).

jax.jit replaces the reference's entire compilation stack; see api.py.
"""
from .api import (  # noqa: F401
    InputSpec, StaticFunction, TrainStep, compile_train_step,
    enable_to_static, not_to_static, to_static,
)
from .functional import functional_call, get_buffers, get_params  # noqa: F401
from .serialization import load, save  # noqa: F401
