"""Layer functionalization — the bridge between dygraph Layers and jax.jit.

The reference compiles dygraph code by capturing Python bytecode (SOT,
python/paddle/jit/sot) or rewriting ASTs (dy2static). On TPU neither is
needed: jax traces the *same eager op calls* the tape sees, so compiling a
Layer is just (1) lift its parameters/buffers into a pytree, (2) re-bind
them to traced arrays, (3) run forward under the tracer. This file
implements that re-binding.
"""
from __future__ import annotations

import contextlib
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from ..core import tape as tape_mod
from ..core.dispatch import unwrap, wrap
from ..core.tensor import Tensor


def get_params(layer) -> Dict[str, jnp.ndarray]:
    """{structured_name: array} for trainable parameters."""
    return {name: p._data for name, p in layer.named_parameters()
            if not p.stop_gradient}

def get_frozen(layer) -> Dict[str, jnp.ndarray]:
    return {name: p._data for name, p in layer.named_parameters()
            if p.stop_gradient}


def get_buffers(layer) -> Dict[str, jnp.ndarray]:
    return {name: b._data for name, b in layer.named_buffers()}


def _tensor_registry(layer):
    reg = {}
    for name, p in layer.named_parameters():
        reg[name] = p
    for name, b in layer.named_buffers():
        reg[name] = b
    return reg


@contextlib.contextmanager
def bind_state(layer, *state_dicts):
    """Temporarily swap the arrays inside the layer's Tensors for the given
    (possibly traced) arrays; restore on exit. Mutated buffer values are
    visible on the swapped Tensors when the context exits — callers read
    them before restore via the yielded registry."""
    reg = _tensor_registry(layer)
    saved = {name: t._data for name, t in reg.items()}
    try:
        for sd in state_dicts:
            for name, arr in sd.items():
                if name in reg:
                    reg[name]._data = arr
        yield reg
    finally:
        for name, t in reg.items():
            t._data = saved[name]


def functional_call(layer, params, buffers, args, kwargs=None,
                    frozen=None, rng_key=None, training=None):
    """Run layer.forward with params/buffers taken from pytrees.

    Returns (outputs_pytree_of_arrays, new_buffers). Runs with the dygraph
    tape disabled — differentiation happens at the whole-step level via
    jax.grad, the idiomatic XLA design (SURVEY.md §7.1).
    """
    from ..core import random as random_mod
    kwargs = kwargs or {}
    was_training = layer.training
    if training is not None:
        layer.train() if training else layer.eval()
    key_scope = random_mod.traced_key_scope(rng_key) if rng_key is not None \
        else contextlib.nullcontext()
    try:
        with bind_state(layer, params, buffers, frozen or {}) as reg, \
                tape_mod.no_grad_guard(), key_scope:
            targs = [Tensor._from_array(a) if isinstance(
                a, (jnp.ndarray, jax.Array)) else a for a in args]
            out = layer(*targs, **kwargs)
            buf_names = set(buffers)
            new_buffers = {n: reg[n]._data for n in buf_names}
            out_arrays = jax.tree_util.tree_map(
                lambda t: t._data if isinstance(t, Tensor) else t, out,
                is_leaf=lambda t: isinstance(t, Tensor))
    finally:
        if training is not None:
            layer.train() if was_training else layer.eval()
    return out_arrays, new_buffers


def write_back(layer, params, buffers=None, registry=None):
    """Push updated arrays back into the layer's Tensors (post-step sync).
    Pass a prebuilt `registry` (from _tensor_registry) on hot paths to
    skip the per-call module-tree walk."""
    reg = registry if registry is not None else _tensor_registry(layer)
    for name, arr in params.items():
        if name in reg:
            reg[name]._data = arr
    if buffers:
        for name, arr in buffers.items():
            if name in reg:
                reg[name]._data = arr
