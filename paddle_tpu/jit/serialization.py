"""jit.save / jit.load — AOT export of compiled functions.

Reference: python/paddle/jit/api.py:1788 (save TranslatedLayer),
paddle/fluid/jit (C++ loader). TPU-native: the portable artifact is a
serialized StableHLO module (jax.export) plus a parameter archive; load
returns a callable running the deserialized executable — the analog of the
reference's inference Program + params files.
"""
from __future__ import annotations

import os
import pickle

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import unwrap, wrap
from ..core.tensor import Tensor


def save(layer, path, input_spec=None, **config):
    """Serialize layer.forward (traced at input_spec shapes) + params."""
    from .api import InputSpec, StaticFunction
    from .functional import functional_call, get_buffers, get_frozen, \
        get_params

    if input_spec is None:
        raise ValueError("jit.save on TPU requires input_spec (static "
                         "shapes are what make AOT export possible)")
    params = get_params(layer)
    frozen = get_frozen(layer)
    buffers = get_buffers(layer)

    def infer(params_and_frozen, *arrays):
        p, f = params_and_frozen
        out, _ = functional_call(layer, p, buffers, arrays, {}, frozen=f,
                                 training=False)
        return out

    specs = []
    for s in input_spec:
        shape = s.shape if isinstance(s, InputSpec) else list(s)
        dtype = s.dtype if isinstance(s, InputSpec) else "float32"
        specs.append(jax.ShapeDtypeStruct(
            [1 if d is None or d == -1 else d for d in shape],
            jnp.dtype(dtype) if not hasattr(dtype, "np_dtype")
            else dtype.np_dtype))

    from jax import export as jax_export
    exported = jax_export.export(jax.jit(infer))(
        (params, frozen),
        *specs)
    blob = exported.serialize()

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path + ".pdmodel", "wb") as f:
        f.write(blob)
    state = {k: np.asarray(v) for k, v in params.items()}
    state.update({k: np.asarray(v) for k, v in frozen.items()})
    state["@buffers"] = {k: np.asarray(v) for k, v in buffers.items()}
    with open(path + ".pdiparams", "wb") as f:
        pickle.dump({"params": {k: np.asarray(v) for k, v in params.items()},
                     "frozen": {k: np.asarray(v) for k, v in frozen.items()},
                     "buffers": {k: np.asarray(v)
                                 for k, v in buffers.items()},
                     "n_inputs": len(specs)}, f)


class TranslatedLayer:
    """Loaded AOT artifact; callable like the original layer (inference)."""

    def __init__(self, exported, params, frozen, n_inputs=1):
        self._exported = exported
        self._params = {k: jnp.asarray(v) for k, v in params.items()}
        self._frozen = {k: jnp.asarray(v) for k, v in frozen.items()}
        self.num_inputs = n_inputs

    def __call__(self, *args):
        arrays = [unwrap(a) for a in args]
        out = self._exported.call((self._params, self._frozen), *arrays)
        return jax.tree_util.tree_map(
            lambda a: wrap(a), out,
            is_leaf=lambda a: isinstance(a, (jax.Array, np.ndarray)))

    def eval(self):
        return self

    def train(self):
        raise RuntimeError("a loaded inference artifact cannot be trained; "
                           "load the state_dict into a Layer instead")


def load(path, **config):
    from jax import export as jax_export
    with open(path + ".pdmodel", "rb") as f:
        exported = jax_export.deserialize(f.read())
    with open(path + ".pdiparams", "rb") as f:
        state = pickle.load(f)
    return TranslatedLayer(exported, state["params"], state["frozen"],
                           state.get("n_inputs", 1))
