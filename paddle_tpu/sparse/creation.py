"""Module alias (reference: sparse/creation.py)."""
from . import sparse_coo_tensor, sparse_csr_tensor  # noqa: F401

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor"]
