"""paddle.sparse.nn.functional (reference: sparse/nn/functional):
activations over sparse values + attention with a sparse mask."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .. import SparseTensor
from ...core.dispatch import unwrap, wrap


def _on_values(x: SparseTensor, fn):
    from jax.experimental import sparse as jsparse
    return SparseTensor(jsparse.BCOO((fn(x._bcoo.data), x._bcoo.indices),
                                     shape=x._bcoo.shape), x._fmt)


def relu(x, name=None):
    return _on_values(x, lambda d: jnp.maximum(d, 0))


def leaky_relu(x, negative_slope=0.01, name=None):
    return _on_values(x, lambda d: jnp.where(d >= 0, d,
                                             negative_slope * d))


def relu6(x, name=None):
    return _on_values(x, lambda d: jnp.clip(d, 0, 6))


def softmax(x, axis=-1, name=None):
    """Softmax over the stored values per row (reference:
    sparse.nn.functional.softmax on CSR rows). Densifies the row,
    masking empty entries out of the normalization."""
    dense = unwrap(x.to_dense()) if hasattr(x, "to_dense") else unwrap(x)
    present = dense != 0
    scores = jnp.where(present, dense, -jnp.inf)
    out = jax.nn.softmax(scores, axis=axis)
    out = jnp.where(present, out, 0.0)
    from .. import to_sparse_coo
    return to_sparse_coo(wrap(out), sparse_dim=out.ndim)


def attention(query, key, value, sparse_mask, key_padding_mask=None,
              attn_mask=None, name=None):
    """Attention restricted to a sparse mask pattern (reference:
    sparse.nn.functional.attention)."""
    from ...nn.functional.common import sparse_attention
    raise NotImplementedError(
        "use paddle.nn.functional.sparse_attention (CSR offsets/columns "
        "form) — the fused QKV-sparse kernel shape is CUDA-specific")


def _dense_conv(x, weight, bias, stride, padding, dilation, groups, nd,
                subm):
    """Shared sparse-conv path: densify -> XLA conv -> re-sparsify.
    subm (submanifold) masks the output to the input's active sites
    (reference sparse conv semantics)."""
    from ... import nn as dense_nn
    from ...nn import functional as dF
    from .. import to_sparse_coo
    dense = wrap(unwrap(x.to_dense()))
    # sparse layout is channels-last [N, *spatial, C]; dense convs here
    # are channels-first
    perm_in = (0, nd + 1) + tuple(range(1, nd + 1))
    perm_out = (0,) + tuple(range(2, nd + 2)) + (1,)
    a = jnp.transpose(unwrap(dense), perm_in)
    conv = dF.conv3d if nd == 3 else dF.conv2d
    out = conv(wrap(a), weight, bias, stride=stride, padding=padding,
               dilation=dilation, groups=groups)
    out_cl = jnp.transpose(unwrap(out), perm_out)
    if subm:
        active = jnp.any(unwrap(dense) != 0, axis=-1, keepdims=True)
        out_cl = jnp.where(active, out_cl, 0.0)
    return to_sparse_coo(wrap(out_cl), sparse_dim=nd + 1)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
           groups=1, data_format="NHWC", name=None):
    """Sparse 2-D conv (reference: sparse/nn/functional/conv.py conv2d;
    x: [N, H, W, C] sparse)."""
    return _dense_conv(x, weight, bias, stride, padding, dilation,
                       groups, nd=2, subm=False)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
           groups=1, data_format="NDHWC", name=None):
    """Sparse 3-D conv (reference: conv3d; x: [N, D, H, W, C])."""
    return _dense_conv(x, weight, bias, stride, padding, dilation,
                       groups, nd=3, subm=False)


def subm_conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NHWC", key=None, name=None):
    """Submanifold sparse conv: output sparsity == input sparsity
    (reference: subm_conv2d)."""
    return _dense_conv(x, weight, bias, stride, padding, dilation,
                       groups, nd=2, subm=True)


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC", key=None, name=None):
    """(reference: subm_conv3d)"""
    return _dense_conv(x, weight, bias, stride, padding, dilation,
                       groups, nd=3, subm=True)


# igemm variants: same math, different CUDA kernel in the reference
subm_conv2d_igemm = subm_conv2d
subm_conv3d_igemm = subm_conv3d


def max_pool3d(x, kernel_size, stride=None, padding=0,
               data_format="NDHWC", name=None):
    """Sparse 3-D max pool (reference: sparse/nn/functional/pooling)."""
    from ...nn import functional as dF
    from .. import to_sparse_coo
    dense = unwrap(x.to_dense())
    a = jnp.transpose(dense, (0, 4, 1, 2, 3))
    out = dF.max_pool3d(wrap(a), kernel_size, stride, padding)
    out_cl = jnp.transpose(unwrap(out), (0, 2, 3, 4, 1))
    return to_sparse_coo(wrap(out_cl), sparse_dim=4)
