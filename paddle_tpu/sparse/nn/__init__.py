"""paddle.sparse.nn (reference: python/paddle/sparse/nn): layer wrappers
over the sparse functional ops."""
from __future__ import annotations

from ...nn import Layer
from . import functional  # noqa: F401


class ReLU(Layer):
    def forward(self, x):
        from .. import relu
        return relu(x)


class Softmax(Layer):
    def __init__(self, axis=-1):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return functional.softmax(x, self.axis)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        return functional.leaky_relu(x, self.negative_slope)


class ReLU6(Layer):
    def forward(self, x):
        from .. import SparseTensor
        from jax.experimental import sparse as jsparse
        import jax.numpy as jnp
        return SparseTensor(jsparse.BCOO(
            (jnp.clip(x._bcoo.data, 0, 6), x._bcoo.indices),
            shape=x._bcoo.shape), x._fmt)


class _SparseConvBase(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, nd,
                 stride=1, padding=0, dilation=1, groups=1,
                 subm=False, weight_attr=None, bias_attr=None):
        super().__init__()
        ks = (kernel_size,) * nd if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, *ks], attr=weight_attr)
        self.bias = self.create_parameter(
            [out_channels], attr=bias_attr, is_bias=True) \
            if bias_attr is not False else None
        self.stride, self.padding = stride, padding
        self.dilation, self.groups = dilation, groups
        self._nd, self._subm = nd, subm

    def forward(self, x):
        fn = {(2, False): functional.conv2d,
              (2, True): functional.subm_conv2d,
              (3, False): functional.conv3d,
              (3, True): functional.subm_conv3d}[(self._nd, self._subm)]
        return fn(x, self.weight, self.bias, self.stride, self.padding,
                  self.dilation, self.groups)


class Conv2D(_SparseConvBase):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NHWC"):
        super().__init__(in_channels, out_channels, kernel_size, 2,
                         stride, padding, dilation, groups, False,
                         weight_attr, bias_attr)


class Conv3D(_SparseConvBase):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        super().__init__(in_channels, out_channels, kernel_size, 3,
                         stride, padding, dilation, groups, False,
                         weight_attr, bias_attr)


class SubmConv2D(_SparseConvBase):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 key=None, weight_attr=None, bias_attr=None,
                 data_format="NHWC"):
        super().__init__(in_channels, out_channels, kernel_size, 2,
                         stride, padding, dilation, groups, True,
                         weight_attr, bias_attr)


class SubmConv3D(_SparseConvBase):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 key=None, weight_attr=None, bias_attr=None,
                 data_format="NDHWC"):
        super().__init__(in_channels, out_channels, kernel_size, 3,
                         stride, padding, dilation, groups, True,
                         weight_attr, bias_attr)


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NDHWC", name=None):
        super().__init__()
        self.kernel_size, self.stride = kernel_size, stride
        self.padding = padding

    def forward(self, x):
        return functional.max_pool3d(x, self.kernel_size, self.stride,
                                     self.padding)


class BatchNorm(Layer):
    """Sparse batch norm over the channel dim (reference:
    sparse/nn/layer/norm.py BatchNorm): normalizes stored values."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 use_global_stats=None, name=None):
        super().__init__()
        from ...nn import BatchNorm1D
        self._bn = BatchNorm1D(num_features, momentum=momentum,
                               epsilon=epsilon, weight_attr=weight_attr,
                               bias_attr=bias_attr)

    def forward(self, x):
        from jax.experimental import sparse as jsparse

        from .. import SparseTensor
        from ...core.dispatch import unwrap as _u, wrap as _w
        data = x._bcoo.data
        if data.ndim == 1:
            # fully-sparse layout (channel dim in the indices): densify,
            # normalize the channel axis, re-sparsify
            from .. import to_dense, to_sparse_coo
            dense = _u(to_dense(x))
            flat = dense.reshape(-1, dense.shape[-1])
            out = _u(self._bn(_w(flat))).reshape(dense.shape)
            return to_sparse_coo(_w(out), sparse_dim=dense.ndim)
        vals = self._bn(_w(data))
        return SparseTensor(jsparse.BCOO((_u(vals), x._bcoo.indices),
                                         shape=x._bcoo.shape), x._fmt)


class SyncBatchNorm(BatchNorm):
    """GSPMD reduces the stats across the mesh under jit (reference:
    sparse SyncBatchNorm)."""
