"""paddle.sparse parity surface (reference python/paddle/sparse: COO/CSR
creation + unary/binary/matmul/nn ops; N1 SparseCooTensor
paddle/phi/core/sparse_coo_tensor.h:33).

TPU-native: backed by jax.experimental.sparse.BCOO — XLA's batched-COO
format with compiled scatter/gather kernels. The SparseTensor wrapper
keeps the paddle API (indices()/values()/to_dense()).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..core.dispatch import unwrap, wrap
from ..core.tensor import Tensor


class SparseTensor:
    """COO sparse tensor (reference SparseCooTensor)."""

    def __init__(self, bcoo: jsparse.BCOO, fmt: str = "coo"):
        self._bcoo = bcoo
        self._fmt = fmt

    # -- paddle surface ------------------------------------------------------
    @property
    def shape(self):
        return list(self._bcoo.shape)

    @property
    def dtype(self):
        return self._bcoo.dtype

    def indices(self) -> Tensor:
        return wrap(self._bcoo.indices.T)  # paddle: [ndim, nnz]

    def values(self) -> Tensor:
        return wrap(self._bcoo.data)

    def nnz(self) -> int:
        return int(self._bcoo.nse)

    def to_dense(self) -> Tensor:
        return wrap(self._bcoo.todense())

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return self._fmt == "coo"

    def is_sparse_csr(self):
        return self._fmt == "csr"

    def __repr__(self):
        return (f"SparseTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"format={self._fmt})")

    # -- arithmetic ----------------------------------------------------------
    def __add__(self, other):
        if isinstance(other, SparseTensor):
            return SparseTensor(self._bcoo + other._bcoo)
        return wrap(self._bcoo.todense() + unwrap(other))

    def __mul__(self, other):
        if isinstance(other, SparseTensor):
            return SparseTensor(jsparse.bcoo_multiply_sparse(
                self._bcoo, other._bcoo))
        o = jnp.asarray(unwrap(other))
        if o.ndim == 0:  # scalar scales the stored values, stays sparse
            return SparseTensor(
                jsparse.BCOO((self._bcoo.data * o, self._bcoo.indices),
                             shape=self._bcoo.shape), self._fmt)
        return wrap(self._bcoo.todense() * o)

    def matmul(self, other):
        return matmul(self, other)


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True):
    """Reference: paddle.sparse.sparse_coo_tensor (indices [ndim, nnz])."""
    idx = jnp.asarray(unwrap(indices)).T  # BCOO wants [nnz, ndim]
    vals = jnp.asarray(unwrap(values))
    if shape is None:
        shape = tuple(int(i) + 1 for i in jnp.max(idx, axis=0))
    return SparseTensor(jsparse.BCOO((vals, idx), shape=tuple(shape)))


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    """CSR creation — stored as BCOO internally (format metadata kept)."""
    crows_a = np.asarray(unwrap(crows))
    cols_a = np.asarray(unwrap(cols))
    vals = jnp.asarray(unwrap(values))
    rows = np.repeat(np.arange(len(crows_a) - 1),
                     np.diff(crows_a))
    idx = jnp.asarray(np.stack([rows, cols_a], axis=1))
    st = SparseTensor(jsparse.BCOO((vals, idx), shape=tuple(shape)),
                      fmt="csr")
    return st


def is_sparse(x):
    return isinstance(x, SparseTensor)


def to_dense(x: SparseTensor) -> Tensor:
    return x.to_dense()


def to_sparse_coo(x, sparse_dim=None) -> SparseTensor:
    a = unwrap(x)
    return SparseTensor(jsparse.BCOO.fromdense(a))


def matmul(x: SparseTensor, y):
    """Sparse @ dense (reference paddle.sparse.matmul)."""
    other = unwrap(y) if not isinstance(y, SparseTensor) else \
        y._bcoo.todense()
    return wrap(x._bcoo @ other)


def add(x: SparseTensor, y: SparseTensor):
    return SparseTensor(x._bcoo + y._bcoo)


def multiply(x: SparseTensor, y: SparseTensor):
    return SparseTensor(jsparse.bcoo_multiply_sparse(x._bcoo, y._bcoo))


def _unary(name, fn):
    def op(x: SparseTensor):
        return SparseTensor(jsparse.BCOO((fn(x._bcoo.data),
                                          x._bcoo.indices),
                                         shape=x._bcoo.shape), x._fmt)
    op.__name__ = name
    return op


relu = _unary("relu", lambda d: jnp.maximum(d, 0))
abs = _unary("abs", jnp.abs)  # noqa: A001
sin = _unary("sin", jnp.sin)
tanh = _unary("tanh", jnp.tanh)
sqrt = _unary("sqrt", jnp.sqrt)
square = _unary("square", jnp.square)
neg = _unary("neg", jnp.negative)
asin = _unary("asin", jnp.arcsin)
asinh = _unary("asinh", jnp.arcsinh)
atan = _unary("atan", jnp.arctan)
atanh = _unary("atanh", jnp.arctanh)
sinh = _unary("sinh", jnp.sinh)
tan = _unary("tan", jnp.tan)
expm1 = _unary("expm1", jnp.expm1)
log1p = _unary("log1p", jnp.log1p)
deg2rad = _unary("deg2rad", jnp.deg2rad)
rad2deg = _unary("rad2deg", jnp.rad2deg)
isnan = _unary("isnan", jnp.isnan)


def pow(x: SparseTensor, factor):  # noqa: A001
    """Elementwise power on stored values (reference: paddle.sparse.pow)."""
    return SparseTensor(jsparse.BCOO(
        (jnp.power(x._bcoo.data, factor), x._bcoo.indices),
        shape=x._bcoo.shape), x._fmt)


def cast(x: SparseTensor, index_dtype=None, value_dtype=None, name=None):
    """Cast index/value dtypes (reference: paddle.sparse.cast)."""
    from ..core import dtype as dtype_mod
    data, idx = x._bcoo.data, x._bcoo.indices
    if value_dtype is not None:
        data = data.astype(dtype_mod.dtype(value_dtype).np_dtype)
    if index_dtype is not None:
        idx = idx.astype(dtype_mod.dtype(index_dtype).np_dtype)
    return SparseTensor(jsparse.BCOO((data, idx), shape=x._bcoo.shape),
                        x._fmt)


def coalesce(x: SparseTensor, name=None):
    """Merge duplicate indices (reference: paddle.sparse.coalesce;
    BCOO sum_duplicates underneath)."""
    return SparseTensor(x._bcoo.sum_duplicates(), x._fmt)


def subtract(x: SparseTensor, y: SparseTensor, name=None):
    neg_y = jsparse.BCOO((-y._bcoo.data, y._bcoo.indices),
                         shape=y._bcoo.shape)
    return SparseTensor(x._bcoo + neg_y)


def divide(x: SparseTensor, y: SparseTensor, name=None):
    """Elementwise divide; densifies (quotient of sparse tensors is dense
    wherever y==0 anyway, so the dense route is the honest one)."""
    out = unwrap(to_dense(x)) / unwrap(to_dense(y))
    return to_sparse_coo(wrap(out), sparse_dim=len(x.shape))


def is_same_shape(x, y) -> bool:
    """Shape equality across sparse/dense operands (reference:
    paddle.sparse.is_same_shape)."""
    return list(x.shape) == list(y.shape)


def reshape(x: SparseTensor, shape, name=None):
    from jax.experimental.sparse import bcoo_reshape
    return SparseTensor(bcoo_reshape(x._bcoo.sum_duplicates(),
                                     new_sizes=tuple(int(s) for s in shape)),
                        x._fmt)


def transpose(x: SparseTensor, perm, name=None):
    from jax.experimental.sparse import bcoo_transpose
    return SparseTensor(bcoo_transpose(x._bcoo,
                                       permutation=tuple(int(p)
                                                         for p in perm)),
                        x._fmt)


def slice(x: SparseTensor, axes, starts, ends, name=None):  # noqa: A001
    """Slice a sparse tensor (reference: paddle.sparse.slice)."""
    import builtins
    idx = [builtins.slice(None)] * len(x.shape)
    for ax, st, en in zip(axes, starts, ends):
        ax = int(ax)
        size = x.shape[ax]
        st, en = int(st), int(en)
        st = st + size if st < 0 else st
        en = en + size if en < 0 else min(en, size)
        idx[ax] = builtins.slice(st, en)
    dense = unwrap(to_dense(x))[tuple(idx)]
    return to_sparse_coo(wrap(dense), sparse_dim=len(x.shape))


def sum(x: SparseTensor, axis=None, dtype=None, keepdim=False,  # noqa: A001
        name=None):
    """Reduce-sum; returns a SparseTensor like the reference."""
    from ..core import dtype as dtype_mod
    dense = unwrap(to_dense(x))
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    out = jnp.sum(dense, axis=ax, keepdims=keepdim)
    if dtype is not None:
        out = out.astype(dtype_mod.dtype(dtype).np_dtype)
    nd = max(out.ndim, 1)
    return to_sparse_coo(wrap(out.reshape((1,) if out.ndim == 0 else
                                          out.shape)), sparse_dim=nd)


def mv(x: SparseTensor, vec, name=None):
    """Sparse matrix x dense vector (reference: paddle.sparse.mv)."""
    v = unwrap(vec)
    out = x._bcoo @ v
    return wrap(out)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """beta*input + alpha*(x@y) with sparse x (reference:
    paddle.sparse.addmm)."""
    xy = x._bcoo @ unwrap(y) if isinstance(x, SparseTensor) \
        else unwrap(x) @ unwrap(y)
    base = unwrap(to_dense(input)) if isinstance(input, SparseTensor) \
        else unwrap(input)
    return wrap(beta * base + alpha * xy)


def masked_matmul(x, y, mask: SparseTensor, name=None):
    """(x @ y) sampled at mask's sparsity pattern (reference:
    paddle.sparse.masked_matmul — SDDMM). Computes only the nnz outputs
    by gathering the needed rows/cols, so the dense product never
    materialises."""
    a, b = unwrap(x), unwrap(y)
    idx = mask._bcoo.indices  # [nnz, 2]
    rows = a[idx[:, 0], :]           # [nnz, k]
    cols = b[:, idx[:, 1]].T         # [nnz, k]
    vals = jnp.sum(rows * cols, axis=-1).astype(a.dtype)
    return SparseTensor(jsparse.BCOO((vals, idx), shape=mask._bcoo.shape),
                        mask._fmt)


def mask_as(x, mask: SparseTensor, name=None):
    """Sample dense x at mask's sparsity pattern (reference:
    paddle.sparse.mask_as)."""
    a = unwrap(x)
    idx = mask._bcoo.indices
    vals = a[tuple(idx[:, i] for i in range(idx.shape[1]))]
    return SparseTensor(jsparse.BCOO((vals, idx), shape=mask._bcoo.shape),
                        mask._fmt)


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """Low-rank PCA of a (sparse or dense) matrix (reference:
    paddle.sparse.pca_lowrank). Densifies — the decomposition output is
    dense regardless, and XLA's SVD wants the dense operand."""
    a = unwrap(to_dense(x)) if isinstance(x, SparseTensor) else unwrap(x)
    m, n = a.shape[-2], a.shape[-1]
    if q is None:
        q = min(6, m, n)
    if center:
        a = a - jnp.mean(a, axis=-2, keepdims=True)
    u, s, vh = jnp.linalg.svd(a, full_matrices=False)
    return (wrap(u[..., :q]), wrap(s[..., :q]),
            wrap(jnp.swapaxes(vh, -2, -1)[..., :q]))

from . import creation  # noqa: F401,E402
from . import nn  # noqa: F401,E402
