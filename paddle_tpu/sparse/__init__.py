"""paddle.sparse parity surface (reference python/paddle/sparse: COO/CSR
creation + unary/binary/matmul/nn ops; N1 SparseCooTensor
paddle/phi/core/sparse_coo_tensor.h:33).

TPU-native: backed by jax.experimental.sparse.BCOO — XLA's batched-COO
format with compiled scatter/gather kernels. The SparseTensor wrapper
keeps the paddle API (indices()/values()/to_dense()).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..core.dispatch import unwrap, wrap
from ..core.tensor import Tensor


class SparseTensor:
    """COO sparse tensor (reference SparseCooTensor)."""

    def __init__(self, bcoo: jsparse.BCOO, fmt: str = "coo"):
        self._bcoo = bcoo
        self._fmt = fmt

    # -- paddle surface ------------------------------------------------------
    @property
    def shape(self):
        return list(self._bcoo.shape)

    @property
    def dtype(self):
        return self._bcoo.dtype

    def indices(self) -> Tensor:
        return wrap(self._bcoo.indices.T)  # paddle: [ndim, nnz]

    def values(self) -> Tensor:
        return wrap(self._bcoo.data)

    def nnz(self) -> int:
        return int(self._bcoo.nse)

    def to_dense(self) -> Tensor:
        return wrap(self._bcoo.todense())

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return self._fmt == "coo"

    def is_sparse_csr(self):
        return self._fmt == "csr"

    def __repr__(self):
        return (f"SparseTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"format={self._fmt})")

    # -- arithmetic ----------------------------------------------------------
    def __add__(self, other):
        if isinstance(other, SparseTensor):
            return SparseTensor(self._bcoo + other._bcoo)
        return wrap(self._bcoo.todense() + unwrap(other))

    def __mul__(self, other):
        if isinstance(other, SparseTensor):
            return SparseTensor(jsparse.bcoo_multiply_sparse(
                self._bcoo, other._bcoo))
        o = jnp.asarray(unwrap(other))
        if o.ndim == 0:  # scalar scales the stored values, stays sparse
            return SparseTensor(
                jsparse.BCOO((self._bcoo.data * o, self._bcoo.indices),
                             shape=self._bcoo.shape), self._fmt)
        return wrap(self._bcoo.todense() * o)

    def matmul(self, other):
        return matmul(self, other)


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True):
    """Reference: paddle.sparse.sparse_coo_tensor (indices [ndim, nnz])."""
    idx = jnp.asarray(unwrap(indices)).T  # BCOO wants [nnz, ndim]
    vals = jnp.asarray(unwrap(values))
    if shape is None:
        shape = tuple(int(i) + 1 for i in jnp.max(idx, axis=0))
    return SparseTensor(jsparse.BCOO((vals, idx), shape=tuple(shape)))


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    """CSR creation — stored as BCOO internally (format metadata kept)."""
    crows_a = np.asarray(unwrap(crows))
    cols_a = np.asarray(unwrap(cols))
    vals = jnp.asarray(unwrap(values))
    rows = np.repeat(np.arange(len(crows_a) - 1),
                     np.diff(crows_a))
    idx = jnp.asarray(np.stack([rows, cols_a], axis=1))
    st = SparseTensor(jsparse.BCOO((vals, idx), shape=tuple(shape)),
                      fmt="csr")
    return st


def is_sparse(x):
    return isinstance(x, SparseTensor)


def to_dense(x: SparseTensor) -> Tensor:
    return x.to_dense()


def to_sparse_coo(x, sparse_dim=None) -> SparseTensor:
    a = unwrap(x)
    return SparseTensor(jsparse.BCOO.fromdense(a))


def matmul(x: SparseTensor, y):
    """Sparse @ dense (reference paddle.sparse.matmul)."""
    other = unwrap(y) if not isinstance(y, SparseTensor) else \
        y._bcoo.todense()
    return wrap(x._bcoo @ other)


def add(x: SparseTensor, y: SparseTensor):
    return SparseTensor(x._bcoo + y._bcoo)


def multiply(x: SparseTensor, y: SparseTensor):
    return SparseTensor(jsparse.bcoo_multiply_sparse(x._bcoo, y._bcoo))


def _unary(name, fn):
    def op(x: SparseTensor):
        return SparseTensor(jsparse.BCOO((fn(x._bcoo.data),
                                          x._bcoo.indices),
                                         shape=x._bcoo.shape), x._fmt)
    op.__name__ = name
    return op


relu = _unary("relu", lambda d: jnp.maximum(d, 0))
abs = _unary("abs", jnp.abs)  # noqa: A001
sin = _unary("sin", jnp.sin)
tanh = _unary("tanh", jnp.tanh)
sqrt = _unary("sqrt", jnp.sqrt)
square = _unary("square", jnp.square)
neg = _unary("neg", jnp.negative)
