"""paddle.tensor namespace (reference: python/paddle/tensor/__init__.py —
the functional tensor library the top level re-exports from).

Here the implementation modules live in paddle_tpu.ops; this package
mirrors the reference layout so `from paddle.tensor import creation`
style imports keep working.
"""
from ..ops import creation, linalg, logic, manipulation, search, stat  # noqa: F401
from ..ops import math  # noqa: F401
from ..ops.creation import *  # noqa: F401,F403
from ..ops.math import *  # noqa: F401,F403
from ..ops.manipulation import *  # noqa: F401,F403
from ..ops.logic import *  # noqa: F401,F403
from ..ops.search import *  # noqa: F401,F403
from ..ops.stat import *  # noqa: F401,F403
from ..ops.inplace import *  # noqa: F401,F403

random = creation  # reference tensor/random.py: sampling creation ops
attribute = manipulation  # shape/rank/is_* live in manipulation here
