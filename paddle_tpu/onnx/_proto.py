"""Minimal ONNX protobuf wire-format writer/reader.

Reference: python/paddle/onnx/export.py:35 delegates to the external
paddle2onnx package; this build has no onnx dependency, so the exporter
serializes ModelProto directly in the protobuf wire format (varint +
length-delimited fields — the format is stable and public). Only the
message fields the exporter emits are implemented. The reader exists so
tests can round-trip a model without the onnx package installed; any
ONNX runtime can consume the files.

Field numbers follow onnx/onnx.proto (public schema):
  ModelProto:   ir_version=1 producer_name=2 graph=7 opset_import=8
  GraphProto:   node=1 name=2 initializer=5 input=11 output=12
  NodeProto:    input=1 output=2 name=3 op_type=4 attribute=5
  AttributeProto: name=1 f=2 i=3 s=4 ints=8 type=20
  TensorProto:  dims=1 data_type=2 name=8 raw_data=9
  ValueInfoProto: name=1 type=2; TypeProto.tensor_type=1
  TypeProto.Tensor: elem_type=1 shape=2; TensorShapeProto.dim=1
  Dimension:    dim_value=1
"""
from __future__ import annotations

import struct

import numpy as np

# ONNX TensorProto.DataType
FLOAT, INT32, INT64 = 1, 6, 7
_NP2ONNX = {np.dtype(np.float32): FLOAT, np.dtype(np.int32): INT32,
            np.dtype(np.int64): INT64}
_ONNX2NP = {v: k for k, v in _NP2ONNX.items()}

# AttributeProto.AttributeType
ATTR_FLOAT, ATTR_INT, ATTR_STRING, ATTR_TENSOR = 1, 2, 3, 4
ATTR_INTS = 7


def _varint(n: int) -> bytes:
    if n < 0:
        n += 1 << 64  # two's-complement, proto int64 convention
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def field_varint(field: int, value: int) -> bytes:
    return _tag(field, 0) + _varint(int(value))


def field_bytes(field: int, data: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(data)) + data


def field_string(field: int, s: str) -> bytes:
    return field_bytes(field, s.encode())


def field_packed_ints(field: int, values) -> bytes:
    body = b"".join(_varint(int(v)) for v in values)
    return field_bytes(field, body)


def field_float(field: int, value: float) -> bytes:
    return _tag(field, 5) + struct.pack("<f", float(value))


def tensor_proto(name: str, arr: np.ndarray) -> bytes:
    arr = np.ascontiguousarray(arr)
    dt = _NP2ONNX[arr.dtype]
    msg = b"".join([
        field_packed_ints(1, arr.shape),
        field_varint(2, dt),
        field_string(8, name),
        field_bytes(9, arr.tobytes()),
    ])
    return msg


def attr_int(name: str, value: int) -> bytes:
    return b"".join([field_string(1, name), field_varint(3, value),
                     field_varint(20, ATTR_INT)])


def attr_float(name: str, value: float) -> bytes:
    return b"".join([field_string(1, name), field_float(2, value),
                     field_varint(20, ATTR_FLOAT)])


def attr_ints(name: str, values) -> bytes:
    return b"".join([field_string(1, name), field_packed_ints(8, values),
                     field_varint(20, ATTR_INTS)])


def node_proto(op_type: str, inputs, outputs, name: str = "",
               attrs: bytes = b"") -> bytes:
    msg = b"".join(field_string(1, i) for i in inputs)
    msg += b"".join(field_string(2, o) for o in outputs)
    if name:
        msg += field_string(3, name)
    msg += field_string(4, op_type)
    msg += attrs
    return msg


def _attr_wrap(attr_msgs) -> bytes:
    return b"".join(field_bytes(5, a) for a in attr_msgs)


def value_info(name: str, elem_type: int, shape) -> bytes:
    """``None`` dims emit a symbolic dim_param ("N") so dynamic batch
    survives export instead of being baked to a literal."""
    dims = b"".join(
        field_bytes(1, field_string(2, "N")) if d is None
        else field_bytes(1, field_varint(1, int(d))) for d in shape)
    shape_msg = dims
    tensor_t = field_varint(1, elem_type) + field_bytes(2, shape_msg)
    type_msg = field_bytes(1, tensor_t)
    return field_string(1, name) + field_bytes(2, type_msg)


def graph_proto(nodes, name, initializers, inputs, outputs) -> bytes:
    msg = b"".join(field_bytes(1, n) for n in nodes)
    msg += field_string(2, name)
    msg += b"".join(field_bytes(5, t) for t in initializers)
    msg += b"".join(field_bytes(11, vi) for vi in inputs)
    msg += b"".join(field_bytes(12, vi) for vi in outputs)
    return msg


def model_proto(graph: bytes, opset: int = 13,
                producer: str = "paddle_tpu") -> bytes:
    opset_msg = field_string(1, "") + field_varint(2, opset)
    return b"".join([
        field_varint(1, 8),          # ir_version 8
        field_string(2, producer),
        field_bytes(7, graph),
        field_bytes(8, opset_msg),
    ])


# ---------------------------------------------------------------------------
# Reader (for round-trip tests; tolerant, parses only what the writer emits)
# ---------------------------------------------------------------------------

def _read_varint(buf, pos):
    shift, val = 0, 0
    while True:
        b = buf[pos]
        pos += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, pos
        shift += 7


def parse_message(buf: bytes):
    """-> dict field_number -> list of (wire_type, value)."""
    fields = {}
    pos = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:
            val, pos = _read_varint(buf, pos)
        elif wire == 2:
            ln, pos = _read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wire == 5:
            val = struct.unpack("<f", buf[pos:pos + 4])[0]
            pos += 4
        else:  # pragma: no cover
            raise ValueError(f"unsupported wire type {wire}")
        fields.setdefault(field, []).append((wire, val))
    return fields


def _one(fields, n, default=None):
    return fields[n][0][1] if n in fields else default


def parse_packed_ints(data: bytes):
    vals, pos = [], 0
    while pos < len(data):
        v, pos = _read_varint(data, pos)
        if v >= 1 << 63:
            v -= 1 << 64
        vals.append(v)
    return vals


def parse_tensor(buf: bytes):
    f = parse_message(buf)
    dims = parse_packed_ints(_one(f, 1, b""))
    dt = _one(f, 2, FLOAT)
    name = _one(f, 8, b"").decode()
    raw = _one(f, 9, b"")
    arr = np.frombuffer(raw, dtype=_ONNX2NP[dt]).reshape(dims)
    return name, arr


def parse_model(buf: bytes):
    """-> {"graph": {"nodes": [...], "initializers": {name: arr},
    "inputs": [names], "outputs": [names]}, "opset": int}"""
    mf = parse_message(buf)
    g = parse_message(_one(mf, 7))
    nodes = []
    for _, nb in g.get(1, []):
        nf = parse_message(nb)
        attrs = {}
        for _, ab in nf.get(5, []):
            af = parse_message(ab)
            aname = _one(af, 1, b"").decode()
            atype = _one(af, 20, 0)
            if atype == ATTR_INT:
                v = _one(af, 3)
                attrs[aname] = v - (1 << 64) if v >= 1 << 63 else v
            elif atype == ATTR_FLOAT:
                attrs[aname] = _one(af, 2)
            elif atype == ATTR_INTS:
                attrs[aname] = parse_packed_ints(_one(af, 8, b""))
            else:
                attrs[aname] = _one(af, 4)
        nodes.append({
            "op_type": _one(nf, 4, b"").decode(),
            "inputs": [v.decode() for _, v in nf.get(1, [])],
            "outputs": [v.decode() for _, v in nf.get(2, [])],
            "attrs": attrs,
        })
    inits = dict(parse_tensor(tb) for _, tb in g.get(5, []))

    def _vi_names(field):
        return [parse_message(vb)[1][0][1].decode()
                for _, vb in g.get(field, [])]

    opset = 13
    if 8 in mf:
        opset = _one(parse_message(_one(mf, 8)), 2, 13)
    return {"graph": {"nodes": nodes, "initializers": inits,
                      "inputs": _vi_names(11), "outputs": _vi_names(12)},
            "opset": opset}
