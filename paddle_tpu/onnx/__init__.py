"""paddle.onnx.export — self-contained ONNX exporter.

Reference: python/paddle/onnx/export.py:35 delegates to the external
paddle2onnx package (and raises when it is missing). This build ships
its own minimal exporter instead: a layer walk over Sequential-composed
models emitting ONNX ModelProto directly in the protobuf wire format
(`_proto.py`), with no dependency on the onnx package. Covered layers:
Linear, Conv2D, BatchNorm2D, MaxPool2D/AvgPool2D, Flatten, Dropout
(dropped at export — inference semantics), ReLU/Tanh/Sigmoid/Softmax/
LeakyReLU. Anything else raises with guidance to use paddle.jit.save
(StableHLO) — the portable compiled format on TPU.

A Flatten node is inserted automatically when a rank>2 activation meets
a Linear, so conv stacks like LeNet's Sequential export directly.
"""
from __future__ import annotations

import numpy as np

from . import _proto as P

__all__ = ["export"]


def _np(t):
    return np.asarray(t.numpy())


def _pair(v):
    if isinstance(v, (tuple, list)):
        return [int(v[0]), int(v[1])]
    return [int(v), int(v)]


class _Exporter:
    def __init__(self):
        self.nodes = []
        self.inits = []
        self.n = 0
        # lowest opset the emitted op set is valid under (Gelu: 20,
        # LayerNormalization: 17); export() stamps max(this, requested)
        self.min_opset = 13

    def name(self, kind):
        self.n += 1
        return f"{kind}_{self.n}"

    def add_init(self, name, arr):
        arr = np.asarray(arr)
        if arr.dtype not in (np.dtype(np.int64), np.dtype(np.int32)):
            arr = arr.astype(np.float32)
        self.inits.append(P.tensor_proto(name, arr))

    def emit(self, op, inputs, attrs=b""):
        if op == "Gelu":
            self.min_opset = max(self.min_opset, 20)
        out = self.name(op.lower())
        self.nodes.append(P.node_proto(op, inputs, [out],
                                       name=self.name(op), attrs=attrs))
        return out

    # -- per-layer emitters -------------------------------------------------
    def linear(self, lyr, x, shape):
        w = _np(lyr.weight)  # [in, out]
        in_f = w.shape[0]
        if len(shape) > 2:
            tail = int(np.prod(shape[1:]))
            if shape[-1] == in_f:
                # paddle Linear contracts the LAST dim on rank>2 inputs:
                # rank-preserving MatMul (+ Add for bias) — Gemm/Flatten
                # would contract prod(shape[1:]) and be silently wrong
                wn = self.name("w")
                self.add_init(wn, w)
                out = self.emit("MatMul", [x, wn])
                if lyr.bias is not None:
                    bn = self.name("b")
                    self.add_init(bn, _np(lyr.bias))
                    out = self.emit("Add", [out, bn])
                return out, list(shape[:-1]) + [w.shape[1]]
            if tail != in_f:
                raise NotImplementedError(
                    f"onnx.export: Linear(in={in_f}) fed a rank-"
                    f"{len(shape)} activation of shape {shape}: neither "
                    "the last dim nor the flattened width matches")
            x = self.emit("Flatten", [x],
                          P._attr_wrap([P.attr_int("axis", 1)]))
            shape = [shape[0], tail]
        wn = self.name("w")
        self.add_init(wn, w)
        ins = [x, wn]
        if lyr.bias is not None:
            bn = self.name("b")
            self.add_init(bn, _np(lyr.bias))
            ins.append(bn)
        out = self.emit("Gemm", ins)
        return out, [shape[0], w.shape[1]]

    def conv2d(self, lyr, x, shape):
        w = _np(lyr.weight)  # [out, in/g, kh, kw] — ONNX Conv layout
        pad = lyr._padding
        if isinstance(pad, str):
            raise NotImplementedError(
                f"onnx.export: string padding {pad!r} is not supported; "
                "use explicit integer padding")
        ph, pw = _pair(pad)
        sh, sw = [int(s) for s in lyr._stride]
        dh, dw = [int(d) for d in lyr._dilation]
        kh, kw = w.shape[2], w.shape[3]
        wn = self.name("w")
        self.add_init(wn, w)
        ins = [x, wn]
        if lyr.bias is not None:
            bn = self.name("b")
            self.add_init(bn, _np(lyr.bias))
            ins.append(bn)
        attrs = P._attr_wrap([
            P.attr_ints("kernel_shape", [kh, kw]),
            P.attr_ints("strides", [sh, sw]),
            P.attr_ints("pads", [ph, pw, ph, pw]),
            P.attr_ints("dilations", [dh, dw]),
            P.attr_int("group", int(lyr._groups)),
        ])
        out = self.emit("Conv", ins, attrs)
        oh = (shape[2] + 2 * ph - dh * (kh - 1) - 1) // sh + 1
        ow = (shape[3] + 2 * pw - dw * (kw - 1) - 1) // sw + 1
        return out, [shape[0], w.shape[0], oh, ow]

    def pool2d(self, lyr, x, shape, op):
        if getattr(lyr, "ceil_mode", False):
            raise NotImplementedError("onnx.export: ceil_mode pooling")
        kh, kw = _pair(lyr.kernel_size)
        sh, sw = _pair(lyr.stride if lyr.stride is not None
                       else lyr.kernel_size)
        ph, pw = _pair(lyr.padding)
        attrs = P._attr_wrap([
            P.attr_ints("kernel_shape", [kh, kw]),
            P.attr_ints("strides", [sh, sw]),
            P.attr_ints("pads", [ph, pw, ph, pw]),
        ])
        out = self.emit(op, [x], attrs)
        oh = (shape[2] + 2 * ph - kh) // sh + 1
        ow = (shape[3] + 2 * pw - kw) // sw + 1
        return out, [shape[0], shape[1], oh, ow]

    def batchnorm(self, lyr, x, shape):
        names = []
        for suffix, arr in [("scale", _np(lyr.weight)),
                            ("bias", _np(lyr.bias)),
                            ("mean", _np(lyr._mean)),
                            ("var", _np(lyr._variance))]:
            n = self.name(suffix)
            self.add_init(n, arr)
            names.append(n)
        attrs = P._attr_wrap([P.attr_float("epsilon",
                                           float(lyr._epsilon))])
        return self.emit("BatchNormalization", [x] + names, attrs), shape

    def layer_norm(self, lyr, x, shape):
        """ONNX LayerNormalization (opset 17) over the trailing dims."""
        self.min_opset = max(self.min_opset, 17)
        parts = [x]
        for t, fill in ((lyr.weight, 1.0), (lyr.bias, 0.0)):
            n = self.name("ln")
            self.add_init(n, _np(t) if t is not None
                          else np.full(lyr._normalized_shape, fill,
                                       np.float32))
            parts.append(n)
        axis = -len(lyr._normalized_shape)
        attrs = P._attr_wrap([
            P.attr_int("axis", axis),
            P.attr_float("epsilon", float(lyr._epsilon))])
        return self.emit("LayerNormalization", parts, attrs), shape

    def embedding(self, lyr, x, shape):
        """int ids -> Gather over the [num, dim] table (axis 0)."""
        wn = self.name("embed")
        self.add_init(wn, _np(lyr.weight))
        out = self.emit("Gather", [wn, x],
                        P._attr_wrap([P.attr_int("axis", 0)]))
        return out, list(shape) + [int(lyr.weight.shape[1])]

    def _transpose(self, x, perm):
        return self.emit("Transpose", [x],
                         P._attr_wrap([P.attr_ints("perm", perm)]))

    def _reshape(self, x, tgt):
        sn = self.name("shape")
        self.add_init(sn, np.asarray(
            [0 if d is None else int(d) for d in tgt], np.int64))
        return self.emit("Reshape", [x, sn])

    def bert_attention(self, lyr, x, shape):
        """BertSelfAttention decomposed to MatMul/Reshape/Transpose/
        Softmax primitives: the fused qkv weight is SLICED into per-head
        q/k/v mats at export time, scores = softmax(q k^T / sqrt(d))."""
        b, s, hmod = shape
        heads, hd = lyr.num_heads, lyr.head_dim
        w = _np(lyr.qkv.weight)                   # [h, 3h]
        bias = _np(lyr.qkv.bias) if lyr.qkv.bias is not None else None
        pieces = []
        for i, nm in enumerate(("q", "k", "v")):
            wn = self.name(f"w{nm}")
            self.add_init(wn, w[:, i * hmod:(i + 1) * hmod])
            part = self.emit("MatMul", [x, wn])
            if bias is not None:
                bn = self.name(f"b{nm}")
                self.add_init(bn, bias[i * hmod:(i + 1) * hmod])
                part = self.emit("Add", [part, bn])
            part = self._reshape(part, [None, s, heads, hd])
            pieces.append(self._transpose(part, [0, 2, 1, 3]))
        q, k, v = pieces                         # [b, heads, s, hd]
        kt = self._transpose(k, [0, 1, 3, 2])
        scores = self.emit("MatMul", [q, kt])
        sc = self.name("scale")
        self.add_init(sc, np.float32(1.0 / np.sqrt(hd)))
        scores = self.emit("Mul", [scores, sc])
        probs = self.emit("Softmax", [scores],
                          P._attr_wrap([P.attr_int("axis", -1)]))
        ctx = self.emit("MatMul", [probs, v])    # [b, heads, s, hd]
        ctx = self._transpose(ctx, [0, 2, 1, 3])
        ctx = self._reshape(ctx, [None, s, hmod])
        return self.linear(lyr.out, ctx, [b, s, hmod])

    def bert_layer(self, lyr, x, shape):
        """BertEncoderLayer: post-LN residual attention + GELU FFN
        (dropout dropped — inference export)."""
        attn, _ = self.bert_attention(lyr.attention, x, shape)
        x = self.emit("Add", [x, attn])
        x, _ = self.layer_norm(lyr.attn_norm, x, shape)
        h, hshape = self.linear(lyr.fc1, x, shape)
        h = self.emit("Gelu", [h])
        h, _ = self.linear(lyr.fc2, h, hshape)
        x = self.emit("Add", [x, h])
        return self.layer_norm(lyr.ffn_norm, x, shape)

    def walk(self, layer, x, shape):
        kind = type(layer).__name__
        simple = {"ReLU": "Relu", "Tanh": "Tanh", "Sigmoid": "Sigmoid",
                  "LeakyReLU": "LeakyRelu", "GELU": "Gelu"}
        if kind in ("Sequential", "LayerList"):
            for _, child in layer.named_children():
                x, shape = self.walk(child, x, shape)
            return x, shape
        if kind == "LayerNorm":
            return self.layer_norm(layer, x, shape)
        if kind == "Embedding":
            return self.embedding(layer, x, shape)
        if kind == "BertSelfAttention":
            return self.bert_attention(layer, x, shape)
        if kind == "BertEncoderLayer":
            return self.bert_layer(layer, x, shape)
        if kind == "Linear":
            return self.linear(layer, x, shape)
        if kind == "Conv2D":
            return self.conv2d(layer, x, shape)
        if kind == "MaxPool2D":
            return self.pool2d(layer, x, shape, "MaxPool")
        if kind == "AvgPool2D":
            return self.pool2d(layer, x, shape, "AveragePool")
        if kind == "BatchNorm2D":
            return self.batchnorm(layer, x, shape)
        if kind == "Flatten":
            r = len(shape)
            s = int(layer.start_axis) % r
            e = int(layer.stop_axis) % r
            new_shape = list(shape[:s]) + \
                [int(np.prod(shape[s:e + 1]))] + list(shape[e + 1:])
            if s == 1 and e == r - 1:
                # exactly ONNX Flatten(axis=1) semantics
                out = self.emit("Flatten", [x], P._attr_wrap(
                    [P.attr_int("axis", 1)]))
                return out, new_shape
            # general (start, stop) range: ONNX Flatten collapses the
            # WHOLE tensor to 2-D around one axis — not the same op.
            # Emit Reshape with a static target (0 = copy input dim,
            # covering symbolic batch dims)
            tgt = np.asarray([0 if d is None else int(d)
                              for d in new_shape], np.int64)
            sn = self.name("shape")
            self.add_init(sn, tgt)
            out = self.emit("Reshape", [x, sn])
            return out, new_shape
        if kind == "Softmax":
            axis = int(getattr(layer, "axis", -1))
            return self.emit("Softmax", [x], P._attr_wrap(
                [P.attr_int("axis", axis)])), shape
        if kind.startswith("Dropout"):
            return x, shape  # inference export: identity
        if kind in simple:
            return self.emit(simple[kind], [x]), shape
        raise NotImplementedError(
            f"onnx.export: layer {kind} is not supported by the minimal "
            "exporter; supported: Sequential/LayerList/Linear/Conv2D/"
            "BatchNorm2D/MaxPool2D/AvgPool2D/Flatten/Dropout/LayerNorm/"
            "Embedding/BertSelfAttention/BertEncoderLayer/activations. "
            "For arbitrary models use paddle.jit.save (StableHLO).")


def export(layer, path, input_spec=None, opset_version=13, **configs):
    """Export a Sequential-composed Layer to ``path + '.onnx'``
    (reference: paddle.onnx.export signature and file-naming behavior,
    python/paddle/onnx/export.py:35).

    input_spec: [InputSpec] or a [shape] list — the first entry fixes
    the graph input shape. Returns the written filename.
    """
    if input_spec is None or not input_spec:
        raise ValueError(
            "onnx.export requires input_spec=[InputSpec([...])] to fix "
            "the graph input shape")
    spec = input_spec[0]
    # None / -1 dims stay symbolic (ONNX dim_param): shape arithmetic
    # below never consumes the batch dim, so it flows through untouched
    shape = [int(d) if d is not None and int(d) > 0 else None
             for d in getattr(spec, "shape", spec)]
    in_dtype = str(getattr(spec, "dtype", "float32"))
    in_elem = P.INT64 if "int64" in in_dtype else (
        P.INT32 if "int32" in in_dtype else P.FLOAT)
    ex = _Exporter()
    out, out_shape = ex.walk(layer, "input", shape)
    graph = P.graph_proto(
        ex.nodes, "paddle_tpu_graph", ex.inits,
        [P.value_info("input", in_elem, shape)],
        [P.value_info(out, P.FLOAT, out_shape)])
    # never stamp an opset the emitted ops are invalid under (Gelu
    # needs 20, LayerNormalization 17 — onnx.checker would reject)
    model = P.model_proto(graph, opset=max(int(opset_version),
                                           ex.min_opset))
    fname = path if path.endswith(".onnx") else path + ".onnx"
    with open(fname, "wb") as f:
        f.write(model)
    return fname
