"""ONNX export surface (reference: python/paddle/onnx/__init__.py).

The reference delegates to the external paddle2onnx package; here export
goes through ONNX's own python package when present. Without it, the
portable interchange format on TPU is StableHLO via paddle.jit.save —
export() raises with that guidance, mirroring the reference's behavior
when paddle2onnx is absent.
"""
from __future__ import annotations

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    """Export a Layer to ONNX (reference: paddle.onnx.export, which
    requires the optional paddle2onnx dependency)."""
    try:
        import onnx  # noqa: F401
    except ImportError:
        raise ImportError(
            "paddle.onnx.export needs the 'onnx' package, which is not "
            "installed in this environment. For a portable compiled "
            "artifact on TPU use paddle.jit.save (StableHLO), the "
            "cross-runtime format XLA toolchains consume.") from None
    raise NotImplementedError(
        "ONNX graph translation is not implemented for the TPU build; "
        "use paddle.jit.save (StableHLO) for serialized programs")
