"""Content-addressed store of full KV pages — shared-prefix reuse.

Production serving traffic is dominated by shared system prompts:
thousands of requests open with the same instruction block, yet a
naive engine re-prefills that prefix into private pages for every one
of them. The paged layout makes dedup nearly free: a KV page is an
immutable value once written (positions only ever grow), so identical
token prefixes produce identical pages, and one physical page can sit
in many block tables at once (vLLM's automatic prefix caching /
SGLang's RadixAttention capability, on the PageAllocator refcounts).

Addressing is a CHAINED hash over page-aligned token chunks:

    h_0 = H(tokens[0:ps])          h_i = H(h_{i-1} || tokens[i*ps:...])

so an entry hit at depth i implies the ENTIRE prefix up to and
including chunk i matches — a lookup walks the chain from the root and
stops at the first miss, and a page can never be reused under a
different left context. Only FULL pages are ever cached: the partial
tail page (and, when the prompt is exactly page-aligned, the last full
page — the request keeps appending generated tokens into that page's
slots or right after it) stays private, which is the copy-on-write
fork: the first write a request would make into shared territory lands
in its own page instead (docs/SERVING.md "Prefix sharing & COW").

Hashes are blake2b over the raw token bytes, and every entry ALSO
keeps its exact chunk tokens: a digest collision (or a test forcing
one) degrades to a cache MISS, never to serving another prompt's KV.

Lifecycle: the cache holds ONE allocator reference per entry, so a
cached page survives its writer finishing; requests mapping it take
their own reference (``PageAllocator.share``). Entries whose page
refcount is 1 (cache-only — "refcount 0" users) are evictable,
leaves-first in LRU order so a chain never loses an interior page
while a descendant could still be hit. Eviction runs from the engine's
admission and preemption paths: idle cached pages are reclaimed before
any live sequence is preempted.
"""
from __future__ import annotations

import hashlib
import heapq
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


def _chunk_hash(parent: Optional[bytes], tokens) -> bytes:
    """Chained digest of one page-aligned chunk under its prefix."""
    h = hashlib.blake2b(digest_size=16)
    if parent is not None:
        h.update(parent)
    for t in tokens:
        h.update(int(t).to_bytes(8, "little", signed=True))
    return h.digest()


@dataclass
class _Entry:
    key: bytes                     # chained digest (identity in store)
    page: int                      # pool page backing this chunk
    chunk: Tuple[int, ...]         # exact tokens (collision guard)
    parent: Optional[bytes]        # previous chunk's key (chain link)
    depth: int                     # chunk index in its prefix
    children: set = field(default_factory=set)
    last_use: int = 0              # LRU tick


class PrefixCache:
    """Hash-chained page store over a ``PageAllocator``.

    The engine drives four operations per request lifecycle:
    ``acquire`` at admission (map the longest cached prefix into the
    block table, taking one reference per page), ``insert`` after
    prefill (register the request's freshly written full-prompt pages),
    ``PageAllocator.free`` of the request's pages at finish/preemption
    (shared pages just drop a reference), and ``evict`` under pool
    pressure (reclaim idle entries, leaves first, LRU order).
    """

    #: digest every forced collision resolves to (fault injection /
    #: collision tests — a constant key makes ANY two chunks collide)
    COLLIDED = b"\x00" * 16

    def __init__(self, allocator, page_size: int,
                 hash_fn=_chunk_hash):
        self._alloc = allocator
        self.page_size = int(page_size)
        self._store: Dict[bytes, _Entry] = {}
        self._tick = 0
        # injectable for the collision tests; production is blake2b
        self._hash_fn = hash_fn
        # fault-injection hook (inference/reliability.py): each armed
        # count forces the NEXT first-chunk digest to the COLLIDED
        # constant, so two different prompts land on one key and the
        # exact-token compare must degrade the hit to a miss
        self._collide_next = 0
        self.hits = 0
        self.lookups = 0

    def _hash(self, parent, tokens) -> bytes:
        if self._collide_next > 0 and parent is None:
            self._collide_next -= 1
            return self.COLLIDED
        return self._hash_fn(parent, tokens)

    def force_collision(self, n: int = 1) -> None:
        """Arm ``n`` forced digest collisions (the
        ``prefix.hash_collision`` fault point): the next ``n``
        root-chunk hashes all return one constant digest. Correctness
        must not depend on digests — the exact-token compare turns the
        collision into a miss, never into serving another prompt's
        KV."""
        self._collide_next += int(n)

    def corrupt_entry(self, rng) -> Optional[bytes]:
        """Make one cached entry STALE (the ``prefix.stale_entry``
        fault point): its recorded chunk tokens are overwritten with
        out-of-vocab sentinels, simulating index metadata that no
        longer matches the page contents. A stale entry can never be
        HIT again (token compare fails), so it degrades to a miss and
        is reclaimed by ``check_integrity``/eviction. Returns the
        corrupted key (None when the cache is empty)."""
        if not self._store:
            return None
        keys = sorted(self._store)
        key = keys[int(rng.integers(0, len(keys)))]
        ent = self._store[key]
        ent.chunk = tuple([-1] * len(ent.chunk))
        return key

    def check_integrity(self, repair: bool = False) -> List[str]:
        """Verify every entry's key still equals the chained digest of
        (parent, chunk) — the invariant ``insert`` establishes. A
        mismatch marks a STALE entry (corrupted metadata, or an
        injected fault); with ``repair=True`` stale entries and their
        (now unreachable) subtrees are dropped, returning their pages
        to the pool. Forced-collision roots (key == COLLIDED) are
        exempt: they were legitimately inserted under the forced
        digest and still satisfy the exact-token compare."""
        findings: List[str] = []
        stale = []
        for key, ent in self._store.items():
            if key == self.COLLIDED:
                continue
            if self._hash_fn(ent.parent, ent.chunk) != key:
                findings.append(
                    f"stale prefix-cache entry depth {ent.depth} "
                    f"(key {key.hex()[:12]}…): stored chunk no longer "
                    f"matches its digest")
                stale.append(key)
        if repair and stale:
            for key in stale:
                self._drop_subtree(key)
        return findings

    def _drop_subtree(self, key: bytes) -> int:
        """Drop an entry and every descendant (they are unreachable
        once an ancestor is gone — the chain walk stops at the first
        miss). Returns pages freed."""
        ent = self._store.get(key)
        if ent is None:
            return 0
        freed = 0
        for child in list(ent.children):
            freed += self._drop_subtree(child)
        self._drop(ent)
        return freed + 1

    def __len__(self) -> int:
        return len(self._store)

    # -- chain walk ----------------------------------------------------------

    def _walk(self, tokens, max_chunks: int) -> List[_Entry]:
        """Longest chain of cached entries matching ``tokens``' leading
        full-page chunks (at most ``max_chunks``). The exact-token
        compare turns any digest collision into a miss."""
        ps = self.page_size
        out: List[_Entry] = []
        parent: Optional[bytes] = None
        for i in range(min(len(tokens) // ps, max_chunks)):
            chunk = tuple(int(t) for t in tokens[i * ps:(i + 1) * ps])
            key = self._hash(parent, chunk)
            ent = self._store.get(key)
            if ent is None or ent.chunk != chunk:
                break
            out.append(ent)
            parent = key
        return out

    def lookup(self, tokens, max_chunks: Optional[int] = None) -> int:
        """Number of leading tokens covered by cached pages (a multiple
        of page_size), WITHOUT taking references — the admission
        planner's view of how many pages a prompt would reuse."""
        if max_chunks is None:
            max_chunks = len(tokens) // self.page_size
        return len(self._walk(tokens, max_chunks)) * self.page_size

    def acquire(self, tokens, max_chunks: Optional[int] = None
                ) -> Tuple[List[int], int]:
        """Map the longest cached prefix of ``tokens``: returns the
        shared page ids (one reference taken on each — the caller must
        eventually ``PageAllocator.free`` them) and the number of
        tokens they cover. ``max_chunks`` caps the depth (the engine
        passes (len-1)//page_size so at least one real token is left
        for the tail prefill — the COW rule keeps the append page
        private even when its contents are cached)."""
        if max_chunks is None:
            max_chunks = len(tokens) // self.page_size
        chain = self._walk(tokens, max_chunks)
        self.lookups += 1
        if chain:
            self.hits += 1
        self._tick += 1
        pages = []
        for ent in chain:
            self._alloc.share(ent.page)
            ent.last_use = self._tick     # whole matched chain is hot
            pages.append(ent.page)
        return pages, len(pages) * self.page_size

    def insert(self, tokens, pages: List[int], n_tokens: int) -> int:
        """Register the full-page chunks of ``tokens[:n_tokens]`` whose
        backing pages (``pages[i]`` = chunk i's page, the request's
        block-table prefix) are not yet cached. The cache takes its own
        reference on each newly registered page; chunks already cached
        (under ANY page) are skipped — first writer wins, so two racing
        requests never alias divergent pages under one key. Returns the
        number of pages newly registered."""
        ps = self.page_size
        self._tick += 1
        parent: Optional[bytes] = None
        added = 0
        for i in range(n_tokens // ps):
            chunk = tuple(int(t) for t in tokens[i * ps:(i + 1) * ps])
            key = self._hash(parent, chunk)
            ent = self._store.get(key)
            if ent is not None and ent.chunk != chunk:
                # digest collision with a different chunk: leave the
                # incumbent alone; this prefix (and its descendants)
                # simply stays uncached
                break
            if ent is None:
                ent = _Entry(key=key, page=self._alloc.share(pages[i]),
                             chunk=chunk, parent=parent, depth=i)
                self._store[key] = ent
                if parent is not None:
                    self._store[parent].children.add(key)
                added += 1
            ent.last_use = self._tick
            parent = key
        return added

    # -- eviction ------------------------------------------------------------

    def _idle(self, ent: _Entry) -> bool:
        """Evictable: the cache's own reference is the page's last one
        (no live request maps it). A request holding a descendant also
        holds every ancestor page, so an idle entry's whole subtree is
        idle too."""
        return self._alloc.refcount(ent.page) == 1

    @property
    def evictable_pages(self) -> int:
        return sum(1 for e in self._store.values() if self._idle(e))

    def evict(self, n: int) -> int:
        """Free up to ``n`` idle pages back to the pool, LEAVES first
        in LRU order — an interior chunk is never dropped while a
        descendant remains hittable (a headless chain tail would be
        unreachable garbage). Returns the number of pages freed.

        One scan seeds a heap of idle leaves; dropping a leaf pushes
        its parent if that just became an idle leaf — so a bulk evict
        (pool pressure, ``clear``) is O(entries + freed·log) instead
        of a full rescan per freed page."""
        freed = 0
        heap = [(e.last_use, e.depth, e.key)
                for e in self._store.values()
                if not e.children and self._idle(e)]
        heapq.heapify(heap)
        while freed < int(n) and heap:
            _, _, key = heapq.heappop(heap)
            ent = self._store.get(key)
            if ent is None or ent.children or not self._idle(ent):
                continue
            parent = ent.parent
            self._drop(ent)
            freed += 1
            if parent is not None:
                par = self._store.get(parent)
                if par is not None and not par.children \
                        and self._idle(par):
                    heapq.heappush(heap, (par.last_use, par.depth,
                                          par.key))
        return freed

    def _drop(self, ent: _Entry) -> None:
        del self._store[ent.key]
        if ent.parent is not None:
            par = self._store.get(ent.parent)
            if par is not None:
                par.children.discard(ent.key)
        self._alloc.free([ent.page])

    def clear(self) -> int:
        """Drop every idle entry (shutdown / tests); in-use pages stay
        registered. Returns pages freed."""
        return self.evict(len(self._store))

    @property
    def hit_rate(self) -> float:
        """O(1) — safe to read every scheduler tick (the gauge path);
        ``stats()`` is the full diagnostic snapshot."""
        return (self.hits / self.lookups) if self.lookups else 0.0

    def stats(self) -> Dict[str, object]:
        return {
            "entries": len(self._store),
            "evictable": self.evictable_pages,
            "hits": self.hits,
            "lookups": self.lookups,
            "hit_rate": self.hit_rate,
        }

    def __repr__(self):
        return (f"PrefixCache({len(self._store)} entries, "
                f"{self.evictable_pages} evictable, "
                f"{self.hits}/{self.lookups} hits)")
