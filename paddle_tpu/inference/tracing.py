"""Per-request span timelines for the serving stack.

Every request the serving layers touch accumulates a host-truth span
log: QUEUED, each PREFILL slice, MIGRATING (disagg page migration and
fleet live-migration/failover), PREEMPTED, DECODE (tick-aggregated),
and a terminal FINISHED / FAILED(reason) marker. Spans are recorded on
the owning engine's injectable clock, so a replay on the virtual clock
produces bit-identical timelines run over run; span context is plain
serializable host state (a list of dicts on ``Request.spans``), so it
rides ``snapshot()/restore()``, ``Engine.extract_request``, and
worker/replica kills for free — a migrated or failed-over request
stitches into ONE contiguous timeline with the origin replica/worker
labeled per span.

The timeline contract (what ``validate_timeline`` checks):

* the first span is QUEUED (every request enters through a queue);
* spans are CONTIGUOUS — each span's ``t0_ms`` equals the previous
  span's ``t1_ms`` (no gaps, no overlaps; zero-length spans are legal,
  the virtual clock is constant within one tick);
* exactly one terminal span (FINISHED or FAILED) and it is last;
* a FAILED terminal span carries the failure reason in its detail.

Export reuses the chrome-trace conventions of
``profiler/chrome_trace.py`` — pid per origin (replica/worker) with
rank info via ``process_label()``, tid = slot lane — so serving
timelines open in perfetto next to op traces.
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional

# span phase vocabulary — mirrors the Request lifecycle states
QUEUED = "QUEUED"
PREFILL = "PREFILL"
DECODE = "DECODE"
PREEMPTED = "PREEMPTED"
MIGRATING = "MIGRATING"
FINISHED = "FINISHED"
FAILED = "FAILED"

TERMINAL = (FINISHED, FAILED)
PHASES = (QUEUED, PREFILL, DECODE, PREEMPTED, MIGRATING,
          FINISHED, FAILED)

#: ts/dur rounding (decimal places of a microsecond) for export —
#: fixed so the same virtual-clock replay emits the same bytes
_US_DP = 3


# -- span log primitives -----------------------------------------------------


def close_open(spans: List[dict], t_ms: float) -> Optional[dict]:
    """Close the trailing open span (``t1_ms is None``) at ``t_ms``.
    Returns the closed span, or None when nothing was open. A clock
    that did not advance closes a zero-length span; time never runs
    backwards within a timeline (clamped to the span's own start)."""
    if spans and spans[-1].get("t1_ms") is None:
        sp = spans[-1]
        sp["t1_ms"] = max(float(t_ms), sp["t0_ms"])
        return sp
    return None


def open_span(spans: List[dict], phase: str, t_ms: float, origin: str,
              slot: Optional[int] = None, **detail) -> dict:
    """Append a new OPEN span at ``t_ms``, closing any prior open span
    at the same instant — contiguity is structural, not checked after
    the fact."""
    closed = close_open(spans, t_ms)
    t0 = float(t_ms)
    if closed is not None:
        t0 = closed["t1_ms"]
    sp: dict = {"phase": phase, "t0_ms": t0, "t1_ms": None,
                "origin": str(origin)}
    if slot is not None:
        sp["slot"] = int(slot)
    if detail:
        sp["detail"] = {k: v for k, v in detail.items() if v is not None}
    spans.append(sp)
    return sp


def seal(spans: List[dict], phase: str, t_ms: float, origin: str,
         reason: Optional[str] = None) -> None:
    """Terminate a timeline: close the open span at ``t_ms`` and
    append the zero-length FINISHED/FAILED marker (with the failure
    reason in its detail). Idempotent — a timeline that already ends
    terminal is left alone, so a driver-level output path can seal
    defensively after an engine-level retire already did."""
    if spans and spans[-1].get("phase") in TERMINAL \
            and spans[-1].get("t1_ms") is not None:
        return
    closed = close_open(spans, t_ms)
    t = closed["t1_ms"] if closed is not None else float(t_ms)
    sp: dict = {"phase": phase, "t0_ms": t, "t1_ms": t,
                "origin": str(origin)}
    if reason:
        sp["detail"] = {"reason": str(reason)}
    spans.append(sp)


def bump_open(spans: List[dict], phase: str, **counts) -> None:
    """Accumulate numeric detail onto the trailing OPEN span when its
    phase matches — the multi-tick decode path stamps each fused
    dispatch's tick count onto the request's single tick-aggregated
    DECODE stint (spans stay O(lifecycle transitions), not
    O(dispatches)). No-op when nothing matching is open (a harvest
    that just sealed the span, a restore mid-stretch)."""
    if not spans or spans[-1].get("t1_ms") is not None \
            or spans[-1].get("phase") != phase:
        return
    det = spans[-1].setdefault("detail", {})
    for k, v in counts.items():
        det[k] = det.get(k, 0) + v


def current_phase(spans: List[dict]) -> Optional[str]:
    """Phase of the trailing OPEN span (None when nothing is open)."""
    if spans and spans[-1].get("t1_ms") is None:
        return spans[-1]["phase"]
    return None


def copy_spans(spans: List[dict]) -> List[dict]:
    """JSON-safe deep copy (snapshot serialization / Output attach —
    the live Request keeps mutating its own list)."""
    out = []
    for sp in spans:
        c = dict(sp)
        if "detail" in c:
            c["detail"] = dict(c["detail"])
        out.append(c)
    return out


def shift_spans(spans: List[dict], delta_ms: float) -> List[dict]:
    """Translate a timeline by ``delta_ms`` in place (restore onto a
    new clock epoch: durations and contiguity are preserved, absolute
    times re-anchor to the restoring process's clock)."""
    if delta_ms:
        for sp in spans:
            sp["t0_ms"] += delta_ms
            if sp.get("t1_ms") is not None:
                sp["t1_ms"] += delta_ms
    return spans


def restore_spans(spans: Optional[List[dict]], arrival_ms: float,
                  now_ms: float, origin: str,
                  resumed: bool) -> List[dict]:
    """Rebuild a snapshotted timeline on the restoring process's
    clock: shift so the timeline starts at the restored arrival time
    (durations and contiguity preserved; an in-process replay restore
    shifts by zero, keeping byte-identical timelines), close the span
    left open at snapshot time, and open the restored wait — PREEMPTED
    for a has-progress resume, QUEUED for an untouched request. A
    legacy entry with no spans starts a fresh QUEUED timeline."""
    spans = copy_spans(spans or [])
    if not spans:
        open_span(spans, QUEUED, now_ms, origin, kind="restore")
        return spans
    shift_spans(spans, arrival_ms - spans[0]["t0_ms"])
    open_span(spans, PREEMPTED if resumed else QUEUED, now_ms, origin,
              kind="restore")
    return spans


# -- validation --------------------------------------------------------------


def validate_timeline(spans: List[dict], tol_ms: float = 0.0
                      ) -> List[str]:
    """Check one request's span log against the timeline contract.
    Returns a list of human-readable problems — empty means the
    timeline is complete and contiguous. ``tol_ms`` loosens the
    contiguity equality for timelines reconstructed from a rounded
    export (0.0 for live span logs — the same floats propagate)."""
    problems: List[str] = []
    if not spans:
        return ["empty timeline"]
    if spans[0].get("phase") != QUEUED:
        problems.append(
            f"timeline starts {spans[0].get('phase')!r}, not QUEUED")
    last = spans[-1]
    if last.get("phase") not in TERMINAL:
        problems.append(
            f"no terminal span (ends {last.get('phase')!r})")
    elif last.get("phase") == FAILED and \
            not (last.get("detail") or {}).get("reason"):
        problems.append("FAILED terminal span carries no reason")
    prev_end = spans[0].get("t0_ms", 0.0)
    for k, sp in enumerate(spans):
        phase = sp.get("phase")
        if phase not in PHASES:
            problems.append(f"span {k}: unknown phase {phase!r}")
        t0, t1 = sp.get("t0_ms"), sp.get("t1_ms")
        if t1 is None:
            problems.append(f"span {k} ({phase}) left open")
            t1 = t0
        elif t1 < t0:
            problems.append(
                f"span {k} ({phase}) runs backwards ({t0}..{t1})")
        if abs(t0 - prev_end) > tol_ms:
            kind = "gap" if t0 > prev_end else "overlap"
            problems.append(
                f"span {k} ({phase}) {kind}: starts {t0}, previous "
                f"span ended {prev_end}")
        if phase in TERMINAL and k != len(spans) - 1:
            problems.append(
                f"span {k} ({phase}) is terminal but not last")
        prev_end = t1
    return problems


def phase_shares(spans: List[dict]) -> Dict[str, float]:
    """Total time (ms) per phase over one timeline — the per-request
    'where did the time go' summary the trace-summary tool tabulates
    fleet-wide."""
    out: Dict[str, float] = {}
    for sp in spans:
        t1 = sp.get("t1_ms")
        if t1 is None:
            continue
        dur = t1 - sp["t0_ms"]
        out[sp["phase"]] = out.get(sp["phase"], 0.0) + dur
    return out


# -- chrome-trace export -----------------------------------------------------


def build_serving_trace(timelines: Dict[int, List[dict]]) -> dict:
    """Chrome-trace dict for a set of stitched request timelines
    (``{req_id: spans}``). Follows profiler/chrome_trace.py's
    conventions: one pid per origin (replica/worker) carrying rank
    info from ``distributed.env.process_label()``, tid = slot lane
    (lane 0 is the queued/parked/migrating lane — spans with no slot),
    "X" complete events in microseconds off a common origin. Output is
    deterministic: origins, requests, and events are emitted in sorted
    order, times rounded to fixed precision — the same virtual-clock
    replay produces byte-identical bytes."""
    from ..profiler.chrome_trace import _rank_info
    rank, world = _rank_info()

    origins: List[str] = sorted(
        {sp["origin"] for spans in timelines.values() for sp in spans})
    pid_of = {o: i for i, o in enumerate(origins)}
    starts = [sp["t0_ms"] for spans in timelines.values()
              for sp in spans]
    t0 = min(starts) if starts else 0.0

    def us(t_ms: float) -> float:
        return round((t_ms - t0) * 1e3, _US_DP)

    events: List[dict] = []
    lanes = set()
    xevents: List[dict] = []
    for rid in sorted(timelines):
        for seq, sp in enumerate(timelines[rid]):
            t1 = sp.get("t1_ms")
            if t1 is None:       # defensive: export never drops a span
                t1 = sp["t0_ms"]
            pid = pid_of[sp["origin"]]
            lane = sp.get("slot")
            tid = 0 if lane is None else int(lane) + 1
            lanes.add((pid, tid))
            # seq preserves timeline order through the global event
            # sort (zero-length spans share one ts within a tick)
            args = {"req": int(rid), "seq": seq}
            args.update(sp.get("detail") or {})
            xevents.append({
                "ph": "X", "cat": "span", "name": sp["phase"],
                "pid": pid, "tid": tid, "ts": us(sp["t0_ms"]),
                "dur": round((t1 - sp["t0_ms"]) * 1e3, _US_DP),
                "args": args})
    for o in origins:
        pid = pid_of[o]
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0, "args": {"name": f"{o} (serving)"}})
        events.append({"ph": "M", "name": "process_sort_index",
                       "pid": pid, "tid": 0,
                       "args": {"sort_index": pid}})
    for pid, tid in sorted(lanes):
        name = "queue" if tid == 0 else f"slot {tid - 1}"
        events.append({"ph": "M", "name": "thread_name", "pid": pid,
                       "tid": tid, "args": {"name": name}})
    xevents.sort(key=lambda e: (e["ts"], e["args"]["req"],
                                e["args"]["seq"]))
    events.extend(xevents)
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "metadata": {"tool": "paddle_tpu.serving_timeline",
                         "rank": rank, "world_size": world,
                         "requests": len(timelines)}}


def export_serving_trace(timelines: Dict[int, List[dict]],
                         path: str) -> str:
    """Write the stitched timelines as chrome-trace JSON. sort_keys +
    fixed separators: the byte stream is a pure function of the
    timelines, so two replays of one seed diff empty."""
    trace = build_serving_trace(timelines)
    with open(path, "w") as f:
        json.dump(trace, f, sort_keys=True, separators=(",", ":"))
    return path


def timelines_from_trace(trace: dict) -> Dict[int, List[dict]]:
    """Inverse of ``build_serving_trace`` (modulo ts rounding): the
    per-request span logs reconstructed from an export, for round-trip
    tests and the completeness gate's assert-via-the-artifact check.
    Validate reconstructed timelines with a small ``tol_ms`` — export
    rounds to 1e-3 us."""
    names = {}
    for ev in trace.get("traceEvents", []):
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            label = str(ev.get("args", {}).get("name", ev["pid"]))
            if label.endswith(" (serving)"):
                label = label[:-len(" (serving)")]
            names[ev["pid"]] = label
    out: Dict[int, List[dict]] = {}
    for ev in trace.get("traceEvents", []):
        if ev.get("ph") != "X" or ev.get("cat") != "span":
            continue
        rid = int(ev.get("args", {}).get("req", -1))
        seq = int(ev.get("args", {}).get("seq", 0))
        t0 = float(ev["ts"]) / 1e3
        sp = {"phase": ev["name"], "t0_ms": t0,
              "t1_ms": t0 + float(ev.get("dur", 0.0)) / 1e3,
              "origin": names.get(ev["pid"], str(ev["pid"])),
              "_seq": seq}
        if ev.get("tid", 0) > 0:
            sp["slot"] = int(ev["tid"]) - 1
        detail = {k: v for k, v in ev.get("args", {}).items()
                  if k not in ("req", "seq")}
        if detail:
            sp["detail"] = detail
        out.setdefault(rid, []).append(sp)
    for spans in out.values():
        spans.sort(key=lambda s: s.pop("_seq"))
    return out
