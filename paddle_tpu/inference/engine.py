"""paddle_tpu.inference.engine — in-process continuous-batching serving.

Reference capability: the serving layer the reference framework ships
around ``block_multihead_attention`` (PaddleNLP's dynamic-batch
predictor over paged KV blocks). PR 4 built every serving *primitive*
— head-major page pools with block tables, the scalar-prefetched
Pallas paged-decode kernel, int8 KV — but ``text.generate`` is a
static-batch API: all requests arrive together, pad to one length,
finish together. This module adds the missing host-side scheduler that
multiplexes DYNAMIC requests onto a SMALL FIXED SET of XLA executables
(the JaxPP split: a schedule-driven host driver over fixed compiled
per-stage programs).

Design (docs/SERVING.md has the full lifecycle):

* Request state machine: WAITING → PREFILL → DECODE → FINISHED, with
  PREEMPTED looping back into the waiting queue (pages freed, tokens
  and the RNG key kept, cache rebuilt by a resume prefill on
  re-admission — token-for-token identical to the uninterrupted run).
* Slot scheduler: ``max_slots`` decode lanes; every ``step()`` admits
  waiting requests into free slots while the page pool keeps
  ``watermark_pages`` of headroom (admission control: running
  sequences must be able to grow before new ones join).
* Paged allocator: allocator.PageAllocator over the shared pool; page
  0 is the scratch page every INACTIVE slot's block-table row points
  at, so masked lanes write garbage harmlessly. A sequence's pages are
  freed the step it finishes — not at end-of-call.
* Exactly TWO compiled step families, so steady-state recompiles are
  zero under any arrival mix: length-bucketed prefill executables
  (prompt padded to a ``prefill_bucket`` multiple, ``paged_write`` of
  the prompt KV, first token sampled) and the FUSED ``[max_slots]``
  decode step (single-token forward through the paged attention stack
  — the multi-sequence Pallas kernel on TPU — plus per-slot sampling,
  all in one executable over DEVICE-RESIDENT state: last tokens,
  cache positions, sampling params and rng keys stay on device
  between ticks, advanced in-graph; the host fetches only the emitted
  tokens and uploads only scheduler-touched slot rows. Three static
  sampler variants — all-greedy argmax, no-filter, full-filter —
  each compiled once). ``steady_state_recompiles()`` reads 0 after
  warmup.
* Token-exactness: a request decoded through the engine emits the
  SAME tokens as a ``batch=1 text.generate`` with the same seed —
  the sampler (generation.sample_token_arrays) mirrors pick_next's
  filter semantics and per-request RNG chains, and inactive lanes
  cannot perturb active rows (row-independent attention + scratch
  page). tests/test_serving_engine.py holds this exact.

Two opt-in accelerators ride on the same scheduler (this PR):

* Prefix caching (``prefix_cache=True``; prefix_cache.py): a
  content-addressed store of full KV pages maps the longest cached
  page-aligned prompt prefix straight into a new request's block
  table (allocator refcounts, copy-on-write for the partial tail
  page) so prefill runs only the uncached tail chunk.
* Speculative decoding (``draft_model=...``; speculative.py): a small
  draft proposes ``spec_k`` tokens per slot, the target verifies all
  k+1 positions in ONE forward, and exact-match acceptance keeps the
  output bit-identical to the draft-free engine — 1 to k+1 tokens
  per tick.
* Chunked prefill (``max_prefill_tokens_per_step=N``): long prompts
  are written as a sequence of bounded bucketed slices interleaved
  with decode ticks — a 32K-token whale prefills N tokens per step
  while every running request keeps emitting, so whale arrivals
  cannot starve small-request TTFT. Slices reuse the SAME bucketed
  prefill executables at their traced ``start`` offset (zero new
  compiled surfaces in steady state), a partially prefilled request
  holds its pages across slices and stays cancellable / preemptible /
  snapshot-able at slice boundaries, prefix-cache hits deeper than
  one bucket skip their cached chunks with the remaining tail still
  sliced, and the sliced prefix is token-exact vs the monolithic one
  (the paged prefill path reads in-chunk K/V back from the pools it
  writes). docs/SERVING.md "Chunked prefill".

``monitor`` surface (docs/OBSERVABILITY.md): gauges
``serving.slots_active`` / ``serving.pages_free`` /
``serving.queue_depth`` / ``serving.ttft_ms`` / ``serving.tpot_ms``
/ ``serving.prefix_hit_rate`` / ``serving.prefix_pages_shared`` /
``serving.spec_accept_rate`` /
``serving.prefill_tokens_per_step``, counters ``serving.requests`` /
``serving.tokens`` / ``serving.finished`` / ``serving.preemptions``
/ ``serving.steps`` / ``serving.prefill_tokens`` /
``serving.prefill_slices`` /
``serving.prefix_tokens_reused`` / ``serving.prefix_hits`` /
``serving.prefix_lookups`` / ``serving.spec_drafted`` /
``serving.spec_accepted`` / ``serving.decode_fallback`` (engine
built with a Pallas-ineligible page geometry — validated ONCE at
construction, docs/DECODE.md).

Reliability layer (inference/reliability.py has the fault catalog and
the snapshot format):

* Request lifecycle hardening: per-request ``deadline_ms`` /
  ``max_queue_steps`` enforced on the engine's step clock, a
  ``cancel(request_id)`` API, and a terminal FAILED(reason) state —
  one bad request (capacity error, NaN logits, injected device error)
  is retired with its pages freed while every other slot keeps
  serving; the loop never raises out of ``step()`` for a per-request
  failure. NaN/inf on any slot's sampling logits is detected IN-GRAPH
  (a tiny ``ok`` flag vector rides out of each executable) and
  quarantines exactly the offending slot
  (``serving.nan_quarantines``).
* Deterministic fault injection: a seeded ``FaultInjector``
  (``fault_injector=`` or ``FLAGS_serving_fault_*``) fires named
  faults at the allocator, prefix cache, prefill/decode/verify
  executables and the draft loop; chaos runs replay bit-identically
  from the seed.
* Crash-exact snapshot/restore: ``snapshot()`` serializes the
  host-side source of truth (request tokens, rng chains, sampling
  params, admission order — not KV pools) and ``restore()`` re-admits
  everything through the preemption/resume-prefill machinery, so a
  restarted engine's outputs are bit-identical to an uninterrupted
  run. ``run(heartbeat_timeout=...)`` attaches a
  ``distributed.watchdog.Heartbeat`` that snapshots-and-reports when
  the loop stalls.

All of it stays on the fixed compiled surfaces:
``steady_state_recompiles() == 0`` holds across cancel / timeout /
fail / restore traces (the tests assert it).
"""
from __future__ import annotations

import time
import warnings
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import monitor
from ..core import tape as tape_mod
from ..distributed import mesh as _mesh_mod
from ..core.dispatch import unwrap
from ..core.flags import get_flag
from ..jit.functional import get_buffers, get_frozen, get_params
from ..kernels.paged_attention import paged_pallas_requirements
from ..profiler.stats import CompileTracker
from ..text.generation import (_model_forward, _resolve_cache_dtype,
                               sample_token_arrays, verify_token_arrays)
from . import tracing
from .allocator import PageAllocator
from .prefix_cache import PrefixCache
from .reliability import InjectedFault, injector_from_flags

# request lifecycle states
WAITING = "WAITING"
PREFILL = "PREFILL"
DECODE = "DECODE"
FINISHED = "FINISHED"
PREEMPTED = "PREEMPTED"
FAILED = "FAILED"

#: prefill attempts before a transiently failing request is FAILED
#: (injected device errors and unexpected prefill errors requeue up to
#: this many times; a deterministic failure burns through them in 3
#: ticks). Pool-pressure requeues (PoolPressure) are EXEMPT: under
#: chunked prefill, admission deliberately charges only the first
#: slice, so mid-prefill exhaustion is the normal backpressure path —
#: like preemption, it waits for pages, it doesn't consume a failure
#: budget.
MAX_PREFILL_RETRIES = 3


class PoolPressure(RuntimeError):
    """A prefill chunk could not get pages (pool exhausted after
    eviction) — the request backs off and retries WITHOUT burning its
    retry budget; running sequences finishing or preempting will free
    the pages it is waiting for."""


@dataclass
class SamplingParams:
    """Per-request generation config (the engine analog of generate's
    kwargs; every field may differ per request inside one batch)."""

    max_new_tokens: int = 16
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 0.0
    eos_token_id: Optional[int] = None
    seed: int = 0
    # reliability knobs (enforced on the engine's step clock, checked
    # at every tick start): a request past its wall deadline — or one
    # still waiting for a slot after max_queue_steps ticks — is FAILED
    # ("deadline" / "queue_timeout") with its pages freed, instead of
    # occupying capacity forever
    deadline_ms: Optional[float] = None
    max_queue_steps: Optional[int] = None

    def validate(self):
        if int(self.max_new_tokens) < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {self.max_new_tokens}")
        if float(self.temperature) < 0:
            raise ValueError(
                f"temperature must be >= 0, got {self.temperature}")
        if self.deadline_ms is not None and float(self.deadline_ms) <= 0:
            raise ValueError(
                f"deadline_ms must be > 0, got {self.deadline_ms}")
        if self.max_queue_steps is not None \
                and int(self.max_queue_steps) < 1:
            raise ValueError(
                f"max_queue_steps must be >= 1, got "
                f"{self.max_queue_steps}")


@dataclass
class Output:
    """One retired request: the generated continuation (including the
    eos token when one was emitted) plus serving latencies. A FAILED
    request also surfaces here — ``finish_reason`` names the failure
    ("cancelled" / "deadline" / "queue_timeout" / "nan_logits" /
    "error:…"), ``error`` carries it too, and ``token_ids`` holds
    whatever was generated before the failure."""

    req_id: int
    prompt_ids: List[int]
    token_ids: List[int]
    finish_reason: str            # "eos" | "length" | failure reason
    ttft_ms: float                # arrival -> first token
    tpot_ms: float                # mean inter-token latency after that
    preemptions: int = 0
    error: Optional[str] = None   # None iff the request FINISHED
    # the request's stitched span timeline (tracing.py contract):
    # QUEUED -> PREFILL slices -> DECODE -> ... -> FINISHED/FAILED,
    # contiguous on the engine's injectable clock, origin-labeled per
    # span across migrations and failovers
    spans: List[dict] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        """True when the request ran to a normal completion."""
        return self.error is None


@dataclass
class Request:
    req_id: int
    prompt: List[int]
    params: SamplingParams
    state: str = WAITING
    generated: List[int] = field(default_factory=list)
    key: Optional[np.ndarray] = None      # [2] uint32 rng chain state
    slot: Optional[int] = None
    pages: List[int] = field(default_factory=list)
    # prefix-cache state: pages acquired (refcounted) at admission for
    # the longest cached prefix, and how many tokens they cover; None
    # until the admission lookup ran (reset on preemption — the resume
    # prefix is re-looked-up against the cache's current contents)
    shared_pages: Optional[List[int]] = None
    prefix_len: int = 0
    written: int = 0                      # tokens in the paged cache
    admit_seq: int = -1                   # admission order (preemption)
    preemptions: int = 0
    retries: int = 0                      # failed prefill attempts
    queued_step: int = -1                 # step the request last queued
    arrival_t: float = 0.0
    first_token_t: float = 0.0
    finish_t: float = 0.0
    finish_reason: Optional[str] = None
    # host-truth span log (tracing.py): plain dicts on the engine
    # clock, so the timeline serializes through snapshot/restore and
    # rides extract_request across workers/replicas untouched
    spans: List[dict] = field(default_factory=list)

    def resume_tokens(self) -> List[int]:
        """The prefix a (re-)prefill must write into the cache: the
        prompt plus every generated token except the newest (which is
        consumed — and written — by the next decode step)."""
        if self.generated:
            return self.prompt + self.generated[:-1]
        return self.prompt

    def resume_len(self) -> int:
        """len(resume_tokens()) without materializing the concat —
        the chunked-prefill scheduler reads this every tick."""
        if self.generated:
            return len(self.prompt) + len(self.generated) - 1
        return len(self.prompt)


def _ceil_div(a: int, b: int) -> int:
    return -(-int(a) // int(b))


#: config attributes the legacy (no-serving_spec) probe derives the KV
#: geometry from — named in the error when a model carries neither
_SPEC_CONFIG_ATTRS = ("num_hidden_layers", "num_key_value_heads",
                      "num_attention_heads", "hidden_size",
                      "max_position_embeddings", "vocab_size")


def serving_model_spec(model) -> dict:
    """The engine's model-geometry probe. A model that knows how it
    serves publishes ``model.serving_spec()`` (LlamaForCausalLM,
    ErnieMoEForCausalLM, BertModel do) — a plain dict with at least
    ``kind`` ("decoder" | "encoder") plus, for decoders, the KV
    geometry (``num_layers`` / ``kv_heads`` / ``head_dim`` /
    ``max_context`` / ``vocab_size``) and optionally a ``moe`` block
    (fused-dispatch eligibility diagnostics). Models WITHOUT the hook
    fall back to the llama-shaped config attribute read that used to
    be inlined in ``Engine.__init__`` — with a loud error naming the
    missing attributes instead of an AttributeError mid-constructor."""
    fn = getattr(model, "serving_spec", None)
    if callable(fn):
        spec = dict(fn())
        if spec.get("kind") == "decoder":
            missing = [k for k in ("num_layers", "kv_heads", "head_dim",
                                   "max_context")
                       if spec.get(k) is None]
            if missing:
                raise ValueError(
                    f"{type(model).__name__}.serving_spec() is missing "
                    f"decoder geometry key(s) {missing}")
        return spec
    cfg = getattr(model, "config", None)
    missing = [a for a in _SPEC_CONFIG_ATTRS
               if getattr(cfg, a, None) is None]
    if cfg is None or missing:
        raise ValueError(
            f"cannot derive a serving spec for "
            f"{type(model).__name__}: no serving_spec() method and "
            f"model.config lacks {missing or 'a config'} — add a "
            f"serving_spec() returning the KV geometry "
            f"(docs/SERVING.md 'Model polymorphism')")
    return {
        "kind": "decoder",
        "num_layers": int(cfg.num_hidden_layers),
        "kv_heads": int(cfg.num_key_value_heads),
        "head_dim": int(cfg.hidden_size) // int(cfg.num_attention_heads),
        "max_context": int(cfg.max_position_embeddings),
        "vocab_size": int(cfg.vocab_size),
    }


def _normalize_prompt(ids) -> List[int]:
    """One prompt as a python int list — the shared admission
    normalization for every serving front door (Engine.add_request and
    the disaggregated driver's): [s] or [1, s] Tensor/array in, loud
    errors for batches and empties. Shapes both doors accept must stay
    identical or the token-exactness contract between them breaks at
    admission."""
    arr = np.asarray(unwrap(ids))
    if arr.ndim == 2 and arr.shape[0] == 1:
        arr = arr[0]
    if arr.ndim != 1:
        raise ValueError(
            f"add_request takes ONE prompt ([s] or [1, s] ids); got "
            f"shape {arr.shape} — queue a batch as separate "
            f"requests (silently concatenating the rows would "
            f"decode from a nonsense combined context)")
    prompt = [int(t) for t in arr]
    if not prompt:
        raise ValueError("empty prompt")
    return prompt


def _make_paged_pools(layers, rows, hkv, page_size, hd, dtype, quant):
    """Per-layer paged KV pool tuples — (k, v[, ks, vs]) zeros in the
    head-major layout kernels/paged_attention.py expects. The ONE
    constructor for both the target's pools and the draft's
    (speculative.py mirrors the engine's layout exactly — a layout
    change here reaches both models)."""
    return [
        (jnp.zeros((rows, hkv, page_size, hd), dtype),
         jnp.zeros((rows, hkv, page_size, hd), dtype))
        + ((jnp.zeros((rows, hkv, page_size), jnp.float32),
            jnp.zeros((rows, hkv, page_size), jnp.float32))
           if quant else ())
        for _ in range(layers)]


@dataclass
class _PendingTick:
    """One in-flight decode dispatch (the pipelined tick loop's
    handoff between dispatch and harvest): the device output futures,
    a snapshot of the (slot, request) pairs the dispatch covered —
    harvest skips rows whose request was retired during the overlap
    window — and the attribution bookkeeping (dispatch wall time +
    the device-seconds mark, so the harvest sync can attribute host
    work that ran hidden under device execution as OVERLAP instead of
    double-counting it)."""

    kind: str                 # "single" | "spec" | "multi"
    data: tuple               # device outputs to sync + fetch
    active: list              # [(slot, Request)] snapshot at dispatch
    ticks: int                # device ticks this dispatch covers
    t_dispatch: float         # perf_counter at dispatch
    dev_mark: float           # self._device_s at dispatch
    k: int = 0                # spec: draft len / multi: fused ticks


@jax.jit
def _merge_rows(dev, host, mask):
    """Fold host-updated slot rows (admissions, preemptions, finishes)
    into the device-resident decode state: row i comes from ``host``
    where ``mask[i]`` (the scheduler touched the slot since the last
    decode step), else from the state the last decode executable
    produced. ONE fixed-shape executable whatever the number of dirty
    slots — a per-index scatter would compile a fresh tiny program per
    dirty-set shape and show up as steady-state recompiles."""
    def pick(d, h):
        m = mask.reshape((-1,) + (1,) * (d.ndim - 1))
        return jnp.where(m, h.astype(d.dtype), d)
    return jax.tree_util.tree_map(pick, dev, host)


def _lint_armed() -> bool:
    """PADDLE_TPU_LINT=1: arm the steady-tick transfer guard (read per
    tick through analysis.lint_enabled so tests can toggle the env)."""
    from .. import analysis
    return analysis.lint_enabled()


class Engine:
    """In-process continuous-batching engine over the paged KV stack.

        eng = Engine(model, max_slots=8, page_size=16, pool_pages=256)
        rid = eng.add_request(ids, SamplingParams(max_new_tokens=32))
        while ...:
            for out in eng.step():
                ...                      # finished requests
        # or offline:
        outs = eng.run([(ids_a, pa), (ids_b, pb)])

    The model must support the ``kv_caches``/``cache_index`` forward
    kwargs (the in-tree LlamaForCausalLM does). Weights are snapshotted
    at construction (the executables close over nothing — params ride
    as arguments — but the engine reads them once; rebuild the engine
    after mutating the model).
    """

    def __init__(self, model, max_slots: int = 8, page_size: int = 16,
                 pool_pages: Optional[int] = None,
                 cache_dtype: str = "auto",
                 max_context: Optional[int] = None,
                 prefill_bucket: int = 32,
                 watermark_pages: Optional[int] = None,
                 prefix_cache: bool = False,
                 draft_model=None, spec_k: int = 4,
                 clock=None, fault_injector=None,
                 debug_invariants: Optional[bool] = None,
                 max_prefill_tokens_per_step: Optional[int] = None,
                 multi_tick: int = 1,
                 label: Optional[str] = None):
        # model polymorphism (docs/SERVING.md): geometry comes from the
        # serving_spec probe, not hard-coded llama config attribute
        # names — an encoder or a spec-less model gets a pointed error
        # instead of an AttributeError three constructors deep
        spec = serving_model_spec(model)
        if spec.get("kind") == "encoder":
            raise ValueError(
                f"{type(model).__name__} is an ENCODER — it has no KV "
                f"decode surface for the continuous-batching Engine. "
                f"Serve it through the embedding service "
                f"(inference.BatchEncoder, docs/SERVING.md "
                f"'Embedding service') instead")
        import inspect
        try:
            fsig = inspect.signature(model.forward)
        except (TypeError, ValueError):
            fsig = None
        if fsig is None or "kv_caches" not in fsig.parameters:
            raise ValueError(
                "Engine requires a model with kv_caches/cache_index "
                "forward kwargs (KV-cache decode support); "
                f"{type(model).__name__}.forward has none — use "
                "text.generate(use_cache=False) for padded one-shot "
                "generation instead")
        self.serving_spec = spec
        self.model = model
        self.max_slots = int(max_slots)
        self.page_size = int(page_size)
        self.prefill_bucket = int(prefill_bucket)
        # chunked prefill (docs/SERVING.md "Chunked prefill"): when set,
        # a prompt is written as a sequence of bounded slices — at most
        # this many tokens of prefill run per step() — interleaved with
        # decode ticks, so one 32K-token whale can never stall TTFT for
        # the small requests decoding beside it. Rounded UP to the
        # bucket so every slice is a whole compiled prefill bucket.
        # None = monolithic (the whole tail in one chunk, as before).
        if max_prefill_tokens_per_step is not None:
            if int(max_prefill_tokens_per_step) < 1:
                raise ValueError(
                    f"max_prefill_tokens_per_step must be >= 1, got "
                    f"{max_prefill_tokens_per_step}")
            max_prefill_tokens_per_step = self._pbucket(
                int(max_prefill_tokens_per_step))
        self.max_prefill_tokens_per_step = max_prefill_tokens_per_step
        self._pf_step_tokens = 0
        # multi-tick fused decode (docs/SERVING.md "Dispatch
        # pipelining & multi-tick decode"): when every live slot is in
        # a pure-greedy decode stretch, up to this many device ticks
        # run per host round trip as ONE lax.scan executable (in-scan
        # eos/budget freeze keeps the output token-exact vs the
        # single-tick loop). 1 = off (the default: one dispatch per
        # tick, still pipelined against the host scheduling window).
        if int(multi_tick) < 1:
            raise ValueError(
                f"multi_tick must be >= 1, got {multi_tick}")
        self.multi_tick = int(multi_tick)
        self.max_context = int(max_context or spec["max_context"])
        # speculative decoding writes k+1 positions per tick (the
        # drafted chunk), so the block tables carry that lookahead of
        # extra slots past max_context — a verify write must never
        # clip into a request's LAST live page
        self._lookahead = (int(spec_k) + 1) if draft_model is not None \
            else 1
        self.max_blocks = _ceil_div(
            self._pbucket(self.max_context) + self._lookahead - 1,
            self.page_size)
        if pool_pages is None:
            # default: every slot can hold a max-context sequence — no
            # preemption unless the caller sizes the pool tighter
            pool_pages = self.max_slots * self.max_blocks
        self.pool_pages = int(pool_pages)
        self.watermark_pages = (max(1, self.pool_pages // 50)
                                if watermark_pages is None
                                else int(watermark_pages))
        self._st = (get_params(model), get_buffers(model),
                    get_frozen(model))
        self.cache_dtype = _resolve_cache_dtype(cache_dtype, self._st[0])
        self._quant = self.cache_dtype == jnp.dtype(jnp.int8)
        hkv = int(spec["kv_heads"])
        hd = int(spec["head_dim"])
        # pool row 0 is the scratch page (inactive lanes) — the
        # allocator hands out ids [1, pool_pages]
        rows = self.pool_pages + 1
        self._alloc = PageAllocator(self.pool_pages, base=1)
        # TP-sharded decode (docs/SERVING.md "TP-sharded decode"):
        # under an mp>1 mesh the KV pools shard over the kv-head axis
        # — the placement GSPMD would pick anyway from the TP attention
        # compute — and the tiny decode state replicates. Committing
        # BOTH at every host→device upload matters beyond bandwidth:
        # an uncommitted (UnspecifiedValue) upload compiles a second
        # copy of the decode executable the first time a donated
        # output comes back with concrete shardings, which reads as a
        # steady-state recompile. One sharding from tick zero keeps
        # the per-worker compiled surface unique.
        self._mp_rep = None
        mesh = _mesh_mod.get_mesh()
        abstract_cls = getattr(jax.sharding, "AbstractMesh", None)
        if mesh is None or (abstract_cls is not None
                            and isinstance(mesh, abstract_cls)):
            # paddle's global is unset (or a device-free fake): on a
            # jax with NATIVE set_mesh, `with jax.set_mesh(mesh):`
            # populates only jax's ambient context — read the concrete
            # mesh from there so TP detection works on both runtimes
            # (the same fallback mesh_mod.axis_degree applies for the
            # TP layer selection)
            mesh = _mesh_mod.ambient_concrete_mesh()
        mp = _mesh_mod.mesh_axis_sizes(mesh).get("mp", 1) \
            if mesh is not None else 1
        self._mp_mesh = None
        self._mp_degree = 1
        if mesh is not None \
                and not (abstract_cls is not None
                         and isinstance(mesh, abstract_cls)) \
                and mp > 1:
            from jax.sharding import NamedSharding, PartitionSpec
            self._mp_mesh = mesh
            self._mp_degree = mp
            self._mp_rep = NamedSharding(mesh, PartitionSpec())
        if self._mp_rep is None:
            # Commitment churn guard beyond mp>1: a model whose params
            # are COMMITTED to a mesh even at degree 1 — MoE expert
            # weights go through shard_tensor at construction — makes
            # every executable output committed too, so donated pools/
            # state uploaded UNCOMMITTED here would flip to committed
            # NamedShardings after their first run and recompile each
            # executable family exactly once (read: 1-2 phantom
            # steady-state recompiles per engine). Commit our uploads
            # to the params' own mesh, replicated, from tick zero.
            from jax.sharding import NamedSharding, PartitionSpec
            for leaf in jax.tree_util.tree_leaves(self._st):
                sh = getattr(leaf, "sharding", None)
                if isinstance(sh, NamedSharding) \
                        and getattr(leaf, "committed", False):
                    self._mp_mesh = sh.mesh
                    self._mp_rep = NamedSharding(sh.mesh,
                                                 PartitionSpec())
                    break
        self._pools = self._commit_pools(_make_paged_pools(
            int(spec["num_layers"]), rows, hkv, self.page_size, hd,
            self.cache_dtype, self._quant), hkv)
        S, MB = self.max_slots, self.max_blocks
        self._bt = np.zeros((S, MB), np.int32)
        self._pos = np.zeros((S,), np.int32)
        self._last = np.zeros((S,), np.int32)
        self._temps = np.zeros((S,), np.float32)
        self._topks = np.zeros((S,), np.int32)
        self._topps = np.zeros((S,), np.float32)
        self._keys = np.zeros((S, 2), np.uint32)
        self._live = np.zeros((S,), np.int32)
        # the decode state — (last, pos, temps, topks, topps, keys,
        # live) — LIVES ON DEVICE between ticks: the fused decode
        # executable advances it in place (donated), so a steady-state
        # tick ships nothing host→device and fetches only the emitted
        # tokens. The numpy mirrors above are the scheduler's view;
        # rows the scheduler touches are marked dirty and merged in
        # before the next decode step (_flush_state).
        self._dev = (self._up(self._last), self._up(self._pos),
                     self._up(self._temps), self._up(self._topks),
                     self._up(self._topps), self._up(self._keys),
                     self._up(self._live))
        self._dirty: set = set()
        self._bt_dev = self._up(self._bt)
        self._bt_dirty = False
        self._slots: List[Optional[Request]] = [None] * S
        self._waiting: "deque[Request]" = deque()
        self.requests: Dict[int, Request] = {}
        self._next_id = 0
        self._admit_counter = 0
        self._steps = 0
        self._last_compile_step = 0
        self._compiles = 0        # compiles inside OUR step() calls
        self._warm_compiles = 0
        self._prefill_fns: Dict[int, object] = {}
        self._decode_fns: Dict[str, object] = {}
        self._verify_fns: Dict[str, object] = {}
        # shared-prefix KV reuse (prefix_cache.py): content-addressed
        # full pages mapped into many block tables via allocator
        # refcounts; idle entries are evicted before admission is
        # refused or a live sequence preempted
        self._prefix = (PrefixCache(self._alloc, self.page_size)
                        if prefix_cache else None)
        # draft/verify speculative decoding (speculative.py): the
        # draft's paged pools mirror this engine's page ids exactly
        self._spec = None
        self._spec_drafted = 0
        self._spec_accepted = 0
        if draft_model is not None:
            from .speculative import SpeculativeDecoder
            self._spec = SpeculativeDecoder(self, draft_model, spec_k)
        # reliability surfaces (inference/reliability.py): the step
        # clock every deadline is measured on (injectable so replay
        # tools and tests run deterministic virtual time), the seeded
        # fault injector (explicit, or armed process-wide via
        # FLAGS_serving_fault_seed), and the per-step invariant audit
        self._clock = clock if clock is not None else time.perf_counter
        # observability plane (docs/OBSERVABILITY.md "Serving
        # timelines & histograms"): `label` names this engine in span
        # timelines and scopes its metrics — a fleet replica or disagg
        # worker writes both the unlabeled aggregate and its
        # serving.<label>.… twin; a plain engine stays unlabeled.
        self.label = str(label) if label is not None else "engine"
        self._mon = monitor.scope(label)
        # host/device tick attribution: wall seconds this tick spent
        # blocked on device results (block_until_ready around the
        # tick's dispatch outputs); step() publishes the split
        self._device_s = 0.0
        # fault_injector: an explicit FaultInjector, None = arm from
        # FLAGS_serving_fault_* (off by default), False = force OFF
        # even when the flags arm the process (the chaos tooling's
        # clean baseline passes)
        if fault_injector is False:
            self._injector = None
        elif fault_injector is None:
            self._injector = injector_from_flags()
        else:
            self._injector = fault_injector
        self._debug_invariants = (
            bool(get_flag("serving_debug_invariants"))
            if debug_invariants is None else bool(debug_invariants))
        # the NaN-injection vector riding into every decode/verify
        # step: all-zeros (one resident device array, re-uploaded only
        # on the rare fault tick) added to the sampling logits — a NaN
        # row turns that slot's in-graph `ok` flag off
        self._poison_zeros = self._up(np.zeros((S,), np.float32))
        self._poison_dev = self._poison_zeros
        self._poisoned = False
        # multi-tick aux state, DEVICE-RESIDENT between fused
        # dispatches: per-slot eos token id (-1 = none; emitted ids
        # are >= 0 so -1 never matches) and the remaining
        # max_new_tokens budget. The scan decrements the budget
        # in-graph (an eos zeroes it), so consecutive fused dispatches
        # upload nothing; any host-side slot touch (_activate /
        # _clear_slot) or token emitted OUTSIDE the fused path
        # (single-tick / spec harvest) marks it stale and the next
        # fused dispatch re-uploads the two [max_slots] vectors.
        self._multi_fns: Dict[int, object] = {}
        self._aux_dev = (self._up(np.full((S,), -1, np.int32)),
                        self._up(np.zeros((S,), np.int32)))
        self._aux_clean = False
        # dispatch-pipelining attribution (see _sync_timed): host work
        # that ran while the device was still executing the in-flight
        # dispatch — hidden under device time, published as the
        # serving.overlap_ms_per_tick gauge, never double-counted
        self._overlap_s = 0.0
        # EWMA of per-device-tick duration on the INJECTABLE clock —
        # the deadline clamp's horizon unit (deterministic under the
        # replay tools' virtual clocks)
        self._tick_est_ms = 0.0
        self.last_stall_snapshot: Optional[dict] = None
        from ..distributed import watchdog as _watchdog
        self._watchdog = _watchdog
        self._tracker = CompileTracker().start()
        # Pallas paged-decode eligibility is a STATIC property of
        # (head_dim, page_size, cache_dtype) — validate it once here
        # instead of letting every decode step silently gather: an
        # ineligible geometry on a TPU backend costs a full-cache copy
        # per token and previously only showed up as slow numbers.
        self.decode_fallback_reason = paged_pallas_requirements(
            hd, self.page_size, self.cache_dtype)
        self.pallas_eligible = self.decode_fallback_reason is None
        if not self.pallas_eligible:
            monitor.counter("serving.decode_fallback").increase()
            if jax.default_backend() in ("tpu", "axon"):
                warnings.warn(
                    f"Engine decode steps will take the XLA gather "
                    f"path (full-cache copy per token): "
                    f"{self.decode_fallback_reason}. Pick a page_size/"
                    f"cache_dtype from docs/DECODE.md's eligibility "
                    f"table to serve on the Pallas kernel.",
                    RuntimeWarning, stacklevel=2)
        # MoE models (docs/SERVING.md "MoE serving"): probe the fused
        # grouped-matmul dispatch eligibility ONCE here, through the
        # SAME fallback ladder the decode trace will take (the model's
        # own MoELayer), so an ineligible geometry/backend is a named
        # diagnostic at construction instead of a silently slower
        # scatter path. serving.moe.decode_path.* counters (republished
        # from the trace-time kernels.moe.decode_path.* deltas each
        # compile-bearing step) then PROVE which dispatch the compiled
        # decode executables actually baked in.
        self._moe_layer = spec.get("moe_layer")
        self.moe_spec = spec.get("moe")
        self.moe_fallback_reason = None
        self.moe_pallas_eligible = None
        self._moe_paths: Dict[str, int] = {}
        # baseline the GLOBAL trace-time counters now, so the per-step
        # republish attributes only deltas that landed after this
        # engine existed (another engine's warmup must not read as ours)
        self._moe_seen: Dict[str, int] = {
            k: int(v) for k, v in monitor.snapshot().items()
            if k.startswith("kernels.moe.decode_path.")}
        # compile count at the last _moe_seen sync: compiles landing
        # BETWEEN our steps (another engine's warmup, a generate()
        # call) re-baseline instead of republishing — see step()
        self._moe_tracker_mark = self._tracker.compiles
        if self._moe_layer is not None:
            # dtype is inert in the eligibility check (lane-width
            # constraints only) — None keeps the probe trace-free
            self.moe_fallback_reason = self._moe_layer.\
                _pallas_fallback_reason(self.max_slots, None,
                                        cap=self.max_slots)
            self.moe_pallas_eligible = self.moe_fallback_reason is None
            if not self.moe_pallas_eligible:
                monitor.counter("serving.moe.decode_fallback").increase()
                if jax.default_backend() in ("tpu", "axon"):
                    warnings.warn(
                        f"MoE decode ticks will take the sparse "
                        f"scatter dispatch, not the fused Pallas "
                        f"grouped-matmul: {self.moe_fallback_reason} "
                        f"(docs/KERNELS.md eligibility).",
                        RuntimeWarning, stacklevel=2)

    # -- compiled step shapes ------------------------------------------------

    def _up(self, x):
        """Host→device upload of engine state, committed to the
        replicated sharding under an mp>1 mesh (see __init__) — plain
        jnp.asarray otherwise."""
        if self._mp_rep is None:
            return jnp.asarray(x)
        return jax.device_put(np.asarray(x), self._mp_rep)

    def _commit_pools(self, pools, kv_heads: int):
        """Commit freshly built KV pools to the kv-head-sharded mp
        placement (identity off-mesh). Shared with the draft model's
        mirrored pools (speculative.py) — the spec is chosen per
        POOL's kv-head count: a 1-kv-head draft beside an 8-head
        target replicates instead of crashing on an indivisible
        partition."""
        if self._mp_mesh is None:
            return pools
        from jax.sharding import NamedSharding, PartitionSpec
        spec = (PartitionSpec(None, "mp")
                if self._mp_degree > 1
                and int(kv_heads) % self._mp_degree == 0
                else PartitionSpec())
        return jax.device_put(pools, NamedSharding(self._mp_mesh, spec))

    def _pbucket(self, n: int) -> int:
        return _ceil_div(n, self.prefill_bucket) * self.prefill_bucket

    def _lifetime_pages(self, plen: int, max_new: int) -> int:
        """Peak page demand of a request over its whole lifetime — the
        can-it-EVER-be-scheduled admission check. Monolithic prefill
        peaks at the whole-prompt bucket padding; CHUNKED prefill pads
        only one slice at a time, so a long prompt is charged its
        per-slice peak (the incremental fit) instead of the bucketed
        whole — the reason a near-pool-sized prompt that fits slice by
        slice is admitted under max_prefill_tokens_per_step but
        rejected without it."""
        need = plen + max_new
        if self.max_prefill_tokens_per_step is None:
            # monolithic: the historical conservative bound (whole-need
            # bucket rounding) — kept for admission-behavior stability
            return _ceil_div(
                self._pbucket(need) + self._lookahead - 1,
                self.page_size)
        # chunked: prefill allocates pages for REAL tokens only (bucket
        # padding writes to the scratch page), so the lifetime peak is
        # simply the decode-side maximum — every written token plus the
        # per-tick write lookahead (_ensure_pages' growth target at the
        # final token). This covers the resume-prefill path too: a
        # resume prefix is at most need - 2 tokens.
        return _ceil_div(need - 1 + self._lookahead, self.page_size)

    def _inject_bt(self, caches, bt):
        """Pool tuples -> the model's per-layer paged cache tuples:
        (k, v, bt[, ks, vs]) — the block table is engine state, shared
        by every layer, injected at call time."""
        return [(c[0], c[1], bt) + tuple(c[2:]) for c in caches]

    def _strip_bt(self, kv):
        return [(t[0], t[1]) + tuple(t[3:]) for t in kv]

    def _get_decode_fn(self, variant: str):
        """The fused [max_slots] decode executable — ONE compiled step
        that consumes the device-resident state (last tokens, cache
        positions, per-slot sampling params, rng keys), runs the model
        forward, samples every slot's next token IN-GRAPH, and returns
        the advanced state. The host fetches only the emitted tokens;
        nothing else crosses per tick.

        Keyed STATICALLY on the cheapest sampler the active slots
        need — three variants, each compiled once, so any greedy/
        sampled arrival mix bounces between fixed executables with
        zero steady-state recompiles:

        * ``"greedy"``  — every active slot at temperature 0: plain
          argmax, no rng consumed (keys pass through untouched,
          pick_next semantics).
        * ``"plain"``   — sampling slots but NO top-k/top-p anywhere:
          the no-filter sampler (``use_filters=False``) skips the
          full-vocab argsort the traced filters would force. Greedy
          rows ride inside it unchanged, so mixed greedy+temperature
          traffic collapses onto this one executable.
        * ``"filtered"`` — some slot filters: the full per-slot
          argsort sampler (work XLA can't dead-code out when top_k/
          top_p ride as traced arrays).
        """
        fn = self._decode_fns.get(variant)
        if fn is not None:
            return fn
        fn = jax.jit(self._decode_body(variant), donate_argnums=(1, 3))
        self._decode_fns[variant] = fn
        self._note_compile()
        return fn

    def _decode_body(self, variant: str):
        """The decode step's traceable body, separate from the jitted
        wrapper so hotpath_lint can abstract-trace the exact program
        `_get_decode_fn` compiles (same closure, same donation
        contract declared in the inventory)."""
        model = self.model

        def body(st, caches, bt, state, poison):
            last, pos, temps, topks, topps, keys, live = state
            kv = self._inject_bt(caches, bt)
            # idle lanes ride at cache_index -1: their context_lens
            # (pos + 1) is then 0, so the multi-sequence decode kernel
            # treats them as DEAD slots — no page DMA, no compute —
            # and their scratch write clips into page 0. Only live
            # lanes advance their position; an idle lane's pos must
            # not drift upward tick over tick (it would re-enter the
            # kernel as a growing fake context and stream scratch
            # pages forever).
            idx = jnp.where(live > 0, pos, -jnp.ones_like(pos))
            logits, new_kv = _model_forward(model, st, last[:, None],
                                            kv, idx)
            # poison (normally all zeros, NaN at a fault-injected
            # slot) rides into the sampling logits so the in-graph
            # NaN/inf detector exercises the SAME path a genuinely
            # NaN-emitting model would hit; `ok` is the per-slot
            # quarantine flag the host checks before trusting a token
            cur = logits[:, -1].astype(jnp.float32) + poison[:, None]
            ok = jnp.isfinite(cur).all(axis=-1)
            if variant == "greedy":
                nxt = jnp.argmax(cur, axis=-1).astype(jnp.int32)
                keys2 = keys
            else:
                nxt, keys2 = sample_token_arrays(
                    cur, keys, temps, topks, topps,
                    use_filters=variant == "filtered")
            state2 = (nxt, pos + live, temps, topks, topps, keys2,
                      live)
            return nxt, ok, state2, self._strip_bt(new_kv)

        return body

    def _get_multi_fn(self, k: int):
        """The fused k-tick greedy decode executable — ``k`` decode
        steps as ONE ``lax.scan`` program (speculative.py's draft loop
        is the template), dispatched when every live slot is in a
        pure-greedy stretch. One compile per k bucket (powers of two
        up to ``multi_tick``, plus ``multi_tick`` itself), so mixed
        clamp traces bounce between a handful of warm executables with
        zero steady-state recompiles."""
        fn = self._multi_fns.get(k)
        if fn is not None:
            return fn
        fn = jax.jit(self._multi_body(k), donate_argnums=(1, 3, 4))
        self._multi_fns[k] = fn
        self._note_compile()
        return fn

    def _multi_body(self, k: int):
        """Traceable body of the k-tick fused decode. Scan step j:
        rows still ALIVE (live slot, budget > 0) feed their newest
        token at position ``pos``; frozen rows ride the dead-slot
        convention (cache_index -1: no page DMA, no compute, scratch-
        page write) — an in-scan eos zeroes the row's budget so it
        writes nothing and consumes nothing for the rest of the scan,
        and a row whose max_new_tokens budget runs out freezes the
        same way. Greedy only: argmax consumes no rng, keys pass
        through untouched, so the emitted stream is bit-identical to
        k single-tick greedy steps. Poison (the decode.nan fault
        vector) rides into every step's sampling logits; the per-step
        ``ok`` matrix lets the host quarantine the offending slot at
        the exact step the NaN appeared."""
        model = self.model

        def body(st, caches, bt, state, aux, poison):
            last, pos, temps, topks, topps, keys, live = state
            eosv, bud = aux

            def step(carry, _):
                tok, kv, p, b = carry
                alive = (live > 0) & (b > 0)
                idx = jnp.where(alive, p, -jnp.ones_like(p))
                kvb = self._inject_bt(kv, bt)
                logits, new_kv = _model_forward(model, st, tok[:, None],
                                                kvb, idx)
                cur = logits[:, -1].astype(jnp.float32) + poison[:, None]
                okr = jnp.isfinite(cur).all(axis=-1)
                sampled = jnp.argmax(cur, axis=-1).astype(jnp.int32)
                nxt = jnp.where(alive, sampled, tok)
                b2 = jnp.where(alive,
                               jnp.where(sampled == eosv,
                                         jnp.zeros_like(b), b - 1),
                               b)
                return (nxt, self._strip_bt(new_kv), p + alive.astype(
                    p.dtype), b2), (sampled, okr)

            (tok_f, caches, pos_f, bud_f), (toks, oks) = jax.lax.scan(
                step, (last, caches, pos, bud), None, length=k)
            state2 = (tok_f, pos_f, temps, topks, topps, keys, live)
            # [S, k] per-step tokens + ok flags: the ONLY fetches
            return (jnp.swapaxes(toks, 0, 1), jnp.swapaxes(oks, 0, 1),
                    state2, (eosv, bud_f), caches)

        return body

    def _get_verify_fn(self, variant: str):
        """The speculative verify executable — ONE fixed-shape
        ``[max_slots, k+1]`` target forward per static sampler variant
        (same three variants as the decode step): scores the drafted
        chunk at every position, walks the acceptance chain with the
        target's own sampler and rng keys (verify_token_arrays — the
        exact-match rule that keeps output bit-identical to the
        draft-free engine), and advances the device-resident state by
        each slot's accepted count + 1 in-graph. The host fetches only
        the candidate tokens and the accept counts."""
        fn = self._verify_fns.get(variant)
        if fn is not None:
            return fn
        fn = jax.jit(self._verify_body(variant), donate_argnums=(1, 3))
        self._verify_fns[variant] = fn
        self._note_compile()
        return fn

    def _verify_body(self, variant: str):
        model = self.model

        def body(st, caches, bt, state, drafts, poison):
            last, pos, temps, topks, topps, keys, live = state
            kv = self._inject_bt(caches, bt)
            # idle lanes at cache_index -1 (context 0), like the plain
            # decode step — their k+1 scratch writes clip into page 0
            idx = jnp.where(live > 0, pos, -jnp.ones_like(pos))
            toks_in = jnp.concatenate([last[:, None], drafts], axis=1)
            logits, new_kv = _model_forward(model, st, toks_in, kv, idx)
            scored = logits.astype(jnp.float32) \
                + poison[:, None, None]
            ok = jnp.isfinite(scored).all(axis=(1, 2))
            toks, acc, keys2 = verify_token_arrays(
                scored, drafts, keys, temps, topks,
                topps, use_filters=variant == "filtered",
                greedy=variant == "greedy")
            # live rows consumed acc+1 context tokens; idle rows must
            # not drift (same contract as the decode step)
            new_last = jnp.take_along_axis(toks, acc[:, None],
                                           axis=1)[:, 0]
            state2 = (jnp.where(live > 0, new_last, last),
                      pos + (acc + 1) * live, temps, topks, topps,
                      jnp.where(live[:, None] > 0, keys2, keys), live)
            return toks, acc, ok, state2, self._strip_bt(new_kv)

        return body

    def _get_prefill_fn(self, pb: int):
        fn = self._prefill_fns.get(pb)
        if fn is not None:
            return fn
        fn = jax.jit(self._prefill_body(), donate_argnums=(1,))
        self._prefill_fns[pb] = fn
        self._note_compile()
        return fn

    def _prefill_body(self):
        model = self.model

        def body(st, caches, bt_row, prompt, plen, start, temps, topks,
                 topps, keys, poison):
            kv = self._inject_bt(caches, bt_row)
            # `start` is the page-aligned token offset the chunk begins
            # at — 0 for a cold prefill, the cached-prefix length on a
            # prefix-cache hit (the chunk attends the shared pages
            # through the block table; only the tail is computed). It
            # rides as a TRACED [1] array so every hit depth reuses
            # this one bucket executable.
            logits, new_kv = _model_forward(model, st, prompt, kv,
                                            start)
            # last REAL chunk position's logits (the chunk is padded
            # to the bucket; causality keeps the pad out of this row)
            idx = jnp.reshape(plen - 1, (1, 1, 1)).astype(jnp.int32)
            last = jnp.take_along_axis(logits, idx, axis=1)[:, 0]
            cur = last.astype(jnp.float32) + poison[:, None]
            ok = jnp.isfinite(cur).all(axis=-1)
            nxt, keys2 = sample_token_arrays(
                cur, keys, temps, topks, topps)
            return nxt, keys2, ok, self._strip_bt(new_kv)

        return body

    def _note_compile(self):
        """Record that THIS step legitimately introduced a new
        executable (warmup accounting for steady_state_recompiles)."""
        self._last_compile_step = self._steps

    # -- hot-path lint (docs/ANALYSIS.md "Hot-path rules") -------------------

    def _hotpath_inventory(self):
        """The engine's compiled-executable inventory + scheduler tick
        path, in hotpath_lint's terms: every per-tick body with its
        abstract args and donation/fetch contract, the tick functions
        to source-walk, the steady-path subset the upload discipline
        applies to, and the executable-cache key sets."""
        from ..analysis import hotpath_lint as hp
        S, MB = self.max_slots, self.max_blocks

        def s(shape, dt):
            return jax.ShapeDtypeStruct(shape, np.dtype(dt))

        st = hp.struct_of(self._st)
        pools = hp.struct_of(self._pools)
        state = hp.struct_of(self._dev)
        bt = hp.struct_of(self._bt_dev)
        poison = hp.struct_of(self._poison_dev)
        specs = []
        variants = tuple(self._decode_fns) or ("greedy", "plain",
                                               "filtered")
        for v in variants:
            specs.append(hp.ExecutableSpec(
                name=f"decode[{v}]", body=self._decode_body(v),
                args=(st, pools, bt, state, poison),
                donate=(1, 3), fetched=(0, 1)))
        aux = hp.struct_of(self._aux_dev)
        mks = tuple(sorted(self._multi_fns)) \
            or ((self.multi_tick,) if self.multi_tick > 1 else ())
        for mk in mks:
            specs.append(hp.ExecutableSpec(
                name=f"decode-multi[k={mk}]", body=self._multi_body(mk),
                args=(st, pools, bt, state, aux, poison),
                donate=(1, 3, 4), fetched=(0, 1)))
        if self._spec is not None:
            k = self._spec.k
            for v in tuple(self._verify_fns) or variants:
                specs.append(hp.ExecutableSpec(
                    name=f"verify[{v}]", body=self._verify_body(v),
                    args=(st, pools, bt, state, s((S, k), np.int32),
                          poison),
                    donate=(1, 3), fetched=(0, 1, 2)))
            specs.extend(self._spec.hotpath_specs())
        pbs = tuple(sorted(self._prefill_fns)) or (self.prefill_bucket,)
        for pb in pbs:
            specs.append(hp.ExecutableSpec(
                name=f"prefill[{pb}]", body=self._prefill_body(),
                args=(st, pools, s((1, MB), np.int32),
                      s((1, pb), np.int32), s((1,), np.int32),
                      s((1,), np.int32), s((1,), np.float32),
                      s((1,), np.int32), s((1,), np.float32),
                      s((1, 2), np.uint32), s((1,), np.float32)),
                donate=(1,), fetched=(0, 1, 2), per_tick=False))
        cache_keys = {"_decode_fns": list(self._decode_fns),
                      "_verify_fns": list(self._verify_fns),
                      "_prefill_fns": list(self._prefill_fns),
                      "_multi_fns": list(self._multi_fns)}
        if self._spec is not None:
            cache_keys["_spec._prefill_fns"] = \
                list(self._spec._prefill_fns)
        tick = [self.step, self._admit, self._expire,
                self._run_prefills, self._safe_prefill, self._prefill,
                self._ensure_pages, self._safe_decode,
                self._decode_dispatch, self._dispatch_multi,
                self._dispatch_spec, self._multi_k,
                self._deadline_ticks, self._decode_harvest,
                self._harvest_single, self._harvest_multi,
                self._harvest_spec, self._flush_state,
                self._poison_slot, self._unpoison]
        return hp.HotpathInventory(
            subject=f"{type(self).__name__}[{self.label}]",
            executables=specs, tick_functions=tick,
            steady_functions=("_decode_dispatch", "_dispatch_multi",
                              "_dispatch_spec", "_flush_state",
                              "_poison_slot", "_unpoison"),
            cache_keys=cache_keys, file=__file__)

    def inspect_hotpath(self):
        """Device-free hot-path audit (missed donation, fetch-set
        bloat, host syncs in the tick, steady-tick uploads, recompile-
        risk cache keys): returns the findings Report and routes its
        per-rule counts through the ``lint.hotpath.*`` counters."""
        from ..analysis import hotpath_lint
        return hotpath_lint.emit_hotpath(
            hotpath_lint.lint_inventory(self._hotpath_inventory()))

    def _dispatch_steady(self, steady, fn, *args):
        """Dispatch one tick executable. On a STEADY tick (warm
        executable, no dirty rows, no fault poison) with
        ``PADDLE_TPU_LINT=1``, the call runs under
        ``jax.transfer_guard("disallow")``: any implicit host<->device
        transfer the static hotpath lint missed raises here instead of
        silently syncing. The guard wraps ONLY the dispatch — the
        attributed np.asarray fetches stay outside it."""
        if steady and _lint_armed():
            monitor.counter("lint.hotpath.guarded_ticks").increase()
            with jax.transfer_guard("disallow"):
                return fn(*args)
        return fn(*args)

    # -- public API ----------------------------------------------------------

    def add_request(self, ids, sampling_params=None) -> int:
        """Queue a prompt (1-D token ids, or a [1, s] Tensor/array) for
        generation under ``sampling_params``. Returns the request id;
        the request is admitted to a slot by a later ``step()``."""
        params = sampling_params or SamplingParams()
        if isinstance(params, dict):
            params = SamplingParams(**params)
        params.validate()
        prompt = _normalize_prompt(ids)
        # validate the whole lifetime's page demand UP FRONT, naming
        # the request and the pages it needs — an oversized request
        # must never get as far as a mid-prefill _page_slots failure
        rid = self._next_id
        need = len(prompt) + int(params.max_new_tokens)
        cap = self.max_blocks * self.page_size - (self._lookahead - 1)
        # chunked prefill pads only ONE slice at a time (and clips that
        # padding at the block table), so capacity is bounded by the
        # REAL tokens; monolithic prefill buckets the whole prompt up
        # front and must reserve the padded length
        chunk_cap = (need if self.max_prefill_tokens_per_step is not None
                     else self._pbucket(need))
        if chunk_cap > cap:
            raise ValueError(
                f"request {rid} needs {need} token slots (prompt "
                f"{len(prompt)} + {params.max_new_tokens} new = "
                f"{_ceil_div(self._pbucket(need), self.page_size)} "
                f"pages), beyond the engine's max_context capacity "
                f"{cap}")
        worst_pages = self._lifetime_pages(len(prompt),
                                           int(params.max_new_tokens))
        if worst_pages > self.pool_pages:
            raise RuntimeError(
                f"request {rid} can never be scheduled: it needs up "
                f"to {worst_pages} page(s) (prompt {len(prompt)} + "
                f"{params.max_new_tokens} new tokens at page_size "
                f"{self.page_size}) but the pool has "
                f"{self.pool_pages} — grow pool_pages or shrink the "
                f"request")
        req = Request(req_id=self._next_id, prompt=prompt, params=params,
                      arrival_t=self._clock(),
                      queued_step=self._steps)
        req.key = np.asarray(jax.random.PRNGKey(int(params.seed)),
                             np.uint32)
        self._next_id += 1
        self.requests[req.req_id] = req    # LIVE requests only (see _finish)
        self._waiting.append(req)
        tracing.open_span(req.spans, tracing.QUEUED,
                          req.arrival_t * 1e3, self.label)
        self._mon.counter("serving.requests").increase()
        return req.req_id

    def step(self) -> List[Output]:
        """One scheduler tick, PIPELINED against the device (JAX async
        dispatch): the decode work for the slots that were live at the
        END of the last step is dispatched FIRST, then the host runs
        the tick-t+1 scheduling — deadline sweeps, admission, prefill
        slices, watchdog — in the overlap window while the device
        executes, and only then syncs + harvests the token/ok vectors
        and grows pages for the next dispatch. Returns the requests
        that finished OR failed during this tick — a per-request
        failure (deadline, NaN logits, prefill error) retires that
        request and never raises out of here.

        With ``multi_tick=k > 1`` a pure-greedy steady stretch runs up
        to k device ticks per step as ONE fused scan dispatch —
        deadline / queue-timeout enforcement then lands on dispatch
        boundaries, so a request can overrun its deadline_ms by at
        most one dispatch (k ticks) before _expire retires it."""
        outputs: List[Output] = []
        wall0 = time.perf_counter()
        clk0 = self._clock()
        self._device_s = 0.0
        self._overlap_s = 0.0
        c0 = self._tracker.compiles
        if self._moe_layer is not None and c0 != self._moe_tracker_mark:
            # compiles landed OUTSIDE our steps since the last sync
            # (a sibling worker's warmup in disagg/fleet, a one-shot
            # generate): fold their kernels.moe.decode_path.* deltas
            # into the baseline WITHOUT republishing — a foreign trace
            # must never read as this engine's dispatch proof
            self._moe_seen = {
                k: int(v) for k, v in monitor.snapshot().items()
                if k.startswith("kernels.moe.decode_path.")}
            self._moe_tracker_mark = c0
        if self._injector is not None:
            self._injector.on_step(self._steps)
            self._prefix_faults()
        with tape_mod.no_grad_guard():
            # (a) dispatch the decode executable for the slots settled
            # by the LAST step — the device starts tick t now
            pending = self._safe_decode()
            # (b) overlap window: tick-t+1 host scheduling runs while
            # the device executes. Exactness is order-insensitive here
            # (rows are independent; a request admitted now joins the
            # NEXT dispatch, exactly as the sequential loop's same-step
            # admission joined the decode after its prefill), and a
            # request _expire retires mid-flight has its in-flight
            # token discarded at harvest — the same token the
            # sequential loop (expire before decode) never produced.
            outputs.extend(self._expire())
            self._pf_step_tokens = 0
            self._admit()
            outputs.extend(self._run_prefills())
            self._watchdog.maybe_start_and_tick()
            # (c) sync + harvest: block on the dispatched outputs
            # (attributed — host work above that hid under device
            # execution lands in the overlap share), append tokens,
            # retire finished rows
            outputs.extend(self._decode_harvest(pending))
            # (d) page growth for the NEXT dispatch (multi-tick
            # horizon pre-allocates k ticks of headroom when free
            # pages allow; preemption key reads are post-sync here)
            self._ensure_pages()
        if self._injector is not None and \
                self._injector.fire("alloc.refcount_skew",
                                    record=False):
            # a stray reference lands on a live page (the lost-free /
            # doubled-share failure mode) — the audit below must
            # detect and repair it before it can become a leak;
            # recorded only when a live page existed to skew
            held = [p for r in self._slots if r is not None
                    for p in r.pages]
            if held:
                self._injector.record("alloc.refcount_skew")
                self._alloc.share(
                    held[int(self._injector.rng.integers(0, len(held)))])
        self._maybe_audit()
        self._mon.counter("serving.steps").increase()
        self._publish_gauges()
        # MoE path proof (docs/OBSERVABILITY.md "serving.moe.*"): a
        # tick that traced something re-publishes the trace-time
        # kernels.moe.decode_path.* deltas into the serving namespace —
        # in steady state (zero recompiles) this branch never runs, so
        # the per-step cost is one int compare
        if self._moe_layer is not None \
                and self._tracker.compiles != c0:
            self._republish_moe_paths()
            self._moe_tracker_mark = self._tracker.compiles
        # O(1) warmup accounting, attributed to THIS engine: only
        # compiles that land inside this step() count (the jax
        # listener is process-global — another engine or a generate()
        # call between ticks must not read as our recompile), and a
        # tick that introduced a new executable folds its compiles
        # into warmup. (Not tracker.on_step(): its per-step list
        # would grow one entry per tick forever in a serving process.)
        self._compiles += self._tracker.compiles - c0
        if self._last_compile_step == self._steps:
            self._warm_compiles = self._compiles
        # host/device tick attribution (ROADMAP item 5's gate input):
        # device time is what the tick spent blocked on dispatched
        # results PLUS the host work that provably ran while the
        # device was still executing the in-flight dispatch (the
        # pipelining overlap — _sync_timed's windowed accounting; the
        # overlap share is also published on its own so the gate
        # measures real EXPOSED host cost, never double-counted).
        # Wall clock, never the injectable clock — timelines stay
        # deterministic, attribution stays honest. One step = one
        # dispatch: under multi_tick these are per-DISPATCH values
        # covering `ticks` device ticks (the sums the bench host-share
        # gate aggregates stay true trace totals).
        wall_ms = (time.perf_counter() - wall0) * 1e3
        dev_ms = min(self._device_s * 1e3, wall_ms)
        host_ms = wall_ms - dev_ms
        ov_ms = min(self._overlap_s * 1e3, dev_ms)
        self._mon.gauge("serving.host_ms_per_tick").set(host_ms)
        self._mon.gauge("serving.device_ms_per_tick").set(dev_ms)
        self._mon.gauge("serving.overlap_ms_per_tick").set(ov_ms)
        self._mon.histogram("serving.hist.host_ms_per_tick").record(
            host_ms)
        self._mon.histogram("serving.hist.device_ms_per_tick").record(
            dev_ms)
        self._mon.histogram("serving.hist.overlap_ms_per_tick").record(
            ov_ms)
        self._mon.histogram("serving.hist.tick_ms").record(wall_ms)
        if pending is not None:
            if self.multi_tick > 1:
                self._mon.gauge(
                    "serving.multi_tick.ticks_per_dispatch").set(
                        pending.ticks)
            # per-device-tick duration EWMA on the INJECTABLE clock —
            # the deadline clamp's horizon unit (_deadline_ticks)
            d_ms = (self._clock() - clk0) * 1e3 / max(1, pending.ticks)
            self._tick_est_ms = d_ms if self._tick_est_ms <= 0.0 \
                else 0.7 * self._tick_est_ms + 0.3 * d_ms
        self._steps += 1
        return outputs

    def run(self, requests: Sequence, max_steps: int = 100_000,
            heartbeat_timeout: Optional[float] = None,
            snapshot_path: Optional[str] = None) -> List[Output]:
        """Offline driver: queue every (ids, SamplingParams) pair —
        bare ids get default params — then step until all finish (or
        fail: failed requests surface as Outputs with ``error`` set).
        Returns Outputs ordered by request id. Drains only its own
        requests; drive a shared/online engine with step() instead
        (other requests' outputs surfacing mid-run would be dropped
        here).

        ``heartbeat_timeout=T`` attaches an in-process
        ``distributed.watchdog.Heartbeat``: every completed step —
        one DISPATCH, which under ``multi_tick=k`` covers up to k
        device ticks, so T must exceed the worst-case fused dispatch,
        not the worst single tick — ticks it, and a loop that makes
        no progress for T seconds triggers ``_stall_report`` — a
        per-thread stack dump plus a best-effort host-state snapshot
        (to ``snapshot_path`` when given, always kept on
        ``last_stall_snapshot``) so a wedged serving process leaves a
        recoverable trail before the pod is killed."""
        ids_list = []
        for item in requests:
            if isinstance(item, (tuple, list)) and len(item) == 2 and \
                    isinstance(item[1], (SamplingParams, dict)):
                ids_list.append(self.add_request(item[0], item[1]))
            else:
                ids_list.append(self.add_request(item))
        want = set(ids_list)
        hb = None
        if heartbeat_timeout is not None:
            from ..distributed.watchdog import Heartbeat
            hb = Heartbeat(
                float(heartbeat_timeout),
                on_stall=lambda age: self._stall_report(
                    age, snapshot_path))
            hb.start()
        outs: List[Output] = []
        try:
            for _ in range(max_steps):
                outs.extend(o for o in self.step() if o.req_id in want)
                if hb is not None:
                    hb.tick()
                if len(outs) == len(want):
                    break
            else:
                raise RuntimeError(
                    f"engine did not drain in {max_steps} steps "
                    f"({len(outs)}/{len(want)} finished)")
        finally:
            if hb is not None:
                hb.stop()
        return sorted(outs, key=lambda o: o.req_id)

    def cancel(self, req_id: int) -> Optional[Output]:
        """Abort a live or queued request NOW: its slot is freed, its
        pages return to the pool, and its Output (``finish_reason
        "cancelled"``, tokens generated so far) is returned. Unknown
        or already-retired ids return None. Safe at any lifecycle
        point — waiting, preempted, or mid-decode (the fixed-shape
        decode step simply sees one more idle lane next tick)."""
        req = self.requests.get(int(req_id))
        if req is None or req.state in (FINISHED, FAILED):
            return None
        self._mon.counter("serving.cancelled").increase()
        return self._fail(req, "cancelled")

    def extract_request(self, req_id: int,
                        device_key: bool = True) -> Optional[Request]:
        """Remove a live request from this engine ENTIRELY — slot
        cleared, pages freed, dropped from the queue and the request
        table — and return it as host source of truth (prompt, tokens
        generated so far, sampling params, rng chain), ready for
        re-admission elsewhere through the preemption/resume-prefill
        machinery. The live-migration hook the serving fleet
        (inference/fleet.py) moves in-flight requests between replicas
        with: re-admitting the returned Request on another engine over
        the same weights continues the token stream bit-exactly.

        ``device_key=True`` pulls the request's rng chain down from the
        device-resident decode state (the same fetch preemption does);
        ``device_key=False`` skips the device read — the caller must
        then set ``req.key`` itself (the fleet replays it from
        (seed, tokens emitted) via ``disagg.replay_rng_key``, the
        host-truth-only migration contract). Returns None for unknown
        or already-retired ids."""
        req = self.requests.get(int(req_id))
        if req is None or req.state in (FINISHED, FAILED):
            return None
        i = req.slot
        if device_key and i is not None and req.state == DECODE \
                and i not in self._dirty:
            # the rng chain lives device-side between decode ticks
            # (see _preempt); a dirty slot's freshest key is already
            # the host mirror
            req.key = np.asarray(self._dev[5])[i].astype(np.uint32)
        self._clear_slot(req)
        try:
            self._waiting.remove(req)
        except ValueError:
            pass
        self.requests.pop(req.req_id, None)
        # PREEMPTED is the has-progress resume state: a re-admission
        # rebuilds the cache from the kept tokens and the rng chain
        # continues exactly (WAITING when no token was emitted yet —
        # no rng was consumed, a from-scratch prefill is exact)
        req.state = PREEMPTED if req.generated else WAITING
        # the extraction IS the migration's start: the open span
        # (DECODE/PREFILL/QUEUED) closes here and MIGRATING runs until
        # the destination engine's next span — origin stays the SOURCE
        # label, so a stitched timeline shows where the request left
        tracing.open_span(req.spans, tracing.MIGRATING,
                          self._clock() * 1e3, self.label)
        return req

    def snapshot(self, sync: bool = True) -> dict:
        """Crash-exact host-state snapshot (reliability.py has the
        format): queued + live request tokens, rng chains, sampling
        params, admission order, prefix-index metadata — NOT KV pools.
        ``sync=False`` skips the device fetch of live rng rows (the
        stall-dump path, where the device may be wedged) at the cost
        of exactness for mid-flight SAMPLING requests."""
        from .reliability import snapshot_engine
        return snapshot_engine(self, sync=sync)

    def restore(self, snap: dict, strict: bool = True) -> int:
        """Re-admit a snapshot's requests into this (fresh or drained)
        engine through the preemption/resume-prefill machinery; the
        restored run's outputs are bit-identical to the uninterrupted
        one. Returns the number of requests re-admitted."""
        from .reliability import restore_engine
        return restore_engine(self, snap, strict=strict)

    def snapshot_to(self, path: str, sync: bool = True) -> str:
        from .reliability import save_snapshot
        return save_snapshot(self.snapshot(sync=sync), path)

    def restore_from(self, path: str, strict: bool = True) -> int:
        from .reliability import load_snapshot
        return self.restore(load_snapshot(path), strict=strict)

    def check_invariants(self, repair: bool = False) -> List[str]:
        """Cross-check the allocator against every reference the
        engine can account for (live requests' pages + one per
        prefix-cache entry) plus the allocator's own free-list/
        refcount consistency and the prefix index's digest integrity.
        Returns findings (empty = healthy); ``repair=True`` also fixes
        them (the chaos-recovery path). Auto-run each step under
        ``FLAGS_serving_debug_invariants`` (raise on findings) or an
        active fault injector (repair + count)."""
        expected: Dict[int, int] = {}
        for r in self.requests.values():
            held = r.pages if r.pages else (r.shared_pages or [])
            for p in held:
                expected[p] = expected.get(p, 0) + 1
        if self._prefix is not None:
            for ent in self._prefix._store.values():
                expected[ent.page] = expected.get(ent.page, 0) + 1
        findings = self._alloc.check_invariants(expected=expected,
                                                repair=repair)
        if self._prefix is not None:
            findings += self._prefix.check_integrity(repair=repair)
        return findings

    def _republish_moe_paths(self) -> None:
        """Mirror the trace-time ``kernels.moe.decode_path.*`` counters
        (bumped while a prefill/decode/verify executable over an MoE
        model traces) into ``serving.moe.decode_path.*`` — the
        engine-scoped proof that its compiled surfaces run the fused
        Pallas dispatch and never silently fell back (docs/SERVING.md
        "MoE serving"; tests and the replay tool assert on these)."""
        prefix = "kernels.moe.decode_path."
        for key, val in monitor.snapshot().items():
            if not key.startswith(prefix):
                continue
            delta = int(val) - self._moe_seen.get(key, 0)
            if delta > 0:
                suffix = key[len(prefix):]
                monitor.counter(
                    "serving.moe.decode_path." + suffix).increase(delta)
                self._moe_paths[suffix] = \
                    self._moe_paths.get(suffix, 0) + delta
            self._moe_seen[key] = int(val)

    def moe_decode_path(self) -> Dict[str, int]:
        """THIS engine's MoE dispatch-path breakdown (suffix -> count;
        the per-engine slice of ``serving.moe.decode_path.*``): which
        MoE dispatch its compiled executables baked in. Empty for
        non-MoE models; ``{"pallas": n}`` with no ``fallback.*`` keys
        is the no-silent-fallback proof the acceptance tests assert."""
        return dict(self._moe_paths)

    def steady_state_recompiles(self) -> int:
        """XLA compiles INSIDE this engine's step() calls after the
        last step that legitimately introduced a new executable (a new
        prefill bucket or a decode variant) — the number that must be
        0 under steady-state mixed traffic. Compiles by other code in
        the process (another engine, a generate() call) don't count."""
        return self._compiles - self._warm_compiles

    def close(self):
        """Detach the engine's compile tracker from the global
        jax.monitoring fan-out (listener hygiene for processes that
        build many engines; also runs at garbage collection)."""
        self._tracker.stop()

    def __del__(self):
        try:
            self._tracker.stop()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass

    @property
    def num_waiting(self) -> int:
        return len(self._waiting)

    @property
    def num_active(self) -> int:
        return sum(1 for r in self._slots
                   if r is not None and r.state == DECODE)

    @property
    def num_prefilling(self) -> int:
        """Slots holding a request mid-prefill between ticks — nonzero
        only under chunked prefill (monolithic prefills complete inside
        the step that admits them). Idle checks must include it: an
        engine with a half-written whale and no decoders is NOT idle."""
        return sum(1 for r in self._slots
                   if r is not None and r.state == PREFILL)

    @property
    def idle(self) -> bool:
        """True when a step() would do no work: nothing queued, nothing
        decoding, nothing mid-prefill. The drive-loop check for replay
        tools and offline batch drivers (fast-forwarding a virtual
        clock, or sleeping to the next arrival, is only safe here)."""
        return (not self._waiting and self.num_active == 0
                and self.num_prefilling == 0)

    @property
    def pages_free(self) -> int:
        return self._alloc.free_pages

    def leaked_pages(self) -> int:
        """Pages still allocated after idle prefix-cache references
        are released — THE drained-engine leak check the bench and
        replay chaos gates share (0 on a healthy drained engine).
        Destructive to the prefix cache's idle entries: call it only
        on a drained engine at gate time."""
        if self._prefix is not None:
            self._prefix.clear()
        return self.pool_pages - self.pages_free

    # -- reliability internals -----------------------------------------------

    def _fault(self, site: str) -> bool:
        """One fault-point query against the injector (False when no
        injector is armed — the production fast path)."""
        return self._injector is not None and self._injector.fire(site)

    def _fault_raise(self, site: str) -> None:
        if self._fault(site):
            raise InjectedFault(site)

    def _prefix_faults(self) -> None:
        """Per-step prefix-cache fault points: a forced digest
        collision (the exact-token compare must degrade it to a miss)
        and a corrupted-stale entry (must never be hit again; the
        audit/eviction reclaims it)."""
        if self._prefix is None:
            return
        if self._fault("prefix.hash_collision"):
            self._prefix.force_collision()
        if self._injector.fire("prefix.stale_entry", record=False) \
                and len(self._prefix):
            # recorded only when there was an entry to corrupt — the
            # chaos report never claims faults that did not land
            self._injector.record("prefix.stale_entry")
            self._prefix.corrupt_entry(self._injector.rng)

    def _maybe_audit(self) -> None:
        auditing = self._debug_invariants or (
            self._injector is not None
            and self._injector.enabled("alloc.refcount_skew"))
        if not auditing:
            return
        repair = self._injector is not None
        findings = self.check_invariants(repair=repair)
        if findings:
            if repair:
                monitor.counter("serving.invariant_repairs").increase(
                    len(findings))
            else:
                raise RuntimeError(
                    "engine invariant audit failed "
                    "(FLAGS_serving_debug_invariants):\n  "
                    + "\n  ".join(findings))

    def _expire(self) -> List[Output]:
        """Tick-start deadline sweep: fail every request past its
        wall deadline (waiting OR mid-decode — its pages free this
        tick) and every waiting request past its queue-step budget.

        Enforcement granularity is one DISPATCH, not one device tick:
        under ``multi_tick=k`` a fused dispatch covers up to k device
        ticks, so a deadline can be overrun by at most one dispatch
        before this sweep retires the request (the _deadline_ticks
        clamp shrinks the fused k toward the nearest deadline, and an
        expired request's in-flight tokens are discarded at harvest).
        ``max_queue_steps`` counts step() calls — dispatches — so its
        wall meaning stretches by up to k during fused stretches; it
        only ever governs WAITING/PREEMPTED requests, which block
        fusion anyway (_multi_k admission rung)."""
        outs: List[Output] = []
        now = self._clock()
        for req in list(self._waiting) + [r for r in self._slots
                                          if r is not None]:
            p = req.params
            if p.deadline_ms is not None and \
                    (now - req.arrival_t) * 1e3 > float(p.deadline_ms):
                self._mon.counter("serving.timeouts").increase()
                outs.append(self._fail(req, "deadline"))
            elif p.max_queue_steps is not None and \
                    req.state in (WAITING, PREEMPTED) and \
                    self._steps - req.queued_step \
                    > int(p.max_queue_steps):
                self._mon.counter("serving.timeouts").increase()
                outs.append(self._fail(req, "queue_timeout"))
        return outs

    def _stall_report(self, age: float,
                      snapshot_path: Optional[str] = None) -> None:
        """Heartbeat stall callback (watchdog thread): dump every
        thread's stack to stderr and best-effort snapshot the host
        state — the recoverable trail a wedged serving process leaves
        before its pod is killed. ``sync=False``: the device may be
        the thing that's wedged, so no device fetch."""
        import faulthandler
        monitor.counter("serving.stalls").increase()
        print(f"engine watchdog: run() loop stalled for {age:.1f}s at "
              f"step {self._steps} ({self.num_active} active, "
              f"{len(self._waiting)} waiting, "
              f"{self._alloc.free_pages} pages free) — dumping stacks "
              f"and snapshotting", flush=True)
        try:
            faulthandler.dump_traceback(all_threads=True)
        except Exception:  # noqa: BLE001 — diagnostics must not raise
            pass
        try:
            self.last_stall_snapshot = self.snapshot(sync=False)
            if snapshot_path:
                from .reliability import save_snapshot
                save_snapshot(self.last_stall_snapshot, snapshot_path)
        except Exception as e:  # noqa: BLE001 — best-effort dump
            print(f"engine watchdog: stall snapshot failed: {e}",
                  flush=True)

    def _safe_prefill(self, req: Request,
                      cap: Optional[int] = None) -> Optional[Output]:
        """Isolation wrapper: a failing prefill retires or requeues
        THIS request — it never takes down the step() loop (the other
        slots' state is untouched; the failed call's pages are rolled
        back)."""
        try:
            return self._prefill(req, cap)
        except PoolPressure as e:
            # resource pressure, not a failure: admission (chunked)
            # charges only the first slice, so a mid-prefill dry pool
            # is the NORMAL backpressure path — wait for pages without
            # burning the retry budget (an admitted request always
            # fits the pool alone; running sequences finishing or
            # preempting unblocks it)
            return self._requeue(req, str(e).partition("\n")[0],
                                 count_retry=False)
        except InjectedFault:
            monitor.counter("serving.step_errors").increase()
            return self._requeue(req, "injected device error")
        except RuntimeError as e:
            # other transient prefill errors: back off and retry on a
            # later tick, against the retry budget
            return self._requeue(req, str(e).partition("\n")[0]
                                 or type(e).__name__)
        except Exception as e:  # noqa: BLE001 — request isolation
            monitor.counter("serving.step_errors").increase()
            return self._fail(req, f"error:{type(e).__name__}")

    def _requeue(self, req: Request, why: str,
                 count_retry: bool = True) -> Optional[Output]:
        self._rollback_prefill(req)
        if count_retry:
            req.retries += 1
            if req.retries > MAX_PREFILL_RETRIES:
                return self._fail(req, f"error:prefill ({why})")
        req.state = PREEMPTED if req.generated else WAITING
        req.queued_step = self._steps
        self._waiting.appendleft(req)
        return None

    def _rollback_prefill(self, req: Request) -> None:
        """Undo a partially executed prefill: drop every page
        reference the request holds — merged (req.pages) or still
        admission-only (shared_pages) — and hand its slot back."""
        self._clear_slot(req)

    def _safe_decode(self) -> Optional[_PendingTick]:
        """Isolation wrapper around the batched decode/verify
        dispatch: an injected device error fires BEFORE dispatch (host
        state still coherent), so the engine just skips the tick and
        retries — requests see one step of extra latency, never
        corruption."""
        try:
            return self._decode_dispatch()
        except InjectedFault:
            monitor.counter("serving.step_errors").increase()
            return None

    # -- scheduler internals -------------------------------------------------

    def _admit(self) -> List[Request]:
        admitted = []
        reserved = 0          # pages already promised this tick: the
        while self._waiting:  # prefills run AFTER the admit loop
            slot = next((i for i, r in enumerate(self._slots)
                         if r is None), None)
            if slot is None:
                break
            req = self._waiting[0]
            toks = req.resume_tokens()
            if self._prefix is not None and req.shared_pages is None:
                # map the longest cached prefix NOW (references taken,
                # so the pages can't be evicted out from under the
                # admission decision), capped so at least one real
                # token is left for the tail prefill — the append page
                # stays private even when its contents are cached (the
                # copy-on-write fork, docs/SERVING.md)
                req.shared_pages, req.prefix_len = self._prefix.acquire(
                    toks, max_chunks=(len(toks) - 1) // self.page_size)
                self._mon.counter("serving.prefix_lookups").increase()
                if req.prefix_len:
                    self._mon.counter("serving.prefix_hits").increase()
            # shared pages are already resident — admission charges
            # only the UNCACHED tail (a would-be-shared prefix must
            # not inflate apparent pool pressure; each shared page is
            # one pool slot however many block tables map it). Under
            # chunked prefill only the FIRST slice is charged: later
            # slices allocate as they run, so a long prompt that fits
            # incrementally is admitted (the per-slice alloc path backs
            # off and requeues if the pool tightens meanwhile).
            tail = len(toks) - req.prefix_len
            if self.max_prefill_tokens_per_step is not None:
                tail = min(tail, self.max_prefill_tokens_per_step)
            need = _ceil_div(self._pbucket(tail), self.page_size)
            # the watermark reserves growth headroom for RUNNING
            # sequences; an otherwise-empty engine admits with the
            # whole pool (a big request must not starve behind
            # headroom nobody needs)
            busy = any(r is not None for r in self._slots)
            wm = self.watermark_pages if busy else 0
            if not self._alloc.can_alloc(need + reserved, wm):
                # reclaim idle prefix-cache pages (refcount==0 users,
                # LRU) before refusing admission
                short = need + reserved + wm - self._alloc.free_pages
                if self._prefix is None or \
                        self._prefix.evict(short) < short:
                    break
            reserved += need
            self._waiting.popleft()
            req.slot = slot
            req.state = PREFILL
            req.admit_seq = self._admit_counter
            self._admit_counter += 1
            self._slots[slot] = req
            admitted.append(req)
        return admitted

    def _open_span(self, req: Request, phase: str,
                   slot: Optional[int] = None, **detail) -> None:
        """Open the request's next timeline span at the engine clock,
        closing the prior one at the same instant (contiguity is
        structural). Span-derived latency histograms record at the
        phase boundary: a QUEUED/PREEMPTED span closing into PREFILL
        is the queue wait; a MIGRATING span closing anywhere is the
        migration latency (recorded by the DESTINATION engine's scope
        — where the request landed)."""
        t = self._clock() * 1e3
        closed = tracing.close_open(req.spans, t)
        if closed is not None:
            dur = closed["t1_ms"] - closed["t0_ms"]
            if closed["phase"] == tracing.MIGRATING:
                self._mon.histogram(
                    "serving.hist.migration_ms").record(dur)
            elif closed["phase"] in (tracing.QUEUED,
                                     tracing.PREEMPTED) \
                    and phase == tracing.PREFILL:
                self._mon.histogram(
                    "serving.hist.queue_wait_ms").record(dur)
        tracing.open_span(req.spans, phase, t, self.label, slot=slot,
                          **detail)

    def _sync_timed(self, outs, dispatch_t: Optional[float] = None,
                    dev_mark: float = 0.0) -> None:
        """Block until this tick's dispatched device results land,
        charging the wait to the tick's DEVICE share (host/device
        attribution, see step()). The immediate np.asarray consumers
        then read ready buffers — total tick wall time is unchanged,
        it just gets attributed.

        Pipelined syncs pass ``dispatch_t`` (perf_counter when the
        executable was dispatched) and ``dev_mark`` (the _device_s
        reading at dispatch): when the wait actually blocked, the
        device was provably busy for the WHOLE dispatch→ready window,
        so the host work that ran inside it is charged to the device
        share and surfaced as OVERLAP (never double-counted — device
        seconds other syncs already claimed inside the window are
        subtracted). A wait that returns immediately means the device
        finished at an unknown point during the host work, so only the
        measured block is charged — the conservative split that keeps
        the host-share gate honest when the HOST is the bottleneck."""
        t0 = time.perf_counter()
        jax.block_until_ready(outs)
        t1 = time.perf_counter()
        blocked = t1 - t0
        if dispatch_t is not None:
            window = t1 - dispatch_t
            inner = self._device_s - dev_mark
            extra = window - inner
            if blocked > 5e-5 and extra > blocked:
                self._device_s += extra
                self._overlap_s += extra - blocked
                return
        self._device_s += blocked

    def _run_prefills(self) -> List[Output]:
        """Run this tick's prefill work over every PREFILL-state slot.

        Monolithic mode (``max_prefill_tokens_per_step=None``): each
        pending request writes its whole tail in one bucketed chunk, in
        admission order — exactly the pre-chunking behavior.

        Chunked mode: each pending request gets at most ONE slice, in
        SHORTEST-REMAINING-FIRST order (a small request admitted beside
        a mid-prefill whale reaches its first token on the next tick
        instead of after the whale's whole prompt); each slice is
        capped at the budget REMAINING when its turn comes, so the
        step's total stays within the budget (± one bucket of
        rounding). The OLDEST pending request always gets at least a
        one-bucket slice even with the budget exhausted — a sustained
        flood of small prefills can slow the whale, never starve it.
        Then the decode tick below runs for every DECODE slot — the
        interleave that bounds whale-induced TTFT inflation to one
        slice."""
        pending = [r for r in self._slots
                   if r is not None and r.state == PREFILL]
        if not pending:
            return []
        budget = self.max_prefill_tokens_per_step
        if budget is None:
            order = sorted(pending, key=lambda r: r.admit_seq)
            oldest = None
        else:
            # remaining REAL work: a fresh request whose head is a
            # prefix-cache hit has written == 0 until its first slice,
            # but its cached prefix_len never runs through a prefill —
            # rank it by the uncached tail it will actually execute
            order = sorted(
                pending,
                key=lambda r: (r.resume_len()
                               - max(r.written, r.prefix_len),
                               r.admit_seq))
            oldest = min(pending, key=lambda r: r.admit_seq)
        outs: List[Output] = []
        for req in order:
            cap = None
            if budget is not None:
                left = budget - self._pf_step_tokens
                if left <= 0 and req is not oldest:
                    continue
                cap = max(self.prefill_bucket, left)
            out = self._safe_prefill(req, cap)
            if out is not None:
                outs.append(out)
        return outs

    def _prefill(self, req: Request,
                 cap: Optional[int] = None) -> Optional[Output]:
        """Write the next chunk of the request's prefix into the pool
        (the whole tail in monolithic mode, one bounded slice — at
        most ``cap`` tokens, the scheduler's remaining step budget —
        under ``max_prefill_tokens_per_step``); fresh requests sample
        their
        first token on the FINAL chunk (TTFT). Resumed (preempted)
        requests only rebuild their cache — the sampled token and key
        are discarded, so the request's RNG chain continues exactly
        where it stopped. A partially prefilled request keeps its slot
        and pages across slices (state PREFILL, ``req.written`` marks
        progress) and stays cancellable / deadline-expirable /
        preemptible / snapshot-able at every slice boundary.

        With the prefix cache on, the shared pages acquired at
        admission land directly in the block table and ONLY the
        uncached tail runs through the model — a hit deeper than one
        bucket skips all of its cached chunks, and a long uncached
        tail is still sliced. All writes stay in private pages: the
        cached prefix is page-aligned and every page from the tail
        onward is freshly allocated.

        Token-exactness vs monolithic prefill: every slice runs the
        SAME bucketed executables at a traced start offset, and the
        in-chunk attention reads K/V back from the paged pools (the
        multi-token paged path gathers the cache it just wrote), so a
        sliced prefix produces bit-identical cache contents and first
        tokens — under any cache_dtype."""
        toks = req.resume_tokens()
        fresh = not req.generated
        P = len(toks)
        if not req.pages:
            # first chunk: the shared prefix pages acquired at
            # admission land in the block table now; every page the
            # request writes from here on is private
            req.pages = list(req.shared_pages or [])
            req.written = req.prefix_len   # page-aligned by construction
        start = req.written
        T = P - start
        if self.max_prefill_tokens_per_step is not None:
            limit = self.max_prefill_tokens_per_step
            if cap is not None:
                # the scheduler's remaining step budget, floored at one
                # bucket so a scheduled request always makes progress
                limit = min(limit, max(self.prefill_bucket, int(cap)))
            T = min(T, limit)
        final = start + T >= P
        # bucket the chunk, but never past the block table: a deep
        # cached prefix (or a near-max_context prompt) leaves less than
        # one full bucket of room, and the padding positions would
        # overflow the [1, max_blocks] row (add_request guarantees the
        # REAL tokens always fit, so clipping only ever drops padding).
        pb = min(self._pbucket(T),
                 self.max_blocks * self.page_size - start)
        # allocate pages for REAL tokens only: block-table rows beyond
        # them stay 0, so the chunk's bucket-padding writes land in the
        # shared scratch page (the masked-lane convention) instead of
        # transiently holding pool pages that would be trimmed right
        # back — the request's peak page demand never exceeds its real
        # token count, which is what _lifetime_pages charges
        need = _ceil_div(start + T, self.page_size) - len(req.pages)
        if self._fault("alloc.exhausted"):
            # simulated admission race / fragmented pool: surfaces as
            # pool pressure, which _safe_prefill turns into a clean
            # budget-free requeue-and-retry
            raise PoolPressure(
                f"injected pool exhaustion: sequence {req.req_id} "
                f"requested {need} page(s)")
        if need > 0:
            try:
                priv = self._alloc.alloc(need, seq=req.req_id)
            except RuntimeError:
                # admission charged only the first slice (or a test may
                # drive _prefill directly): reclaim idle cached pages,
                # then surface ANY remaining shortfall as backpressure
                # (a partial evict must not turn into a retry-budget-
                # burning RuntimeError)
                if self._prefix is not None:
                    self._prefix.evict(need)
                try:
                    priv = self._alloc.alloc(need, seq=req.req_id)
                except RuntimeError as e2:
                    raise PoolPressure(str(e2)) from e2
            req.pages = req.pages + priv
        bt_row = np.zeros((1, self.max_blocks), np.int32)
        bt_row[0, :len(req.pages)] = req.pages
        prompt = np.zeros((1, pb), np.int32)
        prompt[0, :T] = toks[start:start + T]
        p = req.params
        # one timeline span per slice: the QUEUED (or PREEMPTED /
        # MIGRATING) wait closes here, consecutive slices chain
        self._open_span(req, tracing.PREFILL, slot=req.slot,
                        start=int(start), tokens=int(T))
        fn = self._get_prefill_fn(pb)
        bt_dev = jnp.asarray(bt_row)
        prompt_dev = jnp.asarray(prompt)
        start_dev = jnp.asarray([start], jnp.int32)
        self._fault_raise("prefill.device_error")
        poison = jnp.asarray(
            [float("nan") if self._fault("prefill.nan") else 0.0],
            jnp.float32)
        # windowed device attribution, same as the decode dispatches:
        # the chunk's dispatch→ready span is device-busy even on a
        # client whose dispatch call runs the computation inline —
        # without the window the whole prefill forward would read as
        # HOST time in the host-share gate
        mark = self._device_s
        t0 = time.perf_counter()
        tok, key2, okf, self._pools = fn(
            self._st, self._pools, bt_dev, prompt_dev,
            jnp.asarray([T], jnp.int32), start_dev,
            jnp.asarray([p.temperature], jnp.float32),
            jnp.asarray([p.top_k], jnp.int32),
            jnp.asarray([p.top_p], jnp.float32),
            jnp.asarray(req.key[None]), poison)
        if self._spec is not None:
            # mirror the chunk into the draft pools (same pages, same
            # positions) so drafting attends the full context
            self._spec.prefill(pb, bt_dev, prompt_dev, start_dev)
        # key2 rides in the sync set: the fresh-request path below
        # reads it (np.asarray) and an unsynced fetch would be an
        # un-attributed host sync (hotpath.host-sync-in-tick)
        self._sync_timed((tok, key2, okf), dispatch_t=t0, dev_mark=mark)
        self._mon.counter("serving.prefill_tokens").increase(pb)
        self._mon.counter("serving.prefill_slices").increase()
        self._pf_step_tokens += pb
        if start == req.prefix_len:
            monitor.counter(
                "serving.prefix_tokens_reused").increase(start)
        if not bool(np.asarray(okf)[0]):
            # NaN/inf on the chunk's sampling logits: quarantine the
            # request (pages freed, nothing enters the prefix cache)
            # — the other slots never see it
            self._mon.counter("serving.nan_quarantines").increase()
            return self._fail(req, "nan_logits")
        req.written = start + T
        if not final:
            return None       # stays PREFILL; a later tick continues
        if self._prefix is not None:
            # register this prefix's full pages (newly computed chunks
            # only; chunks matched at admission are already cached)
            self._prefix.insert(toks, req.pages, P)
        if fresh:
            t = int(np.asarray(tok)[0])
            req.key = np.asarray(key2)[0].astype(np.uint32)
            req.generated.append(t)
            req.first_token_t = self._clock()
            self._mon.counter("serving.tokens").increase()
            reason = self._finish_reason(req, t)
            if reason:
                return self._finish(req, reason)
        self._activate(req)
        return None

    def _activate(self, req: Request):
        i = req.slot
        self._bt[i] = 0
        self._bt[i, :len(req.pages)] = req.pages
        self._pos[i] = req.written
        self._last[i] = req.generated[-1]
        self._temps[i] = req.params.temperature
        self._topks[i] = req.params.top_k
        self._topps[i] = req.params.top_p
        self._keys[i] = req.key
        self._live[i] = 1
        self._dirty.add(i)
        self._bt_dirty = True
        # the device-resident multi-tick aux (eos/budget) doesn't know
        # this row yet — next fused dispatch re-uploads
        self._aux_clean = False
        req.state = DECODE
        # one tick-aggregated DECODE span from activation to
        # finish/preempt/migrate (not per tick — the timeline stays
        # O(lifecycle transitions), not O(tokens))
        self._open_span(req, tracing.DECODE, slot=i)

    def _ensure_pages(self):
        """Before the decode step, every active slot must own every
        page this tick's writes land in — one position for the plain
        decode step, k+1 for a speculative draft/verify tick; allocate
        lazily, preempting the YOUNGEST sequence when the pool runs
        dry (after reclaiming idle prefix-cache pages). With multi-tick
        enabled the horizon stretches toward ``multi_tick`` positions
        — but only from FREE pages (no eviction, no preemption): a
        short coverage just clamps the fused k, it never costs another
        request its cache."""
        for i in range(self.max_slots):
            req = self._slots[i]
            if req is None or req.state != DECODE:
                continue
            need = _ceil_div(req.written + self._lookahead,
                             self.page_size)
            while len(req.pages) < need:
                page = self._alloc_or_preempt(req)
                if page is None:      # req itself got preempted
                    break
                req.pages.extend(page)
                self._bt[i, :len(req.pages)] = req.pages
                self._bt_dirty = True
        if self.multi_tick > 1 and self._spec is None:
            for i in range(self.max_slots):
                req = self._slots[i]
                if req is None or req.state != DECODE:
                    continue
                rem = int(req.params.max_new_tokens) \
                    - len(req.generated)
                want = _ceil_div(
                    req.written + min(max(rem, 1), self.multi_tick),
                    self.page_size)
                while len(req.pages) < want \
                        and self._alloc.can_alloc(1,
                                                  self.watermark_pages):
                    req.pages.extend(
                        self._alloc.alloc(1, seq=req.req_id))
                    self._bt[i, :len(req.pages)] = req.pages
                    self._bt_dirty = True

    def _alloc_or_preempt(self, req: Request):
        while True:
            try:
                if self._fault("alloc.exhausted"):
                    # simulated mid-decode pool pressure: flows
                    # through the SAME evict-or-preempt ladder a real
                    # dry pool takes (the retry loop re-queries, so
                    # one injection costs at most one eviction)
                    raise RuntimeError(
                        f"injected pool exhaustion: sequence "
                        f"{req.req_id} requested 1 page")
                return self._alloc.alloc(1, seq=req.req_id)
            except RuntimeError:
                # idle cached pages go first: evicting a cold prefix
                # is free, preempting a live sequence costs a resume
                # prefill. Mid-prefill (chunked) requests are victims
                # too — they sit at a slice boundary, and their resume
                # is the same re-prefill every preemption pays — so a
                # whale's half-written prompt can never wedge the pool
                # against running decodes.
                if self._prefix is not None and self._prefix.evict(1):
                    continue
                victims = [r for r in self._slots
                           if r is not None
                           and r.state in (DECODE, PREFILL)]
                if not victims:
                    raise
                victim = max(victims, key=lambda r: r.admit_seq)
                self._preempt(victim)
                if victim is req:
                    return None

    def _preempt(self, req: Request):
        """Evict back to the waiting queue (front): pages freed, tokens
        and RNG chain kept — a resume prefill rebuilds the cache."""
        self._mon.counter("serving.preemptions").increase()
        req.preemptions += 1
        self._open_span(req, tracing.PREEMPTED, kind="pages")
        i = req.slot
        if i is not None and i not in self._dirty \
                and req.state == DECODE:
            # the RNG chain lives device-side between decode steps;
            # pull this slot's key down so the resumed request
            # continues it exactly. (A dirty slot was just activated —
            # req.key is already the freshest value. Fetch the whole
            # array, slice host-side: a device-side row gather would
            # compile a tiny executable per slot index.)
            req.key = np.asarray(self._dev[5])[i].astype(np.uint32)
            self._keys[i] = req.key
        self._clear_slot(req)
        # a mid-PREFILL victim with no generated tokens re-enters as
        # WAITING (PREEMPTED is the has-progress resume state; its rng
        # chain was never consumed, so a from-scratch prefill is exact)
        req.state = PREEMPTED if req.generated else WAITING
        req.queued_step = self._steps       # fresh queue-age budget
        self._waiting.appendleft(req)

    def _flush_state(self) -> None:
        """Host→device sync of the slot rows the scheduler touched
        since the last decode step (admissions, preemptions,
        finishes) plus the block table when a sequence crossed a page
        boundary. A steady-state decode tick — no scheduling events,
        no page growth — uploads NOTHING."""
        if self._dirty:
            mask = np.zeros((self.max_slots,), bool)
            mask[list(self._dirty)] = True
            host = (self._up(self._last), self._up(self._pos),
                    self._up(self._temps),
                    self._up(self._topks),
                    self._up(self._topps), self._up(self._keys),
                    self._up(self._live))
            self._dev = _merge_rows(self._dev, host, self._up(mask))
            self._dirty.clear()
        if self._bt_dirty:
            self._bt_dev = self._up(self._bt)
            self._bt_dirty = False

    def _decode_dispatch(self) -> Optional[_PendingTick]:
        """Dispatch this step's decode work and return WITHOUT
        waiting: the executable runs while step()'s overlap window
        does the tick-t+1 host scheduling; _decode_harvest syncs and
        retires. The sampler variant is chosen from the host mirrors
        of the slots settled by the LAST step — exactly the rows the
        dispatched executable reads."""
        active = [i for i in range(self.max_slots)
                  if self._slots[i] is not None
                  and self._slots[i].state == DECODE]
        if not active:
            return None
        sampling = [i for i in active if self._temps[i] > 0.0]
        if not sampling:
            variant = "greedy"
        elif any(self._topks[i] > 0 or 0.0 < self._topps[i] < 1.0
                 for i in sampling):
            variant = "filtered"
        else:
            variant = "plain"
        # injected device loss fires BEFORE dispatch: host state is
        # still coherent, _safe_decode skips the tick and retries
        self._fault_raise("decode.device_error")
        self._poison_slot(active)
        snap = [(i, self._slots[i]) for i in active]
        if self._spec is not None:
            if self.multi_tick > 1:
                # spec decode owns the draft/verify horizon: fused
                # multi-tick never composes with it, every dispatch
                # in a multi_tick>1 config is an exclusion, not a
                # silent downgrade
                self._mon.counter(
                    "serving.multi_tick.clamp.spec").increase()
            return self._dispatch_spec(snap, variant)
        mk = self._multi_k(active, variant)
        if mk > 1:
            return self._dispatch_multi(snap, mk)
        # steady = the dirty-row-merge discipline says this tick
        # uploads nothing and dispatches a warm executable — the
        # PADDLE_TPU_LINT transfer guard may wrap the dispatch
        steady = (variant in self._decode_fns and not self._dirty
                  and not self._bt_dirty and not self._poisoned)
        fn = self._get_decode_fn(variant)
        self._flush_state()
        mark = self._device_s
        t0 = time.perf_counter()
        # the fused step: forward + per-slot sampling + state advance
        # in ONE executable; only the emitted tokens (and the tiny
        # NaN-quarantine flags) come back
        nxt, okv, self._dev, self._pools = self._dispatch_steady(
            steady, fn, self._st, self._pools, self._bt_dev, self._dev,
            self._poison_dev)
        self._unpoison()
        return _PendingTick(kind="single", data=(nxt, okv),
                            active=snap, ticks=1, t_dispatch=t0,
                            dev_mark=mark)

    def _multi_k(self, active: List[int], variant: str) -> int:
        """Eligibility ladder + per-dispatch clamp for the fused
        multi-tick decode (docs/SERVING.md "Dispatch pipelining &
        multi-tick decode"). Eligible only when EVERY live slot is in
        a pure-greedy stretch with nothing pending host-side: greedy
        variant (no sampler rng), no waiting admissions, no
        mid-prefill slot, no speculative decoder, no armed poison
        tick (quarantine timing must match single-tick). The fused
        length is then clamped so no slot can overrun its allocated
        page coverage at all, or its max_new_tokens / deadline_ms by
        more than one dispatch, and rounded DOWN to a compiled k
        bucket (the in-scan budget freeze makes running FEWER ticks
        than a row needs always exact)."""
        K = self.multi_tick
        if (K <= 1 or self._spec is not None or variant != "greedy"
                or self._waiting or self._poisoned
                or self.num_prefilling):
            return 1
        horizon = 0      # longest remaining budget over live rows
        cov = None       # tightest allocated-page coverage
        for i in active:
            req = self._slots[i]
            horizon = max(horizon, int(req.params.max_new_tokens)
                          - len(req.generated))
            c = len(req.pages) * self.page_size - req.written
            cov = c if cov is None else min(cov, c)
        k = K
        if horizon < k:
            # no point scanning past the longest remaining budget —
            # every row would be frozen (shorter rows freeze in-graph;
            # this clamp only drops dead trailing ticks)
            k = horizon
            self._mon.counter(
                "serving.multi_tick.clamp.max_new").increase()
        if cov is not None and cov < k:
            # page-boundary horizon: the scan writes up to k positions
            # with no host allocator in the loop, so k is HARD-capped
            # by the tightest slot's allocated coverage (_ensure_pages
            # pre-extends toward multi_tick when free pages allow)
            k = cov
            self._mon.counter(
                "serving.multi_tick.clamp.pages").increase()
        dl = self._deadline_ticks(active)
        if dl < k:
            k = dl
            self._mon.counter(
                "serving.multi_tick.clamp.deadline").increase()
        if k < 2:
            return 1
        return self._multi_bucket(k)

    def _multi_bucket(self, k: int) -> int:
        """Largest compiled k bucket <= k: powers of two, plus
        ``multi_tick`` itself (so the configured maximum is one warm
        executable, not two) — a bounded executable set whatever the
        clamp trace does, keeping steady_state_recompiles()==0."""
        best = 2
        b = 2
        while b * 2 <= k:
            b *= 2
            best = b
        if self.multi_tick <= k:
            best = max(best, self.multi_tick)
        return best

    def _deadline_ticks(self, active: List[int]) -> int:
        """Ticks until the nearest active deadline, in units of the
        per-device-tick EWMA on the injectable clock — the deadline
        leg of the multi-tick clamp. Unbounded (multi_tick) when no
        slot has a deadline or no tick estimate exists yet; a slot
        that still overshoots (estimate drift) is bounded by the
        at-most-one-dispatch guarantee and expired by _expire on the
        next step."""
        est = self._tick_est_ms
        if est <= 0.0:
            return self.multi_tick
        ticks = self.multi_tick
        now = self._clock()
        for i in active:
            req = self._slots[i]
            dl = req.params.deadline_ms
            if dl is None:
                continue
            left = float(dl) - (now - req.arrival_t) * 1e3
            ticks = min(ticks, int(left // est))
        return max(1, ticks)

    def _dispatch_multi(self, snap, k: int) -> _PendingTick:
        """Dispatch ONE fused k-tick greedy scan. The aux vectors
        (per-slot eos id + remaining-token budget) are device-resident
        and advanced in-graph; they re-upload only after a host-side
        slot change or tokens emitted outside the fused path
        (_aux_clean), so back-to-back fused dispatches ship nothing
        host-to-device."""
        aux_clean0 = self._aux_clean
        if not aux_clean0:
            eos = np.full((self.max_slots,), -1, np.int32)
            bud = np.zeros((self.max_slots,), np.int32)
            for i, req in snap:
                p = req.params
                if p.eos_token_id is not None:
                    eos[i] = int(p.eos_token_id)
                bud[i] = int(p.max_new_tokens) - len(req.generated)
            self._aux_dev = (self._up(eos), self._up(bud))
            self._aux_clean = True
        steady = (k in self._multi_fns and aux_clean0
                  and not self._dirty and not self._bt_dirty)
        fn = self._get_multi_fn(k)
        self._flush_state()
        mark = self._device_s
        t0 = time.perf_counter()
        toks, oks, self._dev, self._aux_dev, self._pools = \
            self._dispatch_steady(
                steady, fn, self._st, self._pools, self._bt_dev,
                self._dev, self._aux_dev, self._poison_dev)
        self._mon.counter("serving.multi_tick.dispatches").increase()
        self._mon.counter("serving.multi_tick.ticks").increase(k)
        return _PendingTick(kind="multi", data=(toks, oks),
                            active=snap, ticks=k, t_dispatch=t0,
                            dev_mark=mark, k=k)

    def _decode_harvest(self, pend: Optional[_PendingTick]
                        ) -> List[Output]:
        """Sync the in-flight dispatch (attributed: host work that ran
        hidden under the device is booked as overlap, not
        double-counted) and retire its tokens. Rows whose request left
        DECODE during the overlap window (deadline expiry, cancel) are
        skipped — their in-flight tokens are discarded, exactly what
        the sequential expire-before-decode order produced."""
        if pend is None:
            return []
        self._sync_timed(pend.data, dispatch_t=pend.t_dispatch,
                         dev_mark=pend.dev_mark)
        if pend.kind == "multi":
            return self._harvest_multi(pend)
        if pend.kind == "spec":
            return self._harvest_spec(pend)
        return self._harvest_single(pend)

    def _harvest_single(self, pend: _PendingTick) -> List[Output]:
        nxt = np.asarray(pend.data[0])
        okv = np.asarray(pend.data[1])
        # tokens appended here move budgets the device-resident
        # multi-tick aux never saw — next fused dispatch re-uploads
        self._aux_clean = False
        outs: List[Output] = []
        for i, req in pend.active:
            if self._slots[i] is not req or req.state != DECODE:
                continue          # retired in the overlap window
            if not bool(okv[i]):
                # NaN/inf logits on THIS slot only: quarantine it
                # (token discarded, pages freed, slot back to the
                # pool) while every other lane keeps decoding
                self._mon.counter("serving.nan_quarantines").increase()
                outs.append(self._fail(req, "nan_logits"))
                continue
            tok = int(nxt[i])
            req.written += 1          # the step wrote last_token
            # mirror the device-side advance (NOT marked dirty: the
            # device already holds these values; the mirrors keep the
            # scheduler's view coherent for later dirty merges)
            self._pos[i] = req.written
            req.generated.append(tok)
            self._last[i] = tok
            if req.first_token_t == 0.0:
                req.first_token_t = self._clock()
            self._mon.counter("serving.tokens").increase()
            reason = self._finish_reason(req, tok)
            if reason:
                outs.append(self._finish(req, reason))
        return outs

    def _harvest_multi(self, pend: _PendingTick) -> List[Output]:
        """Walk the fused dispatch's [S, k] token/ok matrices exactly
        as k single-tick harvests would: append until the row's eos or
        length exit (the same condition that froze it in-graph — the
        walk never reads past the freeze point), fail the slot at the
        first not-ok step keeping its earlier tokens, and discard the
        post-finish garbage columns."""
        toks = np.asarray(pend.data[0])
        oks = np.asarray(pend.data[1])
        outs: List[Output] = []
        exited = False
        for i, req in pend.active:
            if self._slots[i] is not req or req.state != DECODE:
                continue          # retired in the overlap window
            done = False
            for j in range(pend.k):
                if not bool(oks[i, j]):
                    # NaN/inf logits at scan step j: quarantine the
                    # slot; tokens 0..j-1 were clean and are kept
                    self._mon.counter(
                        "serving.nan_quarantines").increase()
                    self._mon.counter(
                        "serving.multi_tick.scan_exit.nan_logits"
                    ).increase()
                    outs.append(self._fail(req, "nan_logits"))
                    done = True
                    break
                tok = int(toks[i, j])
                req.written += 1
                self._pos[i] = req.written
                req.generated.append(tok)
                self._last[i] = tok
                if req.first_token_t == 0.0:
                    req.first_token_t = self._clock()
                self._mon.counter("serving.tokens").increase()
                reason = self._finish_reason(req, tok)
                if reason:
                    self._mon.counter(
                        "serving.multi_tick.scan_exit." + reason
                    ).increase()
                    outs.append(self._finish(req, reason))
                    done = True
                    break
            if done:
                exited = True
            else:
                # stamp the open DECODE stint with its fused progress
                tracing.bump_open(req.spans, tracing.DECODE,
                                  multi_ticks=pend.k,
                                  multi_dispatches=1)
        if not exited:
            self._mon.counter(
                "serving.multi_tick.scan_exit.horizon").increase()
        return outs

    def _poison_slot(self, active: List[int]) -> None:
        """decode.nan fault point: pick one active slot (seeded rng)
        and ride a NaN into its sampling logits this tick — the
        in-graph detector must flip exactly that slot's ok flag."""
        if active and self._fault("decode.nan"):
            victim = active[int(
                self._injector.rng.integers(0, len(active)))]
            pz = np.zeros((self.max_slots,), np.float32)
            pz[victim] = np.nan
            self._poison_dev = self._up(pz)
            self._poisoned = True

    def _unpoison(self) -> None:
        if self._poisoned:
            self._poison_dev = self._poison_zeros
            self._poisoned = False

    def _dispatch_spec(self, snap, variant: str) -> _PendingTick:
        """Dispatch one draft/verify tick: the draft loop proposes k
        tokens per slot (one executable), the target scores all k+1
        positions in ONE batched forward — the accept walk happens at
        harvest. Each slot will emit its accepted chain + one free
        target token, every one bit-identical to what the plain decode
        loop would have emitted (verify_token_arrays' exact-match
        rule). Fault/poison points already fired in _decode_dispatch."""
        # steady tick: warm verify + draft-loop executables, nothing
        # dirty — the lint transfer guard may wrap the verify dispatch
        steady = (variant in self._verify_fns
                  and self._spec._loop_fn is not None
                  and not self._dirty and not self._bt_dirty
                  and not self._poisoned)
        self._flush_state()
        k = self._spec.k
        mark = self._device_s
        t0 = time.perf_counter()
        drafts = self._spec.draft(self._bt_dev, self._dev[0],
                                  self._dev[1], self._dev[6])
        if self._fault("spec.disagree"):
            # draft/target divergence storm: the drafted tokens are
            # replaced with garbage — exact-match verification must
            # reject them with the emitted stream unchanged (each
            # tick still yields >= 1 target-chain token)
            drafts = self._spec.sabotage(drafts)
        fn = self._get_verify_fn(variant)
        toks, acc, okv, self._dev, self._pools = self._dispatch_steady(
            steady, fn, self._st, self._pools, self._bt_dev, self._dev,
            drafts, self._poison_dev)
        self._unpoison()
        return _PendingTick(kind="spec", data=(toks, acc, okv),
                            active=snap, ticks=1, t_dispatch=t0,
                            dev_mark=mark, k=k)

    def _harvest_spec(self, pend: _PendingTick) -> List[Output]:
        toks = np.asarray(pend.data[0])
        acc = np.asarray(pend.data[1])
        okv = np.asarray(pend.data[2])
        k = pend.k
        # accepted chains move budgets the device-resident multi-tick
        # aux never saw — the next fused dispatch re-uploads
        self._aux_clean = False
        outs: List[Output] = []
        for i, req in pend.active:
            if self._slots[i] is not req or req.state != DECODE:
                continue          # retired in the overlap window
            if not bool(okv[i]):
                # NaN/inf across this slot's verify logits (spec-
                # verify divergence): quarantine the slot, keep the
                # rest of the batch serving
                self._mon.counter("serving.nan_quarantines").increase()
                outs.append(self._fail(req, "nan_logits"))
                continue
            n_acc = int(acc[i])
            self._spec_drafted += k
            self._spec_accepted += n_acc
            monitor.counter("serving.spec_drafted").increase(k)
            monitor.counter("serving.spec_accepted").increase(n_acc)
            finished = False
            for j in range(n_acc + 1):
                tok = int(toks[i, j])
                req.written += 1      # position pos+j held this input
                req.generated.append(tok)
                if req.first_token_t == 0.0:
                    req.first_token_t = self._clock()
                self._mon.counter("serving.tokens").increase()
                reason = self._finish_reason(req, tok)
                if reason:
                    # mid-chain eos/budget: the tail of the chain is
                    # discarded exactly like the plain loop would
                    # never have generated it; _finish dirties the
                    # slot so the device state is overwritten
                    outs.append(self._finish(req, reason))
                    finished = True
                    break
            if not finished:
                # mirror the device-side advance (device already holds
                # these values — not dirty)
                self._pos[i] = req.written
                self._last[i] = req.generated[-1]
        return outs

    def _finish_reason(self, req: Request, tok: int) -> Optional[str]:
        p = req.params
        if p.eos_token_id is not None and tok == int(p.eos_token_id):
            return "eos"
        if len(req.generated) >= int(p.max_new_tokens):
            return "length"
        return None

    def _clear_slot(self, req: Request):
        i = req.slot
        if i is not None:
            self._bt[i] = 0
            self._pos[i] = 0
            self._last[i] = 0
            self._temps[i] = 0.0
            self._topks[i] = 0
            self._topps[i] = 0.0
            self._live[i] = 0
            self._slots[i] = None
            self._dirty.add(i)
            self._bt_dirty = True
            self._aux_clean = False
            req.slot = None
        if req.pages:
            # one reference drop per page: private pages return to the
            # free list, shared prefix pages live on under the cache's
            # (or another request's) reference
            self._alloc.free(req.pages)
            req.pages = []
        elif req.shared_pages:
            # prefix refs taken at admission but never merged into
            # pages (a prefill that failed before assignment): drop
            # them here or they leak
            self._alloc.free(req.shared_pages)
        # a re-admission re-walks the prefix cache (the resume prefix
        # is longer, and entries may have been evicted meanwhile) and
        # restarts any partial (chunked) prefill from scratch
        req.shared_pages = None
        req.prefix_len = 0
        req.written = 0

    def _finish(self, req: Request, reason: str) -> Output:
        self._mon.counter("serving.finished").increase()
        return self._retire(req, reason, FINISHED)

    def _fail(self, req: Request, reason: str) -> Output:
        """Terminal FAILED(reason): the request is retired NOW — slot
        cleared, pages freed, removed from the queue — and surfaced as
        an Output with ``error`` set. The step() loop keeps serving
        every other request."""
        self._mon.counter("serving.failed").increase()
        return self._retire(req, reason, FAILED)

    def _retire(self, req: Request, reason: str, state: str) -> Output:
        req.finish_t = self._clock()
        req.state = state
        req.finish_reason = reason
        try:
            self._waiting.remove(req)     # failed while queued
        except ValueError:
            pass
        self._clear_slot(req)         # pages freed NOW, not end-of-call
        # `requests` tracks LIVE requests only — retaining finished
        # ones (full token lists) would grow without bound in a
        # long-running serving process; the Output carries everything
        self.requests.pop(req.req_id, None)
        n = len(req.generated)
        got_first = req.first_token_t > 0.0
        ttft_ms = ((req.first_token_t - req.arrival_t) * 1e3
                   if got_first else 0.0)
        tpot_ms = ((req.finish_t - req.first_token_t)
                   / (n - 1) * 1e3) if got_first and n > 1 else 0.0
        if got_first:
            self._mon.gauge("serving.ttft_ms").set(ttft_ms)
            self._mon.histogram("serving.hist.ttft_ms").record(ttft_ms)
        if got_first and n > 1:
            self._mon.gauge("serving.tpot_ms").set(tpot_ms)
            self._mon.histogram("serving.hist.tpot_ms").record(tpot_ms)
        # terminal span: timeline sealed at finish_t, the Output
        # carries its own copy (the Request object may be reused by
        # restore paths)
        tracing.seal(req.spans,
                     tracing.FINISHED if state == FINISHED
                     else tracing.FAILED,
                     req.finish_t * 1e3, self.label,
                     reason=None if state == FINISHED else reason)
        return Output(req_id=req.req_id, prompt_ids=list(req.prompt),
                      token_ids=list(req.generated),
                      finish_reason=reason, ttft_ms=ttft_ms,
                      tpot_ms=tpot_ms, preemptions=req.preemptions,
                      error=None if state == FINISHED else reason,
                      spans=tracing.copy_spans(req.spans))

    def _publish_gauges(self):
        mon = self._mon
        mon.gauge("serving.slots_active").set(self.num_active)
        mon.gauge("serving.pages_free").set(self._alloc.free_pages)
        mon.gauge("serving.queue_depth").set(len(self._waiting))
        mon.gauge("serving.prefill_tokens_per_step").set(
            self._pf_step_tokens)
        if self._prefix is not None:
            mon.gauge("serving.prefix_hit_rate").set(
                self._prefix.hit_rate)
            mon.gauge("serving.prefix_pages_shared").set(
                self._alloc.shared_pages)
        if self._spec is not None and self._spec_drafted:
            mon.gauge("serving.spec_accept_rate").set(
                self._spec_accepted / self._spec_drafted)

    @property
    def prefix_hit_rate(self) -> float:
        """Fraction of admission-time prefix lookups that mapped at
        least one cached page (0.0 with the cache off)."""
        if self._prefix is None:
            return 0.0
        return self._prefix.hit_rate

    @property
    def spec_accept_rate(self) -> float:
        """Fraction of drafted tokens the target accepted (0.0 before
        any draft ran or with speculation off)."""
        if not self._spec_drafted:
            return 0.0
        return self._spec_accepted / self._spec_drafted
