"""paddle_tpu.inference.encoder — continuous-batching embedding service.

A genuinely different serving traffic shape from the decode Engine
(docs/SERVING.md "Embedding service"): encoder/embedding requests are
ONE forward each — no KV cache, no pages, no per-token latency chain —
so the whole problem is throughput-bound batch packing. This module
reuses the Engine's serving discipline (admission queue, per-request
deadlines on an injectable clock, tenant fairness, monitor counters,
``steady_state_recompiles() == 0``) over a bucketed continuous-batching
encoder:

* Requests queue per tenant; every ``step()`` forms ONE batch of up to
  ``max_batch`` requests, drawn round-robin across tenants (a flooding
  tenant slows, never starves, another) with the OLDEST waiting request
  always included — its length picks the sequence bucket, and only
  requests that fit that bucket join (shorter ones pad up; longer ones
  wait for their own turn at the head).
* Exactly ONE compiled executable per sequence bucket: the batch dim is
  pinned at ``max_batch`` (dead rows ride an all-zero attention mask
  and are discarded host-side), sequences pad to a ``bucket`` multiple,
  and the mean/CLS pooling choice rides as a TRACED per-row selector —
  any arrival mix of lengths, tenants and pooling modes bounces between
  the per-bucket executables with zero steady-state recompiles.
* The model is an ENCODER with reference semantics — BertModel's
  ``forward(input_ids, attention_mask=...) -> (sequence, pooled)``
  contract — so padding-masked attention rides the flash-SDPA boolean
  key-mask path (kernels.flash.sdpa.* counters name the path the
  executable baked in, docs/KERNELS.md "Encoder flash attention").
  Padding rows/positions cannot perturb real ones (key-masked
  attention + position-wise everything else), which makes a batched
  embedding equal to the same request encoded alone — the b=1
  exactness contract tests/test_serving_embed.py holds.

Pooling variants:

* ``"mean"`` — attention-mask-weighted mean of the final hidden states
  (the sentence-embedding default; padding positions contribute 0).
* ``"cls"``  — the model's pooled output (tanh pooler over [CLS], the
  reference BertPooler head).

``monitor`` surface (docs/OBSERVABILITY.md): counters
``serving.embed.requests`` / ``serving.embed.finished`` /
``serving.embed.batches`` / ``serving.embed.tokens`` /
``serving.embed.pad_tokens`` / ``serving.embed.timeouts`` /
``serving.embed.cancelled`` / ``serving.embed.steps``, gauges
``serving.embed.queue_depth`` / ``serving.embed.batch_fill`` /
``serving.embed.latency_ms``.
"""
from __future__ import annotations

import time
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import monitor
from ..core import tape as tape_mod
from ..jit.functional import (functional_call, get_buffers, get_frozen,
                              get_params)
from ..profiler.stats import CompileTracker
from .engine import _ceil_div, _normalize_prompt, serving_model_spec

POOLING_MODES = ("mean", "cls")


@dataclass
class EmbedParams:
    """Per-request embedding config (the encoder analog of
    SamplingParams — every field may differ per request inside one
    compiled batch)."""

    pooling: str = "mean"
    # reliability knobs, enforced at every tick start on the service's
    # injectable clock (same contract as the decode Engine's)
    deadline_ms: Optional[float] = None
    max_queue_steps: Optional[int] = None

    def validate(self):
        if self.pooling not in POOLING_MODES:
            raise ValueError(
                f"pooling must be one of {POOLING_MODES}, got "
                f"{self.pooling!r}")
        if self.deadline_ms is not None and float(self.deadline_ms) <= 0:
            raise ValueError(
                f"deadline_ms must be > 0, got {self.deadline_ms}")
        if self.max_queue_steps is not None \
                and int(self.max_queue_steps) < 1:
            raise ValueError(
                f"max_queue_steps must be >= 1, got "
                f"{self.max_queue_steps}")


@dataclass
class EmbedOutput:
    """One retired embedding request. ``embedding`` is the [hidden]
    float32 vector (None on failure); ``finish_reason`` is "done" or
    the failure name ("deadline" / "queue_timeout" / "cancelled")."""

    req_id: int
    embedding: Optional[np.ndarray]
    tokens: int                   # real (unpadded) sequence length
    pooling: str
    finish_reason: str
    latency_ms: float             # arrival -> embedding fetched
    error: Optional[str] = None

    @property
    def ok(self) -> bool:
        return self.error is None


@dataclass
class _EmbedRequest:
    req_id: int
    tokens: List[int]
    params: EmbedParams
    tenant: str
    arrival_t: float
    queued_step: int


class BatchEncoder:
    """Bucketed continuous-batching embedding service over an encoder.

        svc = BatchEncoder(bert_model, max_batch=8, bucket=32)
        rid = svc.add_request(ids, EmbedParams(pooling="mean"))
        for out in svc.step():
            ...                       # finished EmbedOutputs
        # or offline:
        outs = svc.run([ids_a, (ids_b, EmbedParams(pooling="cls"))])

    The model must follow the reference encoder contract —
    ``forward(input_ids, attention_mask=...)`` returning ``(sequence
    [b, s, h], pooled [b, h])`` (the in-tree BertModel does). Weights
    are snapshotted at construction, like the decode Engine.
    """

    def __init__(self, model, max_batch: int = 8, bucket: int = 32,
                 max_seq: Optional[int] = None, clock=None):
        spec = serving_model_spec(model)
        if spec.get("kind") == "decoder":
            raise ValueError(
                f"{type(model).__name__} is a DECODER — serve it "
                f"through the continuous-batching Engine "
                f"(inference.Engine, docs/SERVING.md); BatchEncoder "
                f"embeds with encoder models (BertModel)")
        import inspect
        try:
            fsig = inspect.signature(model.forward)
        except (TypeError, ValueError):
            fsig = None
        if fsig is None or "attention_mask" not in fsig.parameters:
            raise ValueError(
                f"BatchEncoder requires an encoder with an "
                f"attention_mask forward kwarg (padding-masked "
                f"batching); {type(model).__name__}.forward has none")
        if int(max_batch) < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if int(bucket) < 1:
            raise ValueError(f"bucket must be >= 1, got {bucket}")
        self.model = model
        self.serving_spec = spec
        self.max_batch = int(max_batch)
        self.bucket = int(bucket)
        self.max_seq = int(max_seq or spec["max_context"])
        self._st = (get_params(model), get_buffers(model),
                    get_frozen(model))
        self._clock = clock if clock is not None else time.perf_counter
        self._device_s = 0.0
        # tenant fairness state: per-tenant FIFO queues walked
        # round-robin when a batch is formed (the Engine/DisaggEngine
        # fairness shape). OrderedDict keeps a stable walk order.
        self._queues: "OrderedDict[str, deque]" = OrderedDict()
        self._rr = 0
        self.requests: Dict[int, _EmbedRequest] = {}
        self._next_id = 0
        self._steps = 0
        self._fns: Dict[int, object] = {}
        self._tracker = CompileTracker().start()
        self._compiles = 0
        self._warm_compiles = 0
        self._last_compile_step = 0

    # -- compiled surface ----------------------------------------------------

    def _bucketed(self, n: int) -> int:
        return min(_ceil_div(n, self.bucket) * self.bucket,
                   self.max_seq)

    def _get_encode_fn(self, L: int):
        """ONE executable per sequence bucket L: the padded batch
        forward plus BOTH pooling reductions, the per-row traced
        selector picking which lands in the output row — so mean and
        CLS requests share every executable."""
        fn = self._fns.get(L)
        if fn is not None:
            return fn
        fn = jax.jit(self._encode_body())
        self._fns[L] = fn
        self._last_compile_step = self._steps
        return fn

    def _encode_body(self):
        model = self.model

        def body(st, ids, amask, sel):
            p, buf, frz = st
            out, _ = functional_call(
                model, p, buf, (ids,), {"attention_mask": amask},
                frozen=frz, training=False)
            x, pooled = out
            m = (amask > 0).astype(jnp.float32)            # [B, L]
            denom = jnp.maximum(jnp.sum(m, axis=1, keepdims=True), 1.0)
            mean = jnp.sum(jnp.asarray(x, jnp.float32)
                           * m[:, :, None], axis=1) / denom
            emb = jnp.where(sel[:, None] > 0,
                            jnp.asarray(pooled, jnp.float32), mean)
            return emb

        return body

    def _sync_timed(self, outs) -> None:
        """Block until the dispatched encode lands, charging the wait
        to the tick's DEVICE share (same attribution contract as
        Engine._sync_timed — and the one sanctioned sync point the
        hot-path lint recognizes)."""
        t0 = time.perf_counter()
        jax.block_until_ready(outs)
        self._device_s += time.perf_counter() - t0

    # -- hot-path lint (docs/ANALYSIS.md "Hot-path rules") -------------------

    def _hotpath_inventory(self):
        """One encode executable per warm sequence bucket (or the base
        bucket, cold); the full embedding batch is the service's
        DELIVERABLE, so its fetch is whitelisted. No resident device
        state — every batch legitimately uploads its ids/mask — so the
        steady-upload set is empty."""
        from ..analysis import hotpath_lint as hp
        import numpy as np
        B = self.max_batch
        st = hp.struct_of(self._st)

        def i32(*shape):
            return jax.ShapeDtypeStruct(shape, np.int32)

        specs = [hp.ExecutableSpec(
            name=f"encode[{L}]", body=self._encode_body(),
            args=(st, i32(B, L), i32(B, L), i32(B)),
            donate=(), fetched=(0,), deliverable=(0,))
            for L in (tuple(sorted(self._fns)) or (self.bucket,))]
        return hp.HotpathInventory(
            subject="BatchEncoder", executables=specs,
            tick_functions=[self.step, self._form_batch, self._expire,
                            self._encode],
            steady_functions=(),
            cache_keys={"_fns": list(self._fns)}, file=__file__)

    def inspect_hotpath(self):
        """Device-free hot-path audit of the embedding service; routes
        per-rule counts through ``lint.hotpath.*``."""
        from ..analysis import hotpath_lint
        return hotpath_lint.emit_hotpath(
            hotpath_lint.lint_inventory(self._hotpath_inventory()))

    # -- public API ----------------------------------------------------------

    def add_request(self, ids, params=None,
                    tenant: str = "default") -> int:
        """Queue one sequence (1-D token ids, or [1, s]) for embedding
        under ``params``. Returns the request id; a later ``step()``
        batches and encodes it."""
        p = params or EmbedParams()
        if isinstance(p, dict):
            p = EmbedParams(**p)
        p.validate()
        tokens = _normalize_prompt(ids)
        rid = self._next_id
        if len(tokens) > self.max_seq:
            raise ValueError(
                f"request {rid} has {len(tokens)} tokens, beyond the "
                f"service's max_seq {self.max_seq}")
        self._next_id += 1
        req = _EmbedRequest(req_id=rid, tokens=tokens, params=p,
                            tenant=str(tenant),
                            arrival_t=self._clock(),
                            queued_step=self._steps)
        self.requests[rid] = req
        self._queues.setdefault(str(tenant), deque()).append(req)
        monitor.counter("serving.embed.requests").increase()
        return rid

    def cancel(self, req_id: int) -> Optional[EmbedOutput]:
        """Drop a queued request NOW; returns its failure Output (None
        for unknown/already-retired ids)."""
        req = self.requests.get(int(req_id))
        if req is None:
            return None
        monitor.counter("serving.embed.cancelled").increase()
        return self._fail(req, "cancelled")

    def step(self) -> List[EmbedOutput]:
        """One service tick: expire deadlines, form one fairness-walked
        bucket batch, encode it, retire its requests."""
        outs: List[EmbedOutput] = []
        wall0 = time.perf_counter()
        self._device_s = 0.0
        c0 = self._tracker.compiles
        with tape_mod.no_grad_guard():
            outs.extend(self._expire())
            batch = self._form_batch()
            if batch:
                outs.extend(self._encode(batch))
        monitor.counter("serving.embed.steps").increase()
        monitor.gauge("serving.embed.queue_depth").set(
            self.num_waiting)
        # host/device attribution: same split Engine.step publishes —
        # device time is the block_until_ready wait on the encode
        # output, host time is everything else in the tick
        wall_ms = (time.perf_counter() - wall0) * 1e3
        dev_ms = min(self._device_s * 1e3, wall_ms)
        monitor.gauge("serving.embed.host_ms_per_tick").set(
            wall_ms - dev_ms)
        monitor.gauge("serving.embed.device_ms_per_tick").set(dev_ms)
        self._compiles += self._tracker.compiles - c0
        if self._last_compile_step == self._steps:
            self._warm_compiles = self._compiles
        self._steps += 1
        return outs

    def run(self, requests: Sequence,
            max_steps: int = 100_000) -> List[EmbedOutput]:
        """Offline driver: queue every item — ``ids`` or ``(ids,
        EmbedParams)`` — then step until all retire. Returns outputs
        ordered by request id."""
        want = set()
        for item in requests:
            if isinstance(item, (tuple, list)) and len(item) == 2 and \
                    isinstance(item[1], (EmbedParams, dict)):
                want.add(self.add_request(item[0], item[1]))
            else:
                want.add(self.add_request(item))
        outs: List[EmbedOutput] = []
        for _ in range(max_steps):
            outs.extend(o for o in self.step() if o.req_id in want)
            if len(outs) == len(want):
                break
        else:
            raise RuntimeError(
                f"encoder did not drain in {max_steps} steps "
                f"({len(outs)}/{len(want)} finished)")
        return sorted(outs, key=lambda o: o.req_id)

    def steady_state_recompiles(self) -> int:
        """Compiles inside this service's step() calls after the last
        step that introduced a new bucket executable — 0 under any
        steady-state length/tenant/pooling mix."""
        return self._compiles - self._warm_compiles

    def close(self):
        self._tracker.stop()

    def __del__(self):
        try:
            self._tracker.stop()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass

    @property
    def num_waiting(self) -> int:
        return sum(len(q) for q in self._queues.values())

    @property
    def idle(self) -> bool:
        return self.num_waiting == 0

    # -- scheduler internals -------------------------------------------------

    def _expire(self) -> List[EmbedOutput]:
        outs: List[EmbedOutput] = []
        now = self._clock()
        for req in [r for q in self._queues.values() for r in q]:
            p = req.params
            if p.deadline_ms is not None and \
                    (now - req.arrival_t) * 1e3 > float(p.deadline_ms):
                monitor.counter("serving.embed.timeouts").increase()
                outs.append(self._fail(req, "deadline"))
            elif p.max_queue_steps is not None and \
                    self._steps - req.queued_step \
                    > int(p.max_queue_steps):
                monitor.counter("serving.embed.timeouts").increase()
                outs.append(self._fail(req, "queue_timeout"))
        return outs

    def _form_batch(self) -> List[_EmbedRequest]:
        """Pick up to max_batch requests round-robin across tenants.
        The OLDEST waiting request is always taken first — its length
        sets the bucket — and the walk then admits any request fitting
        that bucket, so short requests pad up beside a long head but a
        longer one never blocks it."""
        tenants = [t for t, q in self._queues.items() if q]
        if not tenants:
            return []
        oldest = min((self._queues[t][0] for t in tenants),
                     key=lambda r: r.req_id)
        L = self._bucketed(len(oldest.tokens))
        batch = [oldest]
        self._queues[oldest.tenant].remove(oldest)
        # fairness walk: one request per tenant per lap, starting past
        # the round-robin cursor
        names = list(self._queues.keys())
        start = self._rr % max(len(names), 1)
        progressed = True
        while len(batch) < self.max_batch and progressed:
            progressed = False
            for i in range(len(names)):
                t = names[(start + i) % len(names)]
                q = self._queues[t]
                # take the first request in this tenant's queue that
                # fits the bucket (FIFO within tenant)
                take = next((r for r in q if len(r.tokens) <= L), None)
                if take is not None:
                    q.remove(take)
                    batch.append(take)
                    progressed = True
                    if len(batch) >= self.max_batch:
                        break
        self._rr += 1
        return batch

    def _encode(self, batch: List[_EmbedRequest]) -> List[EmbedOutput]:
        L = self._bucketed(max(len(r.tokens) for r in batch))
        B = self.max_batch
        ids = np.zeros((B, L), np.int32)
        amask = np.zeros((B, L), np.int32)
        sel = np.zeros((B,), np.int32)
        for i, r in enumerate(batch):
            n = len(r.tokens)
            ids[i, :n] = r.tokens
            amask[i, :n] = 1
            sel[i] = 1 if r.params.pooling == "cls" else 0
        fn = self._get_encode_fn(L)
        out = fn(self._st, jnp.asarray(ids), jnp.asarray(amask),
                 jnp.asarray(sel))
        self._sync_timed(out)
        emb = np.asarray(out)
        now = self._clock()
        real = sum(len(r.tokens) for r in batch)
        monitor.counter("serving.embed.batches").increase()
        monitor.counter("serving.embed.tokens").increase(real)
        monitor.counter("serving.embed.pad_tokens").increase(
            B * L - real)
        monitor.gauge("serving.embed.batch_fill").set(
            len(batch) / float(B))
        outs = []
        for i, r in enumerate(batch):
            self.requests.pop(r.req_id, None)
            lat = (now - r.arrival_t) * 1e3
            monitor.gauge("serving.embed.latency_ms").set(lat)
            monitor.histogram("serving.hist.embed_latency_ms").record(lat)
            monitor.counter("serving.embed.finished").increase()
            outs.append(EmbedOutput(
                req_id=r.req_id, embedding=emb[i].copy(),
                tokens=len(r.tokens), pooling=r.params.pooling,
                finish_reason="done", latency_ms=lat))
        return outs

    def _fail(self, req: _EmbedRequest, reason: str) -> EmbedOutput:
        try:
            self._queues[req.tenant].remove(req)
        except (KeyError, ValueError):
            pass
        self.requests.pop(req.req_id, None)
        return EmbedOutput(
            req_id=req.req_id, embedding=None,
            tokens=len(req.tokens), pooling=req.params.pooling,
            finish_reason=reason,
            latency_ms=(self._clock() - req.arrival_t) * 1e3,
            error=reason)
