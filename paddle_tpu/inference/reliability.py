"""Serving-engine reliability layer: fault injection + crash recovery.

The engine (inference/engine.py) multiplexes dynamic traffic onto a
small fixed set of compiled executables — which makes its HOST-side
bookkeeping (request lifecycles, the page allocator, the prefix-cache
index, per-slot rng chains) the single source of truth. Production
serving has to survive that bookkeeping being attacked from every
side: malformed requests, pool exhaustion, NaN-emitting slots, device
errors, and whole-process restarts. This module provides the two
mechanisms the engine's hardening is built and PROVEN on:

* **Deterministic fault injection** — a seeded :class:`FaultInjector`
  with named fault points wired through the engine's allocator,
  prefix cache, prefill/decode/verify executables and the draft loop.
  Faults are drawn from one ``numpy`` Generator in scheduler order (or
  forced by a :class:`FaultPlan` schedule), so a chaos run replays
  bit-identically from its seed: the soak tests and
  ``tools/serving_replay.py --chaos`` assert zero leaked pages, zero
  refcount skew and token-exact outputs for every SURVIVING request
  after hundreds of injected faults.

      ============================  =========================================
      fault point                   what fires
      ============================  =========================================
      ``alloc.exhausted``           the next page allocation raises the
                                    pool-exhausted RuntimeError even though
                                    pages are free (admission races, fragmented
                                    pools) — prefills requeue, decode growth
                                    preempts
      ``alloc.refcount_skew``       a stray extra reference lands on a live
                                    page (a lost ``free`` / doubled ``share``)
                                    — the per-step invariant audit must detect
                                    and repair it
      ``prefix.hash_collision``     the next root-chunk digest collides with a
                                    constant — the exact-token compare must
                                    degrade the hit to a miss
      ``prefix.stale_entry``        one cached entry's token metadata is
                                    corrupted — it must never be hit again and
                                    must be reclaimed
      ``prefill.nan``               the prefill chunk's sampling logits turn
                                    NaN — the request is quarantined, pages
                                    freed
      ``decode.nan``                one live slot's decode logits turn NaN —
                                    that slot alone fails; the rest keep
                                    serving
      ``prefill.device_error`` /    the executable call raises (simulated
      ``decode.device_error``       device loss) BEFORE dispatch, so host
                                    state stays coherent — prefills requeue,
                                    decode skips the tick and retries
      ``spec.disagree``             the drafted tokens are replaced with
                                    garbage (a draft/target divergence storm)
                                    — exact-match verification must reject
                                    them with output unchanged
      ============================  =========================================

* **Crash-exact snapshot/restore** — :func:`snapshot_engine` serializes
  the host-side source of truth (queued + live request tokens, rng key
  chains, sampling params, admission order, prefix-cache index
  metadata — NOT the KV pools, which are device state a crash loses
  anyway) as one JSON-able dict; :func:`restore_engine` re-admits every
  request on a fresh engine through the EXISTING preemption/resume-
  prefill machinery (tokens + rng kept, cache rebuilt by a resume
  prefill), so the restarted engine's outputs are bit-identical to an
  uninterrupted run — greedy and seeded sampling, with prefix hits and
  speculative decoding on. ``Engine.snapshot()/restore()`` are the
  public surface; ``distributed.watchdog.Heartbeat`` triggers a
  best-effort snapshot-and-report when a ``run()`` loop stalls.

Driven by flags/env (chaos in any engine-embedding process without
code changes — ``FLAGS_serving_fault_seed=7`` arms every Engine built
without an explicit ``fault_injector``; pass ``fault_injector=False``
to force one engine clean), or explicitly by the replay tool, which
always builds its clean passes with injection forced OFF::

    python tools/serving_replay.py trace.jsonl --chaos \
        --fault-seed 7 --fault-rate 0.05

Counters (docs/OBSERVABILITY.md): ``serving.fault_injected.<site>``,
``serving.invariant_repairs``, ``serving.snapshot_saves``,
``serving.snapshot_restores``, ``serving.stalls`` — next to the
lifecycle counters the engine's hardening emits
(``serving.timeouts`` / ``serving.cancelled`` / ``serving.failed`` /
``serving.nan_quarantines`` / ``serving.step_errors``).
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import monitor
from . import tracing
from ..core.flags import define_flag, get_flag

define_flag("serving_fault_seed", -1,
            "Seed for the serving engine's deterministic FaultInjector; "
            "-1 disables injection (production default)")
define_flag("serving_fault_rate", 0.02,
            "Per-query probability each armed fault point fires "
            "(FLAGS_serving_fault_seed >= 0 arms the injector)")
define_flag("serving_fault_sites", "",
            "Comma-separated fault-point filter for the injector; "
            "empty = every site armed")
define_flag("serving_debug_invariants", False,
            "Audit engine/allocator invariants after every step() and "
            "raise on the first finding (CI / debugging; the chaos "
            "paths audit WITH repair instead)")

#: every named fault point the engine queries, in the order a step
#: visits them (documentation + the injector's site validation)
FAULT_SITES = (
    "alloc.exhausted",
    "alloc.refcount_skew",
    "prefix.hash_collision",
    "prefix.stale_entry",
    "prefill.nan",
    "prefill.device_error",
    "decode.nan",
    "decode.device_error",
    "spec.disagree",
    # disaggregated serving (inference/disagg.py): a whole worker dies
    # — pools, allocator, device state lost — and its requests must
    # re-admit elsewhere token-exact. Never fires on the last worker
    # of a kind (recorded only when a kill actually landed).
    "worker.die_prefill",
    "worker.die_decode",
    # elastic fleet (inference/fleet.py): one whole engine REPLICA dies
    # — pools, allocator, prefix cache, device state lost — and its
    # requests must re-admit on surviving replicas token-exact from
    # host truth alone. Never fires on the last live replica (recorded
    # only when a kill actually landed).
    "replica.die",
)

SNAPSHOT_VERSION = 1


class InjectedFault(RuntimeError):
    """An injected failure (never raised in production). ``site`` names
    the fault point; ``transient`` marks faults the engine should
    absorb by retrying (requeue / next tick) rather than failing the
    request."""

    def __init__(self, site: str, transient: bool = True):
        super().__init__(f"injected fault at {site}")
        self.site = site
        self.transient = transient


@dataclass
class FaultPlan:
    """Explicit fault schedule: fire ``site`` the first time it is
    queried AT or AFTER engine step ``step`` (each entry fires once).
    Entries compose with (and take precedence over) the injector's
    rate-based draws, so a test can pin one fault to one step while a
    soak sprays the rest. Parseable from a flag-friendly string::

        FaultPlan.parse("12:decode.nan,30:alloc.exhausted")
    """

    entries: List[Tuple[int, str]] = field(default_factory=list)

    def __post_init__(self):
        for step, site in self.entries:
            if site not in FAULT_SITES:
                raise ValueError(
                    f"unknown fault site {site!r} in plan — known "
                    f"sites: {', '.join(FAULT_SITES)}")
        self._pending = sorted(
            ((int(s), site) for s, site in self.entries),
            key=lambda e: e[0])

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        entries = []
        for item in spec.split(","):
            item = item.strip()
            if not item:
                continue
            step, _, site = item.partition(":")
            entries.append((int(step), site.strip()))
        return cls(entries)

    def pop(self, site: str, step: int) -> bool:
        for i, (s, target) in enumerate(self._pending):
            if target == site and step >= s:
                del self._pending[i]
                return True
            if s > step:
                break
        return False

    @property
    def pending(self) -> List[Tuple[int, str]]:
        return list(self._pending)


class FaultInjector:
    """Seeded, replayable chaos source for the serving engine.

    The engine queries ``fire(site)`` at each named fault point; the
    injector answers from ONE ``numpy`` rng consumed in query order,
    so the same (seed, rate, sites, plan, trace) always produces the
    same fault schedule — a failing chaos run is reproduced by its
    seed alone. ``counts`` records what actually fired (also emitted
    as ``serving.fault_injected.<site>`` counters).
    """

    def __init__(self, seed: int = 0, rate: float = 0.0,
                 sites: Optional[Sequence[str]] = None,
                 plan: Optional[FaultPlan] = None):
        unknown = set(sites or ()) - set(FAULT_SITES)
        if unknown:
            raise ValueError(
                f"unknown fault site(s) {sorted(unknown)} — known "
                f"sites: {', '.join(FAULT_SITES)}")
        self.seed = int(seed)
        self.rate = float(rate)
        self.sites = frozenset(sites) if sites else frozenset(FAULT_SITES)
        self.plan = plan
        self.rng = np.random.default_rng(self.seed)
        self.counts: Dict[str, int] = {}
        self.step = 0

    def enabled(self, site: str) -> bool:
        return site in self.sites

    def on_step(self, step: int) -> None:
        """Engine hook: the current scheduler tick (plan entries key
        on it; purely informational for rate draws)."""
        self.step = int(step)

    def fire(self, site: str, record: bool = True) -> bool:
        """One fault-point query. Plan entries fire unconditionally;
        otherwise an armed site fires with probability ``rate``. The
        rng is consumed for every armed rate query — fired or not —
        so the draw sequence (and thus the whole chaos schedule) is a
        pure function of the seed and the query order."""
        if site not in FAULT_SITES:
            raise ValueError(f"unknown fault site {site!r}")
        hit = False
        if self.plan is not None and self.plan.pop(site, self.step):
            hit = True
        elif site in self.sites and self.rate > 0.0:
            hit = bool(self.rng.random() < self.rate)
        if hit and record:
            self.record(site)
        return hit

    def record(self, site: str) -> None:
        """Count an APPLIED fault. Sites whose application can be a
        no-op (no live pages to skew, an empty cache to corrupt) draw
        with ``fire(site, record=False)`` and call this only once the
        fault actually landed — the chaos report must never claim
        faults that did not happen."""
        self.counts[site] = self.counts.get(site, 0) + 1
        monitor.counter(f"serving.fault_injected.{site}").increase()

    @property
    def total_injected(self) -> int:
        return sum(self.counts.values())

    def __repr__(self):
        return (f"FaultInjector(seed={self.seed}, rate={self.rate}, "
                f"injected={self.total_injected})")


def injector_from_flags() -> Optional[FaultInjector]:
    """Build an injector from ``FLAGS_serving_fault_*`` (env-settable:
    ``FLAGS_serving_fault_seed=7``); None when injection is off (the
    default, seed -1)."""
    seed = int(get_flag("serving_fault_seed"))
    if seed < 0:
        return None
    sites_spec = str(get_flag("serving_fault_sites")).strip()
    sites = tuple(s.strip() for s in sites_spec.split(",")
                  if s.strip()) or None
    return FaultInjector(seed=seed,
                         rate=float(get_flag("serving_fault_rate")),
                         sites=sites)


# --------------------------------------------------------------------------
# crash-exact snapshot / restore
# --------------------------------------------------------------------------

def _fingerprint(eng) -> Dict[str, object]:
    """The compatibility signature a snapshot is only valid against.
    ``hard`` fields change the TOKENS a request would emit (model
    geometry, cache dtype, sampler surface) — restore refuses a
    mismatch; ``soft`` fields only change scheduling (pool geometry)
    — restore warns, because the preemption-exact engine emits the
    same tokens under any page/slot budget."""
    cfg = eng.model.config
    return {
        "hard": {
            "vocab_size": int(cfg.vocab_size),
            "num_hidden_layers": int(cfg.num_hidden_layers),
            "hidden_size": int(cfg.hidden_size),
            "num_attention_heads": int(cfg.num_attention_heads),
            "num_key_value_heads": int(cfg.num_key_value_heads),
            "cache_dtype": str(np.dtype(eng.cache_dtype).name),
            "spec_k": int(eng._spec.k) if eng._spec is not None else 0,
        },
        "soft": {
            "max_slots": eng.max_slots,
            "page_size": eng.page_size,
            "pool_pages": eng.pool_pages,
            "max_context": eng.max_context,
            "prefill_bucket": eng.prefill_bucket,
            "prefix_cache": eng._prefix is not None,
            "max_prefill_tokens_per_step":
                eng.max_prefill_tokens_per_step,
            # fused-dispatch width is pure scheduling: k single-tick
            # greedy steps and one fused k-tick scan emit identical
            # tokens, so restoring across multi_tick widths is safe
            "multi_tick": getattr(eng, "multi_tick", 1),
        },
    }


def snapshot_engine(eng, sync: bool = True) -> Dict[str, object]:
    """Serialize the engine's host-side source of truth as one
    JSON-able dict: every live + queued request (prompt, generated
    tokens, sampling params, CURRENT rng key — pulled from the
    device-resident chain for active slots — admission order, latency
    ages) plus the prefix-cache index metadata. KV pools are NOT
    serialized: they are device state a crash loses anyway, and the
    resume-prefill machinery rebuilds them token-exactly on restore.

    Called between ``step()`` calls (every request is WAITING,
    PREEMPTED, DECODE, or — under chunked prefill — mid-PREFILL at a
    slice boundary, where it serializes as a queued request: no rng
    was consumed yet, so a from-scratch resume prefill is exact), this
    is non-destructive: the engine keeps serving afterwards.

    ``sync=False`` (the stall-dump path) never touches the device —
    a wedged executable would block the fetch — and falls back to the
    host-mirror rng keys, which lag the device chain for mid-flight
    SAMPLING requests: best-effort diagnostics, not bit-exact.
    """
    from dataclasses import asdict

    from .engine import DECODE, PREEMPTED, WAITING
    now = eng._clock()
    keys_dev = None
    entries: List[Dict[str, object]] = []
    # queue order on restore = live requests first (they were running;
    # the resume machinery puts preempted work at the queue FRONT), in
    # admission order, then the waiting queue as-is
    live = sorted((r for r in eng._slots if r is not None),
                  key=lambda r: r.admit_seq)
    for req in list(live) + list(eng._waiting):
        if (sync and req.state == DECODE and req.slot is not None
                and req.slot not in eng._dirty):
            # the rng chain lives device-side between decode ticks;
            # one bulk fetch covers every live slot
            if keys_dev is None:
                keys_dev = np.asarray(eng._dev[5])
            key = keys_dev[req.slot]
        else:
            key = req.key
        entries.append({
            "req_id": int(req.req_id),
            "prompt": [int(t) for t in req.prompt],
            "generated": [int(t) for t in req.generated],
            "params": asdict(req.params),
            "key": [int(k) for k in np.asarray(key, np.uint32)],
            "live": req.state == DECODE,
            "preemptions": int(req.preemptions),
            "retries": int(req.retries),
            "elapsed_ms": (now - req.arrival_t) * 1e3,
            # a RUNNING (decoding OR mid-chunked-prefill) request has
            # no queue age — it re-enters the restored queue with a
            # fresh max_queue_steps budget (it was making progress;
            # only genuinely WAITING/PREEMPTED requests keep their
            # accumulated wait — counting a whale's in-slot prefill
            # ticks here would let restore spuriously queue_timeout a
            # request the uninterrupted run completes)
            "waited_steps": (eng._steps - req.queued_step
                             if req.state in (WAITING, PREEMPTED)
                             and req.queued_step >= 0 else 0),
            # span timeline: plain host state, rides the snapshot so a
            # restored request's stitched timeline stays contiguous
            "spans": tracing.copy_spans(req.spans),
        })
    prefix_index: List[Dict[str, object]] = []
    if eng._prefix is not None:
        for ent in eng._prefix._store.values():
            prefix_index.append({
                "key": ent.key.hex(),
                "parent": (ent.parent.hex()
                           if ent.parent is not None else None),
                "depth": int(ent.depth),
                "chunk": [int(t) for t in ent.chunk],
            })
    snap = {
        "version": SNAPSHOT_VERSION,
        "fingerprint": _fingerprint(eng),
        "next_id": int(eng._next_id),
        "admit_counter": int(eng._admit_counter),
        "steps": int(eng._steps),
        "requests": entries,
        # index METADATA only — the cached pages' KV content lives in
        # device pools a restart loses; restore starts with an empty
        # cache that re-fills from resume prefills (hit/miss never
        # changes tokens, so exactness is unaffected)
        "prefix_index": prefix_index,
    }
    monitor.counter("serving.snapshot_saves").increase()
    return snap


def restore_engine(eng, snap: Dict[str, object],
                   strict: bool = True) -> int:
    """Re-admit every snapshotted request into ``eng`` (normally a
    freshly constructed engine over the same weights after a restart).
    Requests with generated tokens enter as PREEMPTED — the existing
    resume-prefill path rebuilds their KV from the kept tokens and the
    saved rng key continues the chain exactly — and untouched requests
    enter as WAITING, in the snapshot's queue order, so the restarted
    engine's emissions are bit-identical to the uninterrupted run.
    Returns the number of requests re-admitted.

    ``strict=True`` raises on any fingerprint mismatch; strict or not,
    a HARD mismatch (model geometry / cache dtype / spec_k — anything
    that changes tokens) always raises.
    """
    import warnings

    from .engine import PREEMPTED, WAITING, Request, SamplingParams
    if snap.get("version") != SNAPSHOT_VERSION:
        raise ValueError(
            f"snapshot version {snap.get('version')!r} does not match "
            f"this engine's {SNAPSHOT_VERSION}")
    if eng.requests or any(r is not None for r in eng._slots):
        raise RuntimeError(
            "restore onto a busy engine: "
            f"{len(eng.requests)} live/queued request(s) present — "
            "restore targets a fresh (or fully drained) engine")
    fp = _fingerprint(eng)
    saved = snap.get("fingerprint", {})
    hard_diff = {k: (saved.get("hard", {}).get(k), v)
                 for k, v in fp["hard"].items()
                 if saved.get("hard", {}).get(k) != v}
    if hard_diff:
        raise ValueError(
            f"snapshot is token-incompatible with this engine: "
            f"{hard_diff} (saved vs current) — same model geometry, "
            f"cache dtype and spec_k are required for bit-exact "
            f"restore")
    soft_diff = {k: (saved.get("soft", {}).get(k), v)
                 for k, v in fp["soft"].items()
                 if saved.get("soft", {}).get(k) != v}
    if soft_diff:
        if strict:
            raise ValueError(
                f"snapshot scheduler geometry differs: {soft_diff} "
                f"(saved vs current) — pass strict=False to restore "
                f"anyway (tokens stay exact; only scheduling "
                f"latencies change)")
        warnings.warn(
            f"restoring across scheduler geometries: {soft_diff} "
            f"(saved vs current); outputs stay token-exact",
            RuntimeWarning, stacklevel=2)
    now = eng._clock()
    n = 0
    for ent in snap["requests"]:
        params = SamplingParams(**ent["params"])
        req = Request(
            req_id=int(ent["req_id"]),
            prompt=[int(t) for t in ent["prompt"]],
            params=params,
            state=PREEMPTED if ent["generated"] else WAITING,
            generated=[int(t) for t in ent["generated"]],
            preemptions=int(ent.get("preemptions", 0)),
            retries=int(ent.get("retries", 0)),
            arrival_t=now - float(ent.get("elapsed_ms", 0.0)) / 1e3,
            queued_step=eng._steps - int(ent.get("waited_steps", 0)),
        )
        req.key = np.asarray(ent["key"], np.uint32)
        req.spans = tracing.restore_spans(
            ent.get("spans"), req.arrival_t * 1e3, now * 1e3,
            eng.label, bool(req.generated))
        eng.requests[req.req_id] = req
        eng._waiting.append(req)
        n += 1
    eng._next_id = max(eng._next_id, int(snap.get("next_id", 0)))
    eng._admit_counter = max(eng._admit_counter,
                             int(snap.get("admit_counter", 0)))
    monitor.counter("serving.snapshot_restores").increase()
    return n


def save_snapshot(snap: Dict[str, object], path: str) -> str:
    """Atomic write (temp file + rename): the stall/crash paths call
    this precisely when the process may be killed mid-write — a
    truncated snapshot, or a previous good one clobbered by a partial
    rewrite, would destroy the recovery trail it exists to leave."""
    import os
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(snap, fh)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    return path


def load_snapshot(path: str) -> Dict[str, object]:
    with open(path) as fh:
        return json.load(fh)
