"""Elastic serving fleet — session-aware routing, live migration,
heartbeat failover and autoscaling over N engine replicas.

The single-loop Engine (inference/engine.py) serves one chip's worth
of traffic; the disaggregated driver (inference/disagg.py) splits ONE
request's prefill and decode across workers. Production wants the
third axis: many WHOLE engine replicas behind one front door, so the
fleet can ride load swings, survive replica loss, and keep shared
system prompts hot. This module is that front door — the MPMD
driver/replica shape of JaxPP (arXiv:2412.14374) applied one level up:
a schedule-driven host ROUTER over fixed compiled replicas, with
replica-to-replica state movement treated as portable redistribution
of HOST truth (cf. arXiv:2112.01075's device-free formulation) rather
than device state — a migrated request carries tokens + a replayed rng
chain, never KV bytes.

Four capabilities (docs/SERVING.md "Elastic fleet"):

* **Session-aware routing.** Requests sharing a system prefix hash to
  the same session key (the prefix cache's chained blake2b over the
  first page-aligned prompt chunk), and the router steers them to the
  replica whose prefix cache is WARM for that prefix — scored by the
  replica's own ``PrefixCache.lookup`` depth plus a router-side
  session→replica hint for prefixes still prefilling. Cold requests
  fall back least-loaded; per-tenant fairness is preserved ACROSS
  replicas (one fleet-level round-robin over tenant queues — a
  flooding tenant can slow, never starve, another tenant whichever
  replicas its requests land on). Fleet-wide
  ``serving.prefix_hit_rate`` is the number routing exists to
  maximize; ``router="round_robin"`` / ``"least_loaded"`` are the
  comparison baselines the tests hold it against.

* **Live request migration.** ``migrate_request(rid)`` moves one
  in-flight request between replicas WITHOUT dropping a token: the
  source's ``Engine.extract_request`` hook removes it (slot cleared,
  pages freed), the fleet replays its rng chain from host truth alone
  (``disagg.replay_rng_key(seed, tokens_emitted, temperature)`` — the
  device is never read), and the request re-admits on the target
  through the SAME preemption/resume-prefill machinery every other
  resume takes — so the continued stream is bit-identical to the
  never-migrated run, with prefix hits and speculative decoding on
  (tests hold the full matrix). Between extraction and re-admission
  the request is PARKED on the fleet (``num_parked``) — snapshot()
  serializes parked requests exactly. ``drain_replica(i)`` migrates
  every request off a replica (hot-spot relief, pre-maintenance) and
  blocks new dispatches to it until ``undrain_replica(i)``.

* **Heartbeat failover.** ``heartbeat_timeout=T`` attaches one
  ``distributed.watchdog.Heartbeat`` per replica, ticked by that
  replica's step; a replica whose loop stalls past T is killed and
  failed over at the next fleet tick. ``kill_replica(i)`` (and the
  seeded ``replica.die`` fault site) drops a replica WHOLESALE —
  pools, allocator, prefix cache, device state, no goodbye — and every
  request that lived there re-admits elsewhere from host truth alone
  (prompt + emitted tokens + replayed rng chain) and finishes
  token-exact. The last live replica can never be killed.

* **Autoscaling.** ``autoscale=AutoscalePolicy(...)`` (or ``True``)
  evaluates queue-depth and TTFT-percentile signals on the fleet's
  injectable clock every tick: sustained pressure scales UP (a fresh
  replica compiles its own executables — warmup, not steady-state
  recompiles), sustained low load scales DOWN by draining the
  least-loaded replica via migration, so a scale-down NEVER drops a
  request. Events land in ``scale_log`` and
  ``serving.fleet.scale_events``.

Contract: a request served by the fleet emits EXACTLY the tokens the
single-loop Engine (and the b=1 ``generate``) emits — greedy and
seeded sampling, through routing, migration, replica deaths,
preemptions on the target replica, and scale events — and every live
replica's ``steady_state_recompiles()`` stays 0 across those traces
(a replica compiles its fixed surface once; routing/migration adds no
compiled surface beyond the one-time rng replay warmup).

Observability (docs/OBSERVABILITY.md): counters
``serving.fleet.routed_warm`` / ``serving.fleet.routed_cold`` /
``serving.fleet.migrations`` / ``serving.fleet.replica_deaths`` /
``serving.fleet.readmitted`` / ``serving.fleet.scale_events``, gauges
``serving.fleet.queue_depth`` / ``serving.fleet.replicas`` /
``serving.fleet.parked`` and per-replica
``serving.fleet.replica<i>.queue_depth`` /
``serving.fleet.replica<i>.prefix_hit_rate``.
"""
from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import monitor
from ..profiler.stats import CompileTracker
from . import tracing
from .disagg import replay_rng_key
from .engine import (FAILED, FINISHED, PREEMPTED, WAITING, Engine,
                     Output, Request, SamplingParams, _ceil_div,
                     _normalize_prompt)
from .prefix_cache import _chunk_hash

FLEET_SNAPSHOT_VERSION = 1

#: router policies: "session" steers shared-prefix traffic to the
#: warm replica; the other two are the measurable baselines
ROUTERS = ("session", "least_loaded", "round_robin")

#: how many leading page chunks the router probes per replica cache
#: when scoring warmth — the signal saturates fast, and an uncapped
#: probe would re-digest a whole 8K prompt per replica per dispatch
#: attempt of a capacity-starved queue head, every tick
ROUTE_PROBE_CHUNKS = 8


@dataclass
class AutoscalePolicy:
    """Scale-up/down decision knobs, evaluated every fleet tick on the
    injectable clock (so replay tools and tests drive them on virtual
    time). Scale-up fires after ``patience`` consecutive ticks of
    pressure (fleet queue depth above ``scale_up_queue_depth``, or —
    when set — recent-request p95 TTFT above ``scale_up_ttft_p95_ms``);
    scale-down fires after ``scale_down_patience`` consecutive ticks
    where the fleet queue is empty and the live load would fit HALF of
    one fewer replica's slots. ``cooldown`` ticks separate any two
    scale events so one burst can't thrash the fleet size."""

    min_replicas: int = 1
    max_replicas: int = 4
    scale_up_queue_depth: int = 8
    scale_up_ttft_p95_ms: Optional[float] = None
    patience: int = 3
    scale_down_patience: int = 50
    cooldown: int = 20
    ttft_window: int = 32

    def __post_init__(self):
        if int(self.min_replicas) < 1:
            raise ValueError(
                f"min_replicas must be >= 1, got {self.min_replicas}")
        if int(self.max_replicas) < int(self.min_replicas):
            raise ValueError(
                f"max_replicas {self.max_replicas} < min_replicas "
                f"{self.min_replicas}")


class ServingFleet:
    """Front door over N in-process Engine replicas.

        fleet = ServingFleet(model, replicas=2, max_slots=4,
                             page_size=8, pool_pages=64)
        rid = fleet.add_request(ids, SamplingParams(max_new_tokens=32),
                                tenant="team-a")
        for tok in fleet.stream(rid):
            ...
        # or drive it like the single-loop engine:
        outs = fleet.run([(ids_a, pa), (ids_b, pb)])

    Geometry (page_size / prefill_bucket / max_context / cache_dtype /
    spec_k / pool_pages / max_slots) is shared by every replica — a
    request must be admissible anywhere the router may place it.
    ``prefix_cache`` defaults ON (session-aware routing exists to keep
    the per-replica caches warm; pass False for the cold baseline).
    """

    def __init__(self, model, replicas: int = 2, max_slots: int = 8,
                 page_size: int = 16,
                 pool_pages: Optional[int] = None,
                 cache_dtype: str = "auto",
                 max_context: Optional[int] = None,
                 prefill_bucket: int = 32,
                 watermark_pages: Optional[int] = None,
                 prefix_cache: bool = True,
                 draft_model=None, spec_k: int = 4,
                 clock=None, fault_injector=None,
                 max_prefill_tokens_per_step: Optional[int] = None,
                 router: str = "session",
                 heartbeat_timeout: Optional[float] = None,
                 autoscale=None, multi_tick: int = 1):
        if int(replicas) < 1:
            raise ValueError(f"need at least one replica, got {replicas}")
        if router not in ROUTERS:
            raise ValueError(
                f"unknown router {router!r} — one of {ROUTERS}")
        self.model = model
        self.router = router
        self.label = "fleet"
        self._clock = clock if clock is not None else time.perf_counter
        # same arming contract as Engine/DisaggEngine: explicit
        # injector, None = arm from FLAGS_serving_fault_* (one injector
        # shared fleet-wide so the whole chaos schedule replays from
        # one seed), False = force OFF
        if fault_injector is False:
            self._injector = None
        elif fault_injector is None:
            from .reliability import injector_from_flags
            self._injector = injector_from_flags()
        else:
            self._injector = fault_injector
        self._ctor = dict(
            max_slots=int(max_slots), page_size=int(page_size),
            pool_pages=pool_pages, cache_dtype=cache_dtype,
            max_context=max_context, prefill_bucket=int(prefill_bucket),
            watermark_pages=watermark_pages,
            prefix_cache=bool(prefix_cache),
            draft_model=draft_model, spec_k=int(spec_k),
            clock=self._clock,
            fault_injector=(self._injector
                            if self._injector is not None else False),
            max_prefill_tokens_per_step=max_prefill_tokens_per_step,
            multi_tick=int(multi_tick))
        if autoscale is True:
            autoscale = AutoscalePolicy()
        self._policy: Optional[AutoscalePolicy] = autoscale
        self._heartbeat_timeout = heartbeat_timeout
        self._heartbeats: Dict[int, object] = {}
        self._stalled: set = set()
        self._last_step_t = time.monotonic()
        self._replicas: List[Optional[Engine]] = []
        self._replicas_created = 0
        self.replica_stats: Dict[int, Dict[str, int]] = {}
        for _ in range(int(replicas)):
            self._spawn_replica()
        w0 = next(w for w in self._replicas if w is not None)
        self.max_slots = w0.max_slots
        self.page_size = w0.page_size
        self.max_blocks = w0.max_blocks
        self.max_context = w0.max_context
        self.prefill_bucket = w0.prefill_bucket
        self.cache_dtype = w0.cache_dtype
        self.pool_pages = w0.pool_pages
        self._lookahead = w0._lookahead
        # front door: per-tenant FIFO queues with fleet-level
        # round-robin dispatch; PARKED requests (mid-migration,
        # failed-over, restored-with-progress) are serviced first —
        # they hold partial progress, the single-engine semantics put
        # resumed work at the queue front
        self._queues: Dict[str, deque] = {}
        self._rr: deque = deque()
        self._parked: "deque[Request]" = deque()
        self._migrate_dst: Dict[int, int] = {}
        self.requests: Dict[int, Request] = {}
        self._tenant: Dict[int, str] = {}
        self._home: Dict[int, int] = {}
        self._order: Dict[int, int] = {}
        # session routing state: session key (first-chunk chained
        # digest) -> replica index of the last dispatch, so a burst of
        # same-session requests sticks to one replica even before its
        # first prefill lands in the cache. Bounded (oldest evicted).
        self._sessions: Dict[bytes, int] = {}
        # per-request session key, digested ONCE at admission (the
        # dispatch loop re-routes queue heads every tick — re-hashing
        # the prompt there would be scheduler-hot-path waste)
        self._skey: Dict[int, Optional[bytes]] = {}
        self._draining: set = set()
        self._next_id = 0
        self._steps = 0
        self._outputs: Dict[int, Output] = {}
        self._stream_cursor: Dict[int, int] = {}
        self.scale_log: List[Dict[str, object]] = []
        self._up_streak = 0
        self._down_streak = 0
        self._cooldown = 0
        # autoscale TTFT signal: two rotating log-bucket histograms
        # (current + previous window) instead of an unbounded sample
        # list — O(1) record, p95 from the exact merge of both windows
        self._ttft_hist = monitor.Histogram("fleet.autoscale.ttft")
        self._ttft_hist_prev = monitor.Histogram(
            "fleet.autoscale.ttft.prev")
        self._ttft_sampled: set = set()
        # hit/lookup totals of replicas that died or scaled away, so
        # the fleet-wide prefix_hit_rate survives replica churn
        self._retired_hits = 0
        self._retired_lookups = 0
        self._tracker = CompileTracker().start()
        self._compiles = 0
        self._warm_compiles = 0
        self._replay_used = False
        # precompile the rng-replay surface (PRNGKey + split) so a
        # steady-state migration/failover tick introduces no new
        # driver executable
        replay_rng_key(0, 1, 1.0)

    @classmethod
    def from_plan(cls, model, plan, **overrides) -> "ServingFleet":
        """Build a fleet from a planner serving plan
        (``analysis.planner.plan_serving`` output): ``replicas`` is the
        plan's chip-group count, ``decode_mp`` the per-replica TP
        degree (advisory — takes effect through the ambient mp mesh,
        one mesh group per replica on real hardware)."""
        kw = dict(replicas=int(plan.get("replicas", 2)))
        kw.update(overrides)
        fleet = cls(model, **kw)
        fleet.plan = dict(plan)
        return fleet

    # -- replica lifecycle ---------------------------------------------------

    def _spawn_replica(self, index: Optional[int] = None) -> int:
        """Construct one Engine replica (at ``index`` — a dead
        replica's coordinate — or appended). The new replica compiles
        its own fixed surface on first use: warmup by the per-engine
        accounting, never a steady-state recompile."""
        if index is None:
            index = len(self._replicas)
            self._replicas.append(None)
        w = Engine(self.model, label=f"replica{index}", **self._ctor)
        self._replicas[index] = w
        self._replicas_created += 1
        # a reused coordinate (scale-up after a death) is a NEW engine:
        # fresh stats, or the replay report would conflate two
        # incarnations under one row
        self.replica_stats[index] = {
            "steps": 0, "busy_steps": 0, "routed_warm": 0,
            "routed_cold": 0, "migrated_out": 0, "finished": 0}
        if self._heartbeat_timeout is not None:
            from ..distributed.watchdog import Heartbeat
            hb = Heartbeat(
                float(self._heartbeat_timeout),
                on_stall=lambda age, i=index: self._flag_stall(i),
                name=f"fleet-replica{index}")
            hb.start()
            self._heartbeats[index] = hb
        return index

    def _flag_stall(self, index: int) -> None:
        """Heartbeat callback (runs on the watchdog thread): record
        the verdict; the next fleet tick's sweep decides whether it
        was a real replica wedge or just a paused driver."""
        self._stalled.add(int(index))

    def _remove_replica(self, index: int) -> None:
        w = self._replicas[index]
        if w is None:
            return
        if w._prefix is not None:
            self._retired_hits += w._prefix.hits
            self._retired_lookups += w._prefix.lookups
        hb = self._heartbeats.pop(index, None)
        if hb is not None:
            hb.stop()
        w.close()
        self._replicas[index] = None
        self._draining.discard(index)
        self._stalled.discard(index)
        # stale session hints must not keep scoring a dead replica warm
        for k in [k for k, v in self._sessions.items() if v == index]:
            del self._sessions[k]

    def _alive(self) -> List[Tuple[int, Engine]]:
        return [(i, w) for i, w in enumerate(self._replicas)
                if w is not None]

    # -- front door ----------------------------------------------------------

    def add_request(self, ids, sampling_params=None,
                    tenant: str = "default") -> int:
        """Queue a prompt under ``tenant``'s share of the dispatch.
        Returns immediately with the request id; the router assigns a
        replica at a later ``step()`` and tokens stream out of
        ``stream(rid)`` / ``astream(rid)``."""
        params = sampling_params or SamplingParams()
        if isinstance(params, dict):
            params = SamplingParams(**params)
        params.validate()
        prompt = _normalize_prompt(ids)
        rid = self._next_id
        # admission math DELEGATED to a live replica (geometry is
        # fleet-wide, and at least one replica is always alive): the
        # fleet must never fork Engine's admission contract — a
        # request must be admissible anywhere the router may place it
        probe = next(w for _, w in self._alive())
        need = len(prompt) + int(params.max_new_tokens)
        cap = self.max_blocks * self.page_size - (self._lookahead - 1)
        chunk_cap = (need
                     if probe.max_prefill_tokens_per_step is not None
                     else probe._pbucket(need))
        if chunk_cap > cap:
            raise ValueError(
                f"request {rid} needs {need} token slots, beyond the "
                f"fleet's max_context capacity {cap}")
        worst = probe._lifetime_pages(len(prompt),
                                      int(params.max_new_tokens))
        if worst > self.pool_pages:
            raise RuntimeError(
                f"request {rid} can never be scheduled: it needs up "
                f"to {worst} page(s) but each replica's pool has "
                f"{self.pool_pages}")
        req = Request(req_id=rid, prompt=prompt, params=params,
                      arrival_t=self._clock(), queued_step=self._steps)
        import jax
        req.key = np.asarray(jax.random.PRNGKey(int(params.seed)),
                             np.uint32)
        tracing.open_span(req.spans, tracing.QUEUED,
                          req.arrival_t * 1e3, self.label)
        self._next_id += 1
        self.requests[rid] = req
        self._tenant[rid] = str(tenant)
        self._order[rid] = len(self._order)
        self._skey[rid] = self._session_key(prompt)
        q = self._queues.get(str(tenant))
        if q is None:
            q = self._queues[str(tenant)] = deque()
            self._rr.append(str(tenant))
        q.append(req)
        monitor.counter("serving.requests").increase()
        return rid

    def cancel(self, req_id: int) -> Optional[Output]:
        """Abort a request at any lifecycle point — queued on the
        fleet, parked mid-migration, or live on a replica."""
        req = self.requests.get(int(req_id))
        if req is None or req.state in (FINISHED, FAILED):
            return None
        home = self._home.get(req.req_id)
        if home is not None and self._replicas[home] is not None:
            out = self._replicas[home].cancel(req.req_id)
            if out is not None:
                self._retired(out)
                return out
        self._drop_from_queues(req)
        monitor.counter("serving.cancelled").increase()
        monitor.counter("serving.failed").increase()
        req.state = FAILED
        req.finish_reason = "cancelled"
        req.finish_t = self._clock()
        out = self._make_output(req, "cancelled", failed=True)
        self._retired(out)
        return out

    def stream(self, req_id: int):
        """Synchronous streaming iterator: yields tokens for ``rid``
        as fleet ticks produce them, driving ``step()`` itself while
        the request is unfinished."""
        rid = int(req_id)
        while True:
            tok, done = self._stream_poll(rid)
            for t in tok:
                yield t
            if done:
                return
            if not tok:
                self.step()

    async def astream(self, req_id: int):
        """Async streaming iterator — yields tokens as they decode and
        control between ticks so many consumers interleave over one
        event loop."""
        import asyncio
        rid = int(req_id)
        while True:
            tok, done = self._stream_poll(rid)
            for t in tok:
                yield t
                await asyncio.sleep(0)
            if done:
                return
            if not tok:
                self.step()
                await asyncio.sleep(0)

    def _stream_poll(self, rid: int) -> Tuple[List[int], bool]:
        cur = self._stream_cursor.get(rid, 0)
        out = self._outputs.get(rid)
        if out is not None:
            toks = out.token_ids[cur:]
            self._stream_cursor.pop(rid, None)
            return toks, True
        req = self.requests.get(rid)
        if req is None:
            raise KeyError(f"unknown request id {rid}")
        toks = list(req.generated[cur:])
        self._stream_cursor[rid] = cur + len(toks)
        return toks, False

    # -- driver loop ---------------------------------------------------------

    def step(self) -> List[Output]:
        """One fleet tick: chaos + stall sweep, deadline sweep over
        fleet-held requests, session-aware dispatch, one step per live
        replica, autoscale evaluation. Returns every request that
        finished or failed this tick."""
        outs: List[Output] = []
        step_gap = time.monotonic() - self._last_step_t
        self._last_step_t = time.monotonic()
        c0 = self._tracker.compiles
        sig0 = self._surface_sig()
        inner = 0
        self._maybe_chaos()
        self._sweep_stalled(step_gap)
        outs.extend(self._expire())
        self._dispatch()
        for i, w in self._alive():
            busy = not w.idle
            rc0 = self._tracker.compiles
            for out in w.step():
                self._retired(out, replica=i)
                outs.append(out)
            inner += self._tracker.compiles - rc0
            st = self.replica_stats[i]
            st["steps"] += 1
            st["busy_steps"] += int(busy)
            hb = self._heartbeats.get(i)
            if hb is not None:
                hb.tick()
        self._sample_ttft()
        self._autoscale()
        self._steps += 1
        self._publish_gauges()
        # driver-surface compile accounting (the disagg pattern): the
        # fleet driver itself only compiles when a replica is BORN
        # (pool construction) or the rng-replay surface first runs —
        # both mark warmup via the surface signature; replica-step
        # compiles are each replica's own accounting
        self._compiles += (self._tracker.compiles - c0) - inner
        if self._surface_sig() != sig0:
            self._warm_compiles = self._compiles
        return outs

    def run(self, requests: Sequence, max_steps: int = 100_000
            ) -> List[Output]:
        """Offline driver: queue every (ids, SamplingParams) pair, step
        until all finish. Returns Outputs ordered by request id."""
        want = set()
        for item in requests:
            if isinstance(item, (tuple, list)) and len(item) == 2 and \
                    isinstance(item[1], (SamplingParams, dict)):
                want.add(self.add_request(item[0], item[1]))
            else:
                want.add(self.add_request(item))
        outs: List[Output] = []
        for _ in range(max_steps):
            outs.extend(o for o in self.step() if o.req_id in want)
            if len(outs) == len(want):
                break
        else:
            raise RuntimeError(
                f"fleet did not drain in {max_steps} steps "
                f"({len(outs)}/{len(want)} finished)")
        return sorted(outs, key=lambda o: o.req_id)

    # -- routing -------------------------------------------------------------

    def _pbucket(self, n: int) -> int:
        return _ceil_div(n, self.prefill_bucket) * self.prefill_bucket

    def _session_key(self, prompt: List[int]) -> Optional[bytes]:
        """The request's session identity: the prefix cache's chained
        digest of the FIRST page-aligned prompt chunk (None for
        prompts shorter than one page — nothing cacheable to steer
        on). Same hash, same chunking as the per-replica caches, so a
        key collision can at worst cost a cold route, never a wrong
        token."""
        ps = self.page_size
        if len(prompt) < ps:
            return None
        return _chunk_hash(None, prompt[:ps])

    def _can_take_cold(self, w: Engine) -> bool:
        """A cold dispatch wants immediate admission: a free slot and
        an empty local queue."""
        return (not w._waiting
                and any(r is None for r in w._slots))

    def _can_take_warm(self, w: Engine) -> bool:
        """A warm (session-affine) dispatch may queue behind the
        replica's current work — bounded backlog, so affinity can't
        turn into unbounded head-of-line blocking."""
        return len(w._waiting) < w.max_slots

    def _route(self, req: Request) -> Tuple[Optional[int], bool]:
        """Pick a replica for ``req``: (index, routed_warm). None =
        no capacity anywhere this tick (the request stays queued)."""
        alive = [(i, w) for i, w in self._alive()
                 if i not in self._draining]
        if not alive:
            return None, False
        pinned = self._migrate_dst.get(req.req_id)
        if pinned is not None:
            if self._replicas[pinned] is not None \
                    and pinned not in self._draining:
                if self._can_take_warm(self._replicas[pinned]):
                    return pinned, False
                return None, False
            self._migrate_dst.pop(req.req_id, None)
        if self.router == "round_robin":
            pos = getattr(self, "_rr_pos", 0)
            for k in range(len(alive)):
                i, w = alive[(pos + k) % len(alive)]
                if self._can_take_cold(w):
                    self._rr_pos = (pos + k + 1) % len(alive)
                    return i, False
            return None, False
        if self.router == "session":
            skey = self._skey.get(req.req_id)
            if skey is None and req.req_id not in self._skey:
                skey = self._skey[req.req_id] = \
                    self._session_key(req.prompt)
            if skey is not None:
                hint = self._sessions.get(skey)
                best_i, best_score = None, 0
                probe = min((len(req.prompt) - 1) // self.page_size,
                            ROUTE_PROBE_CHUNKS)
                for i, w in alive:
                    depth = 0
                    if w._prefix is not None:
                        depth = w._prefix.lookup(req.prompt,
                                                 max_chunks=probe)
                    # the hint scores like one warm page: it steers a
                    # same-session burst to one replica before the
                    # first prefill has landed in that cache
                    score = depth + (self.page_size if i == hint else 0)
                    if score > best_score:
                        best_i, best_score = i, score
                if best_i is not None \
                        and self._can_take_warm(self._replicas[best_i]):
                    return best_i, True
        # least-loaded fallback (and the "least_loaded" router): most
        # free slots, then most free pages
        free = [(i, w) for i, w in alive if self._can_take_cold(w)]
        if not free:
            return None, False
        i, _ = max(free, key=lambda e: (
            sum(1 for r in e[1]._slots if r is None),
            e[1]._alloc.free_pages, -e[0]))
        return i, False

    def _assign(self, req: Request, index: int, warm: bool,
                front: bool) -> None:
        w = self._replicas[index]
        req.queued_step = w._steps
        if front:
            w._waiting.appendleft(req)
        else:
            w._waiting.append(req)
        w.requests[req.req_id] = req
        self._home[req.req_id] = index
        self._migrate_dst.pop(req.req_id, None)
        if self.router == "session":
            skey = self._skey.get(req.req_id)
            if skey is not None:
                self._sessions[skey] = index
                while len(self._sessions) > 4096:
                    self._sessions.pop(next(iter(self._sessions)))
        st = self.replica_stats[index]
        st["routed_warm" if warm else "routed_cold"] += 1
        monitor.counter("serving.fleet.routed_warm" if warm
                        else "serving.fleet.routed_cold").increase()

    def _dispatch(self) -> None:
        """Hand fleet-queued requests to replicas: parked requests
        first (partial progress resumes at the target's queue front),
        then one request per tenant per round-robin turn."""
        still: "deque[Request]" = deque()
        while self._parked:
            req = self._parked.popleft()
            if req.state in (FINISHED, FAILED):
                continue
            idx, warm = self._route(req)
            if idx is None:
                still.append(req)
                continue
            self._assign(req, idx, warm, front=True)
        self._parked = still
        stalls = 0
        while self._rr and stalls < len(self._rr):
            tenant = self._rr[0]
            self._rr.rotate(-1)
            q = self._queues.get(tenant)
            if not q:
                stalls += 1
                continue
            req = q[0]
            idx, warm = self._route(req)
            if idx is None:
                stalls += 1
                continue
            q.popleft()
            self._assign(req, idx, warm, front=False)
            stalls = 0

    # -- live migration ------------------------------------------------------

    def migrate_request(self, req_id: int,
                        dst: Optional[int] = None) -> bool:
        """Live-migrate one in-flight request off its replica. The
        request is EXTRACTED from the source (slot cleared, pages
        freed NOW), its rng chain replayed from host truth — (seed,
        tokens emitted); the source device is never read — and parked
        on the fleet for re-admission (at ``dst`` when given and
        alive, else wherever the router places it) through the
        resume-prefill machinery: the continued stream is
        bit-identical to the never-migrated run. False = unknown /
        already-retired / not currently on a replica."""
        rid = int(req_id)
        if dst is not None:
            dst = int(dst)
            if not 0 <= dst < len(self._replicas) \
                    or self._replicas[dst] is None:
                raise ValueError(
                    f"migrate_request dst {dst} is not a live replica")
        src = self._home.get(rid)
        if src is None or self._replicas[src] is None:
            return False
        w = self._replicas[src]
        req = w.extract_request(rid, device_key=False)
        if req is None:
            return False
        self._replay_used = True
        req.key = replay_rng_key(req.params.seed, len(req.generated),
                                 req.params.temperature)
        # extract_request opened the MIGRATING span (origin = source
        # replica); tag it as a LIVE migration for the trace
        if req.spans and req.spans[-1].get("phase") == tracing.MIGRATING:
            req.spans[-1].setdefault("detail", {})["kind"] = "live"
        req.preemptions += 1
        req.queued_step = self._steps
        self._home.pop(rid, None)
        if dst is not None:
            self._migrate_dst[rid] = dst
        self._parked.append(req)
        self.replica_stats[src]["migrated_out"] += 1
        monitor.counter("serving.fleet.migrations").increase()
        monitor.counter("serving.preemptions").increase()
        return True

    def drain_replica(self, index: int) -> int:
        """Migrate EVERY live request off replica ``index`` and block
        new dispatches to it (``undrain_replica`` re-opens it). The
        drain never drops a token — each request re-admits elsewhere
        through the same exact-resume path ``migrate_request`` takes.
        Returns the number of requests migrated."""
        index = int(index)
        if not 0 <= index < len(self._replicas) \
                or self._replicas[index] is None:
            raise ValueError(f"drain_replica: no live replica {index}")
        self._draining.add(index)
        w = self._replicas[index]
        rids = sorted(
            (r.req_id for r in w.requests.values()
             if r.state not in (FINISHED, FAILED)),
            key=lambda rid: self._order.get(rid, 10**9))
        n = 0
        for rid in rids:
            if self.migrate_request(rid):
                n += 1
        return n

    def undrain_replica(self, index: int) -> None:
        self._draining.discard(int(index))

    # -- failover ------------------------------------------------------------

    def _maybe_chaos(self) -> None:
        if self._injector is None:
            return
        self._injector.on_step(self._steps)
        if not self._injector.fire("replica.die", record=False):
            return
        alive = [i for i, _ in self._alive()]
        if len(alive) <= 1:
            return             # never kill the last replica
        self._injector.record("replica.die")
        victim = alive[int(
            self._injector.rng.integers(0, len(alive)))]
        self.kill_replica(victim)

    def _sweep_stalled(self, step_gap: float) -> None:
        """Heartbeat verdicts land here: a replica whose heartbeat
        stalled WHILE THE DRIVER KEPT STEPPING is wedged — kill and
        fail over (unless it is the last one — then the stall stays
        flagged for the next tick, when a scale-up may have replaced
        capacity). When the DRIVER itself paused past the timeout
        (idle service, stopped test loop), every heartbeat aged out
        together through no fault of the replicas: clear the flags and
        re-arm instead of self-inflicting a failover."""
        if not self._stalled:
            return
        if self._heartbeat_timeout is not None \
                and step_gap > float(self._heartbeat_timeout):
            self._stalled.clear()
            return
        for i in sorted(self._stalled):
            if self._replicas[i] is None:
                self._stalled.discard(i)
                continue
            if len(self._alive()) <= 1:
                continue
            self._stalled.discard(i)
            self.kill_replica(i)

    def kill_replica(self, index: int) -> int:
        """Drop a replica WHOLESALE — pools, allocator, prefix cache,
        device state, no goodbye — and re-admit every request that
        lived there from host truth alone (prompt + emitted tokens +
        the replayed rng chain; the dead device is never read). Each
        re-admitted request finishes token-exact. Returns the number
        re-admitted. The last live replica cannot be killed."""
        index = int(index)
        if not 0 <= index < len(self._replicas):
            raise ValueError(
                f"kill_replica index {index} out of range for "
                f"{len(self._replicas)} replica slot(s)")
        w = self._replicas[index]
        if w is None:
            return 0
        if len(self._alive()) <= 1:
            raise RuntimeError(
                "cannot kill the last replica — the fleet must keep "
                "serving")
        monitor.counter("serving.fleet.replica_deaths").increase()
        doomed = sorted(
            (r.req_id for r in w.requests.values()
             if r.state not in (FINISHED, FAILED)),
            key=lambda rid: (self._order.get(rid, 10**9), rid))
        n = 0
        zero_progress: List[Request] = []
        self._replay_used = self._replay_used or bool(doomed)
        for rid in doomed:
            # the SAME extraction path migration takes (device never
            # read — the pools are dying anyway; page frees on the
            # doomed allocator are harmless), so failover can never
            # drift from the live-migration state transition
            req = w.extract_request(rid, device_key=False)
            if req is None:
                continue
            req.preemptions += 1
            req.key = replay_rng_key(req.params.seed,
                                     len(req.generated),
                                     req.params.temperature)
            # has-progress: the extraction's MIGRATING span (origin =
            # dead replica) carries the failover; zero-progress goes
            # straight back to QUEUED — it never really moved
            if req.generated:
                if req.spans and \
                        req.spans[-1].get("phase") == tracing.MIGRATING:
                    req.spans[-1].setdefault(
                        "detail", {})["kind"] = "failover"
            else:
                tracing.open_span(req.spans, tracing.QUEUED,
                                  self._clock() * 1e3, self.label,
                                  kind="failover")
            req.queued_step = self._steps
            self._home.pop(req.req_id, None)
            self._migrate_dst.pop(req.req_id, None)
            if req.generated:
                # partial progress earns the parked fast lane
                self._parked.append(req)
            else:
                # an assigned-but-unstarted request holds nothing — it
                # rejoins ITS TENANT's queue front (it is the tenant's
                # oldest); failover must not let it jump other
                # tenants' older work
                zero_progress.append(req)
            monitor.counter("serving.fleet.readmitted").increase()
            n += 1
        for req in reversed(zero_progress):
            tenant = self._tenant.get(req.req_id, "default")
            q = self._queues.get(tenant)
            if q is None:
                q = self._queues[tenant] = deque()
                self._rr.append(tenant)
            q.appendleft(req)
        self._remove_replica(index)
        return n

    # -- autoscaling ---------------------------------------------------------

    def _sample_ttft(self) -> None:
        """Collect TTFT samples (fleet clock) the moment a request
        reaches its first token — the autoscaler's latency signal must
        not wait for requests to FINISH."""
        if self._policy is None:
            return
        window = int(self._policy.ttft_window)
        for rid, req in self.requests.items():
            if req.first_token_t > 0.0 and rid not in self._ttft_sampled:
                self._ttft_sampled.add(rid)
                if self._ttft_hist.count >= window:
                    # rotate: the previous window ages out wholesale
                    self._ttft_hist_prev = self._ttft_hist
                    self._ttft_hist = monitor.Histogram(
                        "fleet.autoscale.ttft")
                self._ttft_hist.record(
                    (req.first_token_t - req.arrival_t) * 1e3)

    def _autoscale(self) -> None:
        pol = self._policy
        if pol is None:
            return
        live = self._alive()
        qd = self.num_waiting
        pressure = qd > int(pol.scale_up_queue_depth)
        if not pressure and pol.scale_up_ttft_p95_ms is not None:
            merged = monitor.Histogram("fleet.autoscale.ttft.merged")
            merged.merge(self._ttft_hist).merge(self._ttft_hist_prev)
            if merged.count >= 4:
                p95 = merged.percentile(95)
                pressure = p95 > float(pol.scale_up_ttft_p95_ms)
        self._up_streak = self._up_streak + 1 if pressure else 0
        load = sum(w.num_active + w.num_prefilling + len(w._waiting)
                   for _, w in live)
        fits = (len(live) > int(pol.min_replicas) and qd == 0
                and 2 * load <= (len(live) - 1) * self.max_slots)
        self._down_streak = self._down_streak + 1 if fits else 0
        if self._cooldown > 0:
            self._cooldown -= 1
            return
        if self._up_streak >= int(pol.patience) \
                and len(live) < int(pol.max_replicas):
            idx = next((i for i, w in enumerate(self._replicas)
                        if w is None), None)
            idx = self._spawn_replica(idx)
            self.scale_log.append({
                "step": self._steps, "action": "up", "replica": idx,
                "queue_depth": qd, "replicas": len(self._alive())})
            monitor.counter("serving.fleet.scale_events").increase()
            self._up_streak = 0
            self._down_streak = 0
            self._cooldown = int(pol.cooldown)
        elif self._down_streak >= int(pol.scale_down_patience):
            # drain-via-migration: the victim's requests re-admit
            # elsewhere token-exact BEFORE the replica closes — a
            # scale-down never drops a request
            idx, w = min(live, key=lambda e: (
                e[1].num_active + e[1].num_prefilling
                + len(e[1]._waiting), e[0]))
            moved = self.drain_replica(idx)
            self._remove_replica(idx)
            self.scale_log.append({
                "step": self._steps, "action": "down", "replica": idx,
                "migrated": moved, "replicas": len(self._alive())})
            monitor.counter("serving.fleet.scale_events").increase()
            self._up_streak = 0
            self._down_streak = 0
            self._cooldown = int(pol.cooldown)

    # -- reliability surfaces ------------------------------------------------

    def snapshot(self) -> dict:
        """Crash-exact host-state snapshot of the whole fleet — every
        queued / parked-mid-migration / live request's host source of
        truth. Rng chains are REPLAYED from (seed, emitted tokens),
        never fetched from a device, so the same path serves live
        snapshots and post-mortem ones."""
        from dataclasses import asdict
        reqs: List[Request] = []
        seen: set = set()
        for _, w in self._alive():
            reqs.extend(r for r in w.requests.values()
                        if r.state not in (FINISHED, FAILED))
        reqs.extend(self._parked)
        for q in self._queues.values():
            reqs.extend(q)
        reqs.sort(key=lambda r: (self._order.get(r.req_id, 10**9),
                                 r.req_id))
        now = self._clock()
        entries = []
        for req in reqs:
            if req.req_id in seen:
                continue
            seen.add(req.req_id)
            entries.append({
                "req_id": int(req.req_id),
                "prompt": [int(t) for t in req.prompt],
                "generated": [int(t) for t in req.generated],
                "params": asdict(req.params),
                "tenant": self._tenant.get(req.req_id, "default"),
                "parked": req in self._parked,
                "preemptions": int(req.preemptions),
                "elapsed_ms": (now - req.arrival_t) * 1e3,
                "spans": tracing.copy_spans(req.spans),
            })
        monitor.counter("serving.snapshot_saves").increase()
        return {
            "version": FLEET_SNAPSHOT_VERSION,
            "kind": "fleet",
            "topology": {"replicas": len(self._alive())},
            "fingerprint": self._fingerprint(),
            "next_id": int(self._next_id),
            "requests": entries,
        }

    def restore(self, snap: dict) -> int:
        """Re-admit a snapshot's requests into this (fresh) fleet:
        requests with emitted tokens — including those snapshotted
        PARKED mid-migration — resume via the parked lane with
        replayed rng chains; untouched ones queue under their tenant.
        Outputs are bit-identical to the uninterrupted run. Replica
        count may differ (scheduling changes, tokens do not)."""
        if snap.get("kind") != "fleet" or \
                snap.get("version") != FLEET_SNAPSHOT_VERSION:
            raise ValueError(
                f"not a fleet snapshot (kind={snap.get('kind')!r} "
                f"version={snap.get('version')!r})")
        if self.requests:
            raise RuntimeError(
                "restore onto a busy fleet: "
                f"{len(self.requests)} live request(s) present")
        fp = self._fingerprint()
        saved = snap.get("fingerprint", {})
        diff = {k: (saved.get(k), v) for k, v in fp.items()
                if saved.get(k) != v}
        if diff:
            raise ValueError(
                f"snapshot is token-incompatible with this fleet: "
                f"{diff} (saved vs current)")
        self._replay_used = True
        n = 0
        for ent in snap["requests"]:
            params = SamplingParams(**ent["params"])
            req = Request(
                req_id=int(ent["req_id"]),
                prompt=[int(t) for t in ent["prompt"]],
                params=params,
                state=PREEMPTED if ent["generated"] else WAITING,
                generated=[int(t) for t in ent["generated"]],
                preemptions=int(ent.get("preemptions", 0)),
                arrival_t=self._clock()
                - float(ent.get("elapsed_ms", 0.0)) / 1e3,
                queued_step=self._steps)
            req.key = replay_rng_key(params.seed, len(req.generated),
                                     params.temperature)
            req.spans = tracing.restore_spans(
                ent.get("spans"), req.arrival_t * 1e3,
                self._clock() * 1e3, self.label, bool(req.generated))
            tenant = str(ent.get("tenant", "default"))
            self.requests[req.req_id] = req
            self._tenant[req.req_id] = tenant
            self._order[req.req_id] = len(self._order)
            self._skey[req.req_id] = self._session_key(req.prompt)
            if req.generated:
                self._parked.append(req)
            else:
                q = self._queues.get(tenant)
                if q is None:
                    q = self._queues[tenant] = deque()
                    self._rr.append(tenant)
                q.append(req)
            n += 1
        self._next_id = max(self._next_id, int(snap.get("next_id", 0)))
        monitor.counter("serving.snapshot_restores").increase()
        return n

    def _fingerprint(self) -> Dict[str, object]:
        cfg = self.model.config
        live = next(w for w in self._replicas if w is not None)
        return {
            "vocab_size": int(cfg.vocab_size),
            "num_hidden_layers": int(cfg.num_hidden_layers),
            "hidden_size": int(cfg.hidden_size),
            "num_attention_heads": int(cfg.num_attention_heads),
            "num_key_value_heads": int(cfg.num_key_value_heads),
            "cache_dtype": str(np.dtype(self.cache_dtype).name),
            "spec_k": (int(live._spec.k)
                       if live._spec is not None else 0),
        }

    def leaked_pages(self) -> int:
        """Fleet-wide drained leak check (Engine.leaked_pages per live
        replica — dead replicas' pools died with them)."""
        return sum(w.leaked_pages() for _, w in self._alive())

    def check_invariants(self, repair: bool = False) -> List[str]:
        findings: List[str] = []
        for i, w in self._alive():
            findings += [f"replica{i}: {f}"
                         for f in w.check_invariants(repair=repair)]
        return findings

    def _surface_sig(self) -> Tuple[int, bool]:
        """Driver compiled-surface inventory: growth marks a
        legitimate warmup step (a replica born, or the rng-replay
        surface first exercised)."""
        return (self._replicas_created, self._replay_used)

    def steady_state_recompiles(self) -> int:
        """Sum of every live replica's steady-state recompiles plus
        the driver's own — the number that must be 0 across
        route/migrate/kill/scale traces."""
        own = self._compiles - self._warm_compiles
        return own + sum(w.steady_state_recompiles()
                         for _, w in self._alive())

    def per_replica_recompiles(self) -> Dict[int, int]:
        return {i: w.steady_state_recompiles()
                for i, w in self._alive()}

    # -- hot-path lint (docs/ANALYSIS.md "Hot-path rules") -------------------

    def _hotpath_inventory(self):
        """The fleet DRIVER compiles nothing of its own — its hot-path
        surface is the routing/sweep tick source; the replicas are
        full Engines, swept separately by inspect_hotpath()."""
        from ..analysis import hotpath_lint as hp
        return hp.HotpathInventory(
            subject="ServingFleet[driver]", executables=[],
            tick_functions=[self.step, self._dispatch,
                            self._sweep_stalled, self._expire,
                            self._sample_ttft, self._autoscale],
            steady_functions=(), cache_keys={}, file=__file__)

    def inspect_hotpath(self):
        """Hot-path audit over the fleet: driver tick path plus every
        live replica's Engine inventory, one combined Report through
        the ``lint.hotpath.*`` counters."""
        from ..analysis import hotpath_lint
        report = hotpath_lint.lint_inventory(self._hotpath_inventory())
        for _, w in self._alive():
            report.extend(hotpath_lint.lint_inventory(
                w._hotpath_inventory()))
        return hotpath_lint.emit_hotpath(report)

    def close(self):
        self._tracker.stop()
        for hb in self._heartbeats.values():
            hb.stop()
        self._heartbeats.clear()
        for _, w in self._alive():
            w.close()

    def __del__(self):
        try:
            self._tracker.stop()
            for hb in self._heartbeats.values():
                hb.stop()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass

    # -- bookkeeping ---------------------------------------------------------

    def _expire(self) -> List[Output]:
        """Deadline/queue-budget sweep over FLEET-held requests
        (tenant queues + parked; replicas sweep their own)."""
        outs: List[Output] = []
        now = self._clock()
        held = [r for q in self._queues.values() for r in q]
        held += list(self._parked)
        for req in held:
            if req.state in (FINISHED, FAILED):
                continue
            p = req.params
            reason = None
            if p.deadline_ms is not None and \
                    (now - req.arrival_t) * 1e3 > float(p.deadline_ms):
                reason = "deadline"
            elif p.max_queue_steps is not None and \
                    req.state in (WAITING, PREEMPTED) and \
                    self._steps - req.queued_step \
                    > int(p.max_queue_steps):
                reason = "queue_timeout"
            if reason is None:
                continue
            monitor.counter("serving.timeouts").increase()
            self._drop_from_queues(req)
            req.state = FAILED
            req.finish_reason = reason
            req.finish_t = now
            monitor.counter("serving.failed").increase()
            out = self._make_output(req, reason, failed=True)
            self._retired(out)
            outs.append(out)
        return outs

    def _drop_from_queues(self, req: Request) -> None:
        for q in self._queues.values():
            try:
                q.remove(req)
            except ValueError:
                pass
        try:
            self._parked.remove(req)
        except ValueError:
            pass
        self._migrate_dst.pop(req.req_id, None)
        home = self._home.get(req.req_id)
        if home is not None and self._replicas[home] is not None:
            w = self._replicas[home]
            if req.slot is None and not req.pages:
                w.requests.pop(req.req_id, None)
                try:
                    w._waiting.remove(req)
                except ValueError:
                    pass

    def _make_output(self, req: Request, reason: str,
                     failed: bool) -> Output:
        n = len(req.generated)
        got_first = req.first_token_t > 0.0
        ttft = ((req.first_token_t - req.arrival_t) * 1e3
                if got_first else 0.0)
        tpot = ((req.finish_t - req.first_token_t) / (n - 1) * 1e3
                if got_first and n > 1 else 0.0)
        tracing.seal(req.spans,
                     tracing.FAILED if failed else tracing.FINISHED,
                     req.finish_t * 1e3, self.label,
                     reason=reason if failed else None)
        return Output(req_id=req.req_id, prompt_ids=list(req.prompt),
                      token_ids=list(req.generated),
                      finish_reason=reason, ttft_ms=ttft, tpot_ms=tpot,
                      preemptions=req.preemptions,
                      error=reason if failed else None,
                      spans=tracing.copy_spans(req.spans))

    #: retired Outputs kept for late/streaming readers; beyond this
    #: many the OLDEST are evicted (step()'s return value is the
    #: durable delivery path)
    MAX_RETAINED_OUTPUTS = 4096

    def _retired(self, out: Output,
                 replica: Optional[int] = None) -> None:
        self._outputs[out.req_id] = out
        self.requests.pop(out.req_id, None)
        self._home.pop(out.req_id, None)
        self._migrate_dst.pop(out.req_id, None)
        self._skey.pop(out.req_id, None)
        self._ttft_sampled.discard(out.req_id)
        if replica is not None:
            self.replica_stats[replica]["finished"] += 1
        tenant = self._tenant.pop(out.req_id, None)
        self._order.pop(out.req_id, None)
        q = self._queues.get(tenant)
        if q is not None and not q:
            del self._queues[tenant]
            try:
                self._rr.remove(tenant)
            except ValueError:
                pass
        while len(self._outputs) > self.MAX_RETAINED_OUTPUTS:
            oldest = next(iter(self._outputs))
            self._outputs.pop(oldest)
            self._stream_cursor.pop(oldest, None)

    def _publish_gauges(self):
        monitor.gauge("serving.fleet.queue_depth").set(self.num_waiting)
        monitor.gauge("serving.fleet.replicas").set(len(self._alive()))
        monitor.gauge("serving.fleet.parked").set(len(self._parked))
        for i, w in self._alive():
            monitor.gauge(
                f"serving.fleet.replica{i}.queue_depth").set(
                len(w._waiting))
            monitor.gauge(
                f"serving.fleet.replica{i}.prefix_hit_rate").set(
                w.prefix_hit_rate)

    # -- introspection -------------------------------------------------------

    @property
    def num_replicas(self) -> int:
        return len(self._alive())

    @property
    def num_waiting(self) -> int:
        return (sum(len(q) for q in self._queues.values())
                + len(self._parked))

    @property
    def num_parked(self) -> int:
        return len(self._parked)

    @property
    def num_active(self) -> int:
        return sum(w.num_active for _, w in self._alive())

    @property
    def num_prefilling(self) -> int:
        return sum(w.num_prefilling for _, w in self._alive())

    @property
    def idle(self) -> bool:
        return (self.num_waiting == 0
                and all(w.idle for _, w in self._alive()))

    @property
    def pages_free(self) -> Dict[str, int]:
        return {f"replica{i}": w._alloc.free_pages
                for i, w in self._alive()}

    @property
    def prefix_hit_rate(self) -> float:
        """FLEET-WIDE prefix reuse: total hits over total lookups
        across every replica that ever served (dead replicas' totals
        are folded in at removal) — the number session-aware routing
        exists to maximize."""
        hits = self._retired_hits
        lookups = self._retired_lookups
        for _, w in self._alive():
            if w._prefix is not None:
                hits += w._prefix.hits
                lookups += w._prefix.lookups
        return hits / lookups if lookups else 0.0

    @property
    def spec_accept_rate(self) -> float:
        drafted = sum(w._spec_drafted for _, w in self._alive())
        accepted = sum(w._spec_accepted for _, w in self._alive())
        return accepted / drafted if drafted else 0.0

    @property
    def pallas_eligible(self) -> bool:
        return all(w.pallas_eligible for _, w in self._alive())

    @property
    def decode_fallback_reason(self) -> Optional[str]:
        for _, w in self._alive():
            if w.decode_fallback_reason:
                return w.decode_fallback_reason
        return None

    def utilization(self) -> Dict[str, Dict[str, object]]:
        """Per-replica utilization snapshot for the replay report:
        busy-step fraction, warm/cold routing counts, migrations out,
        finishes, live prefix hit rate and queue depth; dead replicas
        report ``alive: False``."""
        out: Dict[str, Dict[str, object]] = {}
        for i in sorted(self.replica_stats):
            st = self.replica_stats[i]
            w = (self._replicas[i]
                 if i < len(self._replicas) else None)
            out[f"replica{i}"] = {
                "alive": w is not None,
                "utilization": round(
                    st["busy_steps"] / max(st["steps"], 1), 4),
                "routed_warm": st["routed_warm"],
                "routed_cold": st["routed_cold"],
                "migrated_out": st["migrated_out"],
                "finished": st["finished"],
                "prefix_hit_rate": (round(w.prefix_hit_rate, 4)
                                    if w is not None else None),
                "queue_depth": (len(w._waiting)
                                if w is not None else None),
            }
        return out
