"""Disaggregated prefill/decode serving — many engines over a mesh.

The single-loop Engine (inference/engine.py) multiplexes prefill and
decode onto one set of compiled surfaces on one chip. Production
traffic wants them APART: prefill is compute-bound and bursty, decode
is bandwidth-bound and steady, and sharing one compiled surface means
a whale prefill and a latency-critical decode tick fight for the same
device. This module splits the loop MPMD-style — the JaxPP shape
(arXiv:2412.14374): a schedule-driven host DRIVER (:class:`DisaggEngine`)
over fixed compiled per-stage programs — with the stages being whole
workers:

* **Prefill workers** (:class:`PrefillWorker`): independent engines
  that ONLY run the bucketed prefill executables. Each owns its page
  pool, allocator, prefix cache and (with speculation on) mirrored
  draft pools. A finished prefill does not enter the worker's decode
  plane — the request parks in the MIGRATING state with its pages
  held until the driver moves it.
* **Decode workers** (:class:`DecodeWorker`): independent engines that
  ONLY run the fused decode/verify executables, each with its own pool
  and device-resident slot state. Requests enter via
  :meth:`DecodeWorker.admit_migrated` — pages allocated, migrated KV
  scattered in, the slot activated — never via a local prefill.
* **KV-page migration**: finished-prefill pages move prefill→decode as
  one fixed-shape gather (src pool rows) → collective redistribution →
  fixed-shape scatter (dst pool rows, donated). The redistribution is
  the portable formulation of arXiv:2112.01075 — an
  ``alltoall_single`` over a ``worker`` mesh axis where block ``d`` of
  every worker's contribution is the pages bound for worker ``d`` —
  so ``distributed.communication`` records it and
  ``analysis.shard_lint`` validates it DEVICE-FREE
  (:func:`lint_migration`, the MULTICHIP ``serving disagg`` gate's
  static half). In-process the axis is unbound and the collective is
  the identity on the local block; on a real multi-host mesh the same
  expression lowers to the ICI exchange.

Driver contract (the reason the split is safe to ship):

* **Token exactness.** A request served disaggregated emits EXACTLY
  the tokens the single-loop engine (and the b=1 ``generate``) emits —
  greedy and seeded sampling, with prefix hits, speculative decoding,
  preemption/resume round trips, and worker deaths in the trace. The
  migrated pages are bit-copies, the rng chain is a pure function of
  (seed, tokens emitted), and resume always flows through the same
  prefill machinery. tests/test_serving_disagg.py and the
  ``_dryrun_serving_disagg`` MULTICHIP phase hold this exact.
* **Fixed compiled surfaces per worker.** Each worker compiles its own
  family once (prefill buckets on prefill workers, decode/verify
  variants on decode workers, one gather/scatter pair for migration);
  ``steady_state_recompiles() == 0`` per worker across mixed traces.
* **Multi-tenant fairness.** ``add_request(..., tenant=)`` queues per
  tenant; dispatch round-robins one request per tenant per turn, so a
  flooding tenant can slow — never starve — another tenant's TTFT.
  Re-admissions (preempted / failed-over requests) bypass the tenant
  queues at the front: they hold partial progress and the
  single-engine semantics put resumed work first.
* **Worker-death chaos.** ``kill_worker(kind, i)`` (or the seeded
  ``worker.die_prefill`` / ``worker.die_decode`` fault sites) drops a
  worker WHOLESALE — pools, allocator, device state, no goodbye. Every
  request that lived there re-admits elsewhere from the host source of
  truth alone (prompt + tokens emitted so far + the replayed rng
  chain — :func:`replay_rng_key`; a dead worker's device is never
  read) and finishes token-exact.
* **Async streaming front door.** ``add_request`` returns immediately;
  ``stream(rid)`` / ``astream(rid)`` yield tokens as ticks produce
  them (the async variant yields control between ticks so many
  consumers interleave over one driver loop).

Observability (docs/OBSERVABILITY.md): counters
``serving.migrated_pages`` / ``serving.disagg.migrations`` /
``serving.disagg.worker_kills`` / ``serving.disagg.readmitted`` /
``serving.disagg.migration_preempts``, gauges
``serving.disagg.queue_depth`` / ``serving.disagg.migrating`` and
per-worker ``serving.disagg.<kind><i>.slots_active`` /
``serving.disagg.<kind><i>.pages_free``.
"""
from __future__ import annotations

import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import monitor
from ..profiler.stats import CompileTracker
from . import tracing
from .engine import (FAILED, FINISHED, PREEMPTED, WAITING, Engine,
                     Output, Request, SamplingParams, _ceil_div,
                     _normalize_prompt)

#: lifecycle state between a finished prefill and decode admission:
#: the request holds its prefill-worker pages (the migration source)
#: but occupies no slot on either side
MIGRATING = "MIGRATING"

#: the worker mesh axis the migration collective redistributes over
WORKER_AXIS = "worker"

DISAGG_SNAPSHOT_VERSION = 1


def replay_rng_key(seed: int, n_generated: int,
                   temperature: float) -> np.ndarray:
    """The rng key a request's chain holds after ``n_generated``
    emitted tokens — recomputed from the HOST source of truth alone.

    Every engine sampler (prefill first token, decode tick, verify
    chain) consumes exactly one ``jax.random.split`` per emitted token
    when ``temperature > 0`` and none when greedy, and keeps
    ``split(key)[0]`` as the chain. So a dead worker's in-flight rng
    state is a pure function of (seed, tokens emitted) — the
    failover path re-admits without ever reading the lost device."""
    key = jax.random.PRNGKey(int(seed))
    if float(temperature) > 0.0:
        for _ in range(int(n_generated)):
            key = jax.random.split(key)[0]
    return np.asarray(key, np.uint32)


def migration_collective(block_tree, n_workers: int, src: int, dst: int,
                         group=None):
    """Route one migrated page block through the portable
    collective-redistribution spelling (arXiv:2112.01075): every worker
    contributes ``[n_workers * MB, ...]`` — block ``d`` holds its pages
    bound for worker ``d`` — and ``alltoall_single`` over the worker
    axis deals block ``s`` of worker ``s``'s contribution to worker
    ``s``'s peer. Here the src worker's contribution carries the pages
    in block ``dst`` and zeros elsewhere.

    In-process (single controller, axis unbound) the collective is the
    identity, and the dst extracts the block the src placed for it —
    the degenerate one-rank view of the same program. Under
    ``analysis.shard_lint``'s recorder the call is captured with the
    full ``[W*MB, ...]`` shape and validated against the worker mesh
    device-free (:func:`lint_migration`)."""
    from ..distributed.communication import collectives as coll
    from ..distributed.communication.group import Group
    g = group if group is not None else Group(axis_name=WORKER_AXIS)
    W, d = int(n_workers), int(dst)

    def one(x):
        mb = x.shape[0]
        full = jnp.concatenate(
            [x if i == d else jnp.zeros_like(x) for i in range(W)],
            axis=0)
        out = coll.alltoall_single(None, full, group=g)
        return out[d * mb:(d + 1) * mb]

    return jax.tree_util.tree_map(one, block_tree)


def lint_migration(n_workers: int, max_blocks: int, kv_heads: int,
                   page_size: int, head_dim: int, layers: int = 1,
                   quant: bool = False) -> List[str]:
    """Device-free validation of the migration collective: run the
    redistribution expression for a worker mesh of ``n_workers`` under
    ``analysis.shard_lint``'s recorder + a fake ``{worker: W}`` mesh
    and lint the records. Returns finding strings (empty = the
    migration lowers to a valid, evenly split ``alltoall_single`` over
    the worker axis — the static half of the MULTICHIP ``serving
    disagg`` gate)."""
    from ..analysis import shard_lint
    from ..distributed import mesh as mesh_mod
    block = []
    for _ in range(int(layers)):
        leaf = jnp.zeros((int(max_blocks), int(kv_heads),
                          int(page_size), int(head_dim)), jnp.float32)
        entry = (leaf, leaf)
        if quant:
            s = jnp.zeros((int(max_blocks), int(kv_heads),
                           int(page_size)), jnp.float32)
            entry = entry + (s, s)
        block.append(entry)
    fake = mesh_mod.fake_mesh({WORKER_AXIS: int(n_workers)})
    with shard_lint.recording(fake) as rec:
        migration_collective(block, int(n_workers), src=0,
                             dst=int(n_workers) - 1)
    findings = shard_lint.lint_records(rec.records, fake)
    return [f"{f.rule}: {f.message}" for f in findings]


class PrefillWorker(Engine):
    """An Engine whose compiled surface is prefill-only: a finished
    prefill parks the request as MIGRATING (slot freed for the next
    prompt, pages held as the migration source) instead of entering
    the local decode plane. The decode/verify executables of this
    worker never compile."""

    def __init__(self, *args, **kwargs):
        self.ready: List[Request] = []
        super().__init__(*args, **kwargs)

    def _activate(self, req: Request) -> None:
        i = req.slot
        if i is not None:
            self._slots[i] = None
            req.slot = None
        req.state = MIGRATING
        self._open_span(req, tracing.MIGRATING, kind="pages")
        self.ready.append(req)


class DecodeWorker(Engine):
    """An Engine whose requests arrive pre-prefilled: admission copies
    the migrated KV block into this worker's pools and drops the
    request straight into a decode slot. The local prefill executables
    only ever run for nothing — the driver routes resume prefills back
    through the prefill fleet."""

    def can_admit(self, n_pages: int) -> bool:
        """True when a migrated request needing ``n_pages`` would be
        admitted right now (free slot + pages above the busy-engine
        watermark) — THE admission predicate, shared by the driver's
        cheap pre-check and ``admit_migrated`` itself so the two can
        never drift."""
        if not any(r is None for r in self._slots):
            return False
        busy = any(r is not None for r in self._slots)
        wm = self.watermark_pages if busy else 0
        return self._alloc.can_alloc(n_pages, wm)

    def admit_migrated(self, req: Request, block, n_pages: int) -> bool:
        """Take a MIGRATING request: allocate ``n_pages`` local pages,
        scatter the ``[max_blocks, ...]`` migrated block into this
        worker's pools at those rows (donated, one fixed-shape
        executable), and activate the slot. False = no slot or no
        pages free right now (the driver keeps the request MIGRATING —
        pages stay safe on the prefill side)."""
        if not self.can_admit(n_pages):
            return False
        slot = next(i for i, r in enumerate(self._slots) if r is None)
        pages = self._alloc.alloc(n_pages, seq=req.req_id)
        idx = np.zeros((self.max_blocks,), np.int32)
        idx[:n_pages] = pages
        self._scatter(block, self._up(idx))
        req.pages = pages
        req.shared_pages = None
        req.prefix_len = 0
        req.slot = slot
        self._slots[slot] = req
        self.requests[req.req_id] = req
        Engine._activate(self, req)
        # the pipelined step() dispatches decode FIRST (its
        # _ensure_pages pass runs post-harvest), so a slot activated
        # between steps must get its first write position covered NOW
        # — a migrated prompt that exactly fills its pages would
        # otherwise write token one into the scratch page
        self._ensure_pages()
        return True

    def _scatter_body(self):
        def body(pools, blk, rows):
            return jax.tree_util.tree_map(
                lambda p, r: p.at[rows].set(r.astype(p.dtype)),
                pools, blk)
        return body

    def _scatter(self, block, idx):
        """Write a migrated block into the pools at rows ``idx`` —
        pad entries point at row 0, the scratch page garbage may
        land in harmlessly. ONE executable (fixed [max_blocks]
        shape) however many pages migrate."""
        fn = getattr(self, "_scatter_fn", None)
        if fn is None:
            fn = jax.jit(self._scatter_body(), donate_argnums=(0,))
            self._scatter_fn = fn
        tgt, drf = block
        self._pools = fn(self._pools, tgt, idx)
        if self._spec is not None and drf is not None:
            self._spec._pools = fn(self._spec._pools, drf, idx)
        return self._pools

    def _hotpath_inventory(self):
        """Engine's inventory plus the migration scatter: destination
        pools donated (argnum 0), the incoming block is consumed but
        smaller than the pools, nothing fetched."""
        from ..analysis import hotpath_lint as hp
        inv = Engine._hotpath_inventory(self)
        pools = hp.struct_of(self._pools)
        blk = jax.tree_util.tree_map(
            lambda l: jax.ShapeDtypeStruct(
                (self.max_blocks,) + tuple(l.shape[1:]), l.dtype),
            pools)
        inv.executables.append(hp.ExecutableSpec(
            name="scatter", body=self._scatter_body(),
            args=(pools, blk,
                  jax.ShapeDtypeStruct((self.max_blocks,), np.int32)),
            donate=(0,), fetched=(), per_tick=False))
        inv.tick_functions.extend([self.admit_migrated, self._scatter])
        return inv


class DisaggEngine:
    """Disaggregated serving driver: N prefill workers + M decode
    workers as independent compiled surfaces, KV pages migrating
    between them, one multi-tenant front door.

        eng = DisaggEngine(model, prefill_workers=2, decode_workers=2,
                           max_slots=4, page_size=8, pool_pages=64)
        rid = eng.add_request(ids, SamplingParams(max_new_tokens=32),
                              tenant="team-a")
        for tok in eng.stream(rid):
            ...
        # or drive it like the single-loop engine:
        outs = eng.run([(ids_a, pa), (ids_b, pb)])

    Geometry (page_size / prefill_bucket / max_context / cache_dtype /
    spec_k) is shared by every worker — the migration block shapes
    depend on it. ``max_slots`` / ``pool_pages`` size each DECODE
    worker; ``prefill_slots`` / ``prefill_pool_pages`` size each
    prefill worker (defaults mirror the decode side)."""

    def __init__(self, model, prefill_workers: int = 1,
                 decode_workers: int = 1, max_slots: int = 8,
                 page_size: int = 16,
                 pool_pages: Optional[int] = None,
                 prefill_slots: Optional[int] = None,
                 prefill_pool_pages: Optional[int] = None,
                 cache_dtype: str = "auto",
                 max_context: Optional[int] = None,
                 prefill_bucket: int = 32,
                 watermark_pages: Optional[int] = None,
                 prefix_cache: bool = False,
                 draft_model=None, spec_k: int = 4,
                 clock=None, fault_injector=None,
                 max_prefill_tokens_per_step: Optional[int] = None,
                 multi_tick: int = 1):
        if int(prefill_workers) < 1 or int(decode_workers) < 1:
            raise ValueError(
                f"need at least one worker of each kind, got "
                f"prefill_workers={prefill_workers} "
                f"decode_workers={decode_workers}")
        self.model = model
        self.label = "disagg"
        self._clock = clock if clock is not None else time.perf_counter
        # same arming contract as Engine (reliability.py): an explicit
        # FaultInjector, None = arm from FLAGS_serving_fault_* (ONE
        # injector shared by the driver and every worker, so the whole
        # fleet's chaos schedule replays from one seed), False = force
        # OFF even when the flags arm the process
        if fault_injector is False:
            self._injector = None
        elif fault_injector is None:
            from .reliability import injector_from_flags
            self._injector = injector_from_flags()
        else:
            self._injector = fault_injector
        common = dict(page_size=page_size, cache_dtype=cache_dtype,
                      max_context=max_context,
                      prefill_bucket=prefill_bucket,
                      watermark_pages=watermark_pages,
                      draft_model=draft_model, spec_k=spec_k,
                      clock=self._clock,
                      fault_injector=(self._injector
                                      if self._injector is not None
                                      else False))
        self.prefill: List[Optional[PrefillWorker]] = [
            PrefillWorker(
                model, max_slots=(prefill_slots or max_slots),
                pool_pages=(prefill_pool_pages
                            if prefill_pool_pages is not None
                            else pool_pages),
                prefix_cache=prefix_cache,
                max_prefill_tokens_per_step=max_prefill_tokens_per_step,
                label=f"prefill{i}", **common)
            for i in range(int(prefill_workers))]
        # only DECODE workers fuse ticks — prefill workers never run
        # the decode loop, so multi_tick would be dead weight there
        self.decode: List[Optional[DecodeWorker]] = [
            DecodeWorker(model, max_slots=max_slots,
                         pool_pages=pool_pages, prefix_cache=False,
                         multi_tick=multi_tick,
                         label=f"decode{i}", **common)
            for i in range(int(decode_workers))]
        w0 = self.decode[0]
        self.page_size = w0.page_size
        self.max_blocks = w0.max_blocks
        self.max_context = w0.max_context
        self.prefill_bucket = w0.prefill_bucket
        self.cache_dtype = w0.cache_dtype
        self._lookahead = w0._lookahead
        for w in self.prefill:
            if w.max_blocks != self.max_blocks:
                raise RuntimeError(
                    "prefill/decode worker page geometry diverged "
                    f"({w.max_blocks} vs {self.max_blocks} blocks) — "
                    "migration blocks must be shape-identical")
        # front door: per-tenant FIFO queues, round-robin dispatch;
        # re-admissions (preemption sweep-backs, worker deaths) go to
        # _resume, serviced first — they carry partial progress
        self._queues: Dict[str, deque] = {}
        self._rr: deque = deque()
        self._resume: deque = deque()
        self._ready: List[Tuple[PrefillWorker, Request]] = []
        self.requests: Dict[int, Request] = {}
        self._tenant: Dict[int, str] = {}
        # DRIVER-side arrival order (req_id -> monotone seq): the one
        # ordering migration priority, parked-victim selection and
        # failover re-admission sort by. req.admit_seq is NOT usable
        # here — each prefill worker's slot admission overwrites it
        # with that worker's LOCAL counter, so cross-worker comparisons
        # of admit_seq would shuffle genuinely-older requests behind
        # younger ones on less-loaded workers.
        self._order: Dict[int, int] = {}
        self._next_id = 0
        self._admit_counter = 0
        self._steps = 0
        self._outputs: Dict[int, Output] = {}
        self._gather_fns: Dict[int, object] = {}
        self._routes: set = set()
        self._stream_cursor: Dict[int, int] = {}
        self._tracker = CompileTracker().start()
        self._compiles = 0
        self._warm_compiles = 0
        # per-worker utilization accounting (the replay tool's
        # per-worker report): steps the worker did real work
        self.worker_stats: Dict[str, Dict[str, int]] = {}
        for kind, fleet in (("prefill", self.prefill),
                            ("decode", self.decode)):
            for i in range(len(fleet)):
                self.worker_stats[f"{kind}{i}"] = {
                    "busy_steps": 0, "steps": 0, "migrations": 0,
                    "pages_migrated": 0}

    @classmethod
    def from_plan(cls, model, plan, **overrides) -> "DisaggEngine":
        """Build a disaggregated engine from a planner serving plan
        (``analysis.planner.plan_serving`` output, or any dict with
        ``prefill_workers``/``decode_workers``). ``decode_mp`` is the
        planner's answer to "how should decode workers shard?" — it
        takes effect through the ambient mp mesh (install the plan's
        mesh with ``jax.set_mesh`` before constructing; the workers
        commit kv-head-sharded pools against it exactly as in the
        TP-sharded decode path, docs/SERVING.md)."""
        kw = dict(prefill_workers=int(plan.get("prefill_workers", 1)),
                  decode_workers=int(plan.get("decode_workers", 1)))
        kw.update(overrides)
        eng = cls(model, **kw)
        eng.plan = dict(plan)
        return eng

    # -- front door ----------------------------------------------------------

    def add_request(self, ids, sampling_params=None,
                    tenant: str = "default") -> int:
        """Queue a prompt under ``tenant``'s share of the dispatch.
        Returns immediately with the request id — tokens stream out of
        ``stream(rid)`` / ``astream(rid)`` as later ``step()``s produce
        them, and the finished Output surfaces from ``step()`` like the
        single-loop engine's."""
        params = sampling_params or SamplingParams()
        if isinstance(params, dict):
            params = SamplingParams(**params)
        params.validate()
        prompt = _normalize_prompt(ids)
        rid = self._next_id
        need = len(prompt) + int(params.max_new_tokens)
        cap = self.max_blocks * self.page_size - (self._lookahead - 1)
        if self._pbucket(need) > cap:
            raise ValueError(
                f"request {rid} needs {need} token slots, beyond the "
                f"engine's max_context capacity {cap}")
        # decode-side lifetime demand: every written token plus the
        # per-tick write lookahead must fit ONE decode worker's pool
        worst = _ceil_div(need - 1 + self._lookahead, self.page_size)
        pool = min(w.pool_pages for w in self.decode if w is not None)
        if worst > pool:
            raise RuntimeError(
                f"request {rid} can never be scheduled: it needs up to "
                f"{worst} page(s) but the smallest decode worker pool "
                f"has {pool}")
        # prefill-side: the deepest resume prefix must fit too
        pworst = _ceil_div(max(len(prompt), need - 2), self.page_size)
        ppool = min(w.pool_pages for w in self.prefill if w is not None)
        if pworst > ppool:
            raise RuntimeError(
                f"request {rid} can never be prefilled: its prefix "
                f"needs up to {pworst} page(s) but the smallest "
                f"prefill worker pool has {ppool}")
        req = Request(req_id=rid, prompt=prompt, params=params,
                      arrival_t=self._clock(), queued_step=self._steps)
        req.key = np.asarray(jax.random.PRNGKey(int(params.seed)),
                             np.uint32)
        tracing.open_span(req.spans, tracing.QUEUED,
                          req.arrival_t * 1e3, self.label)
        self._next_id += 1
        self.requests[rid] = req
        self._tenant[rid] = str(tenant)
        self._order[rid] = len(self._order)
        q = self._queues.get(str(tenant))
        if q is None:
            q = self._queues[str(tenant)] = deque()
            self._rr.append(str(tenant))
        q.append(req)
        monitor.counter("serving.requests").increase()
        return rid

    def cancel(self, req_id: int) -> Optional[Output]:
        """Abort a request at any lifecycle point (queued, prefilling,
        migrating, decoding): pages freed on whichever worker holds
        them, the partial Output returned."""
        req = self.requests.get(int(req_id))
        if req is None or req.state in (FINISHED, FAILED):
            return None
        # live on a worker: the worker's own cancel path frees the
        # pages (a MIGRATING request is still in its prefill worker's
        # requests dict, so this covers it too — the parked entry just
        # needs purging from the migration list)
        for fleet in (self.prefill, self.decode):
            for w in fleet:
                if w is not None and req.req_id in w.requests:
                    out = w.cancel(req.req_id)
                    if out is not None:
                        self._ready = [(pw, r) for pw, r in self._ready
                                       if r.req_id != req.req_id]
                        self._retired(out)
                        return out
        self._drop_from_queues(req)
        # same counter pair Engine.cancel emits (cancelled AND the
        # terminal-FAILED count): the metrics must not depend on where
        # in the pipeline the request happened to be when cancelled
        monitor.counter("serving.cancelled").increase()
        monitor.counter("serving.failed").increase()
        req.state = FAILED
        req.finish_reason = "cancelled"
        req.finish_t = self._clock()
        out = self._make_output(req, "cancelled", failed=True)
        self._retired(out)
        return out

    def stream(self, req_id: int):
        """Synchronous streaming iterator: yields tokens for ``rid``
        as engine ticks produce them, driving ``step()`` itself while
        the request is unfinished."""
        rid = int(req_id)
        while True:
            tok, done = self._stream_poll(rid)
            for t in tok:
                yield t
            if done:
                return
            if not tok:
                self.step()

    async def astream(self, req_id: int):
        """Async streaming iterator — the awaitable front door. Yields
        tokens as they decode and control between ticks, so many
        consumers interleave over one event loop; whichever consumer
        observes a stalled stream drives the next ``step()``."""
        import asyncio
        rid = int(req_id)
        while True:
            tok, done = self._stream_poll(rid)
            for t in tok:
                yield t
                await asyncio.sleep(0)
            if done:
                return
            if not tok:
                self.step()
                await asyncio.sleep(0)

    def _stream_poll(self, rid: int) -> Tuple[List[int], bool]:
        cur = self._stream_cursor.get(rid, 0)
        out = self._outputs.get(rid)
        if out is not None:
            toks = out.token_ids[cur:]
            # stream drained: drop this consumer's cursor (the Output
            # itself stays until the retention cap evicts it)
            self._stream_cursor.pop(rid, None)
            return toks, True
        req = self.requests.get(rid)
        if req is None:
            raise KeyError(f"unknown request id {rid}")
        toks = list(req.generated[cur:])
        self._stream_cursor[rid] = cur + len(toks)
        return toks, False

    # -- driver loop ---------------------------------------------------------

    def step(self) -> List[Output]:
        """One driver tick: chaos, deadline sweep over driver-held
        requests, tenant-fair dispatch to prefill workers, prefill
        steps, page migration, decode steps, preemption sweep-back.
        Returns every request that finished or failed this tick."""
        outs: List[Output] = []
        self._maybe_chaos()
        outs.extend(self._expire())
        self._dispatch()
        for i, w in enumerate(self.prefill):
            if w is None:
                continue
            busy = (w.num_prefilling > 0 or w.num_waiting > 0
                    or any(r is not None for r in w._slots))
            for out in w.step():
                self._retired(out)
                outs.append(out)
            st = self.worker_stats[f"prefill{i}"]
            st["steps"] += 1
            st["busy_steps"] += int(busy)
            for req in w.ready:
                self._ready.append((w, req))
            w.ready.clear()
        # driver-surface compile accounting: only the migration
        # section compiles driver-owned executables (the gather/
        # scatter pair per worker plus one redistribution program per
        # (src, dst) route — all bounded by the topology); a step that
        # first exercises a new worker or route folds its compiles
        # into warmup, anything after that is a genuine recompile.
        # Worker-step compiles are the workers' own accounting.
        c0 = self._tracker.compiles
        sig0 = self._surface_sig()
        self._migrate()
        self._compiles += self._tracker.compiles - c0
        if self._surface_sig() != sig0:
            self._warm_compiles = self._compiles
        for i, w in enumerate(self.decode):
            if w is None:
                continue
            busy = w.num_active > 0
            for out in w.step():
                self._retired(out)
                outs.append(out)
            st = self.worker_stats[f"decode{i}"]
            st["steps"] += 1
            st["busy_steps"] += int(busy)
            # sweep preempted requests back to the driver: their
            # resume prefill belongs on the prefill fleet, not on
            # this worker's (never-used) prefill surface
            while w._waiting:
                req = w._waiting.popleft()
                w.requests.pop(req.req_id, None)
                req.queued_step = self._steps
                self._resume.append(req)
                monitor.counter("serving.disagg.readmitted").increase()
        self._relieve_prefill_pressure()
        self._steps += 1
        self._publish_gauges()
        return outs

    def run(self, requests: Sequence, max_steps: int = 100_000
            ) -> List[Output]:
        """Offline driver: queue every (ids, SamplingParams) pair, step
        until all finish. Returns Outputs ordered by request id."""
        want = set()
        for item in requests:
            if isinstance(item, (tuple, list)) and len(item) == 2 and \
                    isinstance(item[1], (SamplingParams, dict)):
                want.add(self.add_request(item[0], item[1]))
            else:
                want.add(self.add_request(item))
        outs: List[Output] = []
        for _ in range(max_steps):
            outs.extend(o for o in self.step() if o.req_id in want)
            if len(outs) == len(want):
                break
        else:
            raise RuntimeError(
                f"disagg engine did not drain in {max_steps} steps "
                f"({len(outs)}/{len(want)} finished)")
        return sorted(outs, key=lambda o: o.req_id)

    # -- scheduling internals ------------------------------------------------

    def _pbucket(self, n: int) -> int:
        return _ceil_div(n, self.prefill_bucket) * self.prefill_bucket

    def _surface_sig(self) -> Tuple[int, int, int]:
        """The driver's compiled-surface inventory — growth marks a
        legitimate warmup step for steady_state_recompiles."""
        return (len(self._gather_fns),
                sum(1 for f in (self.prefill + self.decode)
                    if f is not None and hasattr(f, "_scatter_fn")),
                len(self._routes))

    def _expire(self) -> List[Output]:
        """Deadline/queue-budget sweep over DRIVER-held requests
        (queued or migrating; workers sweep their own live ones)."""
        outs: List[Output] = []
        now = self._clock()
        held = [r for q in self._queues.values() for r in q]
        held += list(self._resume)
        held += [r for _, r in self._ready]
        for req in held:
            if req.state in (FINISHED, FAILED):
                continue     # retired elsewhere, entry not yet purged
            p = req.params
            reason = None
            if p.deadline_ms is not None and \
                    (now - req.arrival_t) * 1e3 > float(p.deadline_ms):
                reason = "deadline"
            elif p.max_queue_steps is not None and \
                    req.state in (WAITING, PREEMPTED) and \
                    self._steps - req.queued_step \
                    > int(p.max_queue_steps):
                reason = "queue_timeout"
            if reason is None:
                continue
            monitor.counter("serving.timeouts").increase()
            for i, (pw, r) in enumerate(list(self._ready)):
                if r is req:
                    pw._alloc.free(req.pages)
                    pw.requests.pop(req.req_id, None)
                    req.pages = []
                    del self._ready[i]
                    break
            self._drop_from_queues(req)
            req.state = FAILED
            req.finish_reason = reason
            req.finish_t = now
            monitor.counter("serving.failed").increase()
            out = self._make_output(req, reason, failed=True)
            self._retired(out)
            outs.append(out)
        return outs

    def _next_candidate(self) -> Optional[Request]:
        if self._resume:
            return self._resume.popleft()
        for _ in range(len(self._rr)):
            tenant = self._rr[0]
            self._rr.rotate(-1)
            q = self._queues.get(tenant)
            if q:
                return q.popleft()
        return None

    def _dispatch(self) -> None:
        """Tenant-fair dispatch: hand queued requests to prefill
        workers with free slots, one per tenant per turn (resume
        re-admissions first). Stops when no worker can take more."""
        while True:
            targets = [w for w in self.prefill
                       if w is not None and
                       any(r is None for r in w._slots)
                       and len(w._waiting) == 0]
            if not targets:
                return
            req = self._next_candidate()
            if req is None:
                return
            # least-loaded prefill worker: most free pages breaks
            # slot-count ties (migrating backlogs show up as held pages)
            w = max(targets,
                    key=lambda x: (sum(1 for r in x._slots if r is None),
                                   x._alloc.free_pages))
            req.queued_step = w._steps
            req.admit_seq = self._admit_counter
            self._admit_counter += 1
            w.requests[req.req_id] = req
            w._waiting.append(req)

    def _gather_body(self):
        def body(pools, rows):
            return jax.tree_util.tree_map(lambda p: p[rows], pools)
        return body

    def _gather(self, w: Engine, pages: List[int]):
        """Pull a request's page rows out of worker ``w``'s pools
        (target + draft) as one fixed-shape ``[max_blocks, ...]``
        block. One executable per worker; pad rows gather the scratch
        page."""
        idx = np.zeros((self.max_blocks,), np.int32)
        idx[:len(pages)] = pages
        fn = self._gather_fns.get(id(w))
        if fn is None:
            fn = jax.jit(self._gather_body())
            self._gather_fns[id(w)] = fn
        tgt = fn(w._pools, w._up(idx))
        drf = (fn(w._spec._pools, w._up(idx))
               if w._spec is not None else None)
        return (tgt, drf)

    # -- hot-path lint (docs/ANALYSIS.md "Hot-path rules") -------------------

    def _hotpath_inventory(self):
        """The DRIVER surface only: one gather executable per live
        worker (a READ — the source pools live on and the output block
        is smaller than any pool, so no donation is wanted) plus the
        driver's dispatch/migration tick path. The workers are full
        Engines and are swept separately by inspect_hotpath()."""
        from ..analysis import hotpath_lint as hp
        specs = []
        for kind, workers in (("p", self.prefill), ("d", self.decode)):
            for i, w in enumerate(workers):
                if w is None:
                    continue
                specs.append(hp.ExecutableSpec(
                    name=f"gather[{kind}{i}]", body=self._gather_body(),
                    args=(hp.struct_of(w._pools),
                          jax.ShapeDtypeStruct((self.max_blocks,),
                                               np.int32)),
                    donate=(), fetched=(), per_tick=False))
        return hp.HotpathInventory(
            subject="DisaggEngine[driver]", executables=specs,
            tick_functions=[self.step, self._expire, self._dispatch,
                            self._gather, self._migrate,
                            self._relieve_prefill_pressure],
            steady_functions=(),
            cache_keys={"_gather_fns": list(self._gather_fns)},
            file=__file__)

    def inspect_hotpath(self):
        """Hot-path audit over the whole disaggregated surface: the
        driver inventory plus every live prefill/decode worker's
        Engine inventory, one combined Report through the
        ``lint.hotpath.*`` counters."""
        from ..analysis import hotpath_lint
        report = hotpath_lint.lint_inventory(self._hotpath_inventory())
        for w in list(self.prefill) + list(self.decode):
            if w is not None:
                report.extend(hotpath_lint.lint_inventory(
                    w._hotpath_inventory()))
        return hotpath_lint.emit_hotpath(report)

    def _migrate(self) -> None:
        """Move every migration-ready request whose KV fits a decode
        worker: gather the page block from the prefill pool, run the
        recorded redistribution collective, scatter into the decode
        pool, free the prefill-side references (prefix-cache-shared
        pages live on under the cache's refs), activate the slot."""
        if not self._ready:
            return
        still: List[Tuple[PrefillWorker, Request]] = []
        # the worker AXIS is the fleet topology (killed workers keep
        # their coordinate — a real mesh does not renumber on failure)
        n_workers = len(self.prefill) + len(self.decode)
        for pw, req in sorted(
                self._ready,
                key=lambda e: self._order.get(e[1].req_id, 10**9)):
            if req.state != MIGRATING:
                continue     # cancelled/expired while parked
            # restamp with the DRIVER's global order before the
            # request enters a decode worker: the prefill worker's
            # slot admission overwrote admit_seq with its local
            # counter, and the decode worker's preempt-youngest
            # victim choice (max admit_seq across ITS slots) must
            # compare one global sequence, not per-worker ones
            req.admit_seq = self._order.get(req.req_id,
                                            req.admit_seq)
            n_pages = len(req.pages)
            targets = [(i, w) for i, w in enumerate(self.decode)
                       if w is not None]
            targets.sort(key=lambda e: (-sum(
                1 for r in e[1]._slots if r is None),
                -e[1]._alloc.free_pages))
            admitted = False
            src_block = None
            for di, dw in targets:
                # cheap capacity pre-check: a back-pressured tick must
                # not pay the gather + redistribution device copies
                # (or record a route) for an admission that will refuse
                if not dw.can_admit(n_pages):
                    continue
                if src_block is None:
                    src_block = self._gather(pw, req.pages)
                src_i = self.prefill.index(pw)
                block = migration_collective(
                    src_block, n_workers, src=src_i,
                    dst=len(self.prefill) + di)
                src_pages = req.pages
                if dw.admit_migrated(req, block, n_pages):
                    self._routes.add((src_i, len(self.prefill) + di))
                    pw._alloc.free(src_pages)
                    pw.requests.pop(req.req_id, None)
                    monitor.counter("serving.migrated_pages").increase(
                        n_pages)
                    monitor.counter(
                        "serving.disagg.migrations").increase()
                    pi = self.prefill.index(pw)
                    self.worker_stats[f"prefill{pi}"][
                        "pages_migrated"] += n_pages
                    self.worker_stats[f"decode{di}"]["migrations"] += 1
                    self.worker_stats[f"decode{di}"][
                        "pages_migrated"] += n_pages
                    admitted = True
                    break
            if not admitted:
                still.append((pw, req))
        self._ready = still

    def preempt_migrating(self, req_id: int) -> bool:
        """Mid-migration preemption: drop a MIGRATING request's
        prefill-side pages and requeue it at the resume front — the
        same tokens come out after its re-prefill (the rng chain never
        advanced while parked). The driver calls this under prefill
        pool pressure; tests exercise it directly."""
        for i, (pw, req) in enumerate(list(self._ready)):
            if req.req_id == int(req_id):
                pw._alloc.free(req.pages)
                pw.requests.pop(req.req_id, None)
                req.pages = []
                req.shared_pages = None
                req.prefix_len = 0
                req.written = 0
                req.preemptions += 1
                req.state = PREEMPTED if req.generated else WAITING
                req.queued_step = self._steps
                # aborted migration: the MIGRATING span closes without
                # a latency record (it never completed)
                tracing.open_span(req.spans, tracing.PREEMPTED,
                                  self._clock() * 1e3, self.label,
                                  kind="migration")
                del self._ready[i]
                self._resume.appendleft(req)
                monitor.counter("serving.preemptions").increase()
                monitor.counter(
                    "serving.disagg.migration_preempts").increase()
                return True
        return False

    def _relieve_prefill_pressure(self) -> None:
        """A prefill worker starved for pages while migration-ready
        requests sit parked (decode fleet full) preempts the YOUNGEST
        parked request — pages freed now, the request re-prefills once
        decode capacity returns. Without this the pool can wedge:
        every page held by parked requests nobody can admit."""
        for w in self.prefill:
            if w is None or not w._waiting:
                continue
            if w._alloc.free_pages * w.page_size >= w.prefill_bucket:
                continue
            parked = [r for pw, r in self._ready if pw is w]
            if parked:
                victim = max(parked, key=lambda r: self._order.get(
                    r.req_id, -1))
                self.preempt_migrating(victim.req_id)

    # -- chaos / worker death ------------------------------------------------

    def _maybe_chaos(self) -> None:
        if self._injector is None:
            return
        self._injector.on_step(self._steps)
        for kind, fleet in (("prefill", self.prefill),
                            ("decode", self.decode)):
            site = f"worker.die_{kind}"
            if not self._injector.fire(site, record=False):
                continue
            alive = [i for i, w in enumerate(fleet) if w is not None]
            if len(alive) <= 1:
                continue    # never kill the last worker of a kind
            self._injector.record(site)
            victim = alive[int(
                self._injector.rng.integers(0, len(alive)))]
            self.kill_worker(kind, victim)

    def kill_worker(self, kind: str, index: int) -> int:
        """Drop a worker wholesale — pools, allocator, device state,
        no goodbye — and re-admit every request that lived there from
        the host source of truth (prompt + emitted tokens + the
        replayed rng chain; the dead device is never read). Returns
        the number of requests re-admitted. The last worker of a kind
        cannot be killed (the fleet must still serve)."""
        if kind not in ("prefill", "decode"):
            raise ValueError(
                f"kill_worker kind must be 'prefill' or 'decode', "
                f"got {kind!r}")
        fleet = self.prefill if kind == "prefill" else self.decode
        index = int(index)
        if not 0 <= index < len(fleet):
            raise ValueError(
                f"kill_worker index {index} out of range for "
                f"{len(fleet)} {kind} worker(s)")
        w = fleet[index]
        if w is None:
            return 0
        if sum(1 for x in fleet if x is not None) <= 1:
            raise RuntimeError(
                f"cannot kill the last {kind} worker — the fleet "
                f"must keep serving")
        monitor.counter("serving.disagg.worker_kills").increase()
        # requests parked for migration out of this worker die with
        # their pages; the host truth re-prefills them elsewhere
        doomed: Dict[int, Request] = {}
        still: List[Tuple[PrefillWorker, Request]] = []
        for pw, req in self._ready:
            if pw is w:
                doomed[req.req_id] = req
            else:
                still.append((pw, req))
        self._ready = still
        for r in w.requests.values():
            if r.state not in (FINISHED, FAILED):
                doomed.setdefault(r.req_id, r)
        n = 0
        now_ms = self._clock() * 1e3
        zero_progress: List[Request] = []
        for req in sorted(doomed.values(), key=lambda r: (
                self._order.get(r.req_id, 10**9), r.req_id)):
            req.slot = None
            req.pages = []
            req.shared_pages = None
            req.prefix_len = 0
            req.written = 0
            req.preemptions += 1
            req.key = replay_rng_key(req.params.seed,
                                     len(req.generated),
                                     req.params.temperature)
            req.state = PREEMPTED if req.generated else WAITING
            tracing.open_span(
                req.spans,
                tracing.PREEMPTED if req.generated else tracing.QUEUED,
                now_ms, self.label, kind="failover")
            req.queued_step = self._steps
            if req.generated:
                # partial progress earns the resume fast lane
                self._resume.append(req)
            else:
                # a dispatched-but-unstarted request holds nothing —
                # it rejoins ITS TENANT's queue (front, it is the
                # tenant's oldest), not the fast lane: failover must
                # not let a flooding tenant's fresh requests jump
                # other tenants' older work
                zero_progress.append(req)
            monitor.counter("serving.disagg.readmitted").increase()
            n += 1
        for req in reversed(zero_progress):
            tenant = self._tenant.get(req.req_id, "default")
            q = self._queues.get(tenant)
            if q is None:
                q = self._queues[tenant] = deque()
                self._rr.append(tenant)
            q.appendleft(req)
        w.close()
        fleet[index] = None
        return n

    # -- reliability surfaces ------------------------------------------------

    def snapshot(self) -> dict:
        """Crash-exact host-state snapshot of the whole disaggregated
        fleet — every queued / prefilling / MIGRATING / decoding
        request's host source of truth. Rng chains are REPLAYED from
        (seed, emitted tokens), never fetched from a device, so the
        same path serves live snapshots and post-mortem ones."""
        from dataclasses import asdict
        entries = []
        seen = set()
        reqs = []
        for fleet in (self.decode, self.prefill):
            for w in fleet:
                if w is None:
                    continue
                reqs.extend(r for r in w.requests.values()
                            if r.state not in (FINISHED, FAILED))
        reqs.extend(r for _, r in self._ready)
        reqs.extend(self._resume)
        for q in self._queues.values():
            reqs.extend(q)
        reqs.sort(key=lambda r: (self._order.get(r.req_id, 10**9),
                                 r.req_id))
        now = self._clock()
        for req in reqs:
            if req.req_id in seen:
                continue
            seen.add(req.req_id)
            entries.append({
                "req_id": int(req.req_id),
                "prompt": [int(t) for t in req.prompt],
                "generated": [int(t) for t in req.generated],
                "params": asdict(req.params),
                "tenant": self._tenant.get(req.req_id, "default"),
                "preemptions": int(req.preemptions),
                "elapsed_ms": (now - req.arrival_t) * 1e3,
                "spans": tracing.copy_spans(req.spans),
            })
        monitor.counter("serving.snapshot_saves").increase()
        return {
            "version": DISAGG_SNAPSHOT_VERSION,
            "kind": "disagg",
            "topology": {
                "prefill_workers": len(self.prefill),
                "decode_workers": len(self.decode),
            },
            "fingerprint": self._fingerprint(),
            "next_id": int(self._next_id),
            "admit_counter": int(self._admit_counter),
            "requests": entries,
        }

    def restore(self, snap: dict) -> int:
        """Re-admit a snapshot's requests into this (fresh) driver:
        requests with emitted tokens resume through the prefill fleet
        with their replayed rng chains, untouched ones queue under
        their tenant — outputs bit-identical to the uninterrupted
        run. Worker topology may differ (scheduling changes, tokens
        do not)."""
        if snap.get("kind") != "disagg" or \
                snap.get("version") != DISAGG_SNAPSHOT_VERSION:
            raise ValueError(
                f"not a disagg snapshot (kind={snap.get('kind')!r} "
                f"version={snap.get('version')!r})")
        if self.requests:
            raise RuntimeError(
                "restore onto a busy driver: "
                f"{len(self.requests)} live request(s) present")
        fp = self._fingerprint()
        saved = snap.get("fingerprint", {})
        diff = {k: (saved.get(k), v) for k, v in fp.items()
                if saved.get(k) != v}
        if diff:
            raise ValueError(
                f"snapshot is token-incompatible with this engine: "
                f"{diff} (saved vs current)")
        n = 0
        for ent in snap["requests"]:
            params = SamplingParams(**ent["params"])
            req = Request(
                req_id=int(ent["req_id"]),
                prompt=[int(t) for t in ent["prompt"]],
                params=params,
                state=PREEMPTED if ent["generated"] else WAITING,
                generated=[int(t) for t in ent["generated"]],
                preemptions=int(ent.get("preemptions", 0)),
                arrival_t=self._clock()
                - float(ent.get("elapsed_ms", 0.0)) / 1e3,
                queued_step=self._steps)
            req.key = replay_rng_key(params.seed, len(req.generated),
                                     params.temperature)
            req.spans = tracing.restore_spans(
                ent.get("spans"), req.arrival_t * 1e3,
                self._clock() * 1e3, self.label, bool(req.generated))
            tenant = str(ent.get("tenant", "default"))
            self.requests[req.req_id] = req
            self._tenant[req.req_id] = tenant
            self._order[req.req_id] = len(self._order)
            if req.generated:
                self._resume.append(req)
            else:
                q = self._queues.get(tenant)
                if q is None:
                    q = self._queues[tenant] = deque()
                    self._rr.append(tenant)
                q.append(req)
            n += 1
        self._next_id = max(self._next_id, int(snap.get("next_id", 0)))
        self._admit_counter = max(self._admit_counter,
                                  int(snap.get("admit_counter", 0)))
        monitor.counter("serving.snapshot_restores").increase()
        return n

    def _fingerprint(self) -> Dict[str, object]:
        cfg = self.model.config
        # spec_k from any LIVE decode worker — worker 0 may be a
        # killed slot (None), and a post-worker-death snapshot is
        # exactly the crash-recovery artifact this signature protects
        live = next(w for w in self.decode if w is not None)
        return {
            "vocab_size": int(cfg.vocab_size),
            "num_hidden_layers": int(cfg.num_hidden_layers),
            "hidden_size": int(cfg.hidden_size),
            "num_attention_heads": int(cfg.num_attention_heads),
            "num_key_value_heads": int(cfg.num_key_value_heads),
            "cache_dtype": str(np.dtype(self.cache_dtype).name),
            "spec_k": (int(live._spec.k)
                       if live._spec is not None else 0),
        }

    def leaked_pages(self) -> int:
        """Fleet-wide drained-engine leak check (Engine.leaked_pages
        per live worker — dead workers' pools died with them)."""
        return sum(w.leaked_pages()
                   for fleet in (self.prefill, self.decode)
                   for w in fleet if w is not None)

    def check_invariants(self, repair: bool = False) -> List[str]:
        findings: List[str] = []
        for kind, fleet in (("prefill", self.prefill),
                            ("decode", self.decode)):
            for i, w in enumerate(fleet):
                if w is None:
                    continue
                findings += [f"{kind}{i}: {f}"
                             for f in w.check_invariants(repair=repair)]
        return findings

    def steady_state_recompiles(self) -> int:
        """Per-worker compiled surfaces must stay fixed: the sum of
        every live worker's steady-state recompiles plus the driver's
        own (migration gather/scatter executables compile once)."""
        own = self._compiles - self._warm_compiles
        return own + sum(
            w.steady_state_recompiles()
            for fleet in (self.prefill, self.decode)
            for w in fleet if w is not None)

    def close(self):
        self._tracker.stop()
        for fleet in (self.prefill, self.decode):
            for w in fleet:
                if w is not None:
                    w.close()

    def __del__(self):
        try:
            self._tracker.stop()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass

    # -- bookkeeping ---------------------------------------------------------

    def _drop_from_queues(self, req: Request) -> None:
        for q in self._queues.values():
            try:
                q.remove(req)
            except ValueError:
                pass
        try:
            self._resume.remove(req)
        except ValueError:
            pass
        for fleet in (self.prefill, self.decode):
            for w in fleet:
                if w is not None and req.req_id in w.requests \
                        and req.slot is None and not req.pages:
                    w.requests.pop(req.req_id, None)
                    try:
                        w._waiting.remove(req)
                    except ValueError:
                        pass

    def _make_output(self, req: Request, reason: str,
                     failed: bool) -> Output:
        n = len(req.generated)
        got_first = req.first_token_t > 0.0
        ttft = ((req.first_token_t - req.arrival_t) * 1e3
                if got_first else 0.0)
        tpot = ((req.finish_t - req.first_token_t) / (n - 1) * 1e3
                if got_first and n > 1 else 0.0)
        tracing.seal(req.spans,
                     tracing.FAILED if failed else tracing.FINISHED,
                     req.finish_t * 1e3, self.label,
                     reason=reason if failed else None)
        return Output(req_id=req.req_id, prompt_ids=list(req.prompt),
                      token_ids=list(req.generated),
                      finish_reason=reason, ttft_ms=ttft, tpot_ms=tpot,
                      preemptions=req.preemptions,
                      error=reason if failed else None,
                      spans=tracing.copy_spans(req.spans))

    #: retired Outputs kept for late/streaming readers; beyond this
    #: many the OLDEST are evicted (a long-running server must not
    #: grow host memory per request served — step()'s return value is
    #: the durable delivery path)
    MAX_RETAINED_OUTPUTS = 4096

    def _retired(self, out: Output) -> None:
        self._outputs[out.req_id] = out
        self.requests.pop(out.req_id, None)
        tenant = self._tenant.pop(out.req_id, None)
        self._order.pop(out.req_id, None)
        # prune a drained tenant's queue + round-robin slot: unique
        # per-user tenant ids must not grow dispatch state forever
        # (add_request recreates both on the tenant's next request)
        q = self._queues.get(tenant)
        if q is not None and not q:
            del self._queues[tenant]
            try:
                self._rr.remove(tenant)
            except ValueError:
                pass
        while len(self._outputs) > self.MAX_RETAINED_OUTPUTS:
            oldest = next(iter(self._outputs))
            self._outputs.pop(oldest)
            self._stream_cursor.pop(oldest, None)

    def _publish_gauges(self):
        monitor.gauge("serving.disagg.queue_depth").set(
            self.num_waiting)
        monitor.gauge("serving.disagg.migrating").set(len(self._ready))
        for kind, fleet in (("prefill", self.prefill),
                            ("decode", self.decode)):
            for i, w in enumerate(fleet):
                if w is None:
                    continue
                monitor.gauge(
                    f"serving.disagg.{kind}{i}.slots_active").set(
                    sum(1 for r in w._slots if r is not None))
                monitor.gauge(
                    f"serving.disagg.{kind}{i}.pages_free").set(
                    w._alloc.free_pages)

    # -- introspection -------------------------------------------------------

    @property
    def num_waiting(self) -> int:
        return (sum(len(q) for q in self._queues.values())
                + len(self._resume))

    @property
    def num_migrating(self) -> int:
        return len(self._ready)

    @property
    def num_active(self) -> int:
        return sum(w.num_active for w in self.decode if w is not None)

    @property
    def num_prefilling(self) -> int:
        return sum(
            sum(1 for r in w._slots if r is not None)
            for w in self.prefill if w is not None)

    @property
    def idle(self) -> bool:
        return (self.num_waiting == 0 and self.num_active == 0
                and self.num_prefilling == 0
                and self.num_migrating == 0)

    @property
    def pages_free(self) -> Dict[str, int]:
        return {f"{kind}{i}": w._alloc.free_pages
                for kind, fleet in (("prefill", self.prefill),
                                    ("decode", self.decode))
                for i, w in enumerate(fleet) if w is not None}

    @property
    def prefix_hit_rate(self) -> float:
        rates = [w.prefix_hit_rate for w in self.prefill
                 if w is not None and w._prefix is not None]
        return float(np.mean(rates)) if rates else 0.0

    @property
    def spec_accept_rate(self) -> float:
        drafted = sum(w._spec_drafted for w in self.decode
                      if w is not None)
        accepted = sum(w._spec_accepted for w in self.decode
                       if w is not None)
        return accepted / drafted if drafted else 0.0

    @property
    def pallas_eligible(self) -> bool:
        """True when every decode worker's page geometry admits the
        Pallas paged-decode kernel (validated once per worker at
        construction, docs/DECODE.md)."""
        return all(w.pallas_eligible for w in self.decode
                   if w is not None)

    @property
    def decode_fallback_reason(self) -> Optional[str]:
        for w in self.decode:
            if w is not None and w.decode_fallback_reason:
                return w.decode_fallback_reason
        return None

    def utilization(self) -> Dict[str, Dict[str, object]]:
        """Per-worker utilization snapshot for the replay report:
        busy-step fraction, migrations, pages migrated; dead workers
        report as ``alive: False``."""
        out: Dict[str, Dict[str, object]] = {}
        for kind, fleet in (("prefill", self.prefill),
                            ("decode", self.decode)):
            for i, w in enumerate(fleet):
                st = self.worker_stats[f"{kind}{i}"]
                out[f"{kind}{i}"] = {
                    "alive": w is not None,
                    "utilization": round(
                        st["busy_steps"] / max(st["steps"], 1), 4),
                    "migrations": st["migrations"],
                    "pages_migrated": st["pages_migrated"],
                }
        return out
