"""paddle.inference parity surface.

Reference: paddle/fluid/inference (AnalysisPredictor,
api/analysis_predictor.h:105 — load program+params, run IR optimization,
zero-copy input/output handles). TPU-native: the artifact is the
jit.save StableHLO module + param archive; "analysis passes" are XLA's
compilation, and the predictor runs the deserialized executable with
donated buffers. API mirrors paddle_infer: Config, create_predictor,
get_input_names/get_input_handle/run/get_output_names.
"""
from __future__ import annotations

import numpy as np

from ..core.dispatch import unwrap, wrap


class Config:
    """Reference: paddle_infer.Config(model_file, params_file)."""

    def __init__(self, prog_file=None, params_file=None,
                 model_dir=None):
        if model_dir is not None and prog_file is None:
            # paddle_infer semantics: the directory contains the artifact
            import glob
            import os
            models = sorted(glob.glob(os.path.join(model_dir,
                                                   "*.pdmodel")))
            if not models:
                raise FileNotFoundError(
                    f"no .pdmodel artifact under {model_dir}")
            prog_file = models[0]
        # accept either the jit.save prefix or explicit file paths
        self.prefix = (prog_file[:-len(".pdmodel")]
                       if prog_file and prog_file.endswith(".pdmodel")
                       else prog_file)
        self._ir_optim = True
        self._memory_optim = True

    # reference-shaped knobs: XLA already does both, keep as metadata
    def switch_ir_optim(self, flag=True):
        self._ir_optim = flag

    def enable_memory_optim(self, flag=True):
        self._memory_optim = flag

    def enable_use_gpu(self, *a, **kw):
        pass  # device selection is PJRT's job on TPU

    def set_cpu_math_library_num_threads(self, n):
        pass


class _Handle:
    """Zero-copy-style tensor handle (reference ZeroCopyTensor)."""

    def __init__(self):
        self._value = None

    def copy_from_cpu(self, arr):
        self._value = np.ascontiguousarray(arr)

    def reshape(self, shape):
        if self._value is not None:
            self._value = self._value.reshape(shape)

    def copy_to_cpu(self):
        return np.asarray(self._value)

    def shape(self):
        return list(np.asarray(self._value).shape)


class Predictor:
    def __init__(self, config: Config):
        from ..jit.serialization import load as jit_load
        self._layer = jit_load(config.prefix)
        n_in = getattr(self._layer, "num_inputs", 1)
        self._input_names = [f"input_{i}" for i in range(n_in)]
        self._inputs = {n: _Handle() for n in self._input_names}
        self._outputs = []

    def get_input_names(self):
        return list(self._input_names)

    def get_input_handle(self, name) -> _Handle:
        return self._inputs[name]

    def run(self, inputs=None):
        """Either positional arrays (returned directly, paddle_infer's
        list API) or via input handles."""
        if inputs is not None:
            args = [wrap(np.asarray(a)) if not hasattr(a, "_data") else a
                    for a in inputs]
        else:
            args = [wrap(self._inputs[n].copy_to_cpu())
                    for n in self._input_names]
        out = self._layer(*args)
        outs = out if isinstance(out, (list, tuple)) else [out]
        self._outputs = [np.asarray(unwrap(o)) for o in outs]
        if inputs is not None:
            return self._outputs
        return True

    def get_output_names(self):
        return [f"output_{i}" for i in range(len(self._outputs))]

    def get_output_handle(self, name) -> _Handle:
        i = int(name.split("_")[-1])
        h = _Handle()
        h.copy_from_cpu(self._outputs[i])
        return h


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)
