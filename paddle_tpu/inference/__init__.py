"""paddle.inference parity surface.

Reference: paddle/fluid/inference (AnalysisPredictor,
api/analysis_predictor.h:105 — load program+params, run IR optimization,
zero-copy input/output handles). TPU-native: the artifact is the
jit.save StableHLO module + param archive; "analysis passes" are XLA's
compilation, and the predictor runs the deserialized executable with
donated buffers. API mirrors paddle_infer: Config, create_predictor,
get_input_names/get_input_handle/run/get_output_names.
"""
from __future__ import annotations

import numpy as np

from ..core.dispatch import unwrap, wrap


class Config:
    """Reference: paddle_infer.Config(model_file, params_file)."""

    def __init__(self, prog_file=None, params_file=None,
                 model_dir=None):
        if model_dir is not None and prog_file is None:
            # paddle_infer semantics: the directory contains the artifact
            import glob
            import os
            models = sorted(glob.glob(os.path.join(model_dir,
                                                   "*.pdmodel")))
            if not models:
                raise FileNotFoundError(
                    f"no .pdmodel artifact under {model_dir}")
            prog_file = models[0]
        # accept either the jit.save prefix or explicit file paths
        self.prefix = (prog_file[:-len(".pdmodel")]
                       if prog_file and prog_file.endswith(".pdmodel")
                       else prog_file)
        self._ir_optim = True
        self._memory_optim = True

    # reference-shaped knobs: XLA already does both, keep as metadata
    def switch_ir_optim(self, flag=True):
        self._ir_optim = flag

    def enable_memory_optim(self, flag=True):
        self._memory_optim = flag

    def enable_use_gpu(self, *a, **kw):
        pass  # device selection is PJRT's job on TPU

    def set_cpu_math_library_num_threads(self, n):
        pass


class _Handle:
    """Zero-copy-style tensor handle (reference ZeroCopyTensor)."""

    def __init__(self):
        self._value = None

    def copy_from_cpu(self, arr):
        self._value = np.ascontiguousarray(arr)

    def reshape(self, shape):
        if self._value is not None:
            self._value = self._value.reshape(shape)

    def copy_to_cpu(self):
        return np.asarray(self._value)

    def shape(self):
        return list(np.asarray(self._value).shape)


class Predictor:
    def __init__(self, config: Config):
        from ..jit.serialization import load as jit_load
        self._layer = jit_load(config.prefix)
        n_in = getattr(self._layer, "num_inputs", 1)
        self._input_names = [f"input_{i}" for i in range(n_in)]
        self._inputs = {n: _Handle() for n in self._input_names}
        self._outputs = []

    def get_input_names(self):
        return list(self._input_names)

    def get_input_handle(self, name) -> _Handle:
        return self._inputs[name]

    def run(self, inputs=None):
        """Either positional arrays (returned directly, paddle_infer's
        list API) or via input handles."""
        if inputs is not None:
            args = [wrap(np.asarray(a)) if not hasattr(a, "_data") else a
                    for a in inputs]
        else:
            args = [wrap(self._inputs[n].copy_to_cpu())
                    for n in self._input_names]
        out = self._layer(*args)
        outs = out if isinstance(out, (list, tuple)) else [out]
        self._outputs = [np.asarray(unwrap(o)) for o in outs]
        if inputs is not None:
            return self._outputs
        return True

    def get_output_names(self):
        return [f"output_{i}" for i in range(len(self._outputs))]

    def get_output_handle(self, name) -> _Handle:
        i = int(name.split("_")[-1])
        h = _Handle()
        h.copy_from_cpu(self._outputs[i])
        return h


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


# -- compat surface (reference: paddle/inference/__init__.py) ----------------

import enum as _enum


class DataType(_enum.Enum):
    """(reference: inference.DataType)"""

    FLOAT32 = 0
    FLOAT16 = 1
    INT64 = 2
    INT32 = 3
    UINT8 = 4
    INT8 = 5
    BOOL = 6
    BFLOAT16 = 7


class PlaceType(_enum.Enum):
    """(reference: inference.PlaceType; TPU rides the custom slot)"""

    UNK = -1
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM = 3


class PrecisionType(_enum.Enum):
    """(reference: inference.PrecisionType)"""

    Float32 = 0
    Half = 1
    Int8 = 2
    Bfloat16 = 3


class XpuConfig:
    """Config bag (reference: inference.XpuConfig); no XPU backend in
    PJRT here — carried for config-file compat."""

    def __init__(self):
        self.device_id = 0
        self.l3_size = 0


class Tensor:
    """Predictor IO tensor handle (reference: inference.Tensor): the
    copy_from_cpu/copy_to_cpu view over a device array."""

    def __init__(self, data=None):
        self._data = data

    def copy_from_cpu(self, arr):
        import jax.numpy as jnp
        self._data = jnp.asarray(arr)

    def copy_to_cpu(self):
        import numpy as np
        return np.asarray(self._data)

    def shape(self):
        return list(self._data.shape) if self._data is not None else []

    def reshape(self, shape):
        self._data = self._data.reshape(shape)


class PredictorPool:
    """N independent predictors over one config (reference:
    inference.PredictorPool)."""

    def __init__(self, config, size=1):
        self._predictors = [create_predictor(config)
                            for _ in range(int(size))]

    def retrive(self, idx):  # reference spells it 'retrive'
        return self._predictors[idx]

    retrieve = retrive


def get_version():
    """(reference: inference.get_version)"""
    from ..version import full_version
    return f"paddle_tpu inference {full_version}"


def get_num_bytes_of_data_type(dtype):
    sizes = {DataType.FLOAT32: 4, DataType.FLOAT16: 2, DataType.INT64: 8,
             DataType.INT32: 4, DataType.UINT8: 1, DataType.INT8: 1,
             DataType.BOOL: 1, DataType.BFLOAT16: 2}
    return sizes[dtype]


def get_trt_compile_version():
    """No TensorRT in the XLA stack (reference returns the linked TRT
    version); (0, 0, 0) is the reference's not-compiled answer."""
    return (0, 0, 0)


def get_trt_runtime_version():
    return (0, 0, 0)


def convert_to_mixed_precision(model_file, params_file, mixed_model_file,
                               mixed_params_file, mixed_precision,
                               backend=None, keep_io_types=True,
                               black_list=None, **kw):
    """Convert a saved StableHLO artifact's params to half precision
    (reference: convert_to_mixed_precision rewrites the program; here
    the params archive is re-saved cast, and jit re-traces in the low
    dtype at load)."""
    import numpy as np

    import paddle_tpu as paddle
    state = paddle.load(params_file)
    want = "bfloat16" if str(getattr(mixed_precision, "name",
                                     mixed_precision)).lower().startswith(
        ("bf", "bfloat")) else "float16"
    out = {}
    for k, v in state.items():
        arr = v.numpy() if hasattr(v, "numpy") else np.asarray(v)
        if arr.dtype in (np.float32, np.float64):
            arr = paddle.to_tensor(arr).astype(want).numpy()
        out[k] = arr
    paddle.save(out, mixed_params_file)
    import shutil
    shutil.copy(model_file, mixed_model_file)


def _get_phi_kernel_name(op_name):
    """(reference: maps fluid op name -> phi kernel name; ops here keep
    one name)"""
    return op_name


# -- serving engine (continuous batching over the paged KV stack) -----------
# Lazy re-exports (PEP 562): the engine pulls in the text model stack,
# which must not load during `paddle_tpu` package init (this module is
# imported early for the Predictor parity surface).

_ENGINE_EXPORTS = ("Engine", "SamplingParams", "Output", "Request")


_RELIABILITY_EXPORTS = ("FaultInjector", "FaultPlan", "InjectedFault",
                        "FAULT_SITES", "save_snapshot", "load_snapshot")


def __getattr__(name):
    if name in _ENGINE_EXPORTS:
        from . import engine as _engine
        return getattr(_engine, name)
    if name in _RELIABILITY_EXPORTS:
        from . import reliability as _reliability
        return getattr(_reliability, name)
    if name in ("BatchEncoder", "EmbedParams", "EmbedOutput"):
        from . import encoder as _encoder
        return getattr(_encoder, name)
    if name == "PageAllocator":
        from .allocator import PageAllocator
        return PageAllocator
    if name == "PrefixCache":
        from .prefix_cache import PrefixCache
        return PrefixCache
    if name == "SpeculativeDecoder":
        from .speculative import SpeculativeDecoder
        return SpeculativeDecoder
    if name == "DisaggEngine":
        from .disagg import DisaggEngine
        return DisaggEngine
    if name in ("ServingFleet", "AutoscalePolicy"):
        from . import fleet as _fleet
        return getattr(_fleet, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
