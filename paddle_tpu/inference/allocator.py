"""Paged-KV block allocator — the free list under the serving engine.

Reference capability: the block manager behind PaddleNLP's
block_multihead_attention serving cache (and vLLM's BlockAllocator):
KV memory is a global pool of fixed-size pages; each sequence owns an
ordered list of page ids recorded in its block-table row, pages are
handed out as sequences grow and returned the moment a sequence
finishes (EOS or budget) — not at the end of the serving call.

Pages are REFCOUNTED: the prefix cache (inference/prefix_cache.py)
maps one physical page into many requests' block tables, so ``free``
only returns a page to the free list when its last reference drops.
A page's content is immutable while shared — writers fork a private
copy first (the engine's copy-on-write rule; docs/SERVING.md) — so
refcounting is pure host bookkeeping, never a device copy.

This is pure host-side bookkeeping (python ints in a deque); the pool
arrays themselves live in kernels/paged_attention.py's head-major
layout and are updated functionally inside the compiled steps. Both
the serving engine (inference/engine.py) and the one-shot
``generate(cache_impl="paged")`` path allocate through here, so pool
exhaustion is ONE loud RuntimeError naming the pool geometry and the
requesting sequence — never a clipped page index silently overwriting
another sequence's tokens.
"""
from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional


class PageAllocator:
    """FIFO free list over ``num_pages`` page ids starting at ``base``.

    ``base=1`` is the serving engine's convention: page 0 is the shared
    scratch page every inactive slot's block-table row points at, so
    masked lanes of the fixed-shape decode step write garbage somewhere
    harmless instead of into a live sequence.
    """

    def __init__(self, num_pages: int, base: int = 0):
        if num_pages < 1:
            raise ValueError(f"num_pages must be >= 1, got {num_pages}")
        self.num_pages = int(num_pages)
        self.base = int(base)
        self._free = deque(range(self.base, self.base + self.num_pages))
        self._owner: Dict[int, Optional[object]] = {}
        self._refs: Dict[int, int] = {}

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def live_pages(self) -> int:
        return self.num_pages - len(self._free)

    @property
    def shared_pages(self) -> int:
        """Pages held by MORE than one reference (prefix-cache sharing).
        Each shared page occupies exactly one pool slot however many
        block tables map it — the admission watermark reads the free
        list, so a would-be-shared prefix never inflates apparent
        pool pressure."""
        return sum(1 for r in self._refs.values() if r > 1)

    def can_alloc(self, n: int, watermark: int = 0) -> bool:
        """True when ``n`` pages fit while leaving ``watermark`` pages
        free — the admission-control check: headroom for RUNNING
        sequences to grow before a new one is let in."""
        return len(self._free) - int(watermark) >= int(n)

    def alloc(self, n: int, seq=None) -> List[int]:
        """Hand out ``n`` page ids (oldest-freed first) with refcount 1,
        owned by ``seq``. Raises RuntimeError naming the pool geometry
        when the pool can't cover the request — the caller either
        preempts a sequence (or evicts idle prefix-cache pages) and
        retries, or surfaces the error."""
        n = int(n)
        if n > len(self._free):
            raise RuntimeError(
                f"paged KV pool exhausted: sequence {seq!r} requested "
                f"{n} page(s) but only {len(self._free)} of "
                f"{self.num_pages} are free ({self.live_pages} live) — "
                f"grow pool_pages, lower max_slots, or let the "
                f"scheduler preempt")
        pages = [self._free.popleft() for _ in range(n)]
        for p in pages:
            self._owner[p] = seq
            self._refs[p] = 1
        return pages

    def share(self, page: int) -> int:
        """Take one more reference on a live page (prefix-cache hit:
        the page is mapped into another block table without a copy).
        Returns the page id; sharing a dead page fails loudly."""
        page = int(page)
        if page not in self._refs:
            raise RuntimeError(
                f"sharing page {page} that is not live — the prefix "
                f"cache may only map allocated pages")
        self._refs[page] += 1
        return page

    def refcount(self, page: int) -> int:
        return self._refs.get(int(page), 0)

    def free(self, pages) -> None:
        """Drop one reference per page; a page returns to the free list
        (EOS/finish/preemption/eviction time — not end-of-call) only
        when its LAST reference drops. Over-frees and foreign ids fail
        loudly: both corrupt the pool silently if let through."""
        for p in pages:
            p = int(p)
            if p not in self._refs:
                lo, hi = self.base, self.base + self.num_pages
                raise RuntimeError(
                    f"freeing page {p} that is not live (pool ids "
                    f"[{lo}, {hi}), {self.live_pages} live) — "
                    f"double-free or foreign page id")
            self._refs[p] -= 1
            if self._refs[p] == 0:
                del self._refs[p]
                del self._owner[p]
                self._free.append(p)

    def owner(self, page: int):
        return self._owner.get(int(page))

    def check_invariants(self,
                         expected: Optional[Dict[int, int]] = None,
                         repair: bool = False) -> List[str]:
        """Free-list / live / refcount consistency audit. Returns one
        human-readable finding per violation (empty list = healthy);
        with ``repair=True`` each finding is also FIXED in place (the
        chaos-recovery path: after injected refcount skew the pool must
        converge back to balanced, not wedge).

        Internal invariants (always checked): every page id is either
        on the free list or refcounted, never both and never neither;
        free ids are unique and in range; the owner map tracks exactly
        the live pages; no live refcount is below 1.

        ``expected`` adds the CALLER's cross-check: a map of page id →
        the number of references the caller can account for (the
        engine builds it from live requests' block-table pages plus
        one per prefix-cache entry). A live page nobody accounts for
        is a LEAK; a refcount above/below the accounted holders is
        REFCOUNT SKEW — the failure modes a lost ``free`` or a stray
        ``share`` produce, invisible to the internal checks because
        the allocator's own books still balance.
        """
        findings: List[str] = []
        lo, hi = self.base, self.base + self.num_pages
        free_list = list(self._free)
        free_set = set(free_list)
        if len(free_list) != len(free_set):
            findings.append(
                f"free list holds duplicate ids "
                f"({len(free_list)} entries, {len(free_set)} unique) — "
                f"double-free let through")
            if repair:
                self._free = deque(dict.fromkeys(free_list))
                free_list = list(self._free)
        bad_range = [p for p in free_set if not lo <= p < hi]
        if bad_range:
            findings.append(
                f"free list holds out-of-range ids {sorted(bad_range)} "
                f"(pool ids [{lo}, {hi}))")
            if repair:
                self._free = deque(p for p in self._free
                                   if lo <= p < hi)
        both = free_set & set(self._refs)
        if both:
            findings.append(
                f"pages {sorted(both)} are BOTH free and refcounted — "
                f"the next alloc would alias a live sequence")
            if repair:
                self._free = deque(p for p in self._free
                                   if p not in both)
        if set(self._owner) != set(self._refs):
            extra = sorted(set(self._owner) - set(self._refs))
            missing = sorted(set(self._refs) - set(self._owner))
            findings.append(
                f"owner/refcount maps diverge (owner-only {extra}, "
                f"refs-only {missing})")
            if repair:
                for p in extra:
                    del self._owner[p]
                for p in missing:
                    self._owner[p] = None
        nonpos = {p: r for p, r in self._refs.items() if r < 1}
        if nonpos:
            findings.append(
                f"live pages with refcount < 1: {nonpos}")
            if repair:
                for p in nonpos:
                    del self._refs[p]
                    self._owner.pop(p, None)
                    self._free.append(p)
        free_now = set(self._free)      # repairs above may have
        lost = [p for p in range(lo, hi)  # mutated the free list
                if p not in self._refs and p not in free_now]
        if lost:
            findings.append(
                f"pages {lost} vanished from both the free list and "
                f"the refcount map")
            if repair:
                self._free.extend(lost)
        if expected is not None:
            for p, refs in sorted(self._refs.items()):
                want = int(expected.get(p, 0))
                if want == 0:
                    findings.append(
                        f"leaked page {p}: refcount {refs} but no "
                        f"request or cache entry holds it")
                    if repair:
                        self.free([p] * refs)
                elif refs != want:
                    findings.append(
                        f"refcount skew on page {p}: allocator has "
                        f"{refs}, holders account for {want}")
                    if repair:
                        if refs > want:
                            self.free([p] * (refs - want))
                        else:
                            for _ in range(want - refs):
                                self.share(p)
            orphans = sorted(p for p, n in expected.items()
                             if n > 0 and p not in self._refs)
            if orphans:
                findings.append(
                    f"pages {orphans} are mapped by a request or "
                    f"cache entry but not live in the allocator — "
                    f"their next reuse aliases foreign KV")
                if repair:
                    free_now = set(self._free)
                    for p in orphans:
                        if p in free_now:
                            self._free.remove(p)
                            free_now.discard(p)
                        self._owner[p] = None
                        self._refs[p] = int(expected[p])
        return findings

    def stats(self) -> Dict[str, object]:
        """Pool state snapshot for admission decisions and the
        ``serving.prefix_pages_shared`` gauge: free/live/shared page
        counts plus a refcount histogram ({refcount: pages}) — a
        healthy prefix-heavy pool shows a tall bucket at the hot
        system prompt's share count."""
        hist: Dict[int, int] = {}
        for r in self._refs.values():
            hist[r] = hist.get(r, 0) + 1
        return {
            "num_pages": self.num_pages,
            "free": self.free_pages,
            "live": self.live_pages,
            "shared": self.shared_pages,
            "refcount_hist": dict(sorted(hist.items())),
        }

    def __repr__(self):
        return (f"PageAllocator({self.live_pages} live / "
                f"{self.num_pages} pages, {self.shared_pages} shared, "
                f"base={self.base})")
