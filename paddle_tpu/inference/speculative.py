"""Draft/verify speculative decoding on the serving engine.

Every output token normally costs one full target-model decode step.
With a small draft model (the zoo's small LLaMA runs ~25x the 1B's
decode rate), the engine instead runs ``k`` cheap draft steps plus ONE
target forward that scores all ``k + 1`` positions at once — the
multi-position paths PR 5 built for continuous batching (per-slot
``cache_index`` arrays, ``[b, s, L]`` cache masks, chunked
``paged_write``) make the verify step just another fixed-shape call.
Acceptance is EXACT-MATCH against the target's own sampling chain
(``generation.verify_token_arrays``): a drafted token is kept only
when it equals the token the target would have emitted with the same
per-request rng key, so the engine's output with a draft attached is
bit-identical to the engine without one — the token-exactness harness
is the acceptance oracle, and each tick emits between 1 and k+1
tokens instead of exactly 1.

Compiled-surface discipline (the JaxPP rule every engine feature
follows): the whole draft loop is ONE executable (a ``lax.scan`` of
k+1 draft steps over the draft's own paged cache), and verify is one
``[max_slots, k+1]`` executable per static sampler variant — no
recompiles whatever the accept/reject trace.

Cache bookkeeping: the draft model's paged KV pools mirror the
engine's geometry EXACTLY — same page size, same page count, same
block tables — so one allocator and one prefix cache govern both
models: a page id handed to a request addresses its chunk in both
pools, a shared prefix page carries both models' KV for those tokens,
and preemption/eviction stay single-bookkeeping. Rejected positions
simply hold stale KV above each sequence's valid length; the next
tick's writes start exactly at the valid length, so stale slots are
overwritten before they could ever be attended (causal masking hides
them meanwhile).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..jit.functional import get_buffers, get_frozen, get_params
from ..text.generation import _model_forward


class SpeculativeDecoder:
    """The draft side of the engine's draft/verify schedule.

    Owns the draft model's functional state and paged KV pools, and
    the two draft executable families: bucketed prefill (mirrors the
    target prefill's cache writes — no sampling, the head matmul is
    dead code XLA drops) and the k+1-step draft loop. The target-side
    verify executable lives in the engine (it is a variant of the
    decode step over the target model).
    """

    def __init__(self, engine, draft_model, k: int):
        import inspect
        if int(k) < 1:
            raise ValueError(f"spec_k must be >= 1, got {k}")
        try:
            fsig = inspect.signature(draft_model.forward)
        except (TypeError, ValueError):
            fsig = None
        if fsig is None or "kv_caches" not in fsig.parameters:
            raise ValueError(
                "speculative decoding needs a draft model with "
                "kv_caches/cache_index forward kwargs; "
                f"{type(draft_model).__name__}.forward has none")
        dcfg = draft_model.config
        tcfg = engine.model.config
        if int(dcfg.vocab_size) != int(tcfg.vocab_size):
            raise ValueError(
                f"draft vocab ({dcfg.vocab_size}) must match the "
                f"target vocab ({tcfg.vocab_size}) — drafted ids are "
                f"verified against target logits position-for-position")
        if int(dcfg.max_position_embeddings) < engine.max_context:
            raise ValueError(
                f"draft max_position_embeddings "
                f"({dcfg.max_position_embeddings}) is shorter than the "
                f"engine max_context ({engine.max_context})")
        self.engine = engine
        self.model = draft_model
        self.k = int(k)
        self._st = (get_params(draft_model), get_buffers(draft_model),
                    get_frozen(draft_model))
        from .engine import _make_paged_pools
        hkv = dcfg.num_key_value_heads
        hd = dcfg.hidden_size // dcfg.num_attention_heads
        self._pools = engine._commit_pools(_make_paged_pools(
            dcfg.num_hidden_layers, engine.pool_pages + 1, hkv,
            engine.page_size, hd, engine.cache_dtype, engine._quant),
            hkv)
        self._prefill_fns = {}
        self._loop_fn = None

    # -- compiled surfaces ---------------------------------------------------

    def _get_prefill_fn(self, pb: int):
        """Draft prefill for a target prefill bucket: identical cache
        writes (chunk at a traced per-call start offset) so the draft
        pools track the target pools position-for-position; no token
        is sampled — only the KV side effects matter."""
        fn = self._prefill_fns.get(pb)
        if fn is not None:
            return fn
        fn = jax.jit(self._prefill_body(), donate_argnums=(1,))
        self._prefill_fns[pb] = fn
        self.engine._note_compile()
        return fn

    def _prefill_body(self):
        eng = self.engine
        model = self.model

        def body(st, caches, bt_row, prompt, start):
            kv = eng._inject_bt(caches, bt_row)
            _, new_kv = _model_forward(model, st, prompt, kv, start)
            return eng._strip_bt(new_kv)

        return body

    def _get_loop_fn(self):
        """The k+1-step draft loop, ONE executable: step j feeds the
        newest token at its slot position, writes draft KV, and argmax
        proposes the next. k proposals come out; the extra (k+1)-th
        step writes the LAST proposal's KV so the draft cache stays
        position-complete through a fully accepted tick (its output
        token is discarded). Greedy drafting is deterministic and
        consumes no rng — the draft only ever influences WHICH
        positions verify accepts, never what tokens the target emits."""
        if self._loop_fn is not None:
            return self._loop_fn
        fn = jax.jit(self._loop_body(), donate_argnums=(1,))
        self._loop_fn = fn
        self.engine._note_compile()
        return fn

    def _loop_body(self):
        eng = self.engine
        model = self.model
        k = self.k

        def body(st, caches, bt, last, pos, live):
            def step(carry, _):
                tok, kv, p = carry
                idx = jnp.where(live > 0, p, -jnp.ones_like(p))
                kvb = eng._inject_bt(kv, bt)
                logits, new_kv = _model_forward(model, st, tok[:, None],
                                                kvb, idx)
                nxt = jnp.argmax(logits[:, -1].astype(jnp.float32),
                                 axis=-1).astype(jnp.int32)
                return (nxt, eng._strip_bt(new_kv), p + live), nxt

            (_, caches, _), toks = jax.lax.scan(
                step, (last, caches, pos), None, length=k + 1)
            # toks[j] = proposal from step j; the (k+1)-th is the
            # write-only step's by-product — dropped
            return jnp.swapaxes(toks, 0, 1)[:, :k], caches

        return body

    def hotpath_specs(self):
        """The draft executables in hotpath_lint's inventory terms:
        both donate the draft caches (argnum 1) and fetch NOTHING —
        proposals feed the verify executable device-side."""
        from ..analysis import hotpath_lint as hp
        eng = self.engine
        S, MB = eng.max_slots, eng.max_blocks

        def i32(*shape):
            return jax.ShapeDtypeStruct(shape, jnp.int32)

        st = hp.struct_of(self._st)
        pools = hp.struct_of(self._pools)
        specs = []
        for pb in tuple(sorted(self._prefill_fns)) \
                or (eng.prefill_bucket,):
            specs.append(hp.ExecutableSpec(
                name=f"draft-prefill[{pb}]", body=self._prefill_body(),
                args=(st, pools, i32(1, MB), i32(1, pb), i32(1)),
                donate=(1,), fetched=(), per_tick=False))
        specs.append(hp.ExecutableSpec(
            name=f"draft-loop[k={self.k}]", body=self._loop_body(),
            args=(st, pools, i32(S, MB), i32(S), i32(S), i32(S)),
            donate=(1,), fetched=()))
        return specs

    # -- engine hooks --------------------------------------------------------

    def prefill(self, pb: int, bt_row, prompt, start) -> None:
        """Mirror one target prefill into the draft pools (same bucket,
        same block-table row, same traced start offset)."""
        fn = self._get_prefill_fn(pb)
        self._pools = fn(self._st, self._pools, bt_row, prompt, start)

    def draft(self, bt, last, pos, live):
        """Propose k tokens per slot from the device-resident decode
        state; returns drafts [max_slots, k] (device array — it feeds
        the verify executable without a host round trip)."""
        fn = self._get_loop_fn()
        drafts, self._pools = fn(self._st, self._pools, bt, last, pos,
                                 live)
        return drafts

    def sabotage(self, drafts):
        """Deterministically corrupt a drafted chunk (the engine's
        ``spec.disagree`` fault point): every proposal is shifted to a
        DIFFERENT in-vocab token, simulating a draft/target divergence
        storm. Exact-match verification then rejects (almost) all of
        them — the emitted stream must stay bit-identical to the
        draft-free engine, each tick just shrinks toward 1 token. Host
        numpy only: no new executable, so chaos ticks stay inside the
        zero-recompile contract."""
        vocab = int(self.model.config.vocab_size)
        arr = np.asarray(drafts)
        return jnp.asarray((arr + 1) % vocab)
