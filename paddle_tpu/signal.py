"""paddle.signal parity surface (reference python/paddle/signal.py:
stft/istft over the frame/overlap_add kernels)."""
from __future__ import annotations

import jax.numpy as jnp

from .core.dispatch import run_op, unwrap
from .ops.manipulation import frame as _frame
from .ops.manipulation import overlap_add as _overlap_add


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    """Short-time Fourier transform (reference signal.py stft)."""
    hop = hop_length or n_fft // 4
    wl = win_length or n_fft
    win = unwrap(window) if window is not None else jnp.ones(wl)

    def fn(a):
        v = a
        if center:
            pad = n_fft // 2
            v = jnp.pad(v, [(0, 0)] * (v.ndim - 1) + [(pad, pad)],
                        mode=pad_mode)
        n = v.shape[-1]
        num = 1 + (n - n_fft) // hop
        starts = jnp.arange(num) * hop
        idx = starts[:, None] + jnp.arange(n_fft)[None, :]
        frames = v[..., idx]                      # [..., num, n_fft]
        # window centered in the n_fft buffer (reference/librosa
        # convention), kept in the signal dtype: under x64 a float64
        # window promotes the spectrum to complex128, unsupported on TPU
        off = (n_fft - wl) // 2
        w = jnp.zeros(n_fft, a.dtype).at[off:off + wl].set(
            jnp.asarray(win, a.dtype))
        spec = jnp.fft.rfft(frames * w, axis=-1) if onesided else \
            jnp.fft.fft(frames * w, axis=-1)
        if normalized:
            spec = spec / jnp.sqrt(jnp.sum(w ** 2))
        return jnp.swapaxes(spec, -1, -2)         # [..., freq, num]
    return run_op("stft", fn, [x])


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    hop = hop_length or n_fft // 4
    wl = win_length or n_fft
    win = unwrap(window) if window is not None else jnp.ones(wl)

    def fn(a):
        spec = jnp.swapaxes(a, -1, -2)            # [..., num, freq]
        frames = jnp.fft.irfft(spec, n=n_fft, axis=-1) if onesided else \
            jnp.fft.ifft(spec, axis=-1).real
        off = (n_fft - wl) // 2
        w = jnp.zeros(n_fft, frames.dtype).at[off:off + wl].set(
            jnp.asarray(win, frames.dtype))
        if normalized:
            frames = frames * jnp.sqrt(jnp.sum(w ** 2))
        frames = frames * w
        num = frames.shape[-2]
        out_len = (num - 1) * hop + n_fft
        out = jnp.zeros(frames.shape[:-2] + (out_len,), frames.dtype)
        norm = jnp.zeros(out_len, frames.dtype)
        for i in range(num):
            sl = slice(i * hop, i * hop + n_fft)
            out = out.at[..., sl].add(frames[..., i, :])
            norm = norm.at[sl].add(w ** 2)
        out = out / jnp.maximum(norm, 1e-11)
        if center:
            pad = n_fft // 2
            out = out[..., pad:out_len - pad]
        if length is not None:
            out = out[..., :length]
        return out
    return run_op("istft", fn, [x])
