"""paddle.linalg namespace module (reference: python/paddle/linalg.py).

The implementations live in paddle_tpu.ops.linalg; this module makes
`import paddle.linalg` work as a real module path.
"""
from .ops.linalg import *  # noqa: F401,F403
from .ops.linalg import (  # noqa: F401
    cholesky, cholesky_inverse, cholesky_solve, cond, corrcoef, cov, det,
    eig, eigh, eigvals, eigvalsh,fp8_fp8_half_gemm_fused,
    householder_product, inv, lstsq, lu, lu_unpack, matrix_exp,
    matrix_norm, matrix_power, matrix_rank, matrix_transpose, multi_dot,
    norm, ormqr, pinv, qr, slogdet, solve, svd, svd_lowrank, svdvals,
    triangular_solve, vector_norm,
)
