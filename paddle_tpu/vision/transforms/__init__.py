"""Vision transforms (reference: python/paddle/vision/transforms).

Operate on numpy CHW float arrays (the DataLoader host path); device-side
augmentation belongs in the jit input pipeline.
"""
from __future__ import annotations

import numpy as np


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class BaseTransform:
    def __call__(self, x):
        return self._apply_image(x)


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        a = np.asarray(img, np.float32)
        if a.max() > 1.5:
            a = a / 255.0
        if a.ndim == 2:
            a = a[None]
        elif a.ndim == 3 and a.shape[-1] in (1, 3) and \
                self.data_format == "CHW":
            a = a.transpose(2, 0, 1)
        return a


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        self.mean = np.asarray(mean, np.float32).reshape(-1, 1, 1)
        self.std = np.asarray(std, np.float32).reshape(-1, 1, 1)

    def _apply_image(self, img):
        return (np.asarray(img, np.float32) - self.mean) / self.std


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        c, h, w = img.shape
        oh, ow = self.size
        yi = (np.arange(oh) * (h / oh)).astype(np.int64).clip(0, h - 1)
        xi = (np.arange(ow) * (w / ow)).astype(np.int64).clip(0, w - 1)
        return img[:, yi][:, :, xi]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return img[:, :, ::-1].copy()
        return img


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=0, keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        if self.padding:
            img = np.pad(img, [(0, 0), (self.padding, self.padding),
                               (self.padding, self.padding)])
        c, h, w = img.shape
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return img[:, i:i + th, j:j + tw]


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        c, h, w = img.shape
        th, tw = self.size
        i = (h - th) // 2
        j = (w - tw) // 2
        return img[:, i:i + th, j:j + tw]


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def to_tensor(img, data_format="CHW"):
    return ToTensor(data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


def hflip(img):
    return img[:, :, ::-1].copy()


from . import functional  # noqa: E402
from .functional import (  # noqa: F401,E402
    adjust_brightness, adjust_contrast, adjust_hue, adjust_saturation,
    affine, center_crop, crop, erase, pad, perspective, rotate,
    to_grayscale, vflip,
)


class RandomVerticalFlip(BaseTransform):
    """(reference: transforms.RandomVerticalFlip)"""

    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return vflip(img)
        return img


class Transpose(BaseTransform):
    """HWC->CHW by default (reference: transforms.Transpose)."""

    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = tuple(order)

    def _apply_image(self, img):
        a = np.asarray(img)
        if a.ndim == 2:
            a = a[..., None]
        return a.transpose(self.order)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.padding, self.fill = padding, fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return pad(img, self.padding, self.fill, self.padding_mode)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.num_output_channels)


class BrightnessTransform(BaseTransform):
    """Random brightness in [max(0,1-v), 1+v] (reference:
    transforms.BrightnessTransform)."""

    def __init__(self, value, keys=None):
        if isinstance(value, (tuple, list)):
            self._range = (float(value[0]), float(value[1]))
        else:
            v = float(value)
            self._range = (max(0.0, 1.0 - v), 1.0 + v)

    def _factor(self):
        return np.random.uniform(*self._range)

    def _apply_image(self, img):
        return adjust_brightness(img, self._factor())


class ContrastTransform(BrightnessTransform):
    def _apply_image(self, img):
        return adjust_contrast(img, self._factor())


class SaturationTransform(BrightnessTransform):
    def _apply_image(self, img):
        return adjust_saturation(img, self._factor())


class HueTransform(BaseTransform):
    """Random hue shift in [-v, v], v <= 0.5 (reference: HueTransform)."""

    def __init__(self, value, keys=None):
        if isinstance(value, (tuple, list)):
            lo, hi = float(value[0]), float(value[1])
        else:
            if not 0 <= value <= 0.5:
                raise ValueError("hue value must be in [0, 0.5]")
            lo, hi = -float(value), float(value)
        if not -0.5 <= lo <= hi <= 0.5:
            raise ValueError("hue range must be within [-0.5, 0.5]")
        self._range = (lo, hi)

    def _apply_image(self, img):
        return adjust_hue(img, np.random.uniform(*self._range))


class ColorJitter(BaseTransform):
    """Random brightness/contrast/saturation/hue in random order
    (reference: transforms.ColorJitter)."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        self.transforms = []
        if brightness:
            self.transforms.append(BrightnessTransform(brightness))
        if contrast:
            self.transforms.append(ContrastTransform(contrast))
        if saturation:
            self.transforms.append(SaturationTransform(saturation))
        if hue:
            self.transforms.append(HueTransform(hue))

    def _apply_image(self, img):
        order = np.random.permutation(len(self.transforms))
        for i in order:
            img = self.transforms[i](img)
        return img


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        if isinstance(degrees, (int, float)):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees = tuple(degrees)
        self.interpolation = interpolation
        self.expand = expand
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        angle = np.random.uniform(*self.degrees)
        return rotate(img, angle, self.interpolation, self.expand,
                      self.center, self.fill)


class RandomAffine(BaseTransform):
    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        if isinstance(degrees, (int, float)):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees = tuple(degrees)
        self.translate = translate
        self.scale = scale
        self.shear = shear
        self.interpolation = interpolation
        self.fill = fill
        self.center = center

    def _apply_image(self, img):
        a = np.asarray(img)
        h, w = (a.shape[-2], a.shape[-1])
        angle = np.random.uniform(*self.degrees)
        tx = ty = 0.0
        if self.translate is not None:
            tx = np.random.uniform(-self.translate[0], self.translate[0]) \
                * w
            ty = np.random.uniform(-self.translate[1], self.translate[1]) \
                * h
        sc = np.random.uniform(*self.scale) if self.scale else 1.0
        if self.shear is None:
            sh = (0.0, 0.0)
        elif isinstance(self.shear, (int, float)):
            sh = (np.random.uniform(-self.shear, self.shear), 0.0)
        elif len(self.shear) == 2:
            sh = (np.random.uniform(self.shear[0], self.shear[1]), 0.0)
        else:
            sh = (np.random.uniform(self.shear[0], self.shear[1]),
                  np.random.uniform(self.shear[2], self.shear[3]))
        return affine(img, angle, (tx, ty), sc, sh, self.interpolation,
                      self.fill, self.center)


class RandomPerspective(BaseTransform):
    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0, keys=None):
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.interpolation = interpolation
        self.fill = fill

    def _apply_image(self, img):
        if np.random.rand() >= self.prob:
            return img
        a = np.asarray(img)
        h, w = a.shape[-2], a.shape[-1]
        d = self.distortion_scale
        half_h, half_w = int(h * d / 2), int(w * d / 2)
        def rnd(k):
            return int(np.random.randint(0, max(k, 1)))
        start = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        end = [(rnd(half_w), rnd(half_h)),
               (w - 1 - rnd(half_w), rnd(half_h)),
               (w - 1 - rnd(half_w), h - 1 - rnd(half_h)),
               (rnd(half_w), h - 1 - rnd(half_h))]
        return perspective(img, start, end, self.interpolation, self.fill)


class RandomErasing(BaseTransform):
    """(reference: transforms.RandomErasing)"""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value
        self.inplace = inplace

    def _apply_image(self, img):
        if np.random.rand() >= self.prob:
            return img
        a = np.asarray(img, np.float32)
        if a.ndim == 2:
            a = a[None]
        c, h, w = a.shape
        area = h * w
        for _ in range(10):
            target = np.random.uniform(*self.scale) * area
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            eh = int(round(np.sqrt(target * ar)))
            ew = int(round(np.sqrt(target / ar)))
            if eh < h and ew < w and eh > 0 and ew > 0:
                i = np.random.randint(0, h - eh + 1)
                j = np.random.randint(0, w - ew + 1)
                if isinstance(self.value, str):
                    if self.value != "random":
                        raise ValueError(
                            "value must be a number, a per-channel "
                            "sequence, or 'random'")
                    v = np.random.standard_normal((c, eh, ew)).astype(
                        np.float32)
                elif isinstance(self.value, (tuple, list, np.ndarray)):
                    v = np.asarray(self.value,
                                   np.float32).reshape(-1, 1, 1)
                else:
                    v = self.value
                return erase(a, i, j, eh, ew, v, self.inplace)
        return a


class RandomResizedCrop(BaseTransform):
    """Random area/aspect crop then resize (reference:
    transforms.RandomResizedCrop)."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        a = np.asarray(img, np.float32)
        if a.ndim == 2:
            a = a[None]
        c, h, w = a.shape
        area = h * w
        for _ in range(10):
            target = np.random.uniform(*self.scale) * area
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            ch_ = int(round(np.sqrt(target / ar)))
            cw = int(round(np.sqrt(target * ar)))
            if 0 < ch_ <= h and 0 < cw <= w:
                i = np.random.randint(0, h - ch_ + 1)
                j = np.random.randint(0, w - cw + 1)
                patch = a[:, i:i + ch_, j:j + cw]
                return Resize(self.size, self.interpolation)(patch)
        return Resize(self.size, self.interpolation)(
            CenterCrop(min(h, w))(a))
