"""Vision transforms (reference: python/paddle/vision/transforms).

Operate on numpy CHW float arrays (the DataLoader host path); device-side
augmentation belongs in the jit input pipeline.
"""
from __future__ import annotations

import numpy as np


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class BaseTransform:
    def __call__(self, x):
        return self._apply_image(x)


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        a = np.asarray(img, np.float32)
        if a.max() > 1.5:
            a = a / 255.0
        if a.ndim == 2:
            a = a[None]
        elif a.ndim == 3 and a.shape[-1] in (1, 3) and \
                self.data_format == "CHW":
            a = a.transpose(2, 0, 1)
        return a


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        self.mean = np.asarray(mean, np.float32).reshape(-1, 1, 1)
        self.std = np.asarray(std, np.float32).reshape(-1, 1, 1)

    def _apply_image(self, img):
        return (np.asarray(img, np.float32) - self.mean) / self.std


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        c, h, w = img.shape
        oh, ow = self.size
        yi = (np.arange(oh) * (h / oh)).astype(np.int64).clip(0, h - 1)
        xi = (np.arange(ow) * (w / ow)).astype(np.int64).clip(0, w - 1)
        return img[:, yi][:, :, xi]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return img[:, :, ::-1].copy()
        return img


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=0, keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        if self.padding:
            img = np.pad(img, [(0, 0), (self.padding, self.padding),
                               (self.padding, self.padding)])
        c, h, w = img.shape
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return img[:, i:i + th, j:j + tw]


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        c, h, w = img.shape
        th, tw = self.size
        i = (h - th) // 2
        j = (w - tw) // 2
        return img[:, i:i + th, j:j + tw]


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def to_tensor(img, data_format="CHW"):
    return ToTensor(data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


def hflip(img):
    return img[:, :, ::-1].copy()
