"""Functional image transforms on CHW float numpy arrays (reference:
python/paddle/vision/transforms/functional.py + functional_cv2.py).

Host-side augmentation for the DataLoader path; geometry goes through
scipy.ndimage. Images are CHW (the module's convention, see __init__);
2-D inputs are treated as single-channel.
"""
from __future__ import annotations

import numpy as np


def _chw(img):
    a = np.asarray(img, np.float32)
    if a.ndim == 2:
        a = a[None]
    return a


def vflip(img):
    """Flip vertically (reference: transforms.vflip)."""
    return _chw(img)[:, ::-1, :].copy()


def hflip(img):
    return _chw(img)[:, :, ::-1].copy()


def crop(img, top, left, height, width):
    """Crop region (reference: transforms.crop)."""
    a = _chw(img)
    return a[:, top:top + height, left:left + width].copy()


def center_crop(img, output_size):
    a = _chw(img)
    th, tw = (output_size, output_size) if isinstance(output_size, int) \
        else tuple(output_size)
    _, h, w = a.shape
    i, j = (h - th) // 2, (w - tw) // 2
    return a[:, i:i + th, j:j + tw].copy()


def pad(img, padding, fill=0, padding_mode="constant"):
    """Pad image borders (reference: transforms.pad). padding is int,
    (pad_x, pad_y), or (left, top, right, bottom)."""
    a = _chw(img)
    if isinstance(padding, int):
        l = t = r = b = padding
    elif len(padding) == 2:
        l, t = padding
        r, b = padding
    else:
        l, t, r, b = padding
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    kw = {"constant_values": fill} if mode == "constant" else {}
    return np.pad(a, [(0, 0), (t, b), (l, r)], mode=mode, **kw)


def erase(img, i, j, h, w, v, inplace=False):
    """Erase a region with value v (reference: transforms.erase)."""
    a = _chw(img) if inplace else _chw(img).copy()
    a[:, i:i + h, j:j + w] = v
    return a


def to_grayscale(img, num_output_channels=1):
    """ITU-R 601-2 luma transform (reference: to_grayscale)."""
    a = _chw(img)
    if a.shape[0] == 3:
        gray = (0.299 * a[0] + 0.587 * a[1] + 0.114 * a[2])[None]
    else:
        gray = a[:1]
    if num_output_channels == 3:
        gray = np.repeat(gray, 3, axis=0)
    return gray


def adjust_brightness(img, brightness_factor):
    """Blend with black (reference: adjust_brightness)."""
    return _chw(img) * float(brightness_factor)


def adjust_contrast(img, contrast_factor):
    """Blend with the grayscale mean (reference: adjust_contrast)."""
    a = _chw(img)
    mean = to_grayscale(a).mean()
    return mean + contrast_factor * (a - mean)


def adjust_saturation(img, saturation_factor):
    """Blend with the grayscale image (reference: adjust_saturation)."""
    a = _chw(img)
    gray = to_grayscale(a, num_output_channels=a.shape[0])
    return gray + saturation_factor * (a - gray)


def _rgb_to_hsv(a):
    r, g, b = a[0], a[1], a[2]
    maxc = np.max(a, axis=0)
    minc = np.min(a, axis=0)
    v = maxc
    delta = maxc - minc
    s = np.where(maxc > 0, delta / np.maximum(maxc, 1e-12), 0.0)
    dz = np.maximum(delta, 1e-12)
    rc = (maxc - r) / dz
    gc = (maxc - g) / dz
    bc = (maxc - b) / dz
    h = np.where(maxc == r, bc - gc,
                 np.where(maxc == g, 2.0 + rc - bc, 4.0 + gc - rc))
    h = np.where(delta > 0, (h / 6.0) % 1.0, 0.0)
    return np.stack([h, s, v])


def _hsv_to_rgb(hsv):
    h, s, v = hsv[0], hsv[1], hsv[2]
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1 - s)
    q = v * (1 - s * f)
    t = v * (1 - s * (1 - f))
    i = i.astype(np.int32) % 6
    r = np.choose(i, [v, q, p, p, t, v])
    g = np.choose(i, [t, v, v, q, p, p])
    b = np.choose(i, [p, p, t, v, v, q])
    return np.stack([r, g, b])


def adjust_hue(img, hue_factor):
    """Cycle hue by hue_factor in [-0.5, 0.5] (reference: adjust_hue)."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    a = _chw(img)
    if a.shape[0] != 3:
        return a.copy()
    scale = a.max() if a.max() > 1.5 else 1.0
    hsv = _rgb_to_hsv(a / max(scale, 1e-12))
    hsv[0] = (hsv[0] + hue_factor) % 1.0
    return _hsv_to_rgb(hsv) * scale


def _affine_matrix(angle, translate, scale, shear, center):
    rot = np.deg2rad(angle)
    sx, sy = np.deg2rad(shear[0]), np.deg2rad(shear[1])
    cx, cy = center
    tx, ty = translate
    # RSS = rotation * shear * scale (torchvision/paddle convention)
    a = np.cos(rot - sy) / max(np.cos(sy), 1e-12)
    b = -np.cos(rot - sy) * np.tan(sx) / max(np.cos(sy), 1e-12) \
        - np.sin(rot)
    c = np.sin(rot - sy) / max(np.cos(sy), 1e-12)
    d = -np.sin(rot - sy) * np.tan(sx) / max(np.cos(sy), 1e-12) \
        + np.cos(rot)
    m = np.array([[a, b, 0.0], [c, d, 0.0], [0, 0, 1]]) * 1.0
    m[:2, :2] *= scale
    # translate to center, apply, translate back + user translation
    pre = np.array([[1, 0, -cx], [0, 1, -cy], [0, 0, 1]], np.float64)
    post = np.array([[1, 0, cx + tx], [0, 1, cy + ty], [0, 0, 1]],
                    np.float64)
    return post @ m @ pre


def affine(img, angle, translate=(0, 0), scale=1.0, shear=(0.0, 0.0),
           interpolation="nearest", fill=0, center=None):
    """Affine transform (reference: transforms.affine). Maps output
    coordinates through the inverse matrix like the reference's cv2 path."""
    from scipy import ndimage
    a = _chw(img)
    _, h, w = a.shape
    if isinstance(shear, (int, float)):
        shear = (float(shear), 0.0)
    if center is None:
        center = ((w - 1) * 0.5, (h - 1) * 0.5)
    m = _affine_matrix(angle, translate, scale, shear, center)
    minv = np.linalg.inv(m)
    order = {"nearest": 0, "bilinear": 1, "bicubic": 3}.get(
        interpolation, 0)
    # ndimage works in (row=y, col=x) index space
    mat = np.array([[minv[1, 1], minv[1, 0]], [minv[0, 1], minv[0, 0]]])
    off = np.array([minv[1, 2], minv[0, 2]])
    out = [ndimage.affine_transform(ch, mat, offset=off, order=order,
                                    mode="constant", cval=fill)
           for ch in a]
    return np.stack(out)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    """Rotate counter-clockwise by angle degrees (reference: rotate)."""
    from scipy import ndimage
    a = _chw(img)
    order = {"nearest": 0, "bilinear": 1, "bicubic": 3}.get(
        interpolation, 0)
    if center is None and not expand:
        out = [ndimage.rotate(ch, angle, reshape=expand, order=order,
                              mode="constant", cval=fill) for ch in a]
        return np.stack(out)
    if expand:
        out = [ndimage.rotate(ch, angle, reshape=True, order=order,
                              mode="constant", cval=fill) for ch in a]
        return np.stack(out)
    _, h, w = a.shape
    return affine(a, angle, (0, 0), 1.0, (0, 0), interpolation, fill,
                  center)


def _perspective_coeffs(startpoints, endpoints):
    # solve the 8-dof homography mapping endpoints -> startpoints
    A = []
    B = []
    for (sx, sy), (ex, ey) in zip(startpoints, endpoints):
        A.append([ex, ey, 1, 0, 0, 0, -sx * ex, -sx * ey])
        A.append([0, 0, 0, ex, ey, 1, -sy * ex, -sy * ey])
        B.extend([sx, sy])
    coeffs = np.linalg.solve(np.asarray(A, np.float64),
                             np.asarray(B, np.float64))
    return coeffs


def perspective(img, startpoints, endpoints, interpolation="nearest",
                fill=0):
    """Perspective transform mapping startpoints->endpoints (reference:
    transforms.perspective)."""
    from scipy import ndimage
    a = _chw(img)
    _, h, w = a.shape
    c = _perspective_coeffs(startpoints, endpoints)
    ys, xs = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    denom = c[6] * xs + c[7] * ys + 1.0
    src_x = (c[0] * xs + c[1] * ys + c[2]) / denom
    src_y = (c[3] * xs + c[4] * ys + c[5]) / denom
    order = {"nearest": 0, "bilinear": 1, "bicubic": 3}.get(
        interpolation, 0)
    out = [ndimage.map_coordinates(ch, [src_y, src_x], order=order,
                                   mode="constant", cval=fill)
           for ch in a]
    return np.stack(out)
