"""DenseNet family (reference: python/paddle/vision/models/densenet.py)."""
from __future__ import annotations

from ... import concat, nn

_CFG = {
    121: (64, 32, (6, 12, 24, 16)),
    161: (96, 48, (6, 12, 36, 24)),
    169: (64, 32, (6, 12, 32, 32)),
    201: (64, 32, (6, 12, 48, 32)),
    264: (64, 32, (6, 12, 64, 48)),
}


class _DenseLayer(nn.Layer):
    def __init__(self, in_ch, growth, bn_size, dropout):
        super().__init__()
        self.bn1 = nn.BatchNorm2D(in_ch)
        self.relu = nn.ReLU()
        self.conv1 = nn.Conv2D(in_ch, bn_size * growth, 1,
                               bias_attr=False)
        self.bn2 = nn.BatchNorm2D(bn_size * growth)
        self.conv2 = nn.Conv2D(bn_size * growth, growth, 3, padding=1,
                               bias_attr=False)
        self.dropout = nn.Dropout(dropout) if dropout else None

    def forward(self, x):
        out = self.conv1(self.relu(self.bn1(x)))
        out = self.conv2(self.relu(self.bn2(out)))
        if self.dropout is not None:
            out = self.dropout(out)
        return concat([x, out], axis=1)


class _Transition(nn.Layer):
    def __init__(self, in_ch, out_ch):
        super().__init__()
        self.bn = nn.BatchNorm2D(in_ch)
        self.relu = nn.ReLU()
        self.conv = nn.Conv2D(in_ch, out_ch, 1, bias_attr=False)
        self.pool = nn.AvgPool2D(2, stride=2)

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.bn(x))))


class DenseNet(nn.Layer):
    def __init__(self, layers=121, bn_size=4, dropout=0.0,
                 num_classes=1000, with_pool=True):
        super().__init__()
        if layers not in _CFG:
            raise ValueError(f"layers must be one of {sorted(_CFG)}")
        init_ch, growth, blocks = _CFG[layers]
        self.num_classes = num_classes
        self.with_pool = with_pool
        feats = [nn.Conv2D(3, init_ch, 7, stride=2, padding=3,
                           bias_attr=False),
                 nn.BatchNorm2D(init_ch), nn.ReLU(),
                 nn.MaxPool2D(3, stride=2, padding=1)]
        ch = init_ch
        for bi, n in enumerate(blocks):
            for _ in range(n):
                feats.append(_DenseLayer(ch, growth, bn_size, dropout))
                ch += growth
            if bi != len(blocks) - 1:
                feats.append(_Transition(ch, ch // 2))
                ch //= 2
        feats += [nn.BatchNorm2D(ch), nn.ReLU()]
        self.features = nn.Sequential(*feats)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = nn.Linear(ch, num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = nn.Flatten()(x)
            x = self.classifier(x)
        return x


def densenet121(pretrained=False, **kwargs):
    return DenseNet(121, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return DenseNet(161, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return DenseNet(169, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return DenseNet(201, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return DenseNet(264, **kwargs)
