"""GoogLeNet / Inception v1 (reference:
python/paddle/vision/models/googlenet.py — returns (out, aux1, aux2) like
the reference's three classifier heads)."""
from __future__ import annotations

from ... import concat, nn


class _BasicConv(nn.Layer):
    def __init__(self, in_ch, out_ch, k, **kw):
        super().__init__()
        self.conv = nn.Conv2D(in_ch, out_ch, k, bias_attr=False, **kw)
        self.bn = nn.BatchNorm2D(out_ch)
        self.relu = nn.ReLU()

    def forward(self, x):
        return self.relu(self.bn(self.conv(x)))


class _Inception(nn.Layer):
    def __init__(self, in_ch, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        self.b1 = _BasicConv(in_ch, c1, 1)
        self.b2 = nn.Sequential(_BasicConv(in_ch, c3r, 1),
                                _BasicConv(c3r, c3, 3, padding=1))
        self.b3 = nn.Sequential(_BasicConv(in_ch, c5r, 1),
                                _BasicConv(c5r, c5, 5, padding=2))
        self.b4 = nn.Sequential(nn.MaxPool2D(3, stride=1, padding=1),
                                _BasicConv(in_ch, proj, 1))

    def forward(self, x):
        return concat([self.b1(x), self.b2(x), self.b3(x), self.b4(x)],
                      axis=1)


class _AuxHead(nn.Layer):
    def __init__(self, in_ch, num_classes):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D((4, 4))
        self.conv = _BasicConv(in_ch, 128, 1)
        self.fc1 = nn.Linear(128 * 16, 1024)
        self.relu = nn.ReLU()
        self.dropout = nn.Dropout(0.7)
        self.fc2 = nn.Linear(1024, num_classes)

    def forward(self, x):
        x = self.conv(self.pool(x))
        x = self.relu(self.fc1(nn.Flatten()(x)))
        return self.fc2(self.dropout(x))


class GoogLeNet(nn.Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _BasicConv(3, 64, 7, stride=2, padding=3),
            nn.MaxPool2D(3, stride=2, ceil_mode=True),
            _BasicConv(64, 64, 1), _BasicConv(64, 192, 3, padding=1),
            nn.MaxPool2D(3, stride=2, ceil_mode=True))
        self.i3a = _Inception(192, 64, 96, 128, 16, 32, 32)
        self.i3b = _Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = nn.MaxPool2D(3, stride=2, ceil_mode=True)
        self.i4a = _Inception(480, 192, 96, 208, 16, 48, 64)
        self.i4b = _Inception(512, 160, 112, 224, 24, 64, 64)
        self.i4c = _Inception(512, 128, 128, 256, 24, 64, 64)
        self.i4d = _Inception(512, 112, 144, 288, 32, 64, 64)
        self.i4e = _Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = nn.MaxPool2D(3, stride=2, ceil_mode=True)
        self.i5a = _Inception(832, 256, 160, 320, 32, 128, 128)
        self.i5b = _Inception(832, 384, 192, 384, 48, 128, 128)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.dropout = nn.Dropout(0.4)
            self.fc = nn.Linear(1024, num_classes)
            self.aux1 = _AuxHead(512, num_classes)
            self.aux2 = _AuxHead(528, num_classes)

    def forward(self, x):
        x = self.stem(x)
        x = self.pool3(self.i3b(self.i3a(x)))
        x = self.i4a(x)
        aux1 = self.aux1(x) if self.num_classes > 0 and self.training \
            else None
        x = self.i4d(self.i4c(self.i4b(x)))
        aux2 = self.aux2(x) if self.num_classes > 0 and self.training \
            else None
        x = self.pool4(self.i4e(x))
        x = self.i5b(self.i5a(x))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(nn.Flatten()(x)))
        if self.training and self.num_classes > 0:
            return x, aux1, aux2
        return x


def googlenet(pretrained=False, **kwargs):
    return GoogLeNet(**kwargs)
