"""MobileNetV2 (reference: python/paddle/vision/models/mobilenetv2.py)."""
from __future__ import annotations

from ... import nn


class _InvertedResidual(nn.Layer):
    def __init__(self, inp, oup, stride, expand_ratio):
        super().__init__()
        hidden = int(round(inp * expand_ratio))
        self.use_res = stride == 1 and inp == oup
        layers = []
        if expand_ratio != 1:
            layers += [nn.Conv2D(inp, hidden, 1, bias_attr=False),
                       nn.BatchNorm2D(hidden), nn.ReLU6()]
        layers += [
            nn.Conv2D(hidden, hidden, 3, stride=stride, padding=1,
                      groups=hidden, bias_attr=False),
            nn.BatchNorm2D(hidden), nn.ReLU6(),
            nn.Conv2D(hidden, oup, 1, bias_attr=False),
            nn.BatchNorm2D(oup),
        ]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        cfg = [
            # t, c, n, s
            (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
            (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1),
        ]
        inp = int(32 * scale)
        feats = [nn.Conv2D(3, inp, 3, stride=2, padding=1,
                           bias_attr=False),
                 nn.BatchNorm2D(inp), nn.ReLU6()]
        for t, c, n, s in cfg:
            oup = int(c * scale)
            for i in range(n):
                feats.append(_InvertedResidual(
                    inp, oup, s if i == 0 else 1, t))
                inp = oup
        last = int(1280 * max(1.0, scale))
        feats += [nn.Conv2D(inp, last, 1, bias_attr=False),
                  nn.BatchNorm2D(last), nn.ReLU6()]
        self.features = nn.Sequential(*feats)
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.2), nn.Linear(last, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV2(scale=scale, **kwargs)
