"""MobileNetV1 (reference: python/paddle/vision/models/mobilenetv1.py)."""
from __future__ import annotations

from ... import nn


class _ConvBNRelu(nn.Layer):
    def __init__(self, in_ch, out_ch, k, stride=1, padding=0, groups=1):
        super().__init__()
        self.conv = nn.Conv2D(in_ch, out_ch, k, stride=stride,
                              padding=padding, groups=groups,
                              bias_attr=False)
        self.bn = nn.BatchNorm2D(out_ch)
        self.relu = nn.ReLU()

    def forward(self, x):
        return self.relu(self.bn(self.conv(x)))


class _DepthwiseSeparable(nn.Layer):
    def __init__(self, in_ch, out1, out2, stride, scale):
        super().__init__()
        c1 = int(out1 * scale)
        self.dw = _ConvBNRelu(in_ch, c1, 3, stride=stride, padding=1,
                              groups=in_ch)
        self.pw = _ConvBNRelu(c1, int(out2 * scale), 1)

    def forward(self, x):
        return self.pw(self.dw(x))


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        s = scale
        self.conv1 = _ConvBNRelu(3, int(32 * s), 3, stride=2, padding=1)
        cfg = [
            (int(32 * s), 32, 64, 1), (int(64 * s), 64, 128, 2),
            (int(128 * s), 128, 128, 1), (int(128 * s), 128, 256, 2),
            (int(256 * s), 256, 256, 1), (int(256 * s), 256, 512, 2),
            (int(512 * s), 512, 512, 1), (int(512 * s), 512, 512, 1),
            (int(512 * s), 512, 512, 1), (int(512 * s), 512, 512, 1),
            (int(512 * s), 512, 512, 1), (int(512 * s), 512, 1024, 2),
            (int(1024 * s), 1024, 1024, 1),
        ]
        self.blocks = nn.Sequential(*[
            _DepthwiseSeparable(in_ch, o1, o2, st, s)
            for in_ch, o1, o2, st in cfg])
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(int(1024 * s), num_classes)

    def forward(self, x):
        x = self.blocks(self.conv1(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = nn.Flatten()(x)
            x = self.fc(x)
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV1(scale=scale, **kwargs)
