"""ShuffleNetV2 (reference: python/paddle/vision/models/shufflenetv2.py)."""
from __future__ import annotations

from ... import concat, nn
from ...nn import functional as F


def _shuffle(x, groups):
    return F.channel_shuffle(x, groups)


class _ShuffleUnit(nn.Layer):
    def __init__(self, in_ch, out_ch, stride, act="relu"):
        super().__init__()
        self.stride = stride
        branch = out_ch // 2
        act_layer = nn.Swish if act == "swish" else nn.ReLU
        if stride > 1:
            self.branch1 = nn.Sequential(
                nn.Conv2D(in_ch, in_ch, 3, stride=stride, padding=1,
                          groups=in_ch, bias_attr=False),
                nn.BatchNorm2D(in_ch),
                nn.Conv2D(in_ch, branch, 1, bias_attr=False),
                nn.BatchNorm2D(branch), act_layer())
            b2_in = in_ch
        else:
            self.branch1 = None
            b2_in = in_ch // 2
        self.branch2 = nn.Sequential(
            nn.Conv2D(b2_in, branch, 1, bias_attr=False),
            nn.BatchNorm2D(branch), act_layer(),
            nn.Conv2D(branch, branch, 3, stride=stride, padding=1,
                      groups=branch, bias_attr=False),
            nn.BatchNorm2D(branch),
            nn.Conv2D(branch, branch, 1, bias_attr=False),
            nn.BatchNorm2D(branch), act_layer())

    def forward(self, x):
        if self.stride == 1:
            half = x.shape[1] // 2
            x1, x2 = x[:, :half], x[:, half:]
            out = concat([x1, self.branch2(x2)], axis=1)
        else:
            out = concat([self.branch1(x), self.branch2(x)], axis=1)
        return _shuffle(out, 2)


_STAGES = {  # scale -> stage output channels + final conv
    0.25: ([24, 48, 96], 512), 0.33: ([32, 64, 128], 512),
    0.5: ([48, 96, 192], 1024), 1.0: ([116, 232, 464], 1024),
    1.5: ([176, 352, 704], 1024), 2.0: ([244, 488, 976], 2048),
}


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        if scale not in _STAGES:
            raise ValueError(f"scale must be one of {sorted(_STAGES)}")
        stage_ch, final_ch = _STAGES[scale]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.conv1 = nn.Sequential(
            nn.Conv2D(3, 24, 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(24), nn.ReLU())
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1)
        blocks = []
        in_ch = 24
        for out_ch, repeat in zip(stage_ch, (4, 8, 4)):
            blocks.append(_ShuffleUnit(in_ch, out_ch, 2, act))
            for _ in range(repeat - 1):
                blocks.append(_ShuffleUnit(out_ch, out_ch, 1, act))
            in_ch = out_ch
        self.blocks = nn.Sequential(*blocks)
        self.conv_last = nn.Sequential(
            nn.Conv2D(in_ch, final_ch, 1, bias_attr=False),
            nn.BatchNorm2D(final_ch), nn.ReLU())
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(final_ch, num_classes)

    def forward(self, x):
        x = self.conv_last(self.blocks(self.maxpool(self.conv1(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = nn.Flatten()(x)
            x = self.fc(x)
        return x


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=0.25, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=0.33, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=0.5, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=1.0, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=1.5, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=2.0, **kwargs)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=1.0, act="swish", **kwargs)
