"""MobileNetV3 Large/Small (reference:
python/paddle/vision/models/mobilenetv3.py)."""
from __future__ import annotations

from ... import nn


def _make_divisible(v, divisor=8):
    new_v = max(divisor, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class _SE(nn.Layer):
    def __init__(self, ch):
        super().__init__()
        squeeze = _make_divisible(ch // 4)
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(ch, squeeze, 1)
        self.relu = nn.ReLU()
        self.fc2 = nn.Conv2D(squeeze, ch, 1)
        self.hs = nn.Hardsigmoid()

    def forward(self, x):
        s = self.hs(self.fc2(self.relu(self.fc1(self.pool(x)))))
        return x * s


class _InvertedResidualV3(nn.Layer):
    def __init__(self, in_ch, exp, out_ch, k, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and in_ch == out_ch
        act_layer = nn.Hardswish if act == "hardswish" else nn.ReLU
        layers = []
        if exp != in_ch:
            layers += [nn.Conv2D(in_ch, exp, 1, bias_attr=False),
                       nn.BatchNorm2D(exp), act_layer()]
        layers += [nn.Conv2D(exp, exp, k, stride=stride,
                             padding=k // 2, groups=exp, bias_attr=False),
                   nn.BatchNorm2D(exp)]
        if use_se:
            layers.append(_SE(exp))
        layers += [act_layer(),
                   nn.Conv2D(exp, out_ch, 1, bias_attr=False),
                   nn.BatchNorm2D(out_ch)]
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


_LARGE = [
    # k, exp, out, se, act, stride
    (3, 16, 16, False, "relu", 1), (3, 64, 24, False, "relu", 2),
    (3, 72, 24, False, "relu", 1), (5, 72, 40, True, "relu", 2),
    (5, 120, 40, True, "relu", 1), (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hardswish", 2),
    (3, 200, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1),
    (3, 480, 112, True, "hardswish", 1),
    (3, 672, 112, True, "hardswish", 1),
    (5, 672, 160, True, "hardswish", 2),
    (5, 960, 160, True, "hardswish", 1),
    (5, 960, 160, True, "hardswish", 1),
]
_SMALL = [
    (3, 16, 16, True, "relu", 2), (3, 72, 24, False, "relu", 2),
    (3, 88, 24, False, "relu", 1), (5, 96, 40, True, "hardswish", 2),
    (5, 240, 40, True, "hardswish", 1),
    (5, 240, 40, True, "hardswish", 1),
    (5, 120, 48, True, "hardswish", 1),
    (5, 144, 48, True, "hardswish", 1),
    (5, 288, 96, True, "hardswish", 2),
    (5, 576, 96, True, "hardswish", 1),
    (5, 576, 96, True, "hardswish", 1),
]


class _MobileNetV3(nn.Layer):
    def __init__(self, cfg, last_exp, last_ch, scale=1.0,
                 num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        in_ch = _make_divisible(16 * scale)
        self.conv = nn.Sequential(
            nn.Conv2D(3, in_ch, 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(in_ch), nn.Hardswish())
        blocks = []
        for k, exp, out, se, act, stride in cfg:
            exp_c = _make_divisible(exp * scale)
            out_c = _make_divisible(out * scale)
            blocks.append(_InvertedResidualV3(in_ch, exp_c, out_c, k,
                                              stride, se, act))
            in_ch = out_c
        self.blocks = nn.Sequential(*blocks)
        exp_c = _make_divisible(last_exp * scale)
        self.lastconv = nn.Sequential(
            nn.Conv2D(in_ch, exp_c, 1, bias_attr=False),
            nn.BatchNorm2D(exp_c), nn.Hardswish())
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(exp_c, last_ch), nn.Hardswish(),
                nn.Dropout(0.2), nn.Linear(last_ch, num_classes))

    def forward(self, x):
        x = self.lastconv(self.blocks(self.conv(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = nn.Flatten()(x)
            x = self.classifier(x)
        return x


class MobileNetV3Large(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_LARGE, 960, 1280, scale, num_classes, with_pool)


class MobileNetV3Small(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_SMALL, 576, 1024, scale, num_classes, with_pool)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3Large(scale=scale, **kwargs)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3Small(scale=scale, **kwargs)
