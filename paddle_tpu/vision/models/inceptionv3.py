"""Inception v3 (reference: python/paddle/vision/models/inceptionv3.py)."""
from __future__ import annotations

from ... import concat, nn


class _BasicConv(nn.Layer):
    def __init__(self, in_ch, out_ch, k, **kw):
        super().__init__()
        self.conv = nn.Conv2D(in_ch, out_ch, k, bias_attr=False, **kw)
        self.bn = nn.BatchNorm2D(out_ch)
        self.relu = nn.ReLU()

    def forward(self, x):
        return self.relu(self.bn(self.conv(x)))


class _InceptionA(nn.Layer):
    def __init__(self, in_ch, pool_feat):
        super().__init__()
        self.b1 = _BasicConv(in_ch, 64, 1)
        self.b5 = nn.Sequential(_BasicConv(in_ch, 48, 1),
                                _BasicConv(48, 64, 5, padding=2))
        self.b3 = nn.Sequential(_BasicConv(in_ch, 64, 1),
                                _BasicConv(64, 96, 3, padding=1),
                                _BasicConv(96, 96, 3, padding=1))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _BasicConv(in_ch, pool_feat, 1))

    def forward(self, x):
        return concat([self.b1(x), self.b5(x), self.b3(x), self.bp(x)],
                      axis=1)


class _InceptionB(nn.Layer):
    def __init__(self, in_ch):
        super().__init__()
        self.b3 = _BasicConv(in_ch, 384, 3, stride=2)
        self.b3d = nn.Sequential(_BasicConv(in_ch, 64, 1),
                                 _BasicConv(64, 96, 3, padding=1),
                                 _BasicConv(96, 96, 3, stride=2))
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return concat([self.b3(x), self.b3d(x), self.pool(x)], axis=1)


class _InceptionC(nn.Layer):
    def __init__(self, in_ch, c7):
        super().__init__()
        self.b1 = _BasicConv(in_ch, 192, 1)
        self.b7 = nn.Sequential(
            _BasicConv(in_ch, c7, 1),
            _BasicConv(c7, c7, (1, 7), padding=(0, 3)),
            _BasicConv(c7, 192, (7, 1), padding=(3, 0)))
        self.b7d = nn.Sequential(
            _BasicConv(in_ch, c7, 1),
            _BasicConv(c7, c7, (7, 1), padding=(3, 0)),
            _BasicConv(c7, c7, (1, 7), padding=(0, 3)),
            _BasicConv(c7, c7, (7, 1), padding=(3, 0)),
            _BasicConv(c7, 192, (1, 7), padding=(0, 3)))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _BasicConv(in_ch, 192, 1))

    def forward(self, x):
        return concat([self.b1(x), self.b7(x), self.b7d(x), self.bp(x)],
                      axis=1)


class _InceptionD(nn.Layer):
    def __init__(self, in_ch):
        super().__init__()
        self.b3 = nn.Sequential(_BasicConv(in_ch, 192, 1),
                                _BasicConv(192, 320, 3, stride=2))
        self.b7 = nn.Sequential(
            _BasicConv(in_ch, 192, 1),
            _BasicConv(192, 192, (1, 7), padding=(0, 3)),
            _BasicConv(192, 192, (7, 1), padding=(3, 0)),
            _BasicConv(192, 192, 3, stride=2))
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return concat([self.b3(x), self.b7(x), self.pool(x)], axis=1)


class _InceptionE(nn.Layer):
    def __init__(self, in_ch):
        super().__init__()
        self.b1 = _BasicConv(in_ch, 320, 1)
        self.b3_1 = _BasicConv(in_ch, 384, 1)
        self.b3_2a = _BasicConv(384, 384, (1, 3), padding=(0, 1))
        self.b3_2b = _BasicConv(384, 384, (3, 1), padding=(1, 0))
        self.bd_1 = nn.Sequential(_BasicConv(in_ch, 448, 1),
                                  _BasicConv(448, 384, 3, padding=1))
        self.bd_2a = _BasicConv(384, 384, (1, 3), padding=(0, 1))
        self.bd_2b = _BasicConv(384, 384, (3, 1), padding=(1, 0))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _BasicConv(in_ch, 192, 1))

    def forward(self, x):
        b3 = self.b3_1(x)
        b3 = concat([self.b3_2a(b3), self.b3_2b(b3)], axis=1)
        bd = self.bd_1(x)
        bd = concat([self.bd_2a(bd), self.bd_2b(bd)], axis=1)
        return concat([self.b1(x), b3, bd, self.bp(x)], axis=1)


class InceptionV3(nn.Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _BasicConv(3, 32, 3, stride=2), _BasicConv(32, 32, 3),
            _BasicConv(32, 64, 3, padding=1), nn.MaxPool2D(3, stride=2),
            _BasicConv(64, 80, 1), _BasicConv(80, 192, 3),
            nn.MaxPool2D(3, stride=2))
        self.blocks = nn.Sequential(
            _InceptionA(192, 32), _InceptionA(256, 64),
            _InceptionA(288, 64), _InceptionB(288),
            _InceptionC(768, 128), _InceptionC(768, 160),
            _InceptionC(768, 160), _InceptionC(768, 192),
            _InceptionD(768), _InceptionE(1280), _InceptionE(2048))
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.dropout = nn.Dropout(0.5)
            self.fc = nn.Linear(2048, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(nn.Flatten()(x)))
        return x


def inception_v3(pretrained=False, **kwargs):
    return InceptionV3(**kwargs)
