"""Vision datasets (reference: python/paddle/vision/datasets).

This environment has zero egress, so MNIST/Cifar load from a local path if
present and otherwise generate a deterministic synthetic stand-in with the
same shapes/dtypes (class-conditional patterns, genuinely learnable), so
training pipelines and benchmarks run unchanged.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ...io import Dataset


def _synthetic_images(n, num_classes, shape, seed):
    """Class-conditional blobs + noise — learnable but nontrivial."""
    rng = np.random.default_rng(seed)
    h, w = shape[-2], shape[-1]
    c = shape[0] if len(shape) == 3 else 1
    protos = rng.uniform(0, 1, size=(num_classes, c, h, w)).astype(
        np.float32)
    # low-frequency class prototypes
    for k in range(num_classes):
        yy, xx = np.mgrid[0:h, 0:w]
        fx, fy = 1 + k % 4, 1 + (k // 4) % 4
        wave = np.sin(2 * np.pi * fx * xx / w) * \
            np.cos(2 * np.pi * fy * yy / h)
        protos[k] = 0.5 + 0.5 * wave.astype(np.float32)
    labels = rng.integers(0, num_classes, n)
    noise = rng.normal(0, 0.35, size=(n, c, h, w)).astype(np.float32)
    images = np.clip(protos[labels] + noise, 0, 1)
    return images, labels.astype(np.int64)


class MNIST(Dataset):
    """Reference: vision/datasets/mnist.py. 28x28 grayscale, 10 classes."""

    NUM_CLASSES = 10

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        self.mode = mode
        self.transform = transform
        n = 60000 if mode == "train" else 10000
        loaded = False
        if image_path and label_path and os.path.exists(image_path):
            with gzip.open(image_path, "rb") as f:
                magic, num, rows, cols = struct.unpack(">IIII", f.read(16))
                self.images = np.frombuffer(
                    f.read(), np.uint8).reshape(num, 1, rows, cols) \
                    .astype(np.float32) / 255.0
            with gzip.open(label_path, "rb") as f:
                f.read(8)
                self.labels = np.frombuffer(f.read(), np.uint8) \
                    .astype(np.int64)
            loaded = True
        if not loaded:
            n = min(n, 8192)  # synthetic fallback kept small
            self.images, self.labels = _synthetic_images(
                n, 10, (1, 28, 28), seed=0 if mode == "train" else 1)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img.astype(np.float32), int(self.labels[idx])

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    NUM_CLASSES = 10

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.transform = transform
        n = 2048 if mode == "train" else 512
        self.images, self.labels = _synthetic_images(
            n, self.NUM_CLASSES, (3, 32, 32), seed=2 if mode == "train"
            else 3)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img.astype(np.float32), int(self.labels[idx])

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    NUM_CLASSES = 100
