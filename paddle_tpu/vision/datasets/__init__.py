"""Vision datasets (reference: python/paddle/vision/datasets).

This environment has zero egress, so MNIST/Cifar load from a local path if
present and otherwise generate a deterministic synthetic stand-in with the
same shapes/dtypes (class-conditional patterns, genuinely learnable), so
training pipelines and benchmarks run unchanged.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ...io import Dataset


def _synthetic_images(n, num_classes, shape, seed):
    """Class-conditional blobs + noise — learnable but nontrivial."""
    rng = np.random.default_rng(seed)
    h, w = shape[-2], shape[-1]
    c = shape[0] if len(shape) == 3 else 1
    protos = rng.uniform(0, 1, size=(num_classes, c, h, w)).astype(
        np.float32)
    # low-frequency class prototypes
    for k in range(num_classes):
        yy, xx = np.mgrid[0:h, 0:w]
        fx, fy = 1 + k % 4, 1 + (k // 4) % 4
        wave = np.sin(2 * np.pi * fx * xx / w) * \
            np.cos(2 * np.pi * fy * yy / h)
        protos[k] = 0.5 + 0.5 * wave.astype(np.float32)
    labels = rng.integers(0, num_classes, n)
    noise = rng.normal(0, 0.35, size=(n, c, h, w)).astype(np.float32)
    images = np.clip(protos[labels] + noise, 0, 1)
    return images, labels.astype(np.int64)


class MNIST(Dataset):
    """Reference: vision/datasets/mnist.py. 28x28 grayscale, 10 classes."""

    NUM_CLASSES = 10

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        self.mode = mode
        self.transform = transform
        n = 60000 if mode == "train" else 10000
        loaded = False
        if image_path and label_path and os.path.exists(image_path):
            with gzip.open(image_path, "rb") as f:
                magic, num, rows, cols = struct.unpack(">IIII", f.read(16))
                self.images = np.frombuffer(
                    f.read(), np.uint8).reshape(num, 1, rows, cols) \
                    .astype(np.float32) / 255.0
            with gzip.open(label_path, "rb") as f:
                f.read(8)
                self.labels = np.frombuffer(f.read(), np.uint8) \
                    .astype(np.int64)
            loaded = True
        if not loaded:
            n = min(n, 8192)  # synthetic fallback kept small
            self.images, self.labels = _synthetic_images(
                n, 10, (1, 28, 28), seed=0 if mode == "train" else 1)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img.astype(np.float32), int(self.labels[idx])

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    NUM_CLASSES = 10

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.transform = transform
        n = 2048 if mode == "train" else 512
        self.images, self.labels = _synthetic_images(
            n, self.NUM_CLASSES, (3, 32, 32), seed=2 if mode == "train"
            else 3)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img.astype(np.float32), int(self.labels[idx])

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    NUM_CLASSES = 100


IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm",
                  ".tif", ".tiff", ".webp")


class DatasetFolder(Dataset):
    """Generic folder-of-class-folders dataset (reference:
    vision/datasets/folder.py DatasetFolder): root/<class>/<file>."""

    def __init__(self, root, loader=None, extensions=None,
                 transform=None, is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or self._default_loader
        extensions = extensions or IMG_EXTENSIONS
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise RuntimeError(f"no class folders found in {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for base, _, files in sorted(os.walk(cdir)):
                for fname in sorted(files):
                    path = os.path.join(base, fname)
                    ok = is_valid_file(path) if is_valid_file else \
                        fname.lower().endswith(tuple(extensions))
                    if ok:
                        self.samples.append((path, self.class_to_idx[c]))
        if not self.samples:
            raise RuntimeError(f"no valid files found under {root}")
        self.targets = [t for _, t in self.samples]

    @staticmethod
    def _default_loader(path):
        from PIL import Image
        with open(path, "rb") as f:
            return Image.open(f).convert("RGB")

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, target


class ImageFolder(Dataset):
    """Flat/recursive folder of images, no labels (reference:
    vision/datasets/folder.py ImageFolder)."""

    def __init__(self, root, loader=None, extensions=None,
                 transform=None, is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or DatasetFolder._default_loader
        extensions = extensions or IMG_EXTENSIONS
        self.samples = []
        for base, _, files in sorted(os.walk(root)):
            for fname in sorted(files):
                path = os.path.join(base, fname)
                ok = is_valid_file(path) if is_valid_file else \
                    fname.lower().endswith(tuple(extensions))
                if ok:
                    self.samples.append(path)
        if not self.samples:
            raise RuntimeError(f"no valid files found under {root}")

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        sample = self.loader(self.samples[idx])
        if self.transform is not None:
            sample = self.transform(sample)
        return [sample]


class Flowers(Dataset):
    """Flowers-102 (reference: vision/datasets/flowers.py). Synthetic
    stand-in (no egress): 102 classes of class-conditional images."""

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True,
                 backend=None, num_samples=600):
        seed = {"train": 0, "valid": 1, "test": 2}.get(mode, 0)
        self._x, self._y = _synthetic_images(num_samples, 102,
                                             (3, 96, 96), seed + 400)
        self.transform = transform

    def __len__(self):
        return len(self._y)

    def __getitem__(self, i):
        img = self._x[i]
        if self.transform is not None:
            img = self.transform(img)
        return img, self._y[i]


class VOC2012(Dataset):
    """VOC2012 segmentation (reference: vision/datasets/voc2012.py):
    (image, label_mask) pairs, 21 classes. Synthetic stand-in: masks are
    thresholded class prototypes so mIoU training is meaningful."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None, num_samples=200):
        seed = {"train": 0, "valid": 1, "test": 2}.get(mode, 0)
        imgs, labels = _synthetic_images(num_samples, 21, (3, 64, 64),
                                         seed + 500)
        self._x = imgs
        # mask: the class's wave pattern thresholded into fg/bg
        self._masks = (imgs.mean(axis=1) > 0.5).astype(np.int64) * \
            (labels[:, None, None] + 0)
        self.transform = transform

    def __len__(self):
        return len(self._x)

    def __getitem__(self, i):
        img = self._x[i]
        if self.transform is not None:
            img = self.transform(img)
        return img, self._masks[i]
