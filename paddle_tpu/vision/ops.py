"""paddle.vision.ops (reference python/paddle/vision/ops.py: nms,
roi_align, roi_pool, box_coder, prior_box, yolo_box, ...).

TPU-first notes: detection post-processing is branch-heavy; these
lowerings keep static shapes (fixed iteration counts, masked selects) so
they compile under jit. NMS returns keep-mask ordering like the
reference's kept-indices (padded with -1) rather than a dynamic-length
tensor.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import run_op, run_op_nodiff, unwrap


def _roi_batch_indices(boxes, boxes_num):
    """Per-RoI batch image index from boxes_num (reference roi_align
    convention: the first boxes_num[0] rois belong to image 0, ...)."""
    n_rois = int(unwrap(boxes).shape[0])
    if boxes_num is None:
        return jnp.zeros((n_rois,), jnp.int32)
    counts = np.asarray(unwrap(boxes_num)).astype(np.int64).reshape(-1)
    return jnp.asarray(np.repeat(np.arange(len(counts)), counts),
                       jnp.int32)


def _iou_matrix(boxes):
    x1, y1, x2, y2 = [boxes[:, i] for i in range(4)]
    area = jnp.maximum(x2 - x1, 0) * jnp.maximum(y2 - y1, 0)
    ix1 = jnp.maximum(x1[:, None], x1[None, :])
    iy1 = jnp.maximum(y1[:, None], y1[None, :])
    ix2 = jnp.minimum(x2[:, None], x2[None, :])
    iy2 = jnp.minimum(y2[:, None], y2[None, :])
    inter = jnp.maximum(ix2 - ix1, 0) * jnp.maximum(iy2 - iy1, 0)
    union = area[:, None] + area[None, :] - inter
    return inter / jnp.maximum(union, 1e-10)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None, name=None):
    """Hard NMS (reference vision/ops.py nms). Returns kept indices
    sorted by score, padded with -1 to the input length (static shape)."""
    def fn(b, s):
        n = b.shape[0]
        order = jnp.argsort(-s)
        iou = _iou_matrix(b)[order][:, order]
        # greedy suppression with a fixed-length scan over rank positions
        def body(keep, i):
            # keep[j] == True means box at rank j survives so far
            suppress = (iou[i] > iou_threshold) & keep[i] & \
                (jnp.arange(n) > i)
            return keep & ~suppress, None
        keep0 = jnp.ones(n, bool)
        keep, _ = jax.lax.scan(body, keep0, jnp.arange(n))
        kept_sorted = jnp.where(keep, order, -1)
        # stable-move -1 entries to the back
        rank = jnp.where(keep, jnp.arange(n), n)
        kept_sorted = kept_sorted[jnp.argsort(rank)]
        if top_k is not None:
            kept_sorted = kept_sorted[:top_k]
        return kept_sorted
    s = scores if scores is not None else \
        jnp.arange(unwrap(boxes).shape[0], 0, -1).astype(jnp.float32)
    return run_op_nodiff("nms", fn, [boxes, s])


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign via bilinear grid sampling (reference ops.yaml: roi_align)."""
    out_h, out_w = (output_size if isinstance(output_size, (tuple, list))
                    else (output_size, output_size))
    batch_idx = _roi_batch_indices(boxes, boxes_num)

    def fn(feat, rois):
        # feat: [N, C, H, W]; rois: [R, 4]; each RoI reads its own
        # image's features (batch assignment from boxes_num)
        c, h, w = feat.shape[1:]
        off = 0.5 if aligned else 0.0
        ratio = sampling_ratio if sampling_ratio > 0 else 2

        def one_roi(roi, bidx):
            x1, y1, x2, y2 = roi * spatial_scale - off
            rw = jnp.maximum(x2 - x1, 1e-6)
            rh = jnp.maximum(y2 - y1, 1e-6)
            ys = y1 + (jnp.arange(out_h * ratio) + 0.5) * rh / (
                out_h * ratio)
            xs = x1 + (jnp.arange(out_w * ratio) + 0.5) * rw / (
                out_w * ratio)

            def sample(py, px):
                # reference border semantics (roi_align kernel): points
                # beyond (-1, size) contribute 0; points in (-1, 0) clamp
                # to the first pixel
                inside = (py > -1.0) & (py < h) & (px > -1.0) & (px < w)
                py = jnp.clip(py, 0.0, h - 1)
                px = jnp.clip(px, 0.0, w - 1)
                y0 = jnp.floor(py).astype(jnp.int32)
                x0 = jnp.floor(px).astype(jnp.int32)
                wy = py - y0
                wx = px - x0

                def g(yy, xx):
                    yc = jnp.clip(yy, 0, h - 1)
                    xc = jnp.clip(xx, 0, w - 1)
                    return feat[bidx, :, yc, xc]
                val = (g(y0, x0) * (1 - wy) * (1 - wx)
                       + g(y0, x0 + 1) * (1 - wy) * wx
                       + g(y0 + 1, x0) * wy * (1 - wx)
                       + g(y0 + 1, x0 + 1) * wy * wx)
                return val * inside

            grid = jax.vmap(lambda py: jax.vmap(
                lambda px: sample(py, px))(xs))(ys)
            # [out_h*r, out_w*r, C] -> average pool r x r
            grid = grid.reshape(out_h, ratio, out_w, ratio, c)
            return jnp.mean(grid, axis=(1, 3)).transpose(2, 0, 1)

        return jax.vmap(one_roi)(rois, batch_idx)  # [R, C, oh, ow]
    return run_op("roi_align", fn, [x, boxes])


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
             name=None):
    """Max RoI pooling (reference ops.yaml: roi_pool) — implemented as
    dense-sampled max (static shapes)."""
    out_h, out_w = (output_size if isinstance(output_size, (tuple, list))
                    else (output_size, output_size))
    batch_idx = _roi_batch_indices(boxes, boxes_num)

    def fn(feat, rois):
        c, h, w = feat.shape[1:]

        def one_roi(roi, bidx):
            x1, y1, x2, y2 = jnp.round(roi * spatial_scale)
            rw = jnp.maximum(x2 - x1 + 1, 1.0)
            rh = jnp.maximum(y2 - y1 + 1, 1.0)
            ratio = 4
            ys = y1 + (jnp.arange(out_h * ratio) + 0.5) * rh / (
                out_h * ratio)
            xs = x1 + (jnp.arange(out_w * ratio) + 0.5) * rw / (
                out_w * ratio)
            yi = jnp.clip(ys.astype(jnp.int32), 0, h - 1)
            xi = jnp.clip(xs.astype(jnp.int32), 0, w - 1)
            patch = feat[bidx][:, yi][:, :, xi]
            patch = patch.reshape(c, out_h, ratio, out_w, ratio)
            return jnp.max(patch, axis=(2, 4))

        return jax.vmap(one_roi)(rois, batch_idx)
    return run_op("roi_pool", fn, [x, boxes])


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    """reference ops.yaml: box_coder."""
    def fn(pb, pbv, tb):
        norm = 0.0 if box_normalized else 1.0
        pw = pb[:, 2] - pb[:, 0] + norm
        ph = pb[:, 3] - pb[:, 1] + norm
        pcx = pb[:, 0] + pw * 0.5
        pcy = pb[:, 1] + ph * 0.5
        if code_type == "encode_center_size":
            tw = tb[:, 2] - tb[:, 0] + norm
            th = tb[:, 3] - tb[:, 1] + norm
            tcx = tb[:, 0] + tw * 0.5
            tcy = tb[:, 1] + th * 0.5
            out = jnp.stack([
                (tcx - pcx) / pw / pbv[:, 0],
                (tcy - pcy) / ph / pbv[:, 1],
                jnp.log(tw / pw) / pbv[:, 2],
                jnp.log(th / ph) / pbv[:, 3]], axis=1)
        else:  # decode_center_size
            dcx = pbv[:, 0] * tb[:, 0] * pw + pcx
            dcy = pbv[:, 1] * tb[:, 1] * ph + pcy
            dw = jnp.exp(pbv[:, 2] * tb[:, 2]) * pw
            dh = jnp.exp(pbv[:, 3] * tb[:, 3]) * ph
            out = jnp.stack([dcx - dw * 0.5, dcy - dh * 0.5,
                             dcx + dw * 0.5 - norm,
                             dcy + dh * 0.5 - norm], axis=1)
        return out
    return run_op("box_coder", fn, [prior_box, prior_box_var, target_box])


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """SSD prior boxes (reference ops.yaml: prior_box)."""
    a = unwrap(input)
    img = unwrap(image)
    h, w = a.shape[-2:]
    ih, iw = img.shape[-2:]
    step_h = steps[1] or ih / h
    step_w = steps[0] or iw / w
    ars = list(aspect_ratios)
    if flip:
        ars += [1.0 / r for r in aspect_ratios if r != 1.0]
    boxes = []
    for ms in min_sizes:
        for ar in ars:
            bw = ms * np.sqrt(ar) / 2
            bh = ms / np.sqrt(ar) / 2
            boxes.append((bw, bh))
        if max_sizes:
            for mx in max_sizes:
                s = np.sqrt(ms * mx) / 2
                boxes.append((s, s))
    cy = (np.arange(h) + offset) * step_h
    cx = (np.arange(w) + offset) * step_w
    gy, gx = np.meshgrid(cy, cx, indexing="ij")
    out = np.zeros((h, w, len(boxes), 4), np.float32)
    for i, (bw, bh) in enumerate(boxes):
        out[..., i, 0] = (gx - bw) / iw
        out[..., i, 1] = (gy - bh) / ih
        out[..., i, 2] = (gx + bw) / iw
        out[..., i, 3] = (gy + bh) / ih
    if clip:
        out = np.clip(out, 0, 1)
    var = np.broadcast_to(np.asarray(variance, np.float32),
                          out.shape).copy()
    from ..core.dispatch import wrap
    return wrap(jnp.asarray(out)), wrap(jnp.asarray(var))


def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5, name=None):
    """YOLO detection decode (reference ops.yaml: yolo_box)."""
    na = len(anchors) // 2

    def fn(a, imgs):
        n, _, h, w = a.shape
        v = a.reshape(n, na, 5 + class_num, h, w)
        gx = jnp.arange(w).reshape(1, 1, 1, w)
        gy = jnp.arange(h).reshape(1, 1, h, 1)
        sx = jax.nn.sigmoid(v[:, :, 0]) * scale_x_y - (scale_x_y - 1) / 2
        sy = jax.nn.sigmoid(v[:, :, 1]) * scale_x_y - (scale_x_y - 1) / 2
        bx = (gx + sx) / w
        by = (gy + sy) / h
        aw = jnp.asarray(anchors[0::2], a.dtype).reshape(1, na, 1, 1)
        ah = jnp.asarray(anchors[1::2], a.dtype).reshape(1, na, 1, 1)
        bw = jnp.exp(v[:, :, 2]) * aw / (w * downsample_ratio)
        bh = jnp.exp(v[:, :, 3]) * ah / (h * downsample_ratio)
        conf = jax.nn.sigmoid(v[:, :, 4])
        probs = jax.nn.sigmoid(v[:, :, 5:]) * conf[:, :, None]
        imh = imgs[:, 0].reshape(n, 1, 1, 1)
        imw = imgs[:, 1].reshape(n, 1, 1, 1)
        x1 = (bx - bw / 2) * imw
        y1 = (by - bh / 2) * imh
        x2 = (bx + bw / 2) * imw
        y2 = (by + bh / 2) * imh
        if clip_bbox:
            x1 = jnp.clip(x1, 0, imw - 1)
            y1 = jnp.clip(y1, 0, imh - 1)
            x2 = jnp.clip(x2, 0, imw - 1)
            y2 = jnp.clip(y2, 0, imh - 1)
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(n, -1, 4)
        scores = probs.transpose(0, 1, 3, 4, 2).reshape(
            n, -1, class_num)
        keep = (conf > conf_thresh).reshape(n, -1, 1)
        return boxes * keep, scores * keep
    return run_op("yolo_box", fn, [x, img_size])


def shuffle_channel(x, group, name=None):
    """reference ops.yaml: shuffle_channel."""
    def fn(a):
        n, c, h, w = a.shape
        return a.reshape(n, group, c // group, h, w).swapaxes(
            1, 2).reshape(n, c, h, w)
    return run_op("shuffle_channel", fn, [x])


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable convolution v1/v2 (reference ops.yaml: deformable_conv).

    Implemented as bilinear gather at offset positions + one einsum
    contraction — the gather vectorises over (kernel pos, output pos) so
    XLA sees a single large batched-gather + matmul instead of the
    reference's per-position CUDA kernel. mask=None is v1; with mask it's
    v2 (modulated)."""
    sh, sw = (stride, stride) if isinstance(stride, int) else stride
    ph, pw = (padding, padding) if isinstance(padding, int) else padding
    dh, dw = (dilation, dilation) if isinstance(dilation, int) else dilation

    def fn(a, off, w, *rest):
        n, cin, h, wdt = a.shape
        cout, cin_g, kh, kw = w.shape
        hout = (h + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
        wout = (wdt + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
        dg = deformable_groups
        off = off.reshape(n, dg, kh * kw, 2, hout, wout)
        msk = None
        if mask is not None:
            msk = rest[0].reshape(n, dg, kh * kw, hout, wout)
        # base sampling grid per kernel tap: tap (i, j) reads
        # (ho*sh - ph + i*dh, wo*sw - pw + j*dw)
        ho = jnp.arange(hout) * sh - ph
        wo = jnp.arange(wout) * sw - pw
        ki = jnp.arange(kh) * dh
        kj = jnp.arange(kw) * dw
        grid_y = ki[:, None, None, None] + ho[None, None, :, None]
        grid_x = kj[None, :, None, None] + wo[None, None, None, :]
        base_y = jnp.broadcast_to(grid_y, (kh, kw, hout, wout)) \
            .reshape(kh * kw, hout, wout).astype(off.dtype)
        base_x = jnp.broadcast_to(grid_x, (kh, kw, hout, wout)) \
            .reshape(kh * kw, hout, wout).astype(off.dtype)
        # offsets are (dy, dx) per tap
        py = base_y[None, None] + off[:, :, :, 0]        # [n, dg, K, ho, wo]
        px = base_x[None, None] + off[:, :, :, 1]

        def bilinear(img, yy, xx):
            # img: [cpg, h, w]; yy/xx: [K, ho, wo]. Reference
            # DmcnIm2colBilinear semantics: the whole sample is 0 outside
            # (-1, size); inside, each out-of-range CORNER contributes 0
            # (no coordinate clamping), so border samples keep partial
            # bilinear weights.
            inside = (yy > -1.0) & (yy < h) & (xx > -1.0) & (xx < wdt)
            y0 = jnp.floor(yy).astype(jnp.int32)
            x0 = jnp.floor(xx).astype(jnp.int32)
            wy = yy - y0
            wx = xx - x0

            def g(yc, xc):
                valid = (yc >= 0) & (yc < h) & (xc >= 0) & (xc < wdt)
                ycs = jnp.clip(yc, 0, h - 1)
                xcs = jnp.clip(xc, 0, wdt - 1)
                return img[:, ycs, xcs] * valid          # [cpg, K, ho, wo]
            val = (g(y0, x0) * (1 - wy) * (1 - wx)
                   + g(y0, x0 + 1) * (1 - wy) * wx
                   + g(y0 + 1, x0) * wy * (1 - wx)
                   + g(y0 + 1, x0 + 1) * wy * wx)
            return val * inside

        cpg = cin // dg                                   # chans per dgroup

        def per_image(img, yy, xx, m=None):
            # img [cin, h, w]; yy/xx [dg, K, ho, wo]
            cols = []
            for g_i in range(dg):
                v = bilinear(img[g_i * cpg:(g_i + 1) * cpg],
                             yy[g_i], xx[g_i])
                if m is not None:
                    v = v * m[g_i][None]
                cols.append(v)
            return jnp.concatenate(cols, axis=0)          # [cin, K, ho, wo]
        if msk is not None:
            sampled = jax.vmap(per_image)(a, py, px, msk)
        else:
            sampled = jax.vmap(
                lambda img, yy, xx: per_image(img, yy, xx))(a, py, px)
        # grouped contraction: [n, cin, K, ho, wo] x [cout, cin_g, K]
        wf = w.reshape(cout, cin_g, kh * kw)
        cpg_out = cout // groups
        outs = []
        for g_i in range(groups):
            s_g = sampled[:, g_i * cin_g:(g_i + 1) * cin_g]
            w_g = wf[g_i * cpg_out:(g_i + 1) * cpg_out]
            outs.append(jnp.einsum("nckhw,ock->nohw", s_g, w_g))
        out = jnp.concatenate(outs, axis=1)
        if bias is not None:
            out = out + rest[-1].reshape(1, -1, 1, 1)
        return out
    args = [x, offset, weight]
    if mask is not None:
        args.append(mask)
    if bias is not None:
        args.append(bias)
    return run_op("deform_conv2d", fn, args)


from ..nn import Layer as _Layer


class DeformConv2D(_Layer):
    """Layer owning the deformable-conv weight/bias (reference:
    python/paddle/vision/ops.py:973 DeformConv2D(Layer)); params register
    on the module tree so optimizers/state_dict see them."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        kh, kw = (kernel_size, kernel_size) \
            if isinstance(kernel_size, int) else kernel_size
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, kh, kw],
            attr=weight_attr)
        self.bias = self.create_parameter(
            [out_channels], attr=bias_attr, is_bias=True) \
            if bias_attr is not False else None
        self.stride, self.padding = stride, padding
        self.dilation = dilation
        self.deformable_groups = deformable_groups
        self.groups = groups

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias,
                             self.stride, self.padding, self.dilation,
                             self.deformable_groups, self.groups, mask)


def distribute_fpn_proposals(*a, **kw):
    raise NotImplementedError("FPN proposal distribution is dynamic-shape "
                              "host logic; run it outside jit")


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI pooling (reference ops.yaml: psroi_pool):
    input channels C = out_c * oh * ow; output bin (i, j) average-pools
    its own channel group over that bin's spatial region."""
    out_h, out_w = (output_size if isinstance(output_size, (tuple, list))
                    else (output_size, output_size))
    batch_idx = _roi_batch_indices(boxes, boxes_num)

    def fn(feat, rois):
        n, c, h, w = feat.shape
        if c % (out_h * out_w) != 0:
            raise ValueError(
                f"psroi_pool needs channels divisible by {out_h * out_w}")
        out_c = c // (out_h * out_w)

        def one_roi(roi, bidx):
            # reference psroi_pool_kernel.cc: start = round(box)*scale,
            # end = (round(box)+1)*scale; each bin averages EVERY pixel
            # in [floor(start), ceil(end)) — done here as a masked mean
            # (static shapes, exact)
            # C++ std::round = half away from zero (jnp.round is
            # half-to-even): sign(x) * floor(|x| + 0.5)
            def cround(v):
                return jnp.sign(v) * jnp.floor(jnp.abs(v) + 0.5)
            x1 = cround(roi[0]) * spatial_scale
            y1 = cround(roi[1]) * spatial_scale
            x2 = (cround(roi[2]) + 1.0) * spatial_scale
            y2 = (cround(roi[3]) + 1.0) * spatial_scale
            rw = jnp.maximum(x2 - x1, 0.1)
            rh = jnp.maximum(y2 - y1, 0.1)
            bin_h = rh / out_h
            bin_w = rw / out_w
            ys = jnp.arange(h)
            xs = jnp.arange(w)
            rows = []
            for i in range(out_h):
                cols = []
                hstart = jnp.clip(jnp.floor(y1 + i * bin_h), 0, h)
                hend = jnp.clip(jnp.ceil(y1 + (i + 1) * bin_h), 0, h)
                my = (ys >= hstart) & (ys < hend)
                for j in range(out_w):
                    wstart = jnp.clip(jnp.floor(x1 + j * bin_w), 0, w)
                    wend = jnp.clip(jnp.ceil(x1 + (j + 1) * bin_w), 0, w)
                    mx = (xs >= wstart) & (xs < wend)
                    mask = my[:, None] & mx[None, :]
                    group = feat[bidx,
                                 i * out_w + j::out_h * out_w]
                    cnt = jnp.maximum(jnp.sum(mask), 1)
                    cols.append(jnp.sum(group * mask, axis=(1, 2)) / cnt)
                rows.append(jnp.stack(cols, axis=-1))
            return jnp.stack(rows, axis=-2)              # [out_c, oh, ow]

        return jax.vmap(one_roi)(rois, batch_idx)
    return run_op("psroi_pool", fn, [x, boxes])


class RoIAlign:
    """Layer wrapper (reference: vision.ops.RoIAlign)."""

    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num, aligned=True):
        return roi_align(x, boxes, boxes_num, self.output_size,
                         self.spatial_scale, aligned=aligned)


class RoIPool:
    """Layer wrapper (reference: vision.ops.RoIPool)."""

    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self.output_size,
                        self.spatial_scale)


class PSRoIPool:
    """Layer wrapper (reference: vision.ops.PSRoIPool)."""

    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self.output_size,
                          self.spatial_scale)


def read_file(filename, name=None):
    """Read a file's bytes into a uint8 tensor (reference: read_file)."""
    from ..core.dispatch import wrap
    with open(filename, "rb") as f:
        data = f.read()
    return wrap(jnp.asarray(np.frombuffer(data, np.uint8)))


def decode_jpeg(x, mode="unchanged", name=None):
    """Decode a uint8 JPEG byte tensor to CHW uint8 (reference:
    decode_jpeg; PIL does the host-side decode)."""
    import io as _io

    from PIL import Image

    from ..core.dispatch import wrap
    data = bytes(np.asarray(unwrap(x)).astype(np.uint8).tobytes())
    img = Image.open(_io.BytesIO(data))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return wrap(jnp.asarray(arr))


def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=400, keep_top_k=200, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True,
               return_index=False, return_rois_num=True, name=None):
    """Matrix NMS (SOLOv2) — decay scores by overlap with higher-scored
    same-class candidates (reference ops.yaml: matrix_nms). Host-side
    like the reference CPU kernel (dynamic output count)."""
    from ..core.dispatch import wrap
    b_np = np.asarray(unwrap(bboxes))   # [N, M, 4]
    s_np = np.asarray(unwrap(scores))   # [N, C, M]
    outs, indices, counts = [], [], []
    for n in range(b_np.shape[0]):
        per_img = []
        for c in range(s_np.shape[1]):
            if c == background_label:
                continue
            sc = s_np[n, c]
            keep = np.where(sc > score_threshold)[0]
            if keep.size == 0:
                continue
            order = keep[np.argsort(-sc[keep])][:nms_top_k]
            boxes_c = b_np[n, order]
            sc_c = sc[order]
            # pairwise IoU of the sorted candidates
            x1 = np.maximum(boxes_c[:, None, 0], boxes_c[None, :, 0])
            y1 = np.maximum(boxes_c[:, None, 1], boxes_c[None, :, 1])
            x2 = np.minimum(boxes_c[:, None, 2], boxes_c[None, :, 2])
            y2 = np.minimum(boxes_c[:, None, 3], boxes_c[None, :, 3])
            off = 0.0 if normalized else 1.0
            inter = (np.clip(x2 - x1 + off, 0, None)
                     * np.clip(y2 - y1 + off, 0, None))
            area = ((boxes_c[:, 2] - boxes_c[:, 0] + off)
                    * (boxes_c[:, 3] - boxes_c[:, 1] + off))
            iou = inter / np.maximum(area[:, None] + area[None, :]
                                     - inter, 1e-10)
            iou = np.triu(iou, k=1)
            iou_cmax = iou.max(axis=0)     # per-candidate max w/ higher
            # decay_j = min_i f(iou_ij) / f(iou_cmax_i): denominator runs
            # over the HIGHER-ranked candidate i (rows)
            if use_gaussian:
                decay = np.exp((iou_cmax[:, None] ** 2 - iou ** 2)
                               * gaussian_sigma)
            else:
                decay = (1 - iou) / np.maximum(
                    1 - iou_cmax[:, None], 1e-10)
            decay = np.triu(decay, k=1) + np.tril(np.ones_like(decay))
            decay = decay.min(axis=0)
            dec_sc = sc_c * decay
            sel = np.where(dec_sc >= post_threshold)[0]
            for i in sel:
                per_img.append((c, dec_sc[i], *boxes_c[i], order[i]))
        per_img.sort(key=lambda r: -r[1])
        per_img = per_img[:keep_top_k]
        counts.append(len(per_img))
        for r in per_img:
            outs.append(r[:6])
            # global index into the flattened [N*M] box tensor
            # (reference matrix_nms_kernel.cc:235 pushes start + idx)
            indices.append(n * b_np.shape[1] + r[6])
    out = wrap(np.asarray(outs, np.float32).reshape(-1, 6))
    res = [out]
    if return_index:
        res.append(wrap(np.asarray(indices, np.int64)))
    if return_rois_num:
        res.append(wrap(np.asarray(counts, np.int32)))
    return tuple(res) if len(res) > 1 else out


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=True,
                       name=None):
    """RPN proposal generation: decode deltas at anchors, clip to image,
    filter small, NMS (reference ops.yaml: generate_proposals). Host-side
    (dynamic output count, like the reference CPU kernel)."""
    from ..core.dispatch import wrap
    sc = np.asarray(unwrap(scores))       # [N, A, H, W]
    bd = np.asarray(unwrap(bbox_deltas))  # [N, 4A, H, W]
    ims = np.asarray(unwrap(img_size))    # [N, 2]
    anc = np.asarray(unwrap(anchors)).reshape(-1, 4)
    var = np.asarray(unwrap(variances)).reshape(-1, 4)
    N = sc.shape[0]
    rois_out, num_out, score_out = [], [], []
    off = 1.0 if pixel_offset else 0.0
    for n in range(N):
        s = sc[n].transpose(1, 2, 0).reshape(-1)
        d = bd[n].reshape(-1, 4, sc.shape[2], sc.shape[3]) \
            .transpose(2, 3, 0, 1).reshape(-1, 4)
        order = np.argsort(-s)[:pre_nms_top_n]
        s_n, d_n, a_n, v_n = s[order], d[order], anc[order], var[order]
        aw = a_n[:, 2] - a_n[:, 0] + off
        ah = a_n[:, 3] - a_n[:, 1] + off
        acx = a_n[:, 0] + aw / 2
        acy = a_n[:, 1] + ah / 2
        cx = v_n[:, 0] * d_n[:, 0] * aw + acx
        cy = v_n[:, 1] * d_n[:, 1] * ah + acy
        wk = aw * np.exp(np.clip(v_n[:, 2] * d_n[:, 2], None, 10))
        hk = ah * np.exp(np.clip(v_n[:, 3] * d_n[:, 3], None, 10))
        props = np.stack([cx - wk / 2, cy - hk / 2,
                          cx + wk / 2 - off, cy + hk / 2 - off], axis=1)
        H_im, W_im = float(ims[n, 0]), float(ims[n, 1])
        props[:, 0::2] = np.clip(props[:, 0::2], 0, W_im - off)
        props[:, 1::2] = np.clip(props[:, 1::2], 0, H_im - off)
        ws = props[:, 2] - props[:, 0] + off
        hs = props[:, 3] - props[:, 1] + off
        keep = np.where((ws >= min_size) & (hs >= min_size))[0]
        props, s_n = props[keep], s_n[keep]
        # greedy NMS
        order2 = np.argsort(-s_n)
        selected = []
        while order2.size and len(selected) < post_nms_top_n:
            i = order2[0]
            selected.append(i)
            xx1 = np.maximum(props[i, 0], props[order2[1:], 0])
            yy1 = np.maximum(props[i, 1], props[order2[1:], 1])
            xx2 = np.minimum(props[i, 2], props[order2[1:], 2])
            yy2 = np.minimum(props[i, 3], props[order2[1:], 3])
            inter = (np.clip(xx2 - xx1 + off, 0, None)
                     * np.clip(yy2 - yy1 + off, 0, None))
            area_i = (props[i, 2] - props[i, 0] + off) \
                * (props[i, 3] - props[i, 1] + off)
            area_o = (props[order2[1:], 2] - props[order2[1:], 0] + off) \
                * (props[order2[1:], 3] - props[order2[1:], 1] + off)
            iou = inter / np.maximum(area_i + area_o - inter, 1e-10)
            order2 = order2[1:][iou <= nms_thresh]
        rois_out.append(props[selected])
        score_out.append(s_n[selected])
        num_out.append(len(selected))
    rois = wrap(np.concatenate(rois_out).astype(np.float32)
                if rois_out else np.zeros((0, 4), np.float32))
    scs = wrap(np.concatenate(score_out).astype(np.float32))
    if return_rois_num:
        return rois, scs, wrap(np.asarray(num_out, np.int32))
    return rois, scs


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 detection loss (reference ops.yaml: yolo_loss kernel).

    x: [N, mask*(5+cls), H, W]; gt_box: [N, B, 4] (xywh, image-relative
    0..1); gt_label: [N, B]. Anchor assignment (best wh-IoU over ALL
    anchors), coordinate SCE/L1 losses weighted by (2 - gw*gh),
    objectness BCE with ignore region, class BCE — same decomposition as
    the reference kernel, all as one vectorised jnp program.
    """
    anchors_np = np.asarray(anchors, np.float32).reshape(-1, 2)
    mask_np = np.asarray(anchor_mask, np.int64)
    n_mask = len(mask_np)

    def fn(xx, gbox, glabel, *rest):
        N, C, H, W = xx.shape
        xx = xx.reshape(N, n_mask, 5 + class_num, H, W)
        B = gbox.shape[1]
        an_w = jnp.asarray(anchors_np[:, 0]) / (downsample_ratio * W)
        an_h = jnp.asarray(anchors_np[:, 1]) / (downsample_ratio * H)

        tx, ty = xx[:, :, 0], xx[:, :, 1]
        tw, th = xx[:, :, 2], xx[:, :, 3]
        tobj = xx[:, :, 4]
        tcls = xx[:, :, 5:]

        # decoded prediction boxes (for the ignore mask)
        gx = (jax.nn.sigmoid(tx) * scale_x_y - 0.5 * (scale_x_y - 1)
              + jnp.arange(W)[None, None, None, :]) / W
        gy = (jax.nn.sigmoid(ty) * scale_x_y - 0.5 * (scale_x_y - 1)
              + jnp.arange(H)[None, None, :, None]) / H
        gw = jnp.exp(tw) * an_w[mask_np][None, :, None, None]
        gh = jnp.exp(th) * an_h[mask_np][None, :, None, None]

        # IoU of every predicted box with every gt box -> ignore mask
        px1, px2 = gx - gw / 2, gx + gw / 2
        py1, py2 = gy - gh / 2, gy + gh / 2
        bx1 = (gbox[:, :, 0] - gbox[:, :, 2] / 2)[:, None, :]
        bx2 = (gbox[:, :, 0] + gbox[:, :, 2] / 2)[:, None, :]
        by1 = (gbox[:, :, 1] - gbox[:, :, 3] / 2)[:, None, :]
        by2 = (gbox[:, :, 1] + gbox[:, :, 3] / 2)[:, None, :]
        ix1 = jnp.maximum(px1[:, :, None], bx1[..., None, None])
        ix2 = jnp.minimum(px2[:, :, None], bx2[..., None, None])
        iy1 = jnp.maximum(py1[:, :, None], by1[..., None, None])
        iy2 = jnp.minimum(py2[:, :, None], by2[..., None, None])
        inter = jnp.clip(ix2 - ix1, 0) * jnp.clip(iy2 - iy1, 0)
        area_p = (gw * gh)[:, :, None]
        area_g = (gbox[:, :, 2] * gbox[:, :, 3])[:, None, :, None, None]
        valid_gt = (gbox[:, :, 2] > 0)[:, None, :, None, None]
        iou = inter / jnp.maximum(area_p + area_g - inter, 1e-10)
        iou = jnp.where(valid_gt, iou, 0.0)
        best_iou = jnp.max(iou, axis=2)               # [N, m, H, W]
        ignore = best_iou > ignore_thresh

        # anchor assignment per gt: best wh-IoU over ALL anchors
        bw = gbox[:, :, 2][..., None]                  # [N, B, 1]
        bh = gbox[:, :, 3][..., None]
        inter_a = jnp.minimum(bw, an_w) * jnp.minimum(bh, an_h)
        iou_a = inter_a / jnp.maximum(bw * bh + an_w * an_h - inter_a,
                                      1e-10)
        best_anchor = jnp.argmax(iou_a, axis=-1)       # [N, B]
        # position of each gt in the grid
        gi = jnp.clip((gbox[:, :, 0] * W).astype(jnp.int32), 0, W - 1)
        gj = jnp.clip((gbox[:, :, 1] * H).astype(jnp.int32), 0, H - 1)
        score = rest[0] if rest else jnp.ones((N, B), xx.dtype)

        loss = jnp.zeros((N,), xx.dtype)
        obj_target = jnp.zeros((N, n_mask, H, W), xx.dtype)
        obj_weight = jnp.zeros((N, n_mask, H, W), xx.dtype)
        smooth_pos = 1.0 - 1.0 / class_num if use_label_smooth and \
            class_num > 1 else 1.0
        smooth_neg = 1.0 / class_num if use_label_smooth and \
            class_num > 1 else 0.0
        ni = jnp.arange(N)[:, None]
        for k, a_idx in enumerate(mask_np):
            resp = (best_anchor == a_idx) & (gbox[:, :, 2] > 0)  # [N, B]
            wgt = (2.0 - gbox[:, :, 2] * gbox[:, :, 3]) * score
            # targets at (gj, gi)
            tgt_x = gbox[:, :, 0] * W - gi
            tgt_y = gbox[:, :, 1] * H - gj
            tgt_w = jnp.log(jnp.maximum(gbox[:, :, 2] / an_w[a_idx],
                                        1e-9))
            tgt_h = jnp.log(jnp.maximum(gbox[:, :, 3] / an_h[a_idx],
                                        1e-9))
            px = tx[:, k][ni, gj, gi]
            py_ = ty[:, k][ni, gj, gi]
            pw = tw[:, k][ni, gj, gi]
            ph = th[:, k][ni, gj, gi]

            def bce(logit, target):
                return jnp.maximum(logit, 0) - logit * target \
                    + jnp.log1p(jnp.exp(-jnp.abs(logit)))
            coord = (bce(px, tgt_x) + bce(py_, tgt_y)
                     + jnp.abs(pw - tgt_w) + jnp.abs(ph - tgt_h)) * wgt
            loss = loss + jnp.sum(jnp.where(resp, coord, 0.0), axis=1)
            # class loss at responsible cells
            pcls = tcls[:, k][ni, :, gj, gi]           # [N, B, cls]
            onehot = jax.nn.one_hot(glabel, class_num, dtype=xx.dtype)
            cls_tgt = onehot * smooth_pos + (1 - onehot) * smooth_neg
            cls_l = jnp.sum(bce(pcls, cls_tgt), axis=-1) * score
            loss = loss + jnp.sum(jnp.where(resp, cls_l, 0.0), axis=1)
            # objectness target map
            obj_target = obj_target.at[ni, k, gj, gi].max(
                jnp.where(resp, 1.0, 0.0))
            obj_weight = obj_weight.at[ni, k, gj, gi].max(
                jnp.where(resp, score, 0.0))
        # objectness: positives weighted by score; negatives (not ignored)
        pos = obj_target > 0
        neg_ok = (~pos) & (~ignore)
        obj_bce = jnp.maximum(tobj, 0) - tobj * obj_target \
            + jnp.log1p(jnp.exp(-jnp.abs(tobj)))
        obj_l = jnp.where(pos, obj_bce * obj_weight,
                          jnp.where(neg_ok, obj_bce, 0.0))
        loss = loss + jnp.sum(obj_l, axis=(1, 2, 3))
        return loss
    args = [x, gt_box, gt_label] + ([gt_score] if gt_score is not None
                                    else [])
    return run_op("yolo_loss", fn, args)


def affine_channel(x, scale, bias, data_format="NCHW", name=None):
    """Per-channel affine y = scale*x + bias (reference ops.yaml:
    affine_channel)."""
    def fn(a, s, b):
        if data_format == "NCHW":
            shape = (1, -1) + (1,) * (a.ndim - 2)
        else:
            shape = (1,) * (a.ndim - 1) + (-1,)
        return a * s.reshape(shape) + b.reshape(shape)
    return run_op("affine_channel", fn, [x, scale, bias])


def box_clip(input, im_info, name=None):
    """Clip boxes to image bounds (reference ops.yaml: box_clip;
    im_info rows are [H, W, scale])."""
    def fn(b, info):
        info2 = info.reshape(-1, info.shape[-1])
        h = info2[:, 0] / info2[:, 2] - 1.0
        w = info2[:, 1] / info2[:, 2] - 1.0
        if b.ndim == 3:
            # batched [N, M, 4]: one limit per image
            h = h[:, None]
            w = w[:, None]
        else:
            # flat [M, 4]: single image -> scalar limits
            h = h[0]
            w = w[0]
        x1 = jnp.clip(b[..., 0], 0, None)
        y1 = jnp.clip(b[..., 1], 0, None)
        x2 = b[..., 2]
        y2 = b[..., 3]
        return jnp.stack([jnp.minimum(x1, w), jnp.minimum(y1, h),
                          jnp.clip(jnp.minimum(x2, w), 0, None),
                          jnp.clip(jnp.minimum(y2, h), 0, None)], axis=-1)
    return run_op("box_clip", fn, [input, im_info])


def bipartite_match(dist_matrix, match_type="bipartite", dist_threshold=0.5,
                    name=None):
    """Greedy bipartite matching of a [M, N] distance matrix (reference
    ops.yaml: bipartite_match kernel's greedy algorithm). Returns
    (match_indices [1, N], match_distances [1, N]); per_prediction mode
    additionally matches leftover columns above the threshold."""
    from ..core.dispatch import wrap
    d = np.array(unwrap(dist_matrix), np.float64)
    m, n_ = d.shape
    idx = np.full(n_, -1, np.int64)
    dist = np.zeros(n_, np.float64)
    work = d.copy()
    # greedy global-max assignment, one row to one column
    for _ in range(min(m, n_)):
        r, c = np.unravel_index(np.argmax(work), work.shape)
        if work[r, c] <= 0:
            break
        idx[c] = r
        dist[c] = d[r, c]
        work[r, :] = -1
        work[:, c] = -1
    if match_type == "per_prediction":
        for c in range(n_):
            if idx[c] == -1:
                r = int(np.argmax(d[:, c]))
                if d[r, c] >= dist_threshold:
                    idx[c] = r
                    dist[c] = d[r, c]
    return (wrap(idx.reshape(1, -1)),
            wrap(dist.astype(np.float32).reshape(1, -1)))


def collect_fpn_proposals(multi_rois, multi_scores, min_level, max_level,
                          post_nms_top_n, rois_num_per_level=None,
                          name=None):
    """Merge per-FPN-level proposals, keep top-N by score PER IMAGE
    (reference ops.yaml: collect_fpn_proposals). Host-side (dynamic
    count). rois_num_per_level: per-level [batch] counts; without it the
    whole input is one image."""
    from ..core.dispatch import wrap
    rois_l = [np.asarray(unwrap(r)) for r in multi_rois]
    scores_l = [np.asarray(unwrap(s)).reshape(-1) for s in multi_scores]
    if rois_num_per_level is None:
        rois = np.concatenate(rois_l)
        scores = np.concatenate(scores_l)
        order = np.argsort(-scores)[:post_nms_top_n]
        return wrap(rois[order].astype(np.float32))
    counts_l = [np.asarray(unwrap(c)).astype(np.int64).reshape(-1)
                for c in rois_num_per_level]
    batch = len(counts_l[0])
    out_rois, out_counts = [], []
    for b in range(batch):
        rs, ss = [], []
        for rois, scores, counts in zip(rois_l, scores_l, counts_l):
            beg = int(counts[:b].sum())
            end = beg + int(counts[b])
            rs.append(rois[beg:end])
            ss.append(scores[beg:end])
        rs = np.concatenate(rs)
        ss = np.concatenate(ss)
        order = np.argsort(-ss)[:post_nms_top_n]
        out_rois.append(rs[order])
        out_counts.append(len(order))
    return (wrap(np.concatenate(out_rois).astype(np.float32)),
            wrap(np.asarray(out_counts, np.int32)))


def multiclass_nms3(bboxes, scores, rois_num=None, score_threshold=0.05,
                    nms_top_k=1000, keep_top_k=100, nms_threshold=0.3,
                    normalized=True, nms_eta=1.0, background_label=-1,
                    return_index=False, name=None):
    """Per-class hard NMS over [N, M, 4] boxes / [N, C, M] scores
    (reference ops.yaml: multiclass_nms3). Output rows are
    [label, score, x1, y1, x2, y2] like the kernel."""
    from ..core.dispatch import wrap
    b_np = np.asarray(unwrap(bboxes))
    s_np = np.asarray(unwrap(scores))
    off = 0.0 if normalized else 1.0
    outs, indices, counts = [], [], []
    for n in range(b_np.shape[0]):
        per_img = []
        for c in range(s_np.shape[1]):
            if c == background_label:
                continue
            sc = s_np[n, c]
            keep = np.where(sc > score_threshold)[0]
            order = keep[np.argsort(-sc[keep])][:nms_top_k]
            boxes_c = b_np[n, order]
            kept = []
            thr = nms_threshold
            cand = list(range(len(order)))
            while cand:
                i = cand.pop(0)
                kept.append(i)
                if not cand:
                    break
                bi = boxes_c[i]
                rest = boxes_c[cand]
                xx1 = np.maximum(bi[0], rest[:, 0])
                yy1 = np.maximum(bi[1], rest[:, 1])
                xx2 = np.minimum(bi[2], rest[:, 2])
                yy2 = np.minimum(bi[3], rest[:, 3])
                inter = (np.clip(xx2 - xx1 + off, 0, None)
                         * np.clip(yy2 - yy1 + off, 0, None))
                ai = (bi[2] - bi[0] + off) * (bi[3] - bi[1] + off)
                ar = ((rest[:, 2] - rest[:, 0] + off)
                      * (rest[:, 3] - rest[:, 1] + off))
                iou = inter / np.maximum(ai + ar - inter, 1e-10)
                cand = [c2 for k, c2 in enumerate(cand) if iou[k] <= thr]
                if nms_eta < 1.0 and thr > 0.5:
                    thr *= nms_eta
            for i in kept:
                per_img.append((c, sc[order[i]], *boxes_c[i],
                                n * b_np.shape[1] + order[i]))
        per_img.sort(key=lambda r: -r[1])
        per_img = per_img[:keep_top_k]
        counts.append(len(per_img))
        for r in per_img:
            outs.append(r[:6])
            indices.append(r[6])
    out = wrap(np.asarray(outs, np.float32).reshape(-1, 6))
    res = [out]
    if return_index:
        res.append(wrap(np.asarray(indices, np.int64)))
    res.append(wrap(np.asarray(counts, np.int32)))
    return tuple(res)


def detection_map(detect_res, label, class_num, background_label=0,
                  overlap_threshold=0.5, evaluate_difficult=True,
                  ap_version="integral", name=None):
    """Mean average precision over detection results (reference ops.yaml:
    detection_map). detect_res rows: [label, score, x1, y1, x2, y2];
    label rows: [label, x1, y1, x2, y2(, difficult)]. Single-image host
    evaluation like the reference CPU kernel's core loop."""
    from ..core.dispatch import wrap
    det = np.asarray(unwrap(detect_res), np.float64).reshape(-1, 6)
    gt = np.asarray(unwrap(label), np.float64)
    has_difficult = gt.shape[1] >= 6
    difficult = gt[:, 5].astype(bool) if has_difficult \
        else np.zeros(len(gt), bool)
    if not evaluate_difficult:
        gt = gt[~difficult]
    aps = []
    for c in range(class_num):
        if c == background_label:
            continue
        d_c = det[det[:, 0] == c]
        g_c = gt[gt[:, 0] == c]
        if len(g_c) == 0:
            continue
        order = np.argsort(-d_c[:, 1])
        d_c = d_c[order]
        matched = np.zeros(len(g_c), bool)
        tp = np.zeros(len(d_c))
        fp = np.zeros(len(d_c))
        for i, row in enumerate(d_c):
            best_iou, best_j = 0.0, -1
            for j, g in enumerate(g_c):
                xx1 = max(row[2], g[1])
                yy1 = max(row[3], g[2])
                xx2 = min(row[4], g[3])
                yy2 = min(row[5], g[4])
                inter = max(xx2 - xx1, 0) * max(yy2 - yy1, 0)
                a1 = (row[4] - row[2]) * (row[5] - row[3])
                a2 = (g[3] - g[1]) * (g[4] - g[2])
                iou = inter / max(a1 + a2 - inter, 1e-10)
                if iou > best_iou:
                    best_iou, best_j = iou, j
            if best_iou >= overlap_threshold and not matched[best_j]:
                tp[i] = 1
                matched[best_j] = True
            else:
                fp[i] = 1
        ctp = np.cumsum(tp)
        cfp = np.cumsum(fp)
        rec = ctp / len(g_c)
        prec = ctp / np.maximum(ctp + cfp, 1e-10)
        if ap_version == "11point":
            ap = np.mean([prec[rec >= t].max() if (rec >= t).any() else 0
                          for t in np.linspace(0, 1, 11)])
        else:  # integral
            ap = 0.0
            prev_r = 0.0
            for r, p2 in zip(rec, prec):
                ap += (r - prev_r) * p2
                prev_r = r
        aps.append(ap)
    m = float(np.mean(aps)) if aps else 0.0
    return wrap(np.asarray(m, np.float32))


def yolo_box_head(x, anchors, class_num, name=None):
    """YOLO head passthrough (reference ops.yaml: yolo_box_head — the
    fused CUDA graph just forwards activations to yolo_box_post)."""
    return x


def yolo_box_post(boxes0, boxes1, boxes2, image_shape, image_scale,
                  anchors0, anchors1, anchors2, class_num, conf_thresh,
                  downsample_ratio0, downsample_ratio1, downsample_ratio2,
                  clip_bbox=True, scale_x_y=1.0, nms_threshold=0.45,
                  name=None):
    """Decode 3 YOLO feature maps + NMS (reference ops.yaml:
    yolo_box_post): yolo_box per level, concat, per-class NMS."""
    from ..core.dispatch import wrap
    from ..ops.manipulation import concat
    levels = [(boxes0, anchors0, downsample_ratio0),
              (boxes1, anchors1, downsample_ratio1),
              (boxes2, anchors2, downsample_ratio2)]
    all_boxes, all_scores = [], []
    img_shape = unwrap(image_shape)
    for feat, anc, ds in levels:
        b, s = yolo_box(feat, wrap(jnp.asarray(img_shape)), list(anc),
                        class_num, conf_thresh, ds, clip_bbox,
                        scale_x_y=scale_x_y)
        all_boxes.append(b)
        all_scores.append(s)
    boxes = concat(all_boxes, axis=1)            # [N, sumM, 4]
    scores = concat(all_scores, axis=1)          # [N, sumM, C]
    # rescale to original-image coordinates (reference divides by scale)
    scale = np.asarray(unwrap(image_scale), np.float32).reshape(-1)
    boxes_np = np.asarray(unwrap(boxes)) / scale[:, None, None]
    scores_t = np.asarray(unwrap(scores)).transpose(0, 2, 1)
    return multiclass_nms3(wrap(boxes_np), wrap(scores_t),
                           score_threshold=conf_thresh,
                           nms_threshold=nms_threshold)


def correlation(x, y, pad_size, kernel_size, max_displacement, stride1,
                stride2, corr_type_multiply=1, name=None):
    """FlowNet correlation layer (reference ops.yaml: correlation): for
    each displacement, the channel-patch inner product between x and the
    displaced y, averaged over channels * kernel_size^2. Displacements
    are static python unrolls -> one fused XLA program; shifts slice a
    zero-padded copy (reference zero-padding semantics, no wraparound)."""
    def fn(a, b):
        n, c, h, w = a.shape
        d = max_displacement // stride2
        k = kernel_size
        # pad a by pad_size; pad b by pad_size + max displacement so any
        # shifted window reads zeros, never wrapped pixels
        ap = jnp.pad(a, [(0, 0), (0, 0), (pad_size, pad_size),
                         (pad_size, pad_size)])
        m = max_displacement
        bp = jnp.pad(b, [(0, 0), (0, 0), (pad_size + m, pad_size + m),
                         (pad_size + m, pad_size + m)])
        h2, w2 = ap.shape[2], ap.shape[3]
        outs = []
        for dy in range(-d, d + 1):
            for dx in range(-d, d + 1):
                oy, ox = dy * stride2, dx * stride2
                b_shift = bp[:, :, m + oy:m + oy + h2,
                             m + ox:m + ox + w2]
                prod = jnp.mean(ap * b_shift, axis=1)    # [n, h2, w2]
                if k > 1:
                    # patch mean over the k x k window (SAME padding)
                    prod = jax.lax.reduce_window(
                        prod, jnp.asarray(0.0, prod.dtype),
                        jax.lax.add, (1, k, k), (1, 1, 1),
                        "SAME") / (k * k)
                outs.append(prod[:, pad_size:pad_size + h,
                                 pad_size:pad_size + w])
        out = jnp.stack(outs, axis=1)                    # [n, D*D, h, w]
        if stride1 > 1:
            out = out[:, :, ::stride1, ::stride1]
        return out
    return run_op("correlation", fn, [x, y])
