"""paddle.vision.ops (reference python/paddle/vision/ops.py: nms,
roi_align, roi_pool, box_coder, prior_box, yolo_box, ...).

TPU-first notes: detection post-processing is branch-heavy; these
lowerings keep static shapes (fixed iteration counts, masked selects) so
they compile under jit. NMS returns keep-mask ordering like the
reference's kept-indices (padded with -1) rather than a dynamic-length
tensor.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import run_op, run_op_nodiff, unwrap


def _roi_batch_indices(boxes, boxes_num):
    """Per-RoI batch image index from boxes_num (reference roi_align
    convention: the first boxes_num[0] rois belong to image 0, ...)."""
    n_rois = int(unwrap(boxes).shape[0])
    if boxes_num is None:
        return jnp.zeros((n_rois,), jnp.int32)
    counts = np.asarray(unwrap(boxes_num)).astype(np.int64).reshape(-1)
    return jnp.asarray(np.repeat(np.arange(len(counts)), counts),
                       jnp.int32)


def _iou_matrix(boxes):
    x1, y1, x2, y2 = [boxes[:, i] for i in range(4)]
    area = jnp.maximum(x2 - x1, 0) * jnp.maximum(y2 - y1, 0)
    ix1 = jnp.maximum(x1[:, None], x1[None, :])
    iy1 = jnp.maximum(y1[:, None], y1[None, :])
    ix2 = jnp.minimum(x2[:, None], x2[None, :])
    iy2 = jnp.minimum(y2[:, None], y2[None, :])
    inter = jnp.maximum(ix2 - ix1, 0) * jnp.maximum(iy2 - iy1, 0)
    union = area[:, None] + area[None, :] - inter
    return inter / jnp.maximum(union, 1e-10)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None, name=None):
    """Hard NMS (reference vision/ops.py nms). Returns kept indices
    sorted by score, padded with -1 to the input length (static shape)."""
    def fn(b, s):
        n = b.shape[0]
        order = jnp.argsort(-s)
        iou = _iou_matrix(b)[order][:, order]
        # greedy suppression with a fixed-length scan over rank positions
        def body(keep, i):
            # keep[j] == True means box at rank j survives so far
            suppress = (iou[i] > iou_threshold) & keep[i] & \
                (jnp.arange(n) > i)
            return keep & ~suppress, None
        keep0 = jnp.ones(n, bool)
        keep, _ = jax.lax.scan(body, keep0, jnp.arange(n))
        kept_sorted = jnp.where(keep, order, -1)
        # stable-move -1 entries to the back
        rank = jnp.where(keep, jnp.arange(n), n)
        kept_sorted = kept_sorted[jnp.argsort(rank)]
        if top_k is not None:
            kept_sorted = kept_sorted[:top_k]
        return kept_sorted
    s = scores if scores is not None else \
        jnp.arange(unwrap(boxes).shape[0], 0, -1).astype(jnp.float32)
    return run_op_nodiff("nms", fn, [boxes, s])


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign via bilinear grid sampling (reference ops.yaml: roi_align)."""
    out_h, out_w = (output_size if isinstance(output_size, (tuple, list))
                    else (output_size, output_size))
    batch_idx = _roi_batch_indices(boxes, boxes_num)

    def fn(feat, rois):
        # feat: [N, C, H, W]; rois: [R, 4]; each RoI reads its own
        # image's features (batch assignment from boxes_num)
        c, h, w = feat.shape[1:]
        off = 0.5 if aligned else 0.0
        ratio = sampling_ratio if sampling_ratio > 0 else 2

        def one_roi(roi, bidx):
            x1, y1, x2, y2 = roi * spatial_scale - off
            rw = jnp.maximum(x2 - x1, 1e-6)
            rh = jnp.maximum(y2 - y1, 1e-6)
            ys = y1 + (jnp.arange(out_h * ratio) + 0.5) * rh / (
                out_h * ratio)
            xs = x1 + (jnp.arange(out_w * ratio) + 0.5) * rw / (
                out_w * ratio)

            def sample(py, px):
                # reference border semantics (roi_align kernel): points
                # beyond (-1, size) contribute 0; points in (-1, 0) clamp
                # to the first pixel
                inside = (py > -1.0) & (py < h) & (px > -1.0) & (px < w)
                py = jnp.clip(py, 0.0, h - 1)
                px = jnp.clip(px, 0.0, w - 1)
                y0 = jnp.floor(py).astype(jnp.int32)
                x0 = jnp.floor(px).astype(jnp.int32)
                wy = py - y0
                wx = px - x0

                def g(yy, xx):
                    yc = jnp.clip(yy, 0, h - 1)
                    xc = jnp.clip(xx, 0, w - 1)
                    return feat[bidx, :, yc, xc]
                val = (g(y0, x0) * (1 - wy) * (1 - wx)
                       + g(y0, x0 + 1) * (1 - wy) * wx
                       + g(y0 + 1, x0) * wy * (1 - wx)
                       + g(y0 + 1, x0 + 1) * wy * wx)
                return val * inside

            grid = jax.vmap(lambda py: jax.vmap(
                lambda px: sample(py, px))(xs))(ys)
            # [out_h*r, out_w*r, C] -> average pool r x r
            grid = grid.reshape(out_h, ratio, out_w, ratio, c)
            return jnp.mean(grid, axis=(1, 3)).transpose(2, 0, 1)

        return jax.vmap(one_roi)(rois, batch_idx)  # [R, C, oh, ow]
    return run_op("roi_align", fn, [x, boxes])


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
             name=None):
    """Max RoI pooling (reference ops.yaml: roi_pool) — implemented as
    dense-sampled max (static shapes)."""
    out_h, out_w = (output_size if isinstance(output_size, (tuple, list))
                    else (output_size, output_size))
    batch_idx = _roi_batch_indices(boxes, boxes_num)

    def fn(feat, rois):
        c, h, w = feat.shape[1:]

        def one_roi(roi, bidx):
            x1, y1, x2, y2 = jnp.round(roi * spatial_scale)
            rw = jnp.maximum(x2 - x1 + 1, 1.0)
            rh = jnp.maximum(y2 - y1 + 1, 1.0)
            ratio = 4
            ys = y1 + (jnp.arange(out_h * ratio) + 0.5) * rh / (
                out_h * ratio)
            xs = x1 + (jnp.arange(out_w * ratio) + 0.5) * rw / (
                out_w * ratio)
            yi = jnp.clip(ys.astype(jnp.int32), 0, h - 1)
            xi = jnp.clip(xs.astype(jnp.int32), 0, w - 1)
            patch = feat[bidx][:, yi][:, :, xi]
            patch = patch.reshape(c, out_h, ratio, out_w, ratio)
            return jnp.max(patch, axis=(2, 4))

        return jax.vmap(one_roi)(rois, batch_idx)
    return run_op("roi_pool", fn, [x, boxes])


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    """reference ops.yaml: box_coder."""
    def fn(pb, pbv, tb):
        norm = 0.0 if box_normalized else 1.0
        pw = pb[:, 2] - pb[:, 0] + norm
        ph = pb[:, 3] - pb[:, 1] + norm
        pcx = pb[:, 0] + pw * 0.5
        pcy = pb[:, 1] + ph * 0.5
        if code_type == "encode_center_size":
            tw = tb[:, 2] - tb[:, 0] + norm
            th = tb[:, 3] - tb[:, 1] + norm
            tcx = tb[:, 0] + tw * 0.5
            tcy = tb[:, 1] + th * 0.5
            out = jnp.stack([
                (tcx - pcx) / pw / pbv[:, 0],
                (tcy - pcy) / ph / pbv[:, 1],
                jnp.log(tw / pw) / pbv[:, 2],
                jnp.log(th / ph) / pbv[:, 3]], axis=1)
        else:  # decode_center_size
            dcx = pbv[:, 0] * tb[:, 0] * pw + pcx
            dcy = pbv[:, 1] * tb[:, 1] * ph + pcy
            dw = jnp.exp(pbv[:, 2] * tb[:, 2]) * pw
            dh = jnp.exp(pbv[:, 3] * tb[:, 3]) * ph
            out = jnp.stack([dcx - dw * 0.5, dcy - dh * 0.5,
                             dcx + dw * 0.5 - norm,
                             dcy + dh * 0.5 - norm], axis=1)
        return out
    return run_op("box_coder", fn, [prior_box, prior_box_var, target_box])


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """SSD prior boxes (reference ops.yaml: prior_box)."""
    a = unwrap(input)
    img = unwrap(image)
    h, w = a.shape[-2:]
    ih, iw = img.shape[-2:]
    step_h = steps[1] or ih / h
    step_w = steps[0] or iw / w
    ars = list(aspect_ratios)
    if flip:
        ars += [1.0 / r for r in aspect_ratios if r != 1.0]
    boxes = []
    for ms in min_sizes:
        for ar in ars:
            bw = ms * np.sqrt(ar) / 2
            bh = ms / np.sqrt(ar) / 2
            boxes.append((bw, bh))
        if max_sizes:
            for mx in max_sizes:
                s = np.sqrt(ms * mx) / 2
                boxes.append((s, s))
    cy = (np.arange(h) + offset) * step_h
    cx = (np.arange(w) + offset) * step_w
    gy, gx = np.meshgrid(cy, cx, indexing="ij")
    out = np.zeros((h, w, len(boxes), 4), np.float32)
    for i, (bw, bh) in enumerate(boxes):
        out[..., i, 0] = (gx - bw) / iw
        out[..., i, 1] = (gy - bh) / ih
        out[..., i, 2] = (gx + bw) / iw
        out[..., i, 3] = (gy + bh) / ih
    if clip:
        out = np.clip(out, 0, 1)
    var = np.broadcast_to(np.asarray(variance, np.float32),
                          out.shape).copy()
    from ..core.dispatch import wrap
    return wrap(jnp.asarray(out)), wrap(jnp.asarray(var))


def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5, name=None):
    """YOLO detection decode (reference ops.yaml: yolo_box)."""
    na = len(anchors) // 2

    def fn(a, imgs):
        n, _, h, w = a.shape
        v = a.reshape(n, na, 5 + class_num, h, w)
        gx = jnp.arange(w).reshape(1, 1, 1, w)
        gy = jnp.arange(h).reshape(1, 1, h, 1)
        sx = jax.nn.sigmoid(v[:, :, 0]) * scale_x_y - (scale_x_y - 1) / 2
        sy = jax.nn.sigmoid(v[:, :, 1]) * scale_x_y - (scale_x_y - 1) / 2
        bx = (gx + sx) / w
        by = (gy + sy) / h
        aw = jnp.asarray(anchors[0::2], a.dtype).reshape(1, na, 1, 1)
        ah = jnp.asarray(anchors[1::2], a.dtype).reshape(1, na, 1, 1)
        bw = jnp.exp(v[:, :, 2]) * aw / (w * downsample_ratio)
        bh = jnp.exp(v[:, :, 3]) * ah / (h * downsample_ratio)
        conf = jax.nn.sigmoid(v[:, :, 4])
        probs = jax.nn.sigmoid(v[:, :, 5:]) * conf[:, :, None]
        imh = imgs[:, 0].reshape(n, 1, 1, 1)
        imw = imgs[:, 1].reshape(n, 1, 1, 1)
        x1 = (bx - bw / 2) * imw
        y1 = (by - bh / 2) * imh
        x2 = (bx + bw / 2) * imw
        y2 = (by + bh / 2) * imh
        if clip_bbox:
            x1 = jnp.clip(x1, 0, imw - 1)
            y1 = jnp.clip(y1, 0, imh - 1)
            x2 = jnp.clip(x2, 0, imw - 1)
            y2 = jnp.clip(y2, 0, imh - 1)
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(n, -1, 4)
        scores = probs.transpose(0, 1, 3, 4, 2).reshape(
            n, -1, class_num)
        keep = (conf > conf_thresh).reshape(n, -1, 1)
        return boxes * keep, scores * keep
    return run_op("yolo_box", fn, [x, img_size])


def shuffle_channel(x, group, name=None):
    """reference ops.yaml: shuffle_channel."""
    def fn(a):
        n, c, h, w = a.shape
        return a.reshape(n, group, c // group, h, w).swapaxes(
            1, 2).reshape(n, c, h, w)
    return run_op("shuffle_channel", fn, [x])


def deform_conv2d(*a, **kw):
    raise NotImplementedError(
        "deformable convolution needs a gather-heavy custom kernel; "
        "planned as a Pallas kernel")


def distribute_fpn_proposals(*a, **kw):
    raise NotImplementedError("FPN proposal distribution is dynamic-shape "
                              "host logic; run it outside jit")
