"""Image backend selection + loading (reference:
python/paddle/vision/image.py set_image_backend/get_image_backend/image_load).
"""
from __future__ import annotations

_image_backend = "pil"


def get_image_backend():
    """Name of the package used to load images ('pil' or 'cv2')."""
    return _image_backend


def set_image_backend(backend):
    """Select the package used to load images (reference: set_image_backend;
    'tensor' decode is not offered — decoding happens on host either way)."""
    global _image_backend
    if backend not in ("pil", "cv2"):
        raise ValueError(
            f"Expected backend 'pil' or 'cv2', got {backend}")
    _image_backend = backend


def image_load(path, backend=None):
    """Load an image file via the selected backend (reference: image_load).

    Returns a PIL.Image under 'pil', an HWC BGR ndarray under 'cv2' —
    matching the reference's return types.
    """
    backend = backend or _image_backend
    if backend not in ("pil", "cv2"):
        raise ValueError(
            f"Expected backend 'pil' or 'cv2', got {backend}")
    if backend == "pil":
        from PIL import Image
        return Image.open(path)
    import cv2
    return cv2.imread(str(path))
