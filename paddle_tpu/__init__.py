"""paddle_tpu — a TPU-native deep learning framework with a
PaddlePaddle-shaped API, built on JAX/XLA/Pallas.

Architecture (see SURVEY.md §7): eager ops execute through jnp on XLA with a
define-by-run tape for dygraph autograd; the performance path compiles whole
train steps with jax.jit/jax.grad over jax.sharding meshes. There are no
per-op device kernels — XLA is the kernel library; Pallas supplies the few
hot kernels XLA can't fuse (flash attention, MoE dispatch).
"""
from __future__ import annotations

import jax as _jax

# paddle semantics need int64/float64 available; defaults remain fp32/int64
_jax.config.update("jax_enable_x64", True)

from .core import dtype as _dtype_mod  # noqa: E402
from .core.dtype import (  # noqa: F401,E402
    bfloat16, bool_, complex128, complex64, dtype, finfo, float16, float32,
    float64, float8_e4m3fn, float8_e5m2, get_default_dtype, iinfo, int16,
    int32, int64, int8, promote_types, pstring, raw, set_default_dtype,
    uint8,
)
bool = bool_  # noqa: A001 (paddle.bool)

from .core.place import (  # noqa: F401,E402
    CPUPlace, CUDAPinnedPlace, CUDAPlace, CustomPlace, Place, TPUPlace,
    XPUPlace, get_device, is_compiled_with_cuda, is_compiled_with_tpu,
    is_compiled_with_xpu, set_device,
)
from .core.tensor import Tensor, to_tensor  # noqa: F401,E402
from .core.random import seed, get_rng_state, set_rng_state  # noqa: F401,E402
from .core.flags import get_flags, set_flags  # noqa: F401,E402
from .core import flags as flags  # noqa: F401,E402

from . import ops  # noqa: F401,E402  (patches Tensor methods)
from .ops.creation import *  # noqa: F401,F403,E402
from .ops.math import *  # noqa: F401,F403,E402
from .ops.manipulation import *  # noqa: F401,F403,E402
from .ops.logic import *  # noqa: F401,F403,E402
from .ops.search import *  # noqa: F401,F403,E402
from .ops.stat import *  # noqa: F401,F403,E402
from . import linalg  # noqa: E402  (real module: import paddle.linalg works)
from .ops.linalg import norm, einsum  # noqa: F401,E402
from .ops.linalg import cdist, pdist, matrix_transpose  # noqa: F401,E402
from .ops.math import matmul, mm, bmm, mv, dot, pow  # noqa: F401,E402
from .ops.inplace import *  # noqa: F401,F403,E402

# numpy-compatible constants (reference: paddle.pi/nan/inf/newaxis)
import numpy as _np  # noqa: E402
pi = float(_np.pi)
nan = float(_np.nan)
inf = float(_np.inf)
newaxis = None

from .core.tape import no_grad_guard as no_grad  # noqa: F401,E402
from .core.tape import enable_grad_guard as enable_grad  # noqa: F401,E402
from .core.tape import is_grad_enabled  # noqa: F401,E402
from .autograd.functional import grad  # noqa: F401,E402
from . import autograd  # noqa: F401,E402
from . import amp  # noqa: F401,E402
from . import device  # noqa: F401,E402
from .framework.io import save, load  # noqa: F401,E402
from . import framework  # noqa: F401,E402
from . import version  # noqa: F401,E402

# Subpackages below are built out incrementally; each line is enabled the
# moment the module lands (tests/test_import.py asserts the package imports).
from . import nn  # noqa: F401,E402
from .framework.param_attr import ParamAttr  # noqa: F401,E402
from . import optimizer  # noqa: F401,E402
from . import regularizer  # noqa: F401,E402
from . import jit  # noqa: F401,E402
from . import io  # noqa: F401,E402
from . import metric  # noqa: F401,E402
from .hapi.model import Model  # noqa: F401,E402
from .hapi import summary  # noqa: F401,E402
from . import hapi  # noqa: F401,E402
from . import vision  # noqa: F401,E402
from . import distributed  # noqa: F401,E402
from . import incubate  # noqa: F401,E402
from . import monitor  # noqa: F401,E402
from . import analysis  # noqa: F401,E402
from . import profiler  # noqa: F401,E402
from . import text  # noqa: F401,E402
from . import utils  # noqa: F401,E402
from . import audio  # noqa: F401,E402
from . import fft  # noqa: F401,E402
from . import quantization  # noqa: F401,E402
from . import sparse  # noqa: F401,E402
from . import distribution  # noqa: F401,E402
from . import geometric  # noqa: F401,E402
from . import signal  # noqa: F401,E402
from . import inference  # noqa: F401,E402
from . import cost_model  # noqa: F401,E402
from . import callbacks  # noqa: F401,E402
from . import hub  # noqa: F401,E402
from . import onnx  # noqa: F401,E402
from . import reader  # noqa: F401,E402
from . import static  # noqa: F401,E402
from . import sysconfig  # noqa: F401,E402
from . import tensor  # noqa: F401,E402


from .framework.misc import (  # noqa: F401,E402
    batch, check_shape, create_parameter, disable_signal_handler,
    get_cuda_rng_state, set_cuda_rng_state, set_grad_enabled,
    set_printoptions,
)
from .nn.initializer.lazy_init import LazyGuard  # noqa: F401,E402
from .utils.dlpack import from_dlpack, to_dlpack  # noqa: F401,E402
from .hapi.dynamic_flops import flops  # noqa: F401,E402
from .distributed.fleet.meta_parallel.parallel_wrappers import (  # noqa: F401,E402
    DataParallel,
)


def disable_static(place=None):
    """Dygraph is the default and only user-visible mode; the performance
    path is jit tracing (paddle_tpu.jit), not a program/executor world."""


def enable_static():
    raise NotImplementedError(
        "static graph mode is subsumed by paddle_tpu.jit.to_static on TPU")


def in_dynamic_mode():
    return True


__version__ = version.full_version
