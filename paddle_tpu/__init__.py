"""paddle_tpu — a TPU-native deep learning framework with a
PaddlePaddle-shaped API, built on JAX/XLA/Pallas.

Architecture (see SURVEY.md §7): eager ops execute through jnp on XLA with a
define-by-run tape for dygraph autograd; the performance path compiles whole
train steps with jax.jit/jax.grad over jax.sharding meshes. There are no
per-op device kernels — XLA is the kernel library; Pallas supplies the few
hot kernels XLA can't fuse (flash attention, MoE dispatch).
"""
from __future__ import annotations

import jax as _jax

# paddle semantics need int64/float64 available; defaults remain fp32/int64
_jax.config.update("jax_enable_x64", True)

from .core import dtype as _dtype_mod  # noqa: E402
from .core.dtype import (  # noqa: F401,E402
    bfloat16, bool_, complex128, complex64, dtype, finfo, float16, float32,
    float64, float8_e4m3fn, float8_e5m2, get_default_dtype, iinfo, int16,
    int32, int64, int8, promote_types, set_default_dtype, uint8,
)
bool = bool_  # noqa: A001 (paddle.bool)

from .core.place import (  # noqa: F401,E402
    CPUPlace, CUDAPinnedPlace, CUDAPlace, CustomPlace, Place, TPUPlace,
    XPUPlace, get_device, is_compiled_with_cuda, is_compiled_with_tpu,
    is_compiled_with_xpu, set_device,
)
from .core.tensor import Tensor, to_tensor  # noqa: F401,E402
from .core.random import seed, get_rng_state, set_rng_state  # noqa: F401,E402
from .core.flags import get_flags, set_flags  # noqa: F401,E402
from .core import flags as flags  # noqa: F401,E402

from . import ops  # noqa: F401,E402  (patches Tensor methods)
from .ops.creation import *  # noqa: F401,F403,E402
from .ops.math import *  # noqa: F401,F403,E402
from .ops.manipulation import *  # noqa: F401,F403,E402
from .ops.logic import *  # noqa: F401,F403,E402
from .ops.search import *  # noqa: F401,F403,E402
from .ops.stat import *  # noqa: F401,F403,E402
from .ops import linalg  # noqa: F401,E402
from .ops.linalg import norm, einsum  # noqa: F401,E402
from .ops.math import matmul, mm, bmm, mv, dot, pow  # noqa: F401,E402

from .core.tape import no_grad_guard as no_grad  # noqa: F401,E402
from .core.tape import enable_grad_guard as enable_grad  # noqa: F401,E402
from .core.tape import is_grad_enabled  # noqa: F401,E402
from .autograd.functional import grad  # noqa: F401,E402
from . import autograd  # noqa: F401,E402
from . import amp  # noqa: F401,E402
from . import nn  # noqa: F401,E402
from . import optimizer  # noqa: F401,E402
from . import io  # noqa: F401,E402
from . import device  # noqa: F401,E402
from .framework.io import save, load  # noqa: F401,E402
from . import framework  # noqa: F401,E402
from . import jit  # noqa: F401,E402
from . import static  # noqa: F401,E402
from . import metric  # noqa: F401,E402
from .hapi.model import Model  # noqa: F401,E402
from .hapi import summary  # noqa: F401,E402
from . import vision  # noqa: F401,E402
from . import distributed  # noqa: F401,E402
from . import incubate  # noqa: F401,E402
from . import profiler  # noqa: F401,E402
from . import utils  # noqa: F401,E402
from . import sparse  # noqa: F401,E402
from . import distribution  # noqa: F401,E402
from . import version  # noqa: F401,E402

disable_static = lambda place=None: None  # dygraph is the default mode
enable_static = None  # replaced by static module hook below


def enable_static():  # noqa: F811
    from . import static as _static
    _static._enable_static()


def in_dynamic_mode():
    from . import static as _static
    return not _static._static_mode_enabled()


def is_grad_enabled_():
    from .core import tape
    return tape.is_grad_enabled()


__version__ = version.full_version
