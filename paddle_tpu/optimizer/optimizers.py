"""Concrete optimizers: SGD, Momentum, Adagrad, RMSProp, Adam, AdamW, Lamb.

Reference: python/paddle/optimizer/{sgd,momentum,adagrad,rmsprop,adam,
adamw,lamb}.py. Each is a pure per-parameter update over jnp arrays; see
optimizer.py for the eager/compiled duality.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .optimizer import Optimizer, _decay_value


def _apply_l2(g, p, wd):
    """L2 regularization folds decay into the gradient (paddle semantics
    for SGD/Momentum/Adam with weight_decay=L2Decay)."""
    c = _decay_value(wd)
    if c:
        g = g + c * p
    return g


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)

    def _update(self, p, g, state, lr, wd=None):
        g = _apply_l2(g, p, wd if wd is not None else self._weight_decay)
        return p - lr * g, state


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _init_state(self, param):
        return {"velocity": jnp.zeros_like(param)}

    def _update(self, p, g, state, lr, wd=None):
        g = _apply_l2(g, p, wd if wd is not None else self._weight_decay)
        v = self._momentum * state["velocity"] + g
        if self._nesterov:
            p_new = p - lr * (g + self._momentum * v)
        else:
            p_new = p - lr * v
        return p_new, {"velocity": v}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, name=None,
                 initial_accumulator_value=0.0):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _init_state(self, param):
        return {"moment": jnp.full_like(param, self._init_acc)}

    def _update(self, p, g, state, lr, wd=None):
        g = _apply_l2(g, p, wd if wd is not None else self._weight_decay)
        m = state["moment"] + g * g
        return p - lr * g / (jnp.sqrt(m) + self._epsilon), {"moment": m}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum
        self._centered = centered

    def _init_state(self, param):
        s = {"mean_square": jnp.zeros_like(param),
             "momentum": jnp.zeros_like(param)}
        if self._centered:
            s["mean_grad"] = jnp.zeros_like(param)
        return s

    def _update(self, p, g, state, lr, wd=None):
        g = _apply_l2(g, p, wd if wd is not None else self._weight_decay)
        ms = self._rho * state["mean_square"] + (1 - self._rho) * g * g
        out = {"mean_square": ms}
        if self._centered:
            mg = self._rho * state["mean_grad"] + (1 - self._rho) * g
            denom = jnp.sqrt(ms - mg * mg + self._epsilon)
            out["mean_grad"] = mg
        else:
            denom = jnp.sqrt(ms + self._epsilon)
        mom = self._momentum * state["momentum"] + lr * g / denom
        out["momentum"] = mom
        return p - mom, out


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 use_multi_tensor=False, amsgrad=False, moment_dtype=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._amsgrad = amsgrad
        self._multi_precision = bool(multi_precision)
        # moment_dtype="bfloat16" halves optimizer-state HBM (the inverse
        # of the reference's multi_precision lever: params stay the fp32
        # masters, the *moments* are stored narrow and the update math
        # still runs in fp32). On a 1B-param model this frees ~4.3 GB —
        # the difference between batch 4 and batch 8 at seq 1024.
        self._moment_dtype = jnp.dtype(moment_dtype) \
            if moment_dtype is not None else None

    def _init_state(self, param):
        mdt = self._moment_dtype or param.dtype
        s = {"moment1": jnp.zeros(param.shape, mdt),
             "moment2": jnp.zeros(param.shape, mdt),
             "beta1_pow": jnp.ones((), param.dtype) * self._beta1,
             "beta2_pow": jnp.ones((), param.dtype) * self._beta2}
        if self._amsgrad:
            s["moment2_max"] = jnp.zeros(param.shape, mdt)
        return s

    def _adam_core(self, p, g, state, lr):
        mdt = state["moment1"].dtype
        cdt = jnp.promote_types(mdt, jnp.float32)  # update math in fp32
        g32 = g.astype(cdt)
        m1 = self._beta1 * state["moment1"].astype(cdt) \
            + (1 - self._beta1) * g32
        m2 = self._beta2 * state["moment2"].astype(cdt) \
            + (1 - self._beta2) * g32 * g32
        b1p, b2p = state["beta1_pow"], state["beta2_pow"]
        lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
        if self._amsgrad:
            m2m = jnp.maximum(state.get("moment2_max").astype(cdt), m2)
            denom = jnp.sqrt(m2m) + self._epsilon * jnp.sqrt(1 - b2p)
            new = {"moment1": m1.astype(mdt), "moment2": m2.astype(mdt),
                   "moment2_max": m2m.astype(mdt),
                   "beta1_pow": b1p * self._beta1,
                   "beta2_pow": b2p * self._beta2}
        else:
            denom = jnp.sqrt(m2) + self._epsilon * jnp.sqrt(1 - b2p)
            new = {"moment1": m1.astype(mdt), "moment2": m2.astype(mdt),
                   "beta1_pow": b1p * self._beta1,
                   "beta2_pow": b2p * self._beta2}
        return p - (lr_t * m1 / denom).astype(p.dtype), new

    def _update(self, p, g, state, lr, wd=None):
        g = _apply_l2(g, p, wd if wd is not None else self._weight_decay)
        return self._adam_core(p, g, state, lr)


class AdamW(Adam):
    """Decoupled weight decay (reference: python/paddle/optimizer/adamw.py:49)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, amsgrad=False,
                 moment_dtype=None, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, lazy_mode, multi_precision,
                         amsgrad=amsgrad, moment_dtype=moment_dtype,
                         name=name)
        self._apply_decay_param_fun = apply_decay_param_fun
        self._lr_ratio = lr_ratio

    def _update(self, p, g, state, lr, wd=None):
        coeff = _decay_value(wd if wd is not None else self._weight_decay)
        if coeff:
            p = p * (1.0 - lr * coeff)
        return self._adam_core(p, g, state, lr)

    def step(self):
        # apply_decay_param_fun filters decay by parameter name
        if self._apply_decay_param_fun is None:
            return super().step()
        fn = self._apply_decay_param_fun
        saved = self._weight_decay
        base_lr = self.get_lr()
        params_grads = [(p, p.grad) for p in self._parameter_list
                        if not p.stop_gradient and p.grad is not None]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        for p, g in params_grads:
            state = self._state_for(p)
            wd = saved if fn(p.name) else None
            import jax.numpy as jnp_
            garr = g._data
            if garr.dtype != p._data.dtype:
                garr = garr.astype(p._data.dtype)
            if wd is None:
                new_p, new_state = self._adam_core(p._data, garr, state,
                                                   base_lr)
            else:
                new_p, new_state = self._update(p._data, garr, state,
                                                base_lr, wd)
            p._data = new_p
            self._accumulators[id(p)] = new_state
        self._global_step += 1


class Lamb(Optimizer):
    """Reference: python/paddle/optimizer/lamb.py."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._beta1, self._beta2 = beta1, beta2
        self._epsilon = epsilon
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _init_state(self, param):
        return {"moment1": jnp.zeros_like(param),
                "moment2": jnp.zeros_like(param),
                "beta1_pow": jnp.ones((), param.dtype) * self._beta1,
                "beta2_pow": jnp.ones((), param.dtype) * self._beta2}

    def _update(self, p, g, state, lr, wd=None):
        m1 = self._beta1 * state["moment1"] + (1 - self._beta1) * g
        m2 = self._beta2 * state["moment2"] + (1 - self._beta2) * g * g
        b1p, b2p = state["beta1_pow"], state["beta2_pow"]
        m1_hat = m1 / (1 - b1p)
        m2_hat = m2 / (1 - b2p)
        r = m1_hat / (jnp.sqrt(m2_hat) + self._epsilon) + self._lamb_wd * p
        w_norm = jnp.sqrt(jnp.sum(p * p))
        r_norm = jnp.sqrt(jnp.sum(r * r))
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return p - lr * trust * r, {
            "moment1": m1, "moment2": m2,
            "beta1_pow": b1p * self._beta1, "beta2_pow": b2p * self._beta2}


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._beta1, self._beta2 = beta1, beta2
        self._epsilon = epsilon

    def _init_state(self, param):
        return {"moment": jnp.zeros_like(param),
                "inf_norm": jnp.zeros_like(param),
                "beta1_pow": jnp.ones((), param.dtype) * self._beta1}

    def _update(self, p, g, state, lr, wd=None):
        g = _apply_l2(g, p, wd if wd is not None else self._weight_decay)
        m = self._beta1 * state["moment"] + (1 - self._beta1) * g
        u = jnp.maximum(self._beta2 * state["inf_norm"], jnp.abs(g))
        b1p = state["beta1_pow"]
        p_new = p - lr / (1 - b1p) * m / (u + self._epsilon)
        return p_new, {"moment": m, "inf_norm": u,
                       "beta1_pow": b1p * self._beta1}


class Adadelta(Optimizer):
    """Reference: python/paddle/optimizer/adadelta.py."""

    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay,
                         grad_clip, name)
        self._epsilon = epsilon
        self._rho = rho

    def _init_state(self, param):
        return {"avg_squared_grad": jnp.zeros_like(param),
                "avg_squared_update": jnp.zeros_like(param)}

    def _update(self, p, g, state, lr, wd=None):
        g = _apply_l2(g, p, wd if wd is not None else self._weight_decay)
        asg = self._rho * state["avg_squared_grad"] + \
            (1 - self._rho) * g * g
        asu = state["avg_squared_update"]
        update = g * jnp.sqrt(asu + self._epsilon) / \
            jnp.sqrt(asg + self._epsilon)
        asu = self._rho * asu + (1 - self._rho) * update * update
        return p - lr * update, {"avg_squared_grad": asg,
                                 "avg_squared_update": asu}


class NAdam(Optimizer):
    """Nesterov Adam (reference: python/paddle/optimizer/nadam.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, momentum_decay=0.004, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay,
                         grad_clip, name)
        self._b1, self._b2 = beta1, beta2
        self._epsilon = epsilon
        self._psi = momentum_decay

    def _init_state(self, param):
        return {"moment1": jnp.zeros_like(param),
                "moment2": jnp.zeros_like(param),
                "step": jnp.zeros((), jnp.float32),
                "mu_product": jnp.ones((), jnp.float32)}

    def _update(self, p, g, state, lr, wd=None):
        g = _apply_l2(g, p, wd if wd is not None else self._weight_decay)
        t = state["step"] + 1
        mu_t = self._b1 * (1 - 0.5 * 0.96 ** (t * self._psi))
        mu_next = self._b1 * (1 - 0.5 * 0.96 ** ((t + 1) * self._psi))
        mu_prod = state["mu_product"] * mu_t
        m = self._b1 * state["moment1"] + (1 - self._b1) * g
        v = self._b2 * state["moment2"] + (1 - self._b2) * g * g
        m_hat = (mu_next * m / (1 - mu_prod * mu_next)
                 + (1 - mu_t) * g / (1 - mu_prod))
        v_hat = v / (1 - self._b2 ** t)
        new_p = p - lr * m_hat / (jnp.sqrt(v_hat) + self._epsilon)
        return new_p, {"moment1": m, "moment2": v, "step": t,
                       "mu_product": mu_prod}


class RAdam(Optimizer):
    """Rectified Adam (reference: python/paddle/optimizer/radam.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay,
                         grad_clip, name)
        self._b1, self._b2 = beta1, beta2
        self._epsilon = epsilon

    def _init_state(self, param):
        return {"moment1": jnp.zeros_like(param),
                "moment2": jnp.zeros_like(param),
                "step": jnp.zeros((), jnp.float32)}

    def _update(self, p, g, state, lr, wd=None):
        g = _apply_l2(g, p, wd if wd is not None else self._weight_decay)
        t = state["step"] + 1
        m = self._b1 * state["moment1"] + (1 - self._b1) * g
        v = self._b2 * state["moment2"] + (1 - self._b2) * g * g
        m_hat = m / (1 - self._b1 ** t)
        rho_inf = 2.0 / (1 - self._b2) - 1
        rho_t = rho_inf - 2 * t * self._b2 ** t / (1 - self._b2 ** t)
        r = jnp.sqrt(((rho_t - 4) * (rho_t - 2) * rho_inf)
                     / jnp.maximum((rho_inf - 4) * (rho_inf - 2) * rho_t,
                                   1e-12))
        v_hat = jnp.sqrt(v / (1 - self._b2 ** t))
        adaptive = lr * r * m_hat / (v_hat + self._epsilon)
        sgd_like = lr * m_hat
        new_p = p - jnp.where(rho_t > 5.0, adaptive, sgd_like)
        return new_p, {"moment1": m, "moment2": v, "step": t}


class ASGD(Optimizer):
    """Averaged SGD (reference: python/paddle/optimizer/asgd.py — the
    asgd_ kernel keeps the last `batch_num` gradients and steps with
    their running mean: d += g - y[i]; y[i] = g; p -= lr * d / n)."""

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay,
                         grad_clip, name)
        self._batch_num = max(int(batch_num), 1)

    def _init_state(self, param):
        n = self._batch_num
        return {"d": jnp.zeros_like(param),
                "y": jnp.zeros((n,) + tuple(param.shape), param.dtype),
                "step": jnp.zeros((), jnp.int32)}

    def _update(self, p, g, state, lr, wd=None):
        g = _apply_l2(g, p, wd if wd is not None else self._weight_decay)
        n = self._batch_num
        i = jnp.mod(state["step"], n)
        y_old = jax.lax.dynamic_index_in_dim(state["y"], i, 0,
                                             keepdims=False)
        d = state["d"] + g - y_old
        y = jax.lax.dynamic_update_index_in_dim(state["y"], g, i, 0)
        # until the window fills, average over the seen count
        seen = jnp.minimum(state["step"] + 1, n).astype(g.dtype)
        new_p = p - lr * d / seen
        return new_p, {"d": d, "y": y, "step": state["step"] + 1}


class Rprop(Optimizer):
    """Resilient backprop (reference: python/paddle/optimizer/rprop.py)."""

    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50),
                 etas=(0.5, 1.2), parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay,
                         grad_clip, name)
        self._lr_min, self._lr_max = learning_rate_range
        self._eta_neg, self._eta_pos = etas

    def _init_state(self, param):
        return {"prev_grad": jnp.zeros_like(param),
                "step_size": jnp.full_like(param, float(self.get_lr()))}

    def _update(self, p, g, state, lr, wd=None):
        g = _apply_l2(g, p, wd if wd is not None else self._weight_decay)
        sign = jnp.sign(g * state["prev_grad"])
        factor = jnp.where(sign > 0, self._eta_pos,
                           jnp.where(sign < 0, self._eta_neg, 1.0))
        step = jnp.clip(state["step_size"] * factor, self._lr_min,
                        self._lr_max)
        g_eff = jnp.where(sign < 0, 0.0, g)
        new_p = p - jnp.sign(g_eff) * step
        return new_p, {"prev_grad": g_eff, "step_size": step}
