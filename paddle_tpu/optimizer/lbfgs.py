"""L-BFGS optimizer (reference: python/paddle/optimizer/lbfgs.py).

Quasi-Newton with a bounded curvature history (two-loop recursion) and an
optional strong-Wolfe line search. Unlike the per-parameter first-order
optimizers this one works on the flattened parameter vector and needs a
closure that re-evaluates the loss, so it overrides ``step`` wholesale.
History vectors live on device; the control flow (line search, convergence
tests) runs eagerly on host scalars, like the reference's.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.dispatch import unwrap
from .optimizer import Optimizer


def _flatten(tensors):
    return jnp.concatenate([unwrap(t).reshape(-1) for t in tensors])


class LBFGS(Optimizer):
    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9,
                 history_size=100, line_search_fn=None, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        if line_search_fn not in (None, "strong_wolfe"):
            raise ValueError("only 'strong_wolfe' line search is supported")
        self.max_iter = max_iter
        self.max_eval = max_eval if max_eval is not None \
            else max_iter * 5 // 4
        self.tolerance_grad = tolerance_grad
        self.tolerance_change = tolerance_change
        self.history_size = history_size
        self.line_search_fn = line_search_fn
        self._s_hist: list = []
        self._y_hist: list = []
        self._rho_hist: list = []
        self._prev_flat_grad = None
        self._n_evals = 0

    # -- flat-vector <-> parameter list ----------------------------------
    def _params(self):
        return [p for g in self._param_groups for p in g["params"]
                if not p.stop_gradient]

    def _set_flat_params(self, flat):
        off = 0
        for p in self._params():
            n = int(np.prod(p.shape)) if p.shape else 1
            p._data = flat[off:off + n].reshape(tuple(p.shape)).astype(
                p._data.dtype)
            p._meta = None
            off += n

    def _gather_flat_grad(self):
        parts = []
        for p in self._params():
            if p.grad is None:
                parts.append(jnp.zeros(int(np.prod(p.shape)) or 1,
                                       unwrap(p).dtype))
            else:
                parts.append(unwrap(p.grad).reshape(-1))
        return jnp.concatenate(parts)

    def _eval(self, closure, flat_x):
        """Set params to flat_x, run closure -> (loss value, flat grad).

        weight_decay adds the L2 term to both loss and gradient (so the
        line search sees the regularised objective); grad_clip runs on the
        per-parameter grads through the standard clip interface before
        flattening.
        """
        self._set_flat_params(flat_x)
        self.clear_grad()
        loss = closure()
        self._n_evals += 1
        if self._grad_clip is not None:
            pg = [(p, p.grad) for p in self._params() if p.grad is not None]
            for p, g in self._grad_clip(pg):
                p.grad = g
        loss_val = float(unwrap(loss))
        flat_grad = self._gather_flat_grad()
        from .optimizer import _decay_value
        coeff = _decay_value(self._weight_decay)
        if coeff:
            loss_val += 0.5 * coeff * float(jnp.vdot(flat_x, flat_x))
            flat_grad = flat_grad + coeff * flat_x
        return loss_val, flat_grad

    # -- search direction -------------------------------------------------
    def _direction(self, flat_grad):
        q = flat_grad
        alphas = []
        for s, y, rho in zip(reversed(self._s_hist), reversed(self._y_hist),
                             reversed(self._rho_hist)):
            a = rho * float(jnp.vdot(s, q))
            alphas.append(a)
            q = q - a * y
        if self._s_hist:
            s, y = self._s_hist[-1], self._y_hist[-1]
            gamma = float(jnp.vdot(s, y)) / max(float(jnp.vdot(y, y)), 1e-10)
            r = gamma * q
        else:
            r = q
        for (s, y, rho), a in zip(
                zip(self._s_hist, self._y_hist, self._rho_hist),
                reversed(alphas)):
            b = rho * float(jnp.vdot(y, r))
            r = r + (a - b) * s
        return -r

    def _push_history(self, s, y):
        ys = float(jnp.vdot(y, s))
        if ys > 1e-10:
            self._s_hist.append(s)
            self._y_hist.append(y)
            self._rho_hist.append(1.0 / ys)
            if len(self._s_hist) > self.history_size:
                self._s_hist.pop(0)
                self._y_hist.pop(0)
                self._rho_hist.pop(0)

    # -- strong Wolfe line search (reference _strong_wolfe) ---------------
    def _strong_wolfe(self, closure, x, t, d, f0, g0, gtd0,
                      c1=1e-4, c2=0.9, max_ls=25):
        f_prev, t_prev = f0, 0.0
        g_prev, gtd_prev = g0, gtd0
        done_f = done_g = None
        for _ in range(max_ls):
            f_new, g_new = self._eval(closure, x + t * d)
            gtd_new = float(jnp.vdot(g_new, d))
            if f_new > f0 + c1 * t * gtd0 or (t_prev > 0 and
                                              f_new >= f_prev):
                return self._zoom(closure, x, d, f0, gtd0, t_prev, t,
                                  f_prev, f_new, c1, c2, max_ls)
            if abs(gtd_new) <= -c2 * gtd0:
                return t, f_new, g_new
            if gtd_new >= 0:
                return self._zoom(closure, x, d, f0, gtd0, t, t_prev,
                                  f_new, f_prev, c1, c2, max_ls)
            t_prev, f_prev, gtd_prev = t, f_new, gtd_new
            t = min(t * 2.0, 1e10)
        return t, f_new, g_new

    def _zoom(self, closure, x, d, f0, gtd0, lo, hi, f_lo, f_hi,
              c1, c2, max_ls):
        f_new, g_new, t = f_lo, None, lo
        for _ in range(max_ls):
            t = 0.5 * (lo + hi)
            f_new, g_new = self._eval(closure, x + t * d)
            gtd_new = float(jnp.vdot(g_new, d))
            if f_new > f0 + c1 * t * gtd0 or f_new >= f_lo:
                hi, f_hi = t, f_new
            else:
                if abs(gtd_new) <= -c2 * gtd0:
                    return t, f_new, g_new
                if gtd_new * (hi - lo) >= 0:
                    hi, f_hi = lo, f_lo
                lo, f_lo = t, f_new
            if abs(hi - lo) < 1e-9:
                break
        if g_new is None:
            f_new, g_new = self._eval(closure, x + t * d)
        return t, f_new, g_new

    # -- main loop ---------------------------------------------------------
    def step(self, closure=None):
        """One LBFGS optimisation step; closure re-evaluates loss + grads
        (reference LBFGS.step contract). Returns the final loss Tensor."""
        if closure is None:
            raise ValueError("LBFGS.step requires a closure")
        import paddle_tpu as paddle

        lr = self.get_lr()
        self._n_evals = 0
        x = _flatten(self._params())
        loss, flat_grad = self._eval(closure, x)
        if float(jnp.max(jnp.abs(flat_grad))) <= self.tolerance_grad:
            return paddle.to_tensor(loss)

        for _ in range(self.max_iter):
            if self._prev_flat_grad is not None:
                self._push_history(x - self._prev_x,
                                   flat_grad - self._prev_flat_grad)
            d = self._direction(flat_grad)
            self._prev_x, self._prev_flat_grad = x, flat_grad
            gtd = float(jnp.vdot(flat_grad, d))
            if gtd > -1e-12:  # not a descent direction; reset history
                self._s_hist, self._y_hist, self._rho_hist = [], [], []
                d = -flat_grad
                gtd = float(jnp.vdot(flat_grad, d))
            t = lr if self._s_hist else min(
                1.0, 1.0 / max(float(jnp.sum(jnp.abs(flat_grad))), 1e-10)
            ) * lr
            if self.line_search_fn == "strong_wolfe":
                t, loss_new, grad_new = self._strong_wolfe(
                    closure, x, t, d, loss, flat_grad, gtd)
                x_new = x + t * d
            else:
                x_new = x + t * d
                loss_new, grad_new = self._eval(closure, x_new)
            if abs(loss_new - loss) < self.tolerance_change or \
                    float(jnp.max(jnp.abs(x_new - x))) < \
                    self.tolerance_change:
                x, loss, flat_grad = x_new, loss_new, grad_new
                break
            x, loss, flat_grad = x_new, loss_new, grad_new
            if float(jnp.max(jnp.abs(flat_grad))) <= self.tolerance_grad:
                break
            if self._n_evals >= self.max_eval:
                break
        self._set_flat_params(x)
        return paddle.to_tensor(loss)
