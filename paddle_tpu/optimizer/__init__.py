"""paddle_tpu.optimizer (reference: python/paddle/optimizer)."""
from . import lr  # noqa: F401
from .optimizer import Optimizer  # noqa: F401
from .lbfgs import LBFGS  # noqa: F401
from .optimizers import (  # noqa: F401
    ASGD, Adadelta, NAdam, RAdam, Rprop,
    SGD, Adagrad, Adam, Adamax, AdamW, Lamb, Momentum, RMSProp,
)
