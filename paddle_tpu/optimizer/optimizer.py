"""Optimizer base.

Reference: python/paddle/optimizer/optimizer.py:127. Key design difference
for TPU: every optimizer expresses its math as a PURE per-parameter update
``_update(param, grad, state, lr) -> (new_param, new_state)`` over jnp
arrays. The eager ``step()`` walks Tensors and applies it; the compiled
train-step path (paddle_tpu.jit) maps the same function over parameter
pytrees inside jax.jit — one implementation, two execution modes.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax.numpy as jnp

from ..core.dispatch import unwrap, wrap
from ..core.tensor import Tensor
from ..nn.clip import ClipGradBase


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        from .lr import LRScheduler
        if parameters is None:
            raise ValueError(
                "parameters is required in dygraph mode "
                "(pass model.parameters())")
        self._parameter_list = list(parameters)
        self._param_groups: List[dict] = []
        if self._parameter_list and isinstance(self._parameter_list[0],
                                               dict):
            groups = self._parameter_list
            self._parameter_list = []
            for g in groups:
                self._add_param_group(dict(g))
        else:
            self._param_groups = [{
                "params": self._parameter_list,
                "learning_rate": 1.0,
                "weight_decay": weight_decay,
            }]
        self._learning_rate = learning_rate
        self._lr_scheduler = learning_rate if isinstance(
            learning_rate, LRScheduler) else None
        self.regularization = weight_decay
        self._weight_decay = weight_decay
        self._grad_clip = grad_clip
        if grad_clip is not None and not isinstance(grad_clip, ClipGradBase):
            raise TypeError("grad_clip must be a paddle_tpu.nn.Clip* object")
        # fp32 master weights for half-precision params (reference:
        # multi_precision kwarg; amp.decorate(level="O2") switches it on)
        self._multi_precision = False
        # accumulator state: {param_id: {name: jnp array}}
        self._accumulators: Dict[int, Dict[str, jnp.ndarray]] = {}
        self._global_step = 0

    def _add_param_group(self, group):
        params = list(group["params"])
        group["params"] = params
        group.setdefault("learning_rate", 1.0)
        group.setdefault("weight_decay", self.__dict__.get("_weight_decay"))
        self._parameter_list.extend(params)
        self._param_groups.append(group)

    # -- lr ------------------------------------------------------------------
    def get_lr(self):
        if self._lr_scheduler is not None:
            return float(self._lr_scheduler())
        if isinstance(self._learning_rate, (int, float)):
            return float(self._learning_rate)
        return float(self._learning_rate)

    def set_lr(self, value):
        if self._lr_scheduler is not None:
            raise RuntimeError(
                "can't set_lr when learning_rate is an LRScheduler")
        self._learning_rate = float(value)

    def set_lr_scheduler(self, scheduler):
        self._lr_scheduler = scheduler

    # -- state (subclasses) --------------------------------------------------
    def _init_state(self, param) -> Dict[str, jnp.ndarray]:
        """Create per-param accumulators (zeros) — pure, shape-driven."""
        return {}

    def _update(self, p, g, state, lr, wd=None):
        """Pure update rule; subclasses implement."""
        raise NotImplementedError

    def _state_for(self, param):
        key = id(param)
        if key not in self._accumulators:
            self._accumulators[key] = self._init_state(unwrap(param))
        return self._accumulators[key]

    # -- eager step ----------------------------------------------------------
    def step(self):
        base_lr = self.get_lr()
        params_grads = []
        for group in self._param_groups:
            for p in group["params"]:
                if p.stop_gradient or p.grad is None:
                    continue
                params_grads.append((p, p.grad))
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        grad_of = {id(p): g for p, g in params_grads}
        for group in self._param_groups:
            lr = base_lr * group.get("learning_rate", 1.0)
            wd = group.get("weight_decay")
            for p in group["params"]:
                g = grad_of.get(id(p))
                if g is None:
                    continue
                plr = lr * p.optimize_attr.get("learning_rate", 1.0) \
                    if hasattr(p, "optimize_attr") else lr
                garr = unwrap(g)
                mp = self._multi_precision and \
                    p._data.dtype in (jnp.float16, jnp.bfloat16)
                if mp:
                    # accumulate in an fp32 master copy; moments init fp32.
                    # A pre-existing state without a master (steps taken
                    # before multi_precision was enabled) gets one lazily.
                    state = self._accumulators.get(id(p))
                    if state is None:
                        master = p._data.astype(jnp.float32)
                        state = self._init_state(master)
                        state["_master_weight"] = master
                        self._accumulators[id(p)] = state
                    elif "_master_weight" not in state:
                        state["_master_weight"] = \
                            p._data.astype(jnp.float32)
                    master = state["_master_weight"]
                    new_master, new_state = self._update(
                        master, garr.astype(jnp.float32), state, plr, wd)
                    new_state["_master_weight"] = new_master
                    p._data = new_master.astype(p._data.dtype)
                else:
                    state = self._state_for(p)
                    if garr.dtype != p._data.dtype:
                        garr = garr.astype(p._data.dtype)
                    new_p, new_state = self._update(p._data, garr, state,
                                                    plr, wd)
                    p._data = new_p
                self._accumulators[id(p)] = new_state
        self._global_step += 1

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        return None, [(p, p.grad) for p in self._parameter_list]

    def clear_grad(self, set_to_zero=True):
        for p in self._parameter_list:
            p.clear_grad(set_to_zero=False)

    clear_gradients = clear_grad

    # -- state dict ----------------------------------------------------------
    def state_dict(self):
        sd = {}
        for i, p in enumerate(self._parameter_list):
            state = self._accumulators.get(id(p))
            if not state:
                continue
            pname = p.name or f"param_{i}"
            for k, v in state.items():
                sd[f"{pname}.{k}"] = Tensor._from_array(v)
        sd["@global_step"] = self._global_step
        if self._lr_scheduler is not None:
            sd["@lr_state"] = self._lr_scheduler.state_dict()
        return sd

    def set_state_dict(self, state_dict):
        self._global_step = int(state_dict.get("@global_step", 0))
        if self._lr_scheduler is not None and "@lr_state" in state_dict:
            self._lr_scheduler.set_state_dict(state_dict["@lr_state"])
        for i, p in enumerate(self._parameter_list):
            pname = p.name or f"param_{i}"
            state = self._state_for(p)
            for k in list(state.keys()):
                key = f"{pname}.{k}"
                if key in state_dict:
                    v = state_dict[key]
                    state[k] = v._data if isinstance(v, Tensor) \
                        else jnp.asarray(v)

    # -- functional API for the jit path ------------------------------------
    def init_state_pytree(self, params: dict):
        """params: {name: jnp array} -> {name: {slot: jnp array}}"""
        return {name: self._init_state(arr) for name, arr in params.items()}

    def apply_gradients_pytree(self, params: dict, grads: dict, state: dict,
                               lr, wd_mask=None):
        """Pure whole-model update used inside jax.jit. wd_mask maps name ->
        bool (False disables weight decay, e.g. for biases/norms)."""
        new_params, new_state = {}, {}
        for name, p in params.items():
            g = grads[name]
            wd = self._weight_decay
            if wd_mask is not None and not wd_mask.get(name, True):
                wd = None
            if g is None:
                new_params[name], new_state[name] = p, state[name]
                continue
            new_params[name], new_state[name] = self._update(
                p, g.astype(p.dtype), state[name], lr, wd)
        return new_params, new_state

    @property
    def _param_dict(self):
        return {i: p for i, p in enumerate(self._parameter_list)}


def _decay_value(wd):
    if wd is None:
        return 0.0
    if isinstance(wd, (int, float)):
        return float(wd)
    # L2Decay object from paddle_tpu.regularizer
    return float(getattr(wd, "_coeff", getattr(wd, "coeff", 0.0)))
