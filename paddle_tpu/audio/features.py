"""Audio feature layers (reference python/paddle/audio/features/layers.py:
Spectrogram / MelSpectrogram / LogMelSpectrogram / MFCC)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import run_op
from ..nn.layer.layers import Layer
from ..signal import stft
from .functional import (compute_fbank_matrix, create_dct, get_window,
                         power_to_db)


class Spectrogram(Layer):
    def __init__(self, n_fft: int = 512, hop_length=None, win_length=None,
                 window: str = "hann", power: float = 2.0, center=True,
                 pad_mode: str = "reflect", dtype: str = "float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.register_buffer(
            "window", get_window(window, self.win_length, dtype=dtype))

    def forward(self, x):
        spec = stft(x, self.n_fft, self.hop_length, self.win_length,
                    window=self.window, center=self.center,
                    pad_mode=self.pad_mode)
        return run_op("spec_power",
                      lambda a: jnp.abs(a) ** self.power, [spec])


class MelSpectrogram(Layer):
    def __init__(self, sr: int = 22050, n_fft: int = 512, hop_length=None,
                 win_length=None, window: str = "hann", power: float = 2.0,
                 center: bool = True, pad_mode: str = "reflect",
                 n_mels: int = 64, f_min: float = 50.0, f_max=None,
                 htk: bool = False, norm: str = "slaney",
                 dtype: str = "float32"):
        super().__init__()
        self.spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                       window, power, center=center,
                                       pad_mode=pad_mode, dtype=dtype)
        self.register_buffer("fbank", compute_fbank_matrix(
            sr, n_fft, n_mels, f_min, f_max, htk, norm, dtype))

    def forward(self, x):
        spec = self.spectrogram(x)
        return run_op("mel_project",
                      lambda s, fb: jnp.einsum("...ft,mf->...mt", s, fb),
                      [spec, self.fbank])


class LogMelSpectrogram(Layer):
    def __init__(self, sr: int = 22050, ref_value: float = 1.0,
                 amin: float = 1e-10, top_db=None, **kw):
        super().__init__()
        self.mel = MelSpectrogram(sr=sr, **kw)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        return power_to_db(self.mel(x), self.ref_value, self.amin,
                           self.top_db)


class MFCC(Layer):
    def __init__(self, sr: int = 22050, n_mfcc: int = 40, n_mels: int = 64,
                 **kw):
        super().__init__()
        self.logmel = LogMelSpectrogram(sr=sr, n_mels=n_mels, **kw)
        self.register_buffer("dct", create_dct(n_mfcc, n_mels))

    def forward(self, x):
        lm = self.logmel(x)
        return run_op("mfcc_dct",
                      lambda a, d: jnp.einsum("...mt,mc->...ct", a, d),
                      [lm, self.dct])
