"""Audio functional ops (reference python/paddle/audio/functional:
window functions, mel frequency helpers)."""
from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from ..core.dispatch import run_op, unwrap, wrap


def get_window(window: str, win_length: int, fftbins: bool = True,
               dtype: str = "float32"):
    """hann/hamming/blackman/bartlett/rect windows (reference
    audio/functional/window.py)."""
    n = win_length
    m = n if not fftbins else n + 1
    if n < 2:  # degenerate 1-sample window (scipy returns [1.0])
        return wrap(jnp.ones(n, jnp.dtype(dtype)))
    k = np.arange(m)
    if window in ("hann", "hanning"):
        w = 0.5 - 0.5 * np.cos(2 * np.pi * k / (m - 1))
    elif window == "hamming":
        w = 0.54 - 0.46 * np.cos(2 * np.pi * k / (m - 1))
    elif window == "blackman":
        w = (0.42 - 0.5 * np.cos(2 * np.pi * k / (m - 1))
             + 0.08 * np.cos(4 * np.pi * k / (m - 1)))
    elif window == "bartlett":
        w = 1.0 - np.abs(2 * k / (m - 1) - 1)
    elif window in ("rect", "boxcar", "ones"):
        w = np.ones(m)
    else:
        raise ValueError(f"unknown window {window!r}")
    w = w[:n] if fftbins else w
    return wrap(jnp.asarray(w, jnp.dtype(dtype)))


def hz_to_mel(freq, htk: bool = False):
    f = np.asarray(unwrap(freq), np.float64)
    if htk:
        out = 2595.0 * np.log10(1.0 + f / 700.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        out = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        out = np.where(f >= min_log_hz,
                       min_log_mel + np.log(np.maximum(f, 1e-10)
                                            / min_log_hz) / logstep, out)
    return float(out) if out.ndim == 0 else wrap(jnp.asarray(out))


def mel_to_hz(mel, htk: bool = False):
    m = np.asarray(unwrap(mel), np.float64)
    if htk:
        out = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        out = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        out = np.where(m >= min_log_mel,
                       min_log_hz * np.exp(logstep * (m - min_log_mel)),
                       out)
    return float(out) if out.ndim == 0 else wrap(jnp.asarray(out))


def compute_fbank_matrix(sr: int, n_fft: int, n_mels: int = 64,
                         f_min: float = 0.0, f_max=None, htk: bool = False,
                         norm: str = "slaney", dtype: str = "float32"):
    """Mel filterbank [n_mels, n_fft//2+1] (reference
    audio/functional/functional.py compute_fbank_matrix)."""
    f_max = f_max or sr / 2
    n_freqs = n_fft // 2 + 1
    freqs = np.linspace(0, sr / 2, n_freqs)
    mel_pts = np.linspace(float(np.asarray(hz_to_mel(f_min, htk))),
                          float(np.asarray(hz_to_mel(f_max, htk))),
                          n_mels + 2)
    hz_pts = np.asarray(unwrap(mel_to_hz(mel_pts, htk)))
    fb = np.zeros((n_mels, n_freqs))
    for i in range(n_mels):
        lo, ce, hi = hz_pts[i], hz_pts[i + 1], hz_pts[i + 2]
        up = (freqs - lo) / max(ce - lo, 1e-10)
        down = (hi - freqs) / max(hi - ce, 1e-10)
        fb[i] = np.maximum(0.0, np.minimum(up, down))
    if norm == "slaney":
        enorm = 2.0 / (hz_pts[2:] - hz_pts[:-2])
        fb *= enorm[:, None]
    return wrap(jnp.asarray(fb, jnp.dtype(dtype)))


def create_dct(n_mfcc: int, n_mels: int, norm="ortho",
               dtype: str = "float32"):
    """DCT-II matrix [n_mels, n_mfcc] (reference create_dct)."""
    k = np.arange(n_mels)
    dct = np.cos(np.pi / n_mels * (k[:, None] + 0.5)
                 * np.arange(n_mfcc)[None, :])
    if norm == "ortho":
        dct[:, 0] *= 1.0 / math.sqrt(2)
        dct *= math.sqrt(2.0 / n_mels)
    else:
        dct *= 2.0
    return wrap(jnp.asarray(dct, jnp.dtype(dtype)))


def power_to_db(magnitude, ref_value: float = 1.0, amin: float = 1e-10,
                top_db=80.0):
    def fn(a):
        db = 10.0 * jnp.log10(jnp.maximum(a, amin))
        db -= 10.0 * jnp.log10(jnp.maximum(jnp.asarray(ref_value), amin))
        if top_db is not None:
            db = jnp.maximum(db, jnp.max(db) - top_db)
        return db
    return run_op("power_to_db", fn, [magnitude])


def fft_frequencies(sr: int, n_fft: int, dtype="float32"):
    """Center frequencies of FFT bins (reference:
    audio/functional/functional.py fft_frequencies)."""
    from ..core.dispatch import wrap
    from ..core import dtype as dtype_mod
    out = jnp.linspace(0, float(sr) / 2, 1 + n_fft // 2)
    return wrap(out.astype(dtype_mod.dtype(dtype).np_dtype))


def mel_frequencies(n_mels: int = 64, f_min: float = 0.0,
                    f_max: float = 11025.0, htk: bool = False,
                    dtype="float32"):
    """Mel-spaced frequency grid (reference: mel_frequencies)."""
    from ..core.dispatch import wrap
    from ..core import dtype as dtype_mod
    lo = hz_to_mel(f_min, htk=htk)
    hi = hz_to_mel(f_max, htk=htk)
    mels = jnp.linspace(lo, hi, n_mels)
    return wrap(jnp.asarray(mel_to_hz(mels, htk=htk)).astype(
        dtype_mod.dtype(dtype).np_dtype))
