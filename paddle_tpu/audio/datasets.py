"""Audio datasets (reference: python/paddle/audio/datasets — TESS,
ESC50). Zero-egress environment: deterministic synthetic waveforms with
the reference's label structure (class-conditional tones + noise), so
feature/classifier pipelines run unchanged."""
from __future__ import annotations

import numpy as np

from ..io import Dataset

__all__ = ["TESS", "ESC50"]


class _SyntheticAudio(Dataset):
    def __init__(self, n_classes, num_samples, sr, duration, seed,
                 feat_type="raw", **feat_kwargs):
        rng = np.random.default_rng(seed)
        t = np.arange(int(sr * duration)) / sr
        self._labels = rng.integers(0, n_classes, num_samples)
        waves = []
        for lab in self._labels:
            f0 = 120.0 * (1 + lab)
            tone = 0.6 * np.sin(2 * np.pi * f0 * t) \
                + 0.2 * np.sin(2 * np.pi * 2 * f0 * t)
            waves.append(tone + 0.1 * rng.standard_normal(len(t)))
        self._waves = np.stack(waves).astype(np.float32)
        self.sample_rate = sr
        self.feat_type = feat_type
        self.feat_kwargs = feat_kwargs

    def __len__(self):
        return len(self._labels)

    def _features(self, wave):
        if self.feat_type == "raw":
            return wave
        from . import features as F
        from ..core.dispatch import unwrap
        import paddle_tpu as paddle
        x = paddle.to_tensor(wave[None])
        if self.feat_type == "mfcc":
            out = F.MFCC(sr=self.sample_rate, **self.feat_kwargs)(x)
        elif self.feat_type == "spectrogram":
            out = F.Spectrogram(**self.feat_kwargs)(x)
        elif self.feat_type == "melspectrogram":
            out = F.MelSpectrogram(sr=self.sample_rate,
                                   **self.feat_kwargs)(x)
        elif self.feat_type == "logmelspectrogram":
            out = F.LogMelSpectrogram(sr=self.sample_rate,
                                      **self.feat_kwargs)(x)
        else:
            raise ValueError(f"unknown feat_type {self.feat_type}")
        return np.asarray(unwrap(out))[0]

    def __getitem__(self, idx):
        return self._features(self._waves[idx]), self._labels[idx]


class TESS(_SyntheticAudio):
    """Toronto emotional speech set shape: 7 emotion classes (reference:
    audio/datasets/tess.py)."""

    def __init__(self, mode="train", n_folds=5, split=1, feat_type="raw",
                 archive=None, num_samples=200, **kwargs):
        seed = 7 if mode == "train" else 8
        super().__init__(7, num_samples, sr=16000, duration=1.0,
                         seed=seed, feat_type=feat_type, **kwargs)


class ESC50(_SyntheticAudio):
    """ESC-50 environmental sounds: 50 classes (reference:
    audio/datasets/esc50.py)."""

    def __init__(self, mode="train", split=1, feat_type="raw",
                 archive=None, num_samples=400, **kwargs):
        seed = 50 if mode == "train" else 51
        super().__init__(50, num_samples, sr=16000, duration=1.0,
                         seed=seed, feat_type=feat_type, **kwargs)
