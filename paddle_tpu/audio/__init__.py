"""paddle.audio parity surface (reference python/paddle/audio:
features/functional over the stft kernels)."""
from . import features  # noqa: F401
from . import functional  # noqa: F401
from . import backends  # noqa: F401,E402
from . import datasets  # noqa: F401,E402
from .backends import info, load, save  # noqa: F401,E402

__all__ = ["functional", "features", "datasets", "backends", "load",
           "info", "save"]
