"""paddle.audio parity surface (reference python/paddle/audio:
features/functional over the stft kernels)."""
from . import features  # noqa: F401
from . import functional  # noqa: F401
