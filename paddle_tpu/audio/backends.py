"""Audio IO backends (reference: python/paddle/audio/backends —
wave_backend + backend registry). The in-tree backend decodes 16-bit PCM
WAV through the stdlib wave module, like the reference's wave_backend.
"""
from __future__ import annotations

import wave as _wave

import numpy as np

__all__ = ["list_available_backends", "get_current_backend",
           "set_backend", "AudioInfo", "info", "load", "save"]

_current = "wave_backend"


def list_available_backends():
    """(reference: backends.list_available_backends — paddleaudio adds
    'soundfile'; only the in-tree wave backend ships here)."""
    return ["wave_backend"]


def get_current_backend():
    return _current


def set_backend(backend_name):
    if backend_name not in list_available_backends():
        raise NotImplementedError(
            f"backend {backend_name} is not available; install "
            "paddleaudio for soundfile support")
    global _current
    _current = backend_name


class AudioInfo:
    """(reference: backends/backend.py AudioInfo)"""

    def __init__(self, sample_rate, num_samples, num_channels,
                 bits_per_sample, encoding):
        self.sample_rate = sample_rate
        self.num_samples = num_samples
        self.num_channels = num_channels
        self.bits_per_sample = bits_per_sample
        self.encoding = encoding


def info(filepath):
    """WAV header info (reference: audio.info)."""
    with _wave.open(str(filepath), "rb") as f:
        return AudioInfo(f.getframerate(), f.getnframes(),
                         f.getnchannels(), f.getsampwidth() * 8,
                         "PCM_S")


def load(filepath, frame_offset=0, num_frames=-1, normalize=True,
         channels_first=True):
    """Load a 16-bit PCM WAV into a float32 Tensor (reference:
    audio.load). Returns (waveform [C, T] or [T, C], sample_rate)."""
    from ..core.dispatch import wrap
    with _wave.open(str(filepath), "rb") as f:
        sr = f.getframerate()
        nch = f.getnchannels()
        width = f.getsampwidth()
        f.setpos(frame_offset)
        n = f.getnframes() - frame_offset if num_frames < 0 else num_frames
        raw = f.readframes(n)
    if width != 2:
        raise NotImplementedError("only 16-bit PCM WAV is supported")
    data = np.frombuffer(raw, np.int16).reshape(-1, nch)
    wavef = data.astype(np.float32) / 32768.0 if normalize \
        else data.astype(np.float32)
    if channels_first:
        wavef = wavef.T
    return wrap(wavef), sr


def save(filepath, src, sample_rate, channels_first=True,
         encoding="PCM_S", bits_per_sample=16):
    """Save a float32 Tensor to 16-bit PCM WAV (reference: audio.save)."""
    from ..core.dispatch import unwrap
    if bits_per_sample != 16:
        raise NotImplementedError("only 16-bit PCM WAV is supported")
    a = np.asarray(unwrap(src))
    if channels_first:
        a = a.T  # -> [T, C]
    pcm = np.clip(a * 32768.0, -32768, 32767).astype(np.int16)
    with _wave.open(str(filepath), "wb") as f:
        f.setnchannels(pcm.shape[1] if pcm.ndim > 1 else 1)
        f.setsampwidth(2)
        f.setframerate(int(sample_rate))
        f.writeframes(pcm.tobytes())
