"""paddle_tpu.monitor — lightweight always-on runtime counter registry.

Reference: Paddle's monitor/statistics surface (StatRegistry in
paddle/utils/stats.h, exposed through paddle.fluid.monitor): named
integer/float counters any layer can bump without pulling in the full
profiler. TPU-native role: the substrate bench.py, hapi callbacks, and
the distributed layers report through — step times, XLA compile counts,
shape-churn flags — with near-zero cost when nobody reads them.

The registry itself is always live (a counter bump is two dict ops);
``PADDLE_TPU_MONITOR=1`` gates only the *emission* side — the per-epoch
telemetry lines hapi prints and the telemetry block bench.py attaches
to its JSON result. ``enable()``/``disable()`` override the env var
programmatically.

    from paddle_tpu import monitor
    monitor.counter("train.steps").increase()
    monitor.gauge("train.step_ms").set(12.5)
    monitor.snapshot()   # {'train.steps': 1, 'train.step_ms': 12.5, ...}

(The C++-backed named monitors behind the paddle parity surface live in
paddle_tpu.device.monitor — monitor_add/monitor_get over csrc. This is
the pure-Python layer the telemetry stack reports through; it needs no
native lib and is safe from any thread.)
"""
from __future__ import annotations

import os
import threading
from typing import Dict, Optional

_lock = threading.Lock()
_counters: Dict[str, "Counter"] = {}
_gauges: Dict[str, "Gauge"] = {}
_enabled_override: Optional[bool] = None


class Counter:
    """Monotonic counter (reference StatRegistry int stat)."""

    __slots__ = ("name", "_value")

    def __init__(self, name: str):
        self.name = name
        self._value = 0

    def increase(self, n: int = 1) -> int:
        # locked: jax.monitoring can fire from background compile
        # threads, and read-modify-write on an attribute is not atomic
        with _lock:
            self._value += n
            return self._value

    # paddle-style alias
    add = increase

    def get(self) -> int:
        return self._value

    def reset(self):
        self._value = 0

    def __repr__(self):
        return f"Counter({self.name}={self._value})"


class Gauge:
    """Last-value gauge with running min/max/mean (for step times,
    memory watermarks)."""

    __slots__ = ("name", "_value", "_count", "_total", "_min", "_max")

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0
        self._count = 0
        self._total = 0.0
        self._min = float("inf")
        self._max = float("-inf")

    def set(self, v: float) -> float:
        v = float(v)
        with _lock:
            self._value = v
            self._count += 1
            self._total += v
            self._min = min(self._min, v)
            self._max = max(self._max, v)
        return v

    # observation-style alias (set + running stats are one operation)
    update = set

    def add(self, v: float) -> float:
        """Accumulate into the last value (for duration totals fed from
        multiple threads — one locked read-modify-write)."""
        v = float(v)
        with _lock:
            self._value += v
            self._count += 1
            self._total += v
            self._min = min(self._min, v)
            self._max = max(self._max, v)
            return self._value

    def get(self) -> float:
        return self._value

    def stats(self) -> Dict[str, float]:
        if not self._count:
            return dict(last=0.0, count=0, mean=0.0, min=0.0, max=0.0)
        return dict(last=self._value, count=self._count,
                    mean=self._total / self._count,
                    min=self._min, max=self._max)

    def reset(self):
        self.__init__(self.name)

    def __repr__(self):
        return f"Gauge({self.name}={self._value})"


def counter(name: str) -> Counter:
    """Get-or-create the named counter."""
    c = _counters.get(name)
    if c is None:
        with _lock:
            c = _counters.setdefault(name, Counter(name))
    return c


def gauge(name: str) -> Gauge:
    """Get-or-create the named gauge."""
    g = _gauges.get(name)
    if g is None:
        with _lock:
            g = _gauges.setdefault(name, Gauge(name))
    return g


def snapshot(detail: bool = False) -> Dict[str, object]:
    """One flat dict of every counter/gauge value. With ``detail=True``
    gauges expand to their running stats dict instead of the last
    value."""
    out: Dict[str, object] = {}
    for name, c in sorted(_counters.items()):
        out[name] = c.get()
    for name, g in sorted(_gauges.items()):
        out[name] = g.stats() if detail else g.get()
    return out


def reset():
    """Zero every registered counter/gauge (registry keys survive so
    held references stay valid)."""
    for c in _counters.values():
        c.reset()
    for g in _gauges.values():
        g.reset()


def enabled() -> bool:
    """True when telemetry *emission* is on: ``PADDLE_TPU_MONITOR=1``
    in the environment, or an explicit ``enable()`` call."""
    if _enabled_override is not None:
        return _enabled_override
    return os.environ.get("PADDLE_TPU_MONITOR", "0").lower() in (
        "1", "true", "yes", "on")


def enable():
    global _enabled_override
    _enabled_override = True


def disable():
    global _enabled_override
    _enabled_override = False


def _clear_override():
    """Test hook: fall back to the env var."""
    global _enabled_override
    _enabled_override = None
