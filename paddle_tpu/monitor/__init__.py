"""paddle_tpu.monitor — lightweight always-on runtime counter registry.

Reference: Paddle's monitor/statistics surface (StatRegistry in
paddle/utils/stats.h, exposed through paddle.fluid.monitor): named
integer/float counters any layer can bump without pulling in the full
profiler. TPU-native role: the substrate bench.py, hapi callbacks, and
the distributed layers report through — step times, XLA compile counts,
shape-churn flags — with near-zero cost when nobody reads them.

The registry itself is always live (a counter bump is two dict ops);
``PADDLE_TPU_MONITOR=1`` gates only the *emission* side — the per-epoch
telemetry lines hapi prints and the telemetry block bench.py attaches
to its JSON result. ``enable()``/``disable()`` override the env var
programmatically.

    from paddle_tpu import monitor
    monitor.counter("train.steps").increase()
    monitor.gauge("train.step_ms").set(12.5)
    monitor.snapshot()   # {'train.steps': 1, 'train.step_ms': 12.5, ...}

(The C++-backed named monitors behind the paddle parity surface live in
paddle_tpu.device.monitor — monitor_add/monitor_get over csrc. This is
the pure-Python layer the telemetry stack reports through; it needs no
native lib and is safe from any thread.)
"""
from __future__ import annotations

import math
import os
import threading
from typing import Dict, Optional

_lock = threading.Lock()
_counters: Dict[str, "Counter"] = {}
_gauges: Dict[str, "Gauge"] = {}
_histograms: Dict[str, "Histogram"] = {}
_enabled_override: Optional[bool] = None


class Counter:
    """Monotonic counter (reference StatRegistry int stat)."""

    __slots__ = ("name", "_value")

    def __init__(self, name: str):
        self.name = name
        self._value = 0

    def increase(self, n: int = 1) -> int:
        # locked: jax.monitoring can fire from background compile
        # threads, and read-modify-write on an attribute is not atomic
        with _lock:
            self._value += n
            return self._value

    # paddle-style alias
    add = increase

    def get(self) -> int:
        return self._value

    def reset(self):
        self._value = 0

    def __repr__(self):
        return f"Counter({self.name}={self._value})"


class Gauge:
    """Last-value gauge with running min/max/mean (for step times,
    memory watermarks)."""

    __slots__ = ("name", "_value", "_count", "_total", "_min", "_max")

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0
        self._count = 0
        self._total = 0.0
        self._min = float("inf")
        self._max = float("-inf")

    def set(self, v: float) -> float:
        v = float(v)
        with _lock:
            self._value = v
            self._count += 1
            self._total += v
            self._min = min(self._min, v)
            self._max = max(self._max, v)
        return v

    # observation-style alias (set + running stats are one operation)
    update = set

    def add(self, v: float) -> float:
        """Accumulate into the last value (for duration totals fed from
        multiple threads — one locked read-modify-write)."""
        v = float(v)
        with _lock:
            self._value += v
            self._count += 1
            self._total += v
            self._min = min(self._min, v)
            self._max = max(self._max, v)
            return self._value

    def get(self) -> float:
        return self._value

    def stats(self) -> Dict[str, float]:
        if not self._count:
            return dict(last=0.0, count=0, mean=0.0, min=0.0, max=0.0)
        return dict(last=self._value, count=self._count,
                    mean=self._total / self._count,
                    min=self._min, max=self._max)

    def reset(self):
        self.__init__(self.name)

    def __repr__(self):
        return f"Gauge({self.name}={self._value})"


#: sub-buckets per power-of-two octave. 16 linear sub-buckets bound a
#: bucket's relative width at 1/16, so the midpoint estimate any
#: percentile returns is within ~3.2% of the recorded value — inside
#: the 5% resolution the replay p99 gates are tested against.
HIST_SUBBUCKETS = 16


def _hist_index(v: float) -> int:
    """Log2-bucketed index of a positive value: octave (frexp
    exponent) x 16 linear sub-buckets — O(1), no log calls."""
    m, e = math.frexp(v)          # v = m * 2**e, m in [0.5, 1)
    return e * HIST_SUBBUCKETS + int((m * 2.0 - 1.0) * HIST_SUBBUCKETS)


def _hist_bounds(idx: int) -> tuple:
    """(lower, upper) value bounds of bucket ``idx``."""
    e, s = divmod(idx, HIST_SUBBUCKETS)
    base = math.ldexp(1.0, e - 1)  # 2**(e-1)
    lo = base * (1.0 + s / HIST_SUBBUCKETS)
    hi = base * (1.0 + (s + 1) / HIST_SUBBUCKETS)
    return lo, hi


class Histogram:
    """Mergeable log-bucketed histogram (HDR-style): fixed log2
    octaves split into 16 linear sub-buckets, sparse storage, O(1)
    record, EXACT merge (bucket counts add). Replaces the unbounded
    host-side percentile lists the serving replay/autoscale paths used
    to keep: memory is bounded by the number of distinct buckets ever
    touched, and per-replica histograms merge fleet-wide without
    losing resolution. Non-positive values (virtual-clock granularity
    can yield 0.0 latencies) land in a dedicated zero bucket."""

    __slots__ = ("name", "_buckets", "_zeros", "_count", "_sum",
                 "_min", "_max")

    def __init__(self, name: str = ""):
        self.name = name
        self._buckets: Dict[int, int] = {}
        self._zeros = 0
        self._count = 0
        self._sum = 0.0
        self._min = float("inf")
        self._max = float("-inf")

    def record(self, v: float, n: int = 1) -> None:
        v = float(v)
        with _lock:
            self._count += n
            self._sum += v * n
            self._min = min(self._min, v)
            self._max = max(self._max, v)
            if v <= 0.0:
                self._zeros += n
            else:
                idx = _hist_index(v)
                self._buckets[idx] = self._buckets.get(idx, 0) + n

    # observation-style alias (gauge.update parity)
    observe = record

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def merge(self, other: "Histogram") -> "Histogram":
        """Fold ``other``'s buckets into this histogram — exact (the
        merged histogram is indistinguishable from one that recorded
        both streams). Returns self for chaining."""
        with _lock:
            self._count += other._count
            self._sum += other._sum
            self._zeros += other._zeros
            self._min = min(self._min, other._min)
            self._max = max(self._max, other._max)
            for idx, n in other._buckets.items():
                self._buckets[idx] = self._buckets.get(idx, 0) + n
        return self

    def percentile(self, q: float) -> float:
        """Value at percentile ``q`` (0..100): bucket-midpoint
        estimate, clamped to the exact observed [min, max]."""
        if self._count == 0:
            return 0.0
        rank = max(1, math.ceil(q / 100.0 * self._count))
        seen = self._zeros
        if rank <= seen:
            return max(0.0, self._min)
        val = self._max
        for idx in sorted(self._buckets):
            seen += self._buckets[idx]
            if rank <= seen:
                lo, hi = _hist_bounds(idx)
                val = (lo + hi) / 2.0
                break
        return min(max(val, self._min), self._max)

    def stats(self) -> Dict[str, float]:
        if not self._count:
            return dict(count=0, mean=0.0, min=0.0, max=0.0,
                        p50=0.0, p90=0.0, p99=0.0)
        return dict(count=self._count,
                    mean=self._sum / self._count,
                    min=self._min, max=self._max,
                    p50=self.percentile(50),
                    p90=self.percentile(90),
                    p99=self.percentile(99))

    def to_dict(self) -> Dict[str, object]:
        """JSON-safe serialization (snapshot files, merge across
        processes via ``from_dict`` + ``merge``)."""
        return {"count": self._count, "sum": self._sum,
                "zeros": self._zeros,
                "min": (self._min if self._count else 0.0),
                "max": (self._max if self._count else 0.0),
                "buckets": {str(k): v
                            for k, v in sorted(self._buckets.items())}}

    @classmethod
    def from_dict(cls, d: Dict[str, object],
                  name: str = "") -> "Histogram":
        h = cls(name)
        h._count = int(d.get("count", 0))
        h._sum = float(d.get("sum", 0.0))
        h._zeros = int(d.get("zeros", 0))
        if h._count:
            h._min = float(d.get("min", 0.0))
            h._max = float(d.get("max", 0.0))
        h._buckets = {int(k): int(v)
                      for k, v in dict(d.get("buckets", {})).items()}
        return h

    def reset(self):
        self.__init__(self.name)

    def __repr__(self):
        return f"Histogram({self.name} n={self._count})"


def counter(name: str) -> Counter:
    """Get-or-create the named counter."""
    c = _counters.get(name)
    if c is None:
        with _lock:
            c = _counters.setdefault(name, Counter(name))
    return c


def gauge(name: str) -> Gauge:
    """Get-or-create the named gauge."""
    g = _gauges.get(name)
    if g is None:
        with _lock:
            g = _gauges.setdefault(name, Gauge(name))
    return g


def histogram(name: str) -> Histogram:
    """Get-or-create the named histogram."""
    h = _histograms.get(name)
    if h is None:
        with _lock:
            h = _histograms.setdefault(name, Histogram(name))
    return h


class _Pair:
    """Fan-out wrapper a Scope hands back: every write lands on both
    the unlabeled aggregate instrument and its ``serving.<label>.…``
    twin; reads come from the aggregate."""

    __slots__ = ("_agg", "_scoped")

    def __init__(self, agg, scoped):
        self._agg = agg
        self._scoped = scoped

    def __getattr__(self, attr):
        agg_fn = getattr(self._agg, attr)
        scoped_fn = getattr(self._scoped, attr)
        if not callable(agg_fn):
            return agg_fn

        def both(*a, **kw):
            out = agg_fn(*a, **kw)
            scoped_fn(*a, **kw)
            return out
        return both


class Scope:
    """Label-scoped view of the registry. ``scope(\"replica0\")``
    returns an emitter whose ``counter/gauge/histogram`` write BOTH
    the unlabeled aggregate (``serving.ttft_ms`` — fleet-wide truth,
    exactly what an unscoped engine writes) and the labeled twin
    (``serving.replica0.ttft_ms``), so per-replica tables read their
    own keys instead of re-deriving deltas by subtraction against a
    flat shared registry. ``scope(None)`` is a passthrough (a plain
    single-process Engine pays nothing)."""

    __slots__ = ("label",)

    def __init__(self, label: Optional[str]):
        self.label = label

    def scoped_name(self, name: str) -> str:
        """serving.x.y -> serving.<label>.x.y (the label slots in
        after the ``serving.`` namespace so prefix filters over the
        unlabeled keys never match a labeled twin)."""
        if name.startswith("serving."):
            return f"serving.{self.label}." + name[len("serving."):]
        return f"{self.label}.{name}"

    def _pair(self, getter, name: str):
        agg = getter(name)
        if self.label is None:
            return agg
        return _Pair(agg, getter(self.scoped_name(name)))

    def counter(self, name: str):
        return self._pair(counter, name)

    def gauge(self, name: str):
        return self._pair(gauge, name)

    def histogram(self, name: str):
        return self._pair(histogram, name)


def scope(label: Optional[str]) -> Scope:
    """Labeled emitter over the registry (see Scope)."""
    return Scope(label if label is None else str(label))


def snapshot(detail: bool = False) -> Dict[str, object]:
    """One flat dict of every counter/gauge/histogram value. With
    ``detail=True`` gauges expand to their running stats dict and
    histograms to count + p50/p90/p99 + mean/min/max; without it
    histograms report their observation count."""
    out: Dict[str, object] = {}
    for name, c in sorted(_counters.items()):
        out[name] = c.get()
    for name, g in sorted(_gauges.items()):
        out[name] = g.stats() if detail else g.get()
    for name, h in sorted(_histograms.items()):
        out[name] = h.stats() if detail else h.count
    return out


def reset():
    """Zero every registered counter/gauge/histogram (registry keys
    survive so held references stay valid)."""
    for c in _counters.values():
        c.reset()
    for g in _gauges.values():
        g.reset()
    for h in _histograms.values():
        h.reset()


def enabled() -> bool:
    """True when telemetry *emission* is on: ``PADDLE_TPU_MONITOR=1``
    in the environment, or an explicit ``enable()`` call."""
    if _enabled_override is not None:
        return _enabled_override
    return os.environ.get("PADDLE_TPU_MONITOR", "0").lower() in (
        "1", "true", "yes", "on")


def enable():
    global _enabled_override
    _enabled_override = True


def disable():
    global _enabled_override
    _enabled_override = False


def _clear_override():
    """Test hook: fall back to the env var."""
    global _enabled_override
    _enabled_override = None
