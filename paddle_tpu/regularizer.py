"""Regularizers (reference: python/paddle/regularizer.py)."""
from __future__ import annotations


class WeightDecayRegularizer:
    pass


class L2Decay(WeightDecayRegularizer):
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)

    def __repr__(self):
        return f"L2Decay({self._coeff})"


class L1Decay(WeightDecayRegularizer):
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)

    def __repr__(self):
        return f"L1Decay({self._coeff})"
