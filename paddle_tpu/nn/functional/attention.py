"""Attention functionals.

Reference: python/paddle/nn/functional/flash_attention.py:195
(flash_attention), :976 (scaled_dot_product_attention). On TPU the fused
path is XLA's fused attention or a Pallas flash kernel
(paddle_tpu.kernels.flash_attention); this module exposes the paddle API
and routes to the best available implementation.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import run_op, unwrap
from ...core.flags import define_flag

define_flag("sdpa_use_flash", True,
            "route scaled_dot_product_attention through the flash "
            "entry when the request is expressible (no mask / boolean "
            "key-padding masks); 0 pins every sdpa to the XLA softmax "
            "core — the exact-XLA-numerics escape hatch for callers "
            "that keep the reference signature")


def _sdpa_core(q, k, v, mask=None, dropout=0.0, causal=False, scale=None):
    # q/k/v: [B, S, H, D] (paddle layout)
    d = q.shape[-1]
    s = scale if scale is not None else (d ** -0.5)
    qt = jnp.swapaxes(q, 1, 2)  # [B,H,S,D]
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * s
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        cmask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(cmask, logits, -jnp.inf)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, -jnp.inf)
        else:
            logits = logits + mask
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    probs = probs.astype(q.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vt)
    return jnp.swapaxes(out, 1, 2)  # back to [B,S,H,D]


def _mask_to_key_bands(mask, batch, sq, sk, n_heads):
    """Boolean keep-mask with NO row (query) structure → raw flashmask
    `startend_row_indices` [b, h_se, sk, 1] int32, or None when the
    mask is not a pure key/padding mask.

    Accepted shapes (paddle's padding-mask conventions): [b, 1, sk] and
    [b, h|1, 1, sk] — every accepted layout masks whole key COLUMNS,
    exactly what flashmask's column bands express (a masked column is
    the band [0, sq), a kept column the empty band [sq, sq)). Masks
    with a real query dim (> 1), additive float masks, and ambiguous
    2-D shapes stay on the XLA path.
    """
    if mask is None or mask.dtype != jnp.bool_:
        return None
    m = mask
    if m.ndim == 3:
        if m.shape[0] != batch or m.shape[1] != 1 or m.shape[2] != sk:
            return None
        m = m[:, :, None, :]
    elif m.ndim == 4:
        if (m.shape[0] != batch or m.shape[2] != 1
                or m.shape[3] != sk):
            return None
    else:
        return None
    h_se = m.shape[1]
    if h_se != 1 and (h_se > n_heads or n_heads % h_se):
        return None
    # kept column -> empty band [sq, sq); masked column -> [0, sq)
    se = jnp.where(m[:, :, 0, :], jnp.int32(sq), jnp.int32(0))
    return se[..., None]


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None, *,
                                 use_flash=True):
    """paddle layout [batch, seq, heads, head_dim]
    (reference flash_attention.py:976).

    Dispatches to the flash-attention entry (kernels/
    flash_attention.py) whenever it can express the request — no mask,
    or a boolean key/padding mask (converted to flashmask column bands,
    so encoder models like BERT reach the fused path bidirectionally) —
    and keeps the XLA softmax core for additive/row-structured masks.
    The route is trace-time counter-visible (docs/OBSERVABILITY.md):
    `kernels.flash.sdpa.pallas[_mask]` when the Pallas kernel will
    actually serve (`pallas_path_eligible` — the same predicate the
    entry point uses), `kernels.flash.sdpa.xla[_mask]` when the flash
    entry's XLA fallback runs (off-TPU, untiled shapes, failed head-dim
    probe), `xla_dense_mask` for unconvertible masks, `xla_core` for an
    explicit ``use_flash=False`` opt-out (the escape hatch models like
    LlamaConfig(use_flash_attention=False) rely on for exact-XLA
    numerics). Note ``dropout_p`` is accepted for API parity but
    attention-probability dropout is not applied on any path (TPU
    flash kernels don't support it; the XLA core never did). Rows
    whose keys are ALL masked emit zeros on the flash paths
    (flash-attn v2 convention) instead of the XLA softmax's NaN.
    """
    from ...core.flags import get_flag
    from ...kernels import flash_attention as kernel_mod
    from ... import monitor

    # FLAGS_sdpa_use_flash=0 is the global escape hatch for callers
    # that cannot reach the keyword (e.g. nn.MultiHeadAttention keeps
    # the reference signature): pins every sdpa to the XLA core
    use_flash = use_flash and get_flag("sdpa_use_flash")

    def fn(q, k, v, *rest):
        m = rest[0] if rest else None
        b, sq = q.shape[0], q.shape[1]
        sk = k.shape[1]
        if not use_flash:
            monitor.counter("kernels.flash.sdpa.xla_core").increase()
            return _sdpa_core(q, k, v, m, dropout_p, is_causal)
        se = None
        if m is not None:
            se = _mask_to_key_bands(m, b, sq, sk, k.shape[2]) \
                if sq == sk else None
            if se is None:
                monitor.counter(
                    "kernels.flash.sdpa.xla_dense_mask").increase()
                return _sdpa_core(q, k, v, m, dropout_p, is_causal)
        pallas = kernel_mod.pallas_path_eligible(sq, sk, q.shape[-1])
        suffix = "_mask" if se is not None else ""
        monitor.counter(
            "kernels.flash.sdpa."
            f"{'pallas' if pallas else 'xla'}{suffix}").increase()
        return kernel_mod.flash_attention_arrays(
            q, k, v, causal=is_causal, startend_row_indices=se)
    args = [query, key, value] + (
        [attn_mask] if attn_mask is not None else [])
    return run_op("scaled_dot_product_attention", fn, args)


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None, rng_name="",
                    training=True, name=None, *, window=None):
    """Reference flash_attention.py:195. Routes to the Pallas flash kernel
    on TPU when shapes allow, else the XLA-fused softmax-attention above.
    Returns (out, softmax) like paddle; softmax is None unless requested.

    TPU extensions beyond the reference signature: key/value may carry
    fewer heads than query (GQA/MQA — served in-kernel by index), and
    the keyword-only ``window`` enables sliding-window local attention
    (requires causal=True; not combinable with return_softmax)."""
    from ...kernels import flash_attention as kernel_mod
    if window is not None and return_softmax:
        raise ValueError("return_softmax with window is not supported")
    out = kernel_mod.flash_attention(query, key, value, causal=causal,
                                     window=window)
    if return_softmax:
        # the paddle contract returns the [B, H, Sq, Sk] probability
        # matrix (recomputed densely — the flash kernel never holds it)
        def probs_fn(q, k):
            qt = jnp.swapaxes(q, 1, 2).astype(jnp.float32)
            kt = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
            if kt.shape[1] != qt.shape[1]:       # GQA: repeat kv heads
                kt = jnp.repeat(kt, qt.shape[1] // kt.shape[1], axis=1)
            logits = jnp.einsum("bhqd,bhkd->bhqk", qt, kt)
            logits = logits / jnp.sqrt(jnp.float32(qt.shape[-1]))
            if causal:
                sq, sk = logits.shape[-2], logits.shape[-1]
                mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
                logits = jnp.where(mask, logits, -1e30)
            import jax
            return jax.nn.softmax(logits, axis=-1)

        sm = run_op("flash_attention_softmax", probs_fn, [query, key])
        return out, sm
    return out, None


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale, dropout=0.0,
                        causal=False, return_softmax=False, name=None):
    """Varlen ("unpadded") attention over packed sequences (reference:
    flash_attn_unpadded). q/k/v: [total_tokens, heads, dim]; cu_seqlens
    mark sequence boundaries. On TPU the ragged batch lowers to ONE dense
    attention over the packed axis with a block-diagonal segment mask —
    XLA fuses the mask, so no per-sequence launches and no padding copies.
    """
    import jax

    def fn(q, k, v, cq, ck):
        tq = q.shape[0]
        tk = k.shape[0]
        # segment id per token: #boundaries <= position
        seg_q = jnp.sum(jnp.arange(tq)[:, None] >= cq[None, 1:], axis=-1)
        seg_k = jnp.sum(jnp.arange(tk)[:, None] >= ck[None, 1:], axis=-1)
        mask = seg_q[:, None] == seg_k[None, :]
        if causal:
            pos_q = jnp.arange(tq) - cq[seg_q]
            pos_k = jnp.arange(tk) - ck[seg_k]
            mask = mask & (pos_q[:, None] >= pos_k[None, :])
        scores = jnp.einsum("qhd,khd->hqk", q, k) * scale
        scores = jnp.where(mask[None], scores, -1e30)
        attn = jax.nn.softmax(scores, axis=-1)
        return jnp.einsum("hqk,khd->qhd", attn, v)
    out = run_op("flash_attn_unpadded", fn,
                 [query, key, value, cu_seqlens_q, cu_seqlens_k])
    # reference contract: ALWAYS (out, softmax-or-None)
    return out, None


def flash_attn_varlen_qkvpacked(qkv, cu_seqlens_q, cu_seqlens_k,
                                max_seqlen_q, max_seqlen_k, scale=None,
                                dropout=0.0, causal=False,
                                return_softmax=False, name=None):
    """Packed-QKV varlen attention (reference:
    flash_attn_varlen_qkvpacked). qkv: [total_tokens, 3, heads, dim]."""
    from ...ops.manipulation import split as _split
    q, k, v = [t.squeeze(1) for t in _split(qkv, 3, axis=1)]
    if scale is None:
        scale = float(q.shape[-1]) ** -0.5
    out, _ = flash_attn_unpadded(q, k, v, cu_seqlens_q, cu_seqlens_k,
                                 max_seqlen_q, max_seqlen_k, scale,
                                 dropout, causal, return_softmax=False)
    return out, None


def sequence_mask(lengths, maxlen=None, dtype="int64", name=None):
    def fn(ln):
        m = maxlen if maxlen is not None else int(jnp.max(ln))
        return (jnp.arange(m)[None, :] < ln[:, None]).astype(dtype)
    return run_op("sequence_mask", fn, [lengths])


# ---- coverage batch (reference ops.yaml names) -----------------------------

def flash_attn(q, k, v, dropout=0.0, causal=False, return_softmax=False,
               **kw):
    """reference ops.yaml: flash_attn (paddle layout [b, s, h, d])."""
    return flash_attention(q, k, v, dropout=dropout, causal=causal)


def flash_attn_qkvpacked(qkv, dropout=0.0, causal=False, **kw):
    """reference ops.yaml: flash_attn_qkvpacked ([b, s, 3, h, d])."""
    from ...ops.manipulation import split as _split
    q, k, v = [t.squeeze(2) for t in _split(qkv, 3, axis=2)]
    return flash_attention(q, k, v, dropout=dropout, causal=causal)


def memory_efficient_attention(query, key, value, attn_bias=None, p=0.0,
                               scale=None, training=True, **kw):
    """reference incubate memory_efficient_attention — on TPU the
    flash/XLA kernel IS the memory-efficient path."""
    return scaled_dot_product_attention(
        query, key, value, attn_mask=attn_bias, dropout_p=p,
        training=training)


def flashmask_attention(query, key, value, startend_row_indices=None,
                        dropout=0.0, causal=False, window_size=None,
                        name=None):
    """reference nn/functional/flash_attention.py:1098 flashmask_attention
    (FlashMask, arXiv:2410.01359): sparse attention masks described by
    per-key-column start/end row indices, [b, h_se, s_k, {1,2,4}] int32.

    TPU-native: the indices stream into the Pallas flash kernel as
    per-column row BANDS — mask memory is O(S), never the [b,h,s,s]
    dense tensor, and key tiles whose rows are fully covered by a band
    are skipped entirely (the column-sparsity win, e.g. cross-document
    blocks in causal document masking). Shapes that don't tile fall back
    to the XLA dense-mask path with identical semantics.

    window_size: int sliding window composed with the mask (causal only,
    like the reference's flashmask window_size).
    """
    from ...kernels import flash_attention as kernel_mod

    if startend_row_indices is None:
        out, _ = flash_attention(query, key, value, dropout=dropout,
                                 causal=causal, window=window_size)
        return out
    if window_size is not None:
        window_size = int(window_size)
        if not causal:
            raise ValueError(
                "flashmask window_size requires causal=True")
    return kernel_mod.flash_attention(
        query, key, value, causal=causal, window=window_size,
        startend_row_indices=startend_row_indices)


def document_startend_row_indices(doc_lens, total=None):
    """Causal DOCUMENT mask as flashmask ``startend_row_indices``
    ([1, 1, total, 1] int32) — the packed-sequence training mask
    (reference flashmask "causal document mask" example): token i may
    attend token j iff j <= i AND both sit in the same document.

    ``doc_lens``: the packed documents' lengths, summing to ``total``
    (default: their sum). Each key column's band starts masking at its
    document's END row, so queries in later documents see nothing of
    earlier ones — O(S) mask memory however long the sequence, and the
    Pallas kernel skips whole cross-document tiles. Feed the result to
    ``flashmask_attention`` or a model's
    ``attn_mask_startend_row_indices`` input (LlamaForCausalLM).
    """
    import numpy as np
    lens = [int(n) for n in doc_lens]
    if any(n < 1 for n in lens):
        raise ValueError(f"document lengths must be >= 1, got {lens}")
    s = sum(lens)
    if total is None:
        total = s
    if s != int(total):
        raise ValueError(
            f"doc_lens sum to {s} but total={total} — packed documents "
            f"must tile the whole sequence")
    idx = np.zeros((1, 1, int(total), 1), np.int32)
    lo = 0
    for n in lens:
        idx[0, 0, lo:lo + n, 0] = lo + n
        lo += n
    from ...core.dispatch import wrap
    return wrap(jnp.asarray(idx))
