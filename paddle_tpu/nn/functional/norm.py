"""Normalization functionals.

Reference: python/paddle/nn/functional/norm.py. batch_norm takes running
mean/var tensors and (at train time) returns updated statistics by mutating
the passed buffers — mirroring the reference's in-place stat update — while
the arithmetic itself stays pure for the jit path.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...core.dispatch import run_op, unwrap, wrap
from ...core.tensor import Tensor


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def fn(a):
        if p == 2:
            n = jnp.sqrt(jnp.sum(a * a, axis=axis, keepdims=True))
        else:
            n = jnp.sum(jnp.abs(a) ** p, axis=axis, keepdims=True) ** (1. / p)
        return a / jnp.maximum(n, epsilon)
    return run_op("normalize", fn, [x])


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5,
               name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    ndim = len(normalized_shape)

    def fn(a, *rest):
        axes = tuple(range(a.ndim - ndim, a.ndim))
        mean = jnp.mean(a, axis=axes, keepdims=True)
        var = jnp.var(a, axis=axes, keepdims=True)
        out = (a - mean) / jnp.sqrt(var + epsilon)
        it = iter(rest)
        if weight is not None:
            out = out * next(it)
        if bias is not None:
            out = out + next(it)
        return out.astype(a.dtype)
    args = [x] + [t for t in (weight, bias) if t is not None]
    return run_op("layer_norm", fn, args)


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    """RMSNorm (no reference equivalent op; used by fused_rms_norm in
    incubate and the LLaMA family)."""
    def fn(a, *rest):
        ms = jnp.mean(jnp.square(a.astype(jnp.float32)), axis=-1,
                      keepdims=True)
        out = a * jnp.reciprocal(jnp.sqrt(ms + epsilon)).astype(a.dtype)
        if rest:
            out = out * rest[0]
        return out
    args = [x] + ([weight] if weight is not None else [])
    return run_op("rms_norm", fn, args)


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5,
               data_format="NCHW", use_global_stats=None, name=None):
    """Reference: nn/functional/norm.py batch_norm. In training mode the
    running stats buffers are updated in place:
    running = momentum * running + (1 - momentum) * batch_stat."""
    channel_axis = 1
    if data_format in ("NHWC", "NDHWC", "NLC"):
        channel_axis = unwrap(x).ndim - 1
    use_batch_stats = training and not use_global_stats

    a = unwrap(x)
    axes = tuple(i for i in range(a.ndim) if i != channel_axis)

    if use_batch_stats:
        batch_mean = jnp.mean(a, axis=axes)
        batch_var = jnp.var(a, axis=axes)
        n = 1
        for i in axes:
            n *= a.shape[i]
        unbiased = batch_var * (n / max(n - 1, 1))
        running_mean._data = (momentum * running_mean._data +
                              (1 - momentum) * batch_mean.astype(
                                  running_mean._data.dtype))
        running_var._data = (momentum * running_var._data +
                             (1 - momentum) * unbiased.astype(
                                 running_var._data.dtype))
        mean_t = wrap(batch_mean)
        var_t = wrap(batch_var)
    else:
        mean_t, var_t = running_mean, running_var

    def fn(v, m, s, *rest):
        shape = [1] * v.ndim
        shape[channel_axis] = v.shape[channel_axis]
        out = (v - m.reshape(shape)) / jnp.sqrt(s.reshape(shape) + epsilon)
        it = iter(rest)
        if weight is not None:
            out = out * next(it).reshape(shape)
        if bias is not None:
            out = out + next(it).reshape(shape)
        return out.astype(v.dtype)
    args = [x, mean_t, var_t] + [t for t in (weight, bias) if t is not None]
    return run_op("batch_norm", fn, args)


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW", name=None):
    def fn(a, *rest):
        axes = tuple(range(2, a.ndim))
        mean = jnp.mean(a, axis=axes, keepdims=True)
        var = jnp.var(a, axis=axes, keepdims=True)
        out = (a - mean) / jnp.sqrt(var + eps)
        it = iter(rest)
        if weight is not None:
            w = next(it)
            out = out * w.reshape((1, -1) + (1,) * (a.ndim - 2))
        if bias is not None:
            b = next(it)
            out = out + b.reshape((1, -1) + (1,) * (a.ndim - 2))
        return out.astype(a.dtype)
    args = [x] + [t for t in (weight, bias) if t is not None]
    return run_op("instance_norm", fn, args)


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    channel_last = data_format in ("NHWC", "NDHWC", "NLC")

    def fn(a, *rest):
        if channel_last:
            a_t = jnp.moveaxis(a, -1, 1)
        else:
            a_t = a
        n, c = a_t.shape[:2]
        sp = a_t.shape[2:]
        g = a_t.reshape(n, num_groups, c // num_groups, *sp)
        axes = tuple(range(2, g.ndim))
        mean = jnp.mean(g, axis=axes, keepdims=True)
        var = jnp.var(g, axis=axes, keepdims=True)
        out = ((g - mean) / jnp.sqrt(var + epsilon)).reshape(a_t.shape)
        it = iter(rest)
        shape = (1, c) + (1,) * len(sp)
        if weight is not None:
            out = out * next(it).reshape(shape)
        if bias is not None:
            out = out + next(it).reshape(shape)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out.astype(a.dtype)
    args = [x] + [t for t in (weight, bias) if t is not None]
    return run_op("group_norm", fn, args)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    def fn(a):
        ch_ax = 1 if data_format.startswith("NC") else a.ndim - 1
        sq = jnp.square(a)
        half = size // 2
        pads = [(0, 0)] * a.ndim
        pads[ch_ax] = (half, size - half - 1)
        padded = jnp.pad(sq, pads)
        win = sum(
            jnp.take(padded,
                     jnp.arange(i, i + a.shape[ch_ax]), axis=ch_ax)
            for i in range(size))
        return a / ((k + alpha * win) ** beta)
    return run_op("local_response_norm", fn, [x])
