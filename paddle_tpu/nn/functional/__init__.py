"""paddle_tpu.nn.functional (reference: python/paddle/nn/functional)."""
from .activation import *  # noqa: F401,F403
from .attention import (  # noqa: F401
    document_startend_row_indices, flash_attention, flash_attn,
    flash_attn_qkvpacked, flash_attn_unpadded,
    flash_attn_varlen_qkvpacked, flashmask_attention,
    memory_efficient_attention, scaled_dot_product_attention,
    sequence_mask,
)
from .common import (  # noqa: F401
    affine_grid, alpha_dropout, bicubic_interp, bilinear, bilinear_interp,
    channel_shuffle, cosine_similarity, dropout, dropout2d, dropout3d,
    embedding, fold, fused_softmax_mask, fused_softmax_mask_upper_triangle,
    grid_sample, interpolate, label_smooth, linear, linear_interp,
    nearest_interp, one_hot, pad, pad3d, pixel_shuffle, pixel_unshuffle,
    temporal_shift, trilinear_interp, unfold, upsample,
)
from .common import (  # noqa: F401
    class_center_sample, feature_alpha_dropout, gather_tree,
    pairwise_distance, sparse_attention, zeropad2d,
)
from .conv import (  # noqa: F401
    conv1d, conv1d_transpose, conv2d, conv2d_transpose, conv3d,
    conv3d_transpose, depthwise_conv2d_transpose,
)
from .loss import (  # noqa: F401
    bce_loss, binary_cross_entropy, binary_cross_entropy_with_logits,
    cosine_embedding_loss, cross_entropy, ctc_loss, hinge_embedding_loss,
    hinge_loss, huber_loss, identity_loss, kl_div, kldiv_loss, l1_loss,
    log_loss, margin_cross_entropy, margin_ranking_loss, mse_loss,
    nll_loss, sigmoid_cross_entropy_with_logits, sigmoid_focal_loss,
    smooth_l1_loss, softmax_with_cross_entropy, square_error_cost,
    triplet_margin_loss,
)
from .loss import (  # noqa: F401
    adaptive_log_softmax_with_loss, dice_loss, gaussian_nll_loss,
    hsigmoid_loss, multi_label_soft_margin_loss, multi_margin_loss,
    npair_loss, poisson_nll_loss, rnnt_loss, soft_margin_loss,
    triplet_margin_with_distance_loss,
)
from .norm import (  # noqa: F401
    batch_norm, group_norm, instance_norm, layer_norm, local_response_norm,
    normalize, rms_norm,
)
from .pooling import (  # noqa: F401
    adaptive_avg_pool1d, adaptive_avg_pool2d, adaptive_avg_pool3d,
    adaptive_max_pool1d, adaptive_max_pool2d, adaptive_max_pool3d,
    avg_pool1d, avg_pool2d, avg_pool3d, fractional_max_pool2d,
    fractional_max_pool3d, lp_pool1d, lp_pool2d, max_pool1d, max_pool2d,
    max_pool3d, max_unpool1d, max_unpool2d, max_unpool3d,
)
