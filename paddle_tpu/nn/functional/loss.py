"""Loss functionals.

Reference: python/paddle/nn/functional/loss.py. cross_entropy follows the
paddle contract: integer labels (sparse) or soft labels, ignore_index,
class weights, reduction modes, axis.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import run_op, unwrap


def _reduce(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    if reduction == "none":
        return loss
    raise ValueError(f"reduction must be mean/sum/none, got {reduction}")


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0, name=None):
    """Reference: nn/functional/loss.py cross_entropy."""
    def fn(logits, lab, *rest):
        lp = jax.nn.log_softmax(logits, axis=axis) if use_softmax \
            else jnp.log(jnp.clip(logits, 1e-15, 1.0))
        nclass = logits.shape[axis]
        if soft_label or (lab.ndim == logits.ndim
                          and lab.shape == logits.shape
                          and jnp.issubdtype(lab.dtype, jnp.floating)):
            soft = lab
            if label_smoothing > 0.0:
                soft = soft * (1 - label_smoothing) + label_smoothing / nclass
            loss = -jnp.sum(soft * lp, axis=axis)
            if rest:
                w = rest[0]
                loss = loss * jnp.sum(soft * w, axis=axis)
            return _reduce(loss, reduction)
        lab_i = lab
        if lab_i.ndim == logits.ndim:
            lab_i = jnp.squeeze(lab_i, axis=axis)
        lab_i = lab_i.astype(jnp.int32)
        valid = lab_i != ignore_index
        safe = jnp.where(valid, lab_i, 0)
        picked = jnp.take_along_axis(
            lp, jnp.expand_dims(safe, axis), axis=axis)
        loss = -jnp.squeeze(picked, axis=axis)
        if label_smoothing > 0.0:
            smooth = -jnp.mean(lp, axis=axis)
            loss = (1 - label_smoothing) * loss + label_smoothing * smooth
        if rest:
            w = rest[0]
            wsel = jnp.take(w, safe)
            loss = loss * wsel
            loss = jnp.where(valid, loss, 0.0)
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(
                    jnp.sum(jnp.where(valid, wsel, 0.0)), 1e-12)
            return _reduce(loss, reduction)
        loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(
                jnp.sum(valid.astype(loss.dtype)), 1.0)
        return _reduce(loss, reduction)
    args = [input, label] + ([weight] if weight is not None else [])
    return run_op("cross_entropy", fn, args)


softmax_with_cross_entropy = cross_entropy


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    def fn(lp, lab, *rest):
        lab_i = lab.astype(jnp.int32)
        valid = lab_i != ignore_index
        safe = jnp.where(valid, lab_i, 0)
        picked = jnp.take_along_axis(lp, jnp.expand_dims(safe, 1), axis=1)
        loss = -jnp.squeeze(picked, axis=1)
        if rest:
            wsel = jnp.take(rest[0], safe)
            loss = loss * wsel
            loss = jnp.where(valid, loss, 0.0)
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(
                    jnp.sum(jnp.where(valid, wsel, 0.0)), 1e-12)
        loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(
                jnp.sum(valid.astype(loss.dtype)), 1.0)
        return _reduce(loss, reduction)
    args = [input, label] + ([weight] if weight is not None else [])
    return run_op("nll_loss", fn, args)


def mse_loss(input, label, reduction="mean", name=None):
    return run_op("mse_loss",
                  lambda a, b: _reduce(jnp.square(a - b), reduction),
                  [input, label])


def l1_loss(input, label, reduction="mean", name=None):
    return run_op("l1_loss",
                  lambda a, b: _reduce(jnp.abs(a - b), reduction),
                  [input, label])


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def fn(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
        return _reduce(loss, reduction)
    return run_op("smooth_l1_loss", fn, [input, label])


def binary_cross_entropy(input, label, weight=None, reduction="mean",
                         name=None):
    def fn(p, y, *rest):
        p = jnp.clip(p, 1e-12, 1 - 1e-12)
        loss = -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
        if rest:
            loss = loss * rest[0]
        return _reduce(loss, reduction)
    args = [input, label] + ([weight] if weight is not None else [])
    return run_op("binary_cross_entropy", fn, args)


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    def fn(z, y, *rest):
        it = iter(rest)
        # numerically stable: max(z,0) - z*y + log(1+exp(-|z|))
        loss = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        if pos_weight is not None:
            pw = next(it)
            log_sig = jax.nn.log_sigmoid(z)
            log_sig_neg = jax.nn.log_sigmoid(-z)
            loss = -(pw * y * log_sig + (1 - y) * log_sig_neg)
        if weight is not None:
            loss = loss * next(it)
        return _reduce(loss, reduction)
    args = [logit, label] + [t for t in (pos_weight, weight)
                             if t is not None]
    return run_op("bce_with_logits", fn, args)


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    def fn(lp, y):
        if log_target:
            loss = jnp.exp(y) * (y - lp)
        else:
            loss = y * (jnp.log(jnp.clip(y, 1e-12)) - lp)
        if reduction == "batchmean":
            return jnp.sum(loss) / lp.shape[0]
        return _reduce(loss, reduction)
    return run_op("kl_div", fn, [input, label])


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean",
                         name=None):
    def fn(a, y):
        loss = jnp.where(y == 1., a, jnp.maximum(0., margin - a))
        return _reduce(loss, reduction)
    return run_op("hinge_embedding_loss", fn, [input, label])


def cosine_embedding_loss(input1, input2, label, margin=0, reduction="mean",
                          name=None):
    def fn(a, b, y):
        cos = jnp.sum(a * b, -1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0., cos - margin))
        return _reduce(loss, reduction)
    return run_op("cosine_embedding_loss", fn, [input1, input2, label])


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    def fn(a, b, y):
        return _reduce(jnp.maximum(0., -y * (a - b) + margin), reduction)
    return run_op("margin_ranking_loss", fn, [input, other, label])


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean",
                        name=None):
    def fn(a, pos, neg):
        def dist(u, v):
            return jnp.sum(jnp.abs(u - v + epsilon) ** p, -1) ** (1 / p)
        d_ap = dist(a, pos)
        d_an = dist(a, neg)
        if swap:
            d_pn = dist(pos, neg)
            d_an = jnp.minimum(d_an, d_pn)
        return _reduce(jnp.maximum(0., d_ap - d_an + margin), reduction)
    return run_op("triplet_margin_loss", fn, [input, positive, negative])


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def fn(z, y, *rest):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * y + (1 - p) * (1 - y)
        mod = (1 - p_t) ** gamma
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * mod * ce
        if rest:
            loss = loss / rest[0]
        return _reduce(loss, reduction)
    args = [logit, label] + ([normalizer] if normalizer is not None else [])
    return run_op("sigmoid_focal_loss", fn, args)


def log_loss(input, label, epsilon=1e-4, name=None):
    def fn(p, y):
        return -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(1 - p + epsilon)
    return run_op("log_loss", fn, [input, label])


def square_error_cost(input, label):
    return run_op("square_error_cost",
                  lambda a, b: jnp.square(a - b), [input, label])


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC via the standard forward algorithm in log space
    (reference: nn/functional/loss.py ctc_loss, warpctc kernel)."""
    def fn(lp, lab, in_len, lab_len):
        # lp: [T, B, C] log probs (paddle convention: logits [T,B,C])
        lp = jax.nn.log_softmax(lp, axis=-1)
        T, B, C = lp.shape
        S = lab.shape[1]
        ext = jnp.full((B, 2 * S + 1), blank, jnp.int32)
        ext = ext.at[:, 1::2].set(lab.astype(jnp.int32))
        L = 2 * lab_len.astype(jnp.int32) + 1
        neg_inf = jnp.float32(-1e30)
        alpha = jnp.full((B, 2 * S + 1), neg_inf)
        alpha = alpha.at[:, 0].set(lp[0, :, blank])
        has1 = (L > 1)
        alpha = alpha.at[:, 1].set(
            jnp.where(has1,
                      jnp.take_along_axis(lp[0], ext[:, 1:2], 1)[:, 0],
                      neg_inf))

        same = jnp.concatenate(
            [jnp.ones((B, 2), bool),
             ext[:, 2:] == ext[:, :-2]], axis=1)

        def step(alpha_prev, lp_t):
            a0 = alpha_prev
            a1 = jnp.concatenate(
                [jnp.full((B, 1), neg_inf), alpha_prev[:, :-1]], 1)
            a2 = jnp.concatenate(
                [jnp.full((B, 2), neg_inf), alpha_prev[:, :-2]], 1)
            a2 = jnp.where(same, neg_inf, a2)
            merged = jnp.logaddexp(jnp.logaddexp(a0, a1), a2)
            emit = jnp.take_along_axis(lp_t, ext, axis=1)
            return merged + emit, None

        def scan_body(carry, lp_t_and_t):
            lp_t, t = lp_t_and_t
            new, _ = step(carry, lp_t)
            keep = (t < in_len)[:, None]
            return jnp.where(keep, new, carry), None

        ts = jnp.arange(1, T)
        alpha, _ = jax.lax.scan(scan_body, alpha, (lp[1:], ts))
        idx_last = (L - 1)[:, None]
        idx_prev = jnp.maximum(L - 2, 0)[:, None]
        a_last = jnp.take_along_axis(alpha, idx_last, 1)[:, 0]
        a_prev = jnp.take_along_axis(alpha, idx_prev, 1)[:, 0]
        ll = jnp.logaddexp(a_last, jnp.where(L > 1, a_prev, neg_inf))
        loss = -ll
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(lab_len, 1))
        return _reduce(loss, reduction)
    return run_op("ctc_loss", fn,
                  [log_probs, labels, input_lengths, label_lengths])


# ---- coverage batch (reference ops.yaml loss names) ------------------------

bce_loss = binary_cross_entropy
sigmoid_cross_entropy_with_logits = binary_cross_entropy_with_logits
kldiv_loss = kl_div


def hinge_loss(input, label, name=None):
    """reference ops.yaml: hinge_loss (labels in {0,1})."""
    def fn(x, y):
        signed = 2.0 * y - 1.0
        return jnp.maximum(0.0, 1.0 - signed * x)
    return run_op("hinge_loss", fn, [input, label])


def huber_loss(input, label, delta=1.0, name=None):
    """reference ops.yaml: huber_loss (elementwise, no reduction)."""
    def fn(x, y):
        d = x - y
        ad = jnp.abs(d)
        return jnp.where(ad <= delta, 0.5 * d * d,
                         delta * (ad - 0.5 * delta))
    return run_op("huber_loss", fn, [input, label])


def identity_loss(x, reduction="none", name=None):
    """reference ops.yaml: identity_loss."""
    red = {0: "sum", 1: "mean", 2: "none"}.get(reduction, reduction)
    return run_op("identity_loss", lambda a: _reduce_arr(a, red), [x])


def _reduce_arr(a, reduction):
    if reduction == "mean":
        return jnp.mean(a)
    if reduction == "sum":
        return jnp.sum(a)
    return a


def margin_cross_entropy(logits, label, return_softmax=False,
                         margin1=1.0, margin2=0.5, margin3=0.0,
                         scale=64.0, group=None, name=None):
    """ArcFace/CosFace-style margin softmax CE (reference ops.yaml:
    margin_cross_entropy). Single-device lowering; under TP the vocab
    dim shards like ParallelCrossEntropy."""
    def fn(lg, lb):
        theta = jnp.arccos(jnp.clip(lg, -1.0, 1.0))
        one_hot = jax.nn.one_hot(lb, lg.shape[-1], dtype=lg.dtype)
        adj = jnp.cos(margin1 * theta + margin2) - margin3
        out = jnp.where(one_hot > 0, adj, lg) * scale
        logp = jax.nn.log_softmax(out, axis=-1)
        loss = -jnp.sum(one_hot * logp, axis=-1, keepdims=True)
        if return_softmax:
            return loss, jnp.exp(logp)
        return loss
    return run_op("margin_cross_entropy", fn, [logits, label])


cross_entropy_with_softmax = cross_entropy


def dice_loss(input, label, epsilon=1e-5, name=None):
    """Dice loss over the last-dim class probabilities (reference:
    nn/functional/loss.py dice_loss)."""
    def fn(p, lab):
        num_classes = p.shape[-1]
        lab_oh = jax.nn.one_hot(lab.reshape(lab.shape[:-1]), num_classes,
                                dtype=p.dtype)
        red = tuple(range(1, p.ndim))
        inter = jnp.sum(p * lab_oh, axis=red)
        union = jnp.sum(p, axis=red) + jnp.sum(lab_oh, axis=red)
        return jnp.mean(1.0 - (2 * inter + epsilon) / (union + epsilon))
    return run_op("dice_loss", fn, [input, label])


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    """Gaussian negative log likelihood (reference: gaussian_nll_loss)."""
    def fn(mu, y, var):
        var = jnp.maximum(var, epsilon)
        loss = 0.5 * (jnp.log(var) + (y - mu) ** 2 / var)
        if full:
            loss = loss + 0.5 * math.log(2 * math.pi)
        return _reduce(loss, reduction)
    return run_op("gaussian_nll_loss", fn, [input, label, variance])


def poisson_nll_loss(input, label, log_input=True, full=False,
                     epsilon=1e-8, reduction="mean", name=None):
    """Poisson NLL (reference: poisson_nll_loss)."""
    def fn(x, y):
        if log_input:
            loss = jnp.exp(x) - y * x
        else:
            loss = x - y * jnp.log(x + epsilon)
        if full:
            # Stirling approximation for log(y!) at y > 1
            stir = y * jnp.log(y) - y + 0.5 * jnp.log(2 * jnp.pi * y)
            loss = loss + jnp.where(y > 1, stir, 0.0)
        return _reduce(loss, reduction)
    return run_op("poisson_nll_loss", fn, [input, label])


def soft_margin_loss(input, label, reduction="mean", name=None):
    """log(1 + exp(-label * input)) (reference: soft_margin_loss)."""
    def fn(x, y):
        return _reduce(jnp.log1p(jnp.exp(-y * x)), reduction)
    return run_op("soft_margin_loss", fn, [input, label])


def multi_label_soft_margin_loss(input, label, weight=None,
                                 reduction="mean", name=None):
    """Multi-label one-versus-all margin loss (reference:
    multi_label_soft_margin_loss)."""
    def fn(x, y, *rest):
        loss = -(y * jax.nn.log_sigmoid(x)
                 + (1 - y) * jax.nn.log_sigmoid(-x))
        if rest:
            loss = loss * rest[0]
        return _reduce(jnp.mean(loss, axis=-1), reduction)
    args = [input, label] + ([weight] if weight is not None else [])
    return run_op("multi_label_soft_margin_loss", fn, args)


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    """Multi-class margin loss (reference: multi_margin_loss)."""
    def fn(x, y, *rest):
        n, c = x.shape
        correct = jnp.take_along_axis(x, y[:, None], axis=1)
        m = jnp.maximum(margin - correct + x, 0.0) ** p
        if rest:
            m = m * rest[0][y][:, None]
        mask = jax.nn.one_hot(y, c, dtype=x.dtype)
        loss = jnp.sum(m * (1 - mask), axis=1) / c
        return _reduce(loss, reduction)
    args = [input, label] + ([weight] if weight is not None else [])
    return run_op("multi_margin_loss", fn, args)


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean",
                                      name=None):
    """Triplet loss with a custom distance callable (reference:
    triplet_margin_with_distance_loss)."""
    if distance_function is None:
        def distance_function(a, b):
            from ...ops import math as M
            return M.sqrt(((a - b) * (a - b)).sum(-1))
    d_pos = distance_function(input, positive)
    d_neg = distance_function(input, negative)
    if swap:
        d_pn = distance_function(positive, negative)
        from ...ops import math as M
        d_neg = M.minimum(d_neg, d_pn)

    def fn(dp, dn):
        return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)
    return run_op("triplet_margin_with_distance_loss", fn, [d_pos, d_neg])


def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    """N-pair loss (reference: npair_loss)."""
    def fn(a, p, y):
        reg = l2_reg * (jnp.sum(a * a) / a.shape[0]
                        + jnp.sum(p * p) / p.shape[0]) * 0.25
        sim = a @ p.T  # [n, n]
        same = (y[:, None] == y[None, :]).astype(a.dtype)
        tgt = same / jnp.sum(same, axis=1, keepdims=True)
        xent = jnp.mean(jnp.sum(
            -tgt * jax.nn.log_softmax(sim, axis=1), axis=1))
        return xent + reg
    return run_op("npair_loss", fn, [anchor, positive, labels])


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid over the default complete binary tree
    (reference: hsigmoid_loss; custom trees via path_table/path_code).

    Default tree: internal node ids follow the heap layout the reference
    kernel uses (codes from the binary expansion of label + num_classes).
    """
    def default_paths(num_classes):
        depth = int(np.ceil(np.log2(max(num_classes, 2))))
        tables, codes = [], []
        for lab in range(num_classes):
            node = lab + num_classes
            tab, code = [], []
            while node > 1:
                tab.append(node // 2 - 1)
                code.append(node % 2)
                node //= 2
            tab = tab[::-1] + [-1] * (depth - len(tab))
            code = code[::-1] + [-1] * (depth - len(code))
            tables.append(tab)
            codes.append(code)
        return (np.asarray(tables, np.int64), np.asarray(codes, np.int64))

    if path_table is None:
        tab_np, code_np = default_paths(int(num_classes))
        path_table_arr = jnp.asarray(tab_np)
        path_code_arr = jnp.asarray(code_np)
    else:
        path_table_arr = unwrap(path_table)
        path_code_arr = unwrap(path_code)

    def fn(x, lab, w, *rest):
        tab = path_table_arr[lab]      # [n, depth]
        code = path_code_arr[lab]      # [n, depth]
        valid = tab >= 0
        safe_tab = jnp.maximum(tab, 0)
        wt = w[safe_tab]               # [n, depth, feat]
        logits = jnp.einsum("ndf,nf->nd", wt, x)
        if rest:
            logits = logits + rest[0][safe_tab]
        # code==1 -> right branch (positive class), matching the kernel
        y = code.astype(x.dtype)
        ll = y * jax.nn.log_sigmoid(logits) \
            + (1 - y) * jax.nn.log_sigmoid(-logits)
        return -jnp.sum(jnp.where(valid, ll, 0.0), axis=1, keepdims=True)
    args = [input, label, weight] + ([bias] if bias is not None else [])
    return run_op("hsigmoid_loss", fn, args)


def adaptive_log_softmax_with_loss(input, label, head_weight, tail_weights,
                                   cutoffs, head_bias=None, name=None):
    """Adaptive softmax (Grave et al.) (reference:
    adaptive_log_softmax_with_loss). Returns (output, loss)."""
    n_clusters = len(cutoffs) - 1  # cutoffs includes n_classes at the end
    head_size = cutoffs[0] + n_clusters

    def fn(x, lab, hw, *rest):
        bias_ct = 1 if head_bias is not None else 0
        hb = rest[0] if bias_ct else None
        tails = rest[bias_ct:]
        head_logits = x @ hw
        if hb is not None:
            head_logits = head_logits + hb
        head_lp = jax.nn.log_softmax(head_logits, axis=-1)
        # in-shortlist term
        out = jnp.take_along_axis(
            head_lp, jnp.clip(lab, 0, cutoffs[0] - 1)[:, None],
            axis=1)[:, 0]
        for i in range(n_clusters):
            lo, hi = cutoffs[i], cutoffs[i + 1]
            in_c = (lab >= lo) & (lab < hi)
            w_dn, w_up = tails[2 * i], tails[2 * i + 1]
            tail_lp = jax.nn.log_softmax((x @ w_dn) @ w_up, axis=-1)
            rel = jnp.clip(lab - lo, 0, hi - lo - 1)
            cluster_lp = head_lp[:, cutoffs[0] + i] \
                + jnp.take_along_axis(tail_lp, rel[:, None], axis=1)[:, 0]
            out = jnp.where(in_c, cluster_lp, out)
        return out, -jnp.mean(out)
    args = [input, label, head_weight]
    if head_bias is not None:
        args.append(head_bias)
    for pair in tail_weights:
        args.extend(pair)
    return run_op("adaptive_log_softmax_with_loss", fn, args)


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.001, reduction="mean", name=None):
    """RNN-T transducer loss via the (T, U) log-space alpha recursion
    (reference: rnnt_loss, warprnnt kernel). input: [B, T, U+1, V]
    joint-network log-probable logits."""
    def fn(logits, lab, in_len, lab_len):
        lp = jax.nn.log_softmax(logits, axis=-1)
        B, T, U1, V = lp.shape
        U = U1 - 1
        blank_lp = lp[..., blank]                      # [B, T, U+1]
        lab_idx = jnp.clip(lab, 0, V - 1)              # [B, U]
        emit_lp = jnp.take_along_axis(
            lp[:, :, :U, :],
            jnp.broadcast_to(lab_idx[:, None, :, None],
                             (B, T, U, 1)), axis=-1)[..., 0]
        # FastEmit (Yu et al. 2021, eq. 9): weight every emission
        # transition by (1 + lambda), which scales emit-path gradients by
        # the same factor; lambda=0 reduces to plain RNN-T
        if fastemit_lambda:
            emit_lp = emit_lp + jnp.log1p(
                jnp.asarray(fastemit_lambda, lp.dtype))
        neg_inf = jnp.asarray(-1e30, lp.dtype)

        def t_step(alpha_prev, t):
            # alpha_prev: [B, U+1] for time t-1; returns alpha at t
            # first compute diagonal blank moves (t-1, u) -> (t, u)
            from_blank = alpha_prev + blank_lp[:, t - 1, :]

            def u_step(carry, u):
                # emit move within time t: (t, u-1) -> (t, u)
                prev_u = carry  # alpha[t, u-1]
                fb = jnp.take_along_axis(
                    from_blank, jnp.full((B, 1), u), axis=1)[:, 0]
                em = prev_u + jnp.take_along_axis(
                    emit_lp[:, t, :], jnp.clip(
                        jnp.full((B, 1), u - 1), 0, U - 1), axis=1)[:, 0]
                val = jnp.where(u == 0, fb,
                                jnp.logaddexp(fb, em))
                return val, val
            _, cols = jax.lax.scan(
                u_step, jnp.zeros((B,), lp.dtype), jnp.arange(U1))
            return jnp.swapaxes(cols, 0, 1), None

        # alpha[0, u]: only emit moves along u at t=0
        def u0_step(carry, u):
            em = carry + jnp.take_along_axis(
                emit_lp[:, 0, :], jnp.clip(jnp.full((B, 1), u - 1), 0,
                                           U - 1), axis=1)[:, 0]
            val = jnp.where(u == 0, jnp.zeros((B,), lp.dtype), em)
            return val, val
        _, cols0 = jax.lax.scan(u0_step, jnp.zeros((B,), lp.dtype),
                                jnp.arange(U1))
        alpha0 = jnp.swapaxes(cols0, 0, 1)

        def scan_t(alpha_prev, t):
            alpha_t, _ = t_step(alpha_prev, t)
            return alpha_t, alpha_t
        alpha_last, alphas = jax.lax.scan(scan_t, alpha0,
                                          jnp.arange(1, T))
        all_alphas = jnp.concatenate([alpha0[None], alphas], axis=0)
        # final: alpha[T_b - 1, U_b] + blank at (T_b - 1, U_b)
        t_idx = jnp.clip(in_len - 1, 0, T - 1)         # [B]
        u_idx = jnp.clip(lab_len, 0, U)                # [B]
        a_fin = all_alphas[t_idx, jnp.arange(B), u_idx]
        ll = a_fin + blank_lp[jnp.arange(B), t_idx, u_idx]
        loss = -ll
        return _reduce(loss, reduction)
    return run_op("rnnt_loss", fn,
                  [input, label, input_lengths, label_lengths])
